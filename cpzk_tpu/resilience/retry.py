"""Client retry policy: exponential backoff, full jitter, retry budget.

Follows the gRPC retry design (gRFC A6): a per-call attempt cap with
exponentially growing backoff, *full* jitter (each sleep is uniform on
``[0, cap]`` — decorrelates synchronized client herds after a server
blip), and a channel-wide token budget so a sustained outage can't turn
every caller into a retry storm.  Only status codes that are safe to
resend land in :attr:`RetryPolicy.retryable_codes`; the mapping of *which
RPCs* are idempotent-safe lives with the caller
(:class:`cpzk_tpu.client.AuthClient` — ``VerifyProof`` is never retried
because the server consumes its challenge on first receipt).

Codes are held as names (``"UNAVAILABLE"``) rather than ``grpc.StatusCode``
members so this module — and everything importing it — works without
grpcio installed.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field

#: Codes safe to resend for idempotent RPCs: the server either never saw
#: the request (UNAVAILABLE) or refused before acting on it
#: (RESOURCE_EXHAUSTED: rate limit / shed queue).  DEADLINE_EXCEEDED is
#: deliberately absent — the work may have committed server-side.
DEFAULT_RETRYABLE_CODES = ("UNAVAILABLE", "RESOURCE_EXHAUSTED")

#: Trailing-metadata key for server retry pushback (gRFC A6): the server
#: attaches it to every admission/overload rejection, sized from the
#: current queue drain rate; the client sleeps exactly this long instead
#: of its own jittered backoff.  A negative value means "do not retry".
#: Held here (not in the admission package) so the client side needs
#: neither grpcio nor the server modules to know the key.
RETRY_PUSHBACK_KEY = "cpzk-retry-after-ms"

#: Safety ceiling on honoring server pushback: a buggy or hostile server
#: must not be able to park a client for minutes with one header.
MAX_PUSHBACK_S = 30.0


class RetryBudget:
    """Channel-wide retry token bucket (gRFC A6 ``retryThrottling``).

    Every retry withdraws one token; every success deposits
    ``token_ratio``.  Retries are allowed only while the balance is at
    least one token, so under a long outage the budget drains and callers
    fail fast instead of multiplying load.  Thread-safe: one budget is
    shared across all of a client's concurrent calls.
    """

    def __init__(self, tokens: float = 10.0, token_ratio: float = 0.1):
        if tokens <= 0:
            raise ValueError("retry budget must start positive")
        self._max = float(tokens)
        self._tokens = float(tokens)
        self._ratio = float(token_ratio)
        self._lock = threading.Lock()

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def try_withdraw(self) -> bool:
        """Take one retry token; False means the budget is exhausted and
        the caller must surface the error instead of retrying."""
        with self._lock:
            if self._tokens < 1.0:
                return False
            self._tokens -= 1.0
            return True

    def deposit(self) -> None:
        """Record a success (refills ``token_ratio`` of a token)."""
        with self._lock:
            self._tokens = min(self._max, self._tokens + self._ratio)


@dataclass
class RetryPolicy:
    """Backoff schedule + budget for one client.

    ``max_attempts`` counts the original call (3 = initial + 2 retries).
    Sleep before retry ``k`` (1-based) is uniform on
    ``[0, min(max_backoff_s, initial_backoff_s * multiplier**(k-1))]``.
    """

    max_attempts: int = 3
    initial_backoff_s: float = 0.05
    max_backoff_s: float = 1.0
    multiplier: float = 2.0
    retryable_codes: tuple[str, ...] = DEFAULT_RETRYABLE_CODES
    budget: RetryBudget | None = field(default_factory=RetryBudget)

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.initial_backoff_s < 0 or self.max_backoff_s < 0:
            raise ValueError("backoff bounds cannot be negative")
        if self.multiplier < 1.0:
            raise ValueError("multiplier must be >= 1")

    def backoff_s(self, attempt: int, rng: random.Random | None = None) -> float:
        """Full-jitter sleep before retry ``attempt`` (1-based)."""
        cap = min(
            self.max_backoff_s,
            self.initial_backoff_s * self.multiplier ** max(0, attempt - 1),
        )
        return (rng or random).uniform(0.0, cap)

    def sleep_s(
        self,
        attempt: int,
        pushback_ms: float | None = None,
        rng: random.Random | None = None,
    ) -> float:
        """The sleep before retry ``attempt``: server pushback verbatim
        when present (gRFC A6 — the server knows its queue drain rate,
        the client's jitter schedule does not), capped at
        :data:`MAX_PUSHBACK_S`; otherwise the full-jitter backoff.
        Negative pushback ("do not retry") is the *caller's* decision to
        enforce before sleeping — here it falls back to jitter."""
        if pushback_ms is not None and pushback_ms >= 0:
            return min(MAX_PUSHBACK_S, pushback_ms / 1000.0)
        return self.backoff_s(attempt, rng)

    def should_retry(self, code_name: str, attempt: int) -> bool:
        """Policy decision for a failed attempt (1-based): code retryable,
        attempts remaining, and a budget token available (withdrawn here)."""
        if code_name not in self.retryable_codes:
            return False
        if attempt >= self.max_attempts:
            return False
        if self.budget is not None and not self.budget.try_withdraw():
            return False
        return True

    def note_success(self) -> None:
        if self.budget is not None:
            self.budget.deposit()
