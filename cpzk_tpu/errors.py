"""Shared error taxonomy.

Mirrors the reference's three-variant error enum (``src/error.rs:4-17``):
``InvalidParams``, ``InvalidScalar``, ``InvalidGroupElement``.
"""


class Error(Exception):
    """Base class for all protocol errors."""


class InvalidParams(Error):
    """Invalid protocol parameters (reference ``Error::InvalidParams``)."""


class InvalidScalar(Error):
    """Invalid scalar encoding/value (reference ``Error::InvalidScalar``)."""


class InvalidGroupElement(Error):
    """Invalid group element encoding/value (reference ``Error::InvalidGroupElement``)."""
