"""Shared error taxonomy.

Mirrors the reference's three-variant error enum (``src/error.rs:4-17``):
``InvalidParams``, ``InvalidScalar``, ``InvalidGroupElement``.
"""


class Error(Exception):
    """Base class for all protocol errors."""


class InvalidParams(Error):
    """Invalid protocol parameters (reference ``Error::InvalidParams``)."""


class InvalidScalar(Error):
    """Invalid scalar encoding/value (reference ``Error::InvalidScalar``)."""


class InvalidGroupElement(Error):
    """Invalid group element encoding/value (reference ``Error::InvalidGroupElement``)."""


class InvalidProofEncoding(InvalidGroupElement):
    """A deferred-parse proof whose commitment wire failed to decode at the
    batch-verify stage.  Same taxonomy slot as the eager parse error
    (``InvalidGroupElement`` from ``element_from_bytes``) — the distinct
    type lets the serving layer report the exact parse-time message
    ("Invalid proof: ...") instead of a generic verification failure, so
    deferred parsing is observationally identical to eager parsing."""
