"""Shared error taxonomy.

Mirrors the reference's three-variant error enum (``src/error.rs:4-17``):
``InvalidParams``, ``InvalidScalar``, ``InvalidGroupElement``.
"""


class Error(Exception):
    """Base class for all protocol errors."""


class InvalidParams(Error):
    """Invalid protocol parameters (reference ``Error::InvalidParams``)."""


class InvalidScalar(Error):
    """Invalid scalar encoding/value (reference ``Error::InvalidScalar``)."""


class InvalidGroupElement(Error):
    """Invalid group element encoding/value (reference ``Error::InvalidGroupElement``)."""


class InvalidProofEncoding(InvalidGroupElement):
    """A deferred-parse proof whose commitment wire failed to decode at the
    batch-verify stage.  Same taxonomy slot as the eager parse error
    (``InvalidGroupElement`` from ``element_from_bytes``) — the distinct
    type lets the serving layer report the exact parse-time message
    ("Invalid proof: ...") instead of a generic verification failure, so
    deferred parsing is observationally identical to eager parsing."""


class UnsupportedFormat(Error):
    """A persisted artifact (state snapshot, WAL record, proof-log
    record) carries a format stamp NEWER than this build writes, or an
    unintelligible one.  Deliberately NOT a quarantine case: the file is
    not corrupt, the binary is old — recovery refuses to boot, naming
    both versions, so the operator runs a binary at least as new as the
    one that wrote the data instead of silently setting it aside."""


class WrongPartition(Error):
    """A user-keyed mutation reached a partition that no longer owns the
    user under the live fleet map.  Raised by :class:`ServerState`'s
    write-time ownership fence (``owner_fence``) when a handler that
    passed its entry ownership check resumes after a live partition
    split flipped the map mid-flight; the serving layer answers it with
    the same ``FAILED_PRECONDITION`` redirect (owner address + map
    version trailers) as the entry check, so the client re-routes and
    no acknowledged write ever lands on a stale copy."""
