"""Batch verification — re-designed from reference ``src/verifier/batch.rs``.

API parity: ``BatchVerifier`` accumulates up to ``MAX_BATCH_SIZE`` entries of
(params, statement, proof, context), validating statements on ``add``
(batch.rs:139-168); ``verify`` returns per-proof results, short-circuiting a
single-entry batch to individual verification (batch.rs:171-183) and falling
back to per-proof verification when the combined check fails
(batch.rs:314-318) — so the *accept set* is always per-proof ground truth.

Math fix (normative deviation, SURVEY.md §3.2): the reference's combined
equation drops the random coefficient on the ``y^c`` term
(batch.rs:297-299), which makes its fast path fail for every n ≥ 2 batch and
silently degrade to per-proof verification. We implement the correct
random-linear-combination check

    Σ αᵢ·(sᵢ·G − r1ᵢ − cᵢ·y1ᵢ)  +  β·Σ αᵢ·(sᵢ·H − r2ᵢ − cᵢ·y2ᵢ)  ==  O

with per-entry random αᵢ and one extra random weight β merging the two
equations (soundness: Schwartz-Zippel over ℓ; per-equation failure
probability ≤ 2/ℓ). Observable accept/reject semantics are identical to the
reference because its fallback already defines acceptance per-proof.

The heavy lifting is delegated to a pluggable ``VerifierBackend``:
``CpuBackend`` (host oracle, default) or the TPU/JAX backend in
:mod:`cpzk_tpu.ops.backend` (one big vectorized pass; see BASELINE.json
north star).
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, field

from ..errors import Error, InvalidParams, InvalidProofEncoding
from ..core import edwards
from ..core.ristretto import Element, Ristretto255, Scalar
from ..core.rng import SecureRng
from ..core.scalars import L, sc_mul
from ..core.transcript import Transcript
from .gadgets import Parameters, Proof, Statement
from .verifier import Verifier

MAX_BATCH_SIZE = 1000


class _NullStages:
    """Inert stage recorder: ``BatchVerifier.verify`` always runs under a
    stage scope, instrumented or not (the real recorder lives in
    :mod:`cpzk_tpu.observability.tracing` — this layer stays import-free
    of it)."""

    def stage(self, name: str):
        del name
        return contextlib.nullcontext()


_NULL_STAGES = _NullStages()


@dataclass
class BatchEntry:
    params: Parameters
    statement: Statement
    proof: Proof
    transcript_context: bytes | None
    #: absolute ``time.monotonic()`` point after which nobody is waiting for
    #: this entry's result (the RPC deadline, threaded through the serving
    #: layer); ``None`` = wait forever.  The dynamic batcher sheds expired
    #: entries before device dispatch instead of verifying them.
    deadline: float | None = None
    #: trace id of the RPC that queued this entry (observability subsystem);
    #: the batcher fans per-stage spans out to every member trace.
    trace_id: str | None = None
    #: ``time.monotonic()`` at enqueue, stamped by the batcher — the
    #: ``queue_wait`` span/histogram measures from here to dispatch.
    enqueued_at: float | None = None


@dataclass
class BatchRow:
    """Flattened, challenge-resolved entry handed to a backend."""

    g: Element
    h: Element
    y1: Element
    y2: Element
    r1: Element
    r2: Element
    s: Scalar
    c: Scalar
    alpha: Scalar


@dataclass
class PreparedBatch:
    """Host-phase output of :meth:`BatchVerifier.prepare_batch` — the
    challenge-resolved rows (or the n == 1 verifier, or the deferred-parse
    splice plan) ready for backend dispatch via
    :meth:`BatchVerifier.run_prepared`.  Built on one thread, consumable
    on another: nothing here touches the backend or the RNG."""

    n: int
    # n == 1 individual-verification path
    entry: BatchEntry | None = None
    verifier: object | None = None      # protocol.verifier.Verifier
    transcript: Transcript | None = None
    # n >= 2 batch path
    rows: list[BatchRow] | None = None
    beta: Scalar | None = None
    same_generators: bool = True
    # deferred-parse splice path: undecodable wires mapped to their parse
    # errors; survivors prepared as a sub-batch
    pre_errors: dict[int, Error] | None = None
    sub: "BatchVerifier | None" = None
    sub_prepared: "PreparedBatch | None" = field(default=None, repr=False)


class VerifierBackend:
    """Backend interface for the batch-verification compute plane.

    Thread-safety contract: the serving layer's pipelined batcher
    (``DynamicBatcher(pipeline_depth>1)``) calls ``verify_combined`` /
    ``verify_each`` for DIFFERENT batches concurrently from worker
    threads.  Implementations must tolerate that — keep per-call state on
    the stack and guard any shared caches (see ``TpuBackend._gh``)."""

    #: Whether the combined RLC fast path is actually faster than per-proof
    #: checks on this backend. False for the scalar CPU oracle (4n+2 muls vs
    #: 4n, and a failed combined check pays both passes); True for vectorized
    #: backends where the combined check amortizes.
    prefers_combined: bool = True

    #: Whether ``verify_each`` reports a deferred-parse proof's commitment
    #: decode failure tri-state (row status 2) instead of crashing or
    #: conflating it with a verification failure.  When False, the
    #: dispatcher eagerly screens deferred proofs before involving the
    #: backend, so backends never see an undecodable wire.
    supports_deferred_decode: bool = False

    def verify_combined(self, rows: list[BatchRow], beta: Scalar) -> bool:
        """Corrected-RLC combined check; True iff the whole batch passes."""
        raise NotImplementedError

    def verify_each(self, rows: list[BatchRow]) -> list[int]:
        """Per-proof ground-truth checks (the accept-set decider).
        Per-row status: 1/True = pass, 0/False = fail, 2 = commitment wire
        failed to decode (deferred-parse rows only)."""
        raise NotImplementedError


class CpuBackend(VerifierBackend):
    """Host-plane backend over the integer-exact core (the oracle)."""

    prefers_combined = False
    supports_deferred_decode = True  # native rows report status 2

    def verify_combined(self, rows: list[BatchRow], beta: Scalar) -> bool:
        acc = edwards.IDENTITY
        sum_as = 0  # Σ αᵢ·sᵢ mod ℓ
        for row in rows:
            a = row.alpha.value
            ac = sc_mul(a, row.c.value)
            sum_as = (sum_as + a * row.s.value) % L
            # subtract αᵢ·r1ᵢ + (αᵢcᵢ)·y1ᵢ + β·(αᵢ·r2ᵢ + (αᵢcᵢ)·y2ᵢ)
            term = edwards.pt_add(
                edwards.pt_scalar_mul(row.r1.point, a),
                edwards.pt_scalar_mul(row.y1.point, ac),
            )
            term2 = edwards.pt_add(
                edwards.pt_scalar_mul(row.r2.point, sc_mul(a, beta.value)),
                edwards.pt_scalar_mul(row.y2.point, sc_mul(ac, beta.value)),
            )
            acc = edwards.pt_add(acc, edwards.pt_add(term, term2))
        # add (Σαs)·G + β(Σαs)·H — valid only when all rows share generators;
        # the dispatcher (BatchVerifier.verify) only takes this fast path in
        # that case and sends mixed-generator batches to verify_each.
        g = rows[0].g.point
        h = rows[0].h.point
        lhs = edwards.pt_add(
            edwards.pt_scalar_mul(g, sum_as),
            edwards.pt_scalar_mul(h, sc_mul(sum_as, beta.value)),
        )
        return edwards.pt_eq(lhs, acc)

    def verify_each(self, rows: list[BatchRow]) -> list[int]:
        native = self._verify_each_native(rows)
        if native is not None:
            return native
        out: list[int] = []
        for row in rows:
            try:
                r1p, r2p = row.r1.point, row.r2.point
            except Error:
                # deferred-parse wire that fails to decode (tri-state twin
                # of the native path's status 2)
                out.append(2)
                continue
            lhs1 = edwards.pt_scalar_mul(row.g.point, row.s.value)
            rhs1 = edwards.pt_add(r1p, edwards.pt_scalar_mul(row.y1.point, row.c.value))
            lhs2 = edwards.pt_scalar_mul(row.h.point, row.s.value)
            rhs2 = edwards.pt_add(r2p, edwards.pt_scalar_mul(row.y2.point, row.c.value))
            out.append(int(edwards.pt_eq(lhs1, rhs1) and edwards.pt_eq(lhs2, rhs2)))
        return out

    @staticmethod
    def _verify_each_native(rows: list[BatchRow]) -> list[int] | None:
        """Threaded C++ row verification (native/ristretto.cpp) when the
        library is loadable and the batch shares one generator pair; None
        routes the caller to the pure-Python oracle.  Statuses per the
        ``verify_each`` contract: 1 pass, 0 fail, 2 commitment-decode
        failure (NOT truthy-pass — deferred rows only)."""
        if not rows:
            return []
        if not all(r.g == rows[0].g and r.h == rows[0].h for r in rows):
            return None
        from ..core import _native

        eb = Ristretto255.element_to_bytes
        sb = Ristretto255.scalar_to_bytes
        return _native.verify_rows(
            eb(rows[0].g),
            eb(rows[0].h),
            b"".join(eb(r.y1) for r in rows),
            b"".join(eb(r.y2) for r in rows),
            b"".join(eb(r.r1) for r in rows),
            b"".join(eb(r.r2) for r in rows),
            b"".join(sb(r.s) for r in rows),
            b"".join(sb(r.c) for r in rows),
        )


class FailoverBackend(VerifierBackend):
    """Self-healing TPU→CPU failover wrapper (SURVEY.md §5 failure
    detection + resilience subsystem circuit breaker).

    Routes to ``primary`` until it raises, then degrades to ``fallback``
    — a failed combined check simply reports False so the dispatcher's
    per-proof path decides, keeping accept/reject semantics byte-identical
    through a mid-batch backend loss.  Unlike the old one-way latch,
    degradation heals: after ``recovery_after_s`` the breaker grants a
    single *probe* — one batch is verified on BOTH planes, the fallback
    result stays authoritative, and the primary is re-armed only when its
    answers match ground truth exactly (a device that comes back *wrong*
    never regains traffic).  ``recovery_after_s=None`` restores the
    permanent-until-``reset()`` behavior.

    Observability: ``tpu.backend.failover`` counts CLOSED→OPEN trips,
    ``tpu.backend.state`` gauges the breaker (0 closed / 1 open / 2
    half-open), ``tpu.backend.degraded_seconds`` accumulates CPU-only
    wall time, and each transition logs WARNING exactly once.
    """

    def __init__(
        self,
        primary: VerifierBackend,
        fallback: VerifierBackend,
        recovery_after_s: float | None = 30.0,
        probe_batch_max: int = 64,
        clock=None,
    ):
        import time as _time

        from ..resilience.breaker import BreakerState, CircuitBreaker

        if probe_batch_max < 1:
            raise InvalidParams("probe_batch_max must be positive")
        self.primary = primary
        self.fallback = fallback
        self.probe_batch_max = probe_batch_max
        self._closed = BreakerState.CLOSED
        self.breaker = CircuitBreaker(
            recovery_after_s=recovery_after_s,
            clock=clock or _time.monotonic,
            on_transition=self._on_transition,
        )

    @property
    def degraded(self) -> bool:
        """True while traffic is (at least partly) on the fallback."""
        return self.breaker.state is not self._closed

    @property
    def state(self):
        """Breaker state, for the admin REPL ``/status`` line."""
        return self.breaker.state

    @property
    def prefers_combined(self) -> bool:  # type: ignore[override]
        backend = self.fallback if self.degraded else self.primary
        return backend.prefers_combined

    def reset(self) -> None:
        """Operator re-arm (bypasses the probe — trust the fix)."""
        self.breaker.reset()

    # -- transitions / observability --------------------------------------

    def _on_transition(self, old, new) -> None:
        import logging

        from ..resilience.breaker import BreakerState

        log = logging.getLogger("cpzk_tpu.protocol.batch")
        if new is BreakerState.OPEN and old is BreakerState.CLOSED:
            log.warning(
                "primary verifier backend failed; degrading to fallback "
                "(probe retry in %ss)", self.breaker.recovery_after_s,
            )
        elif new is BreakerState.OPEN:
            log.warning(
                "primary verifier probe failed or disagreed with fallback "
                "ground truth; staying degraded (next probe in %ss)",
                self.breaker.recovery_after_s,
            )
        elif new is BreakerState.HALF_OPEN:
            log.info("probing primary verifier backend with one batch")
        else:  # -> CLOSED
            log.warning(
                "primary verifier backend recovered after %.1fs degraded; "
                "traffic back on primary", self.breaker.degraded_seconds,
            )
        try:  # metrics live in the server layer; optional here
            from ..server import metrics

            if new is BreakerState.OPEN and old is BreakerState.CLOSED:
                metrics.counter("tpu.backend.failover").inc()
            metrics.gauge("tpu.backend.state").set(
                {"closed": 0, "open": 1, "half-open": 2}[new.value]
            )
        except Exception:
            pass
        try:  # transition also lands in the trace ring buffer, so degraded
            # periods share the /tracez timeline with the requests they hit
            from ..observability import get_tracer

            get_tracer().record_event(
                "breaker_transition", old=old.value, new=new.value,
            )
        except Exception:
            pass

    def _touch_degraded_gauge(self) -> None:
        try:
            from ..server import metrics

            metrics.gauge("tpu.backend.degraded_seconds").set(
                self.breaker.degraded_seconds
            )
        except Exception:
            pass

    def _note_failure(self, exc: Exception) -> None:
        # pipelined dispatches call backends from multiple threads; the
        # breaker hands the CLOSED->OPEN transition to exactly one of them
        # (transition logging/metrics live in _on_transition; the device
        # exception itself is only worth one traceback, not one per batch)
        if self.breaker.record_failure():
            import logging

            logging.getLogger("cpzk_tpu.protocol.batch").warning(
                "primary verifier backend raised", exc_info=exc
            )

    # -- verification routing ----------------------------------------------

    def verify_combined(self, rows: list[BatchRow], beta: Scalar) -> bool:
        self._touch_degraded_gauge()
        route = self.breaker.acquire()
        if route == "primary":
            try:
                return self.primary.verify_combined(rows, beta)
            except Exception as exc:
                self._note_failure(exc)
        elif route == "probe":
            # a combined check has no per-row ground truth to compare the
            # probe against; hand the token back so the dispatcher's
            # verify_each pass (or the next batch) runs the real probe
            self.breaker.release_probe()
        # a False combined check routes the dispatcher to verify_each,
        # which is the ground-truth path on the fallback backend
        if self.fallback.prefers_combined:
            return self.fallback.verify_combined(rows, beta)
        return False

    def verify_each(self, rows: list[BatchRow]) -> list[int]:
        self._touch_degraded_gauge()
        route = self.breaker.acquire()
        if route == "primary":
            try:
                return self.primary.verify_each(rows)
            except Exception as exc:
                self._note_failure(exc)
            return self.fallback.verify_each(rows)
        if route == "probe":
            return self._probe_each(rows)
        return self.fallback.verify_each(rows)

    def _probe_each(self, rows: list[BatchRow]) -> list[int]:
        """Half-open probe: fallback verifies the whole batch (its result
        is returned — authoritative no matter what the primary says); the
        primary re-verifies the first ``probe_batch_max`` rows and must
        reproduce ground truth exactly to re-close the breaker."""
        import logging

        truth = self.fallback.verify_each(rows)
        n = min(len(rows), self.probe_batch_max)
        if n == 0:
            self.breaker.release_probe()
            return truth
        try:
            probe = self.primary.verify_each(rows[:n])
            agreed = [int(v) for v in probe] == [int(v) for v in truth[:n]]
        except Exception as exc:
            logging.getLogger("cpzk_tpu.protocol.batch").warning(
                "primary verifier probe raised: %s", exc
            )
            agreed = False
        if agreed:
            self.breaker.probe_succeeded()
        else:
            self.breaker.probe_failed()
        self._touch_degraded_gauge()
        return truth


_DEFAULT_BACKEND: VerifierBackend | None = None


def default_backend() -> VerifierBackend:
    """Process-wide default backend (CPU oracle unless overridden)."""
    global _DEFAULT_BACKEND
    if _DEFAULT_BACKEND is None:
        _DEFAULT_BACKEND = CpuBackend()
    return _DEFAULT_BACKEND


def set_default_backend(backend: VerifierBackend | None) -> None:
    global _DEFAULT_BACKEND
    _DEFAULT_BACKEND = backend


class BatchVerifier:
    """Accumulate-and-verify batch API (reference ``BatchVerifier`` twin).

    ``max_size`` defaults to the reference's 1000-entry cap (parity for the
    gRPC per-request surface) but is configurable up to device scale — the
    TPU backend amortizes best at 64k+ rows (SURVEY.md §7.5), where the
    reference's O(n) host loop had no reason to go."""

    def __init__(
        self,
        backend: VerifierBackend | None = None,
        max_size: int = MAX_BATCH_SIZE,
    ):
        if max_size < 1:
            raise InvalidParams("Batch capacity must be positive")
        self.entries: list[BatchEntry] = []
        self.max_size = max_size
        self._backend = backend

    @staticmethod
    def with_capacity(capacity: int, backend: VerifierBackend | None = None) -> "BatchVerifier":
        """Capacity is clamped to MAX_BATCH_SIZE (batch.rs:107-117); Python
        lists need no preallocation, so this is a naming-parity constructor."""
        if capacity < 0:
            raise InvalidParams("Capacity cannot be negative")
        return BatchVerifier(backend)

    def __len__(self) -> int:
        return len(self.entries)

    def is_empty(self) -> bool:
        return not self.entries

    def remaining_capacity(self) -> int:
        return max(0, self.max_size - len(self.entries))

    def clear(self) -> None:
        """Empty the batch for reuse (reference BatchVerifier::clear)."""
        self.entries.clear()

    def add(self, params: Parameters, statement: Statement, proof: Proof) -> None:
        self.add_with_context(params, statement, proof, None)

    def add_with_context(
        self,
        params: Parameters,
        statement: Statement,
        proof: Proof,
        context: bytes | None,
    ) -> None:
        """Validates the statement on add (batch.rs:139-168)."""
        if len(self.entries) >= self.max_size:
            raise InvalidParams(f"Batch size limit exceeded (max {self.max_size})")
        statement.validate()
        self.entries.append(BatchEntry(params, statement, proof, context))

    # --- verification ---

    @property
    def backend(self) -> VerifierBackend:
        """The backend this batch will verify on (explicit or default)."""
        return self._backend or default_backend()

    def prepare_rows(self, rng: SecureRng) -> list[BatchRow]:
        """Derive the backend-facing rows: per-entry Fiat-Shamir challenge
        (batched transcript derivation) plus a fresh random RLC weight
        alpha per row.  Public seam for benchmarks and drivers that time
        or shard the backend stage directly (``verify`` composes this
        with the combined-check/fallback policy)."""
        from ..core.transcript import derive_challenges_batch

        challenges = derive_challenges_batch(
            [e.transcript_context for e in self.entries],
            [Ristretto255.element_to_bytes(e.params.generator_g) for e in self.entries],
            [Ristretto255.element_to_bytes(e.params.generator_h) for e in self.entries],
            [Ristretto255.element_to_bytes(e.statement.y1) for e in self.entries],
            [Ristretto255.element_to_bytes(e.statement.y2) for e in self.entries],
            [Ristretto255.element_to_bytes(e.proof.commitment.r1) for e in self.entries],
            [Ristretto255.element_to_bytes(e.proof.commitment.r2) for e in self.entries],
        )
        # RLC coefficients from one pooled CSPRNG draw: a per-row
        # random_scalar() is a getrandom(2) syscall each, which at device
        # batch sizes costs more host time than the wide reductions
        alphas = Ristretto255.random_scalars(rng, len(self.entries))
        rows = []
        for entry, c, alpha in zip(
            self.entries, challenges, alphas, strict=True
        ):
            rows.append(
                BatchRow(
                    g=entry.params.generator_g,
                    h=entry.params.generator_h,
                    y1=entry.statement.y1,
                    y2=entry.statement.y2,
                    r1=entry.proof.commitment.r1,
                    r2=entry.proof.commitment.r2,
                    s=entry.proof.response.s,
                    c=c,
                    alpha=alpha,
                )
            )
        return rows

    def verify(self, rng: SecureRng, stages=None) -> list[Error | None]:
        """Verify all entries; per-entry ``None`` (ok) or ``Error``.

        Mirrors batch.rs:171-183: empty batch is an error; n == 1 verifies
        individually; otherwise the combined check decides the fast path and
        failure falls back to per-proof results.

        ``stages`` is an optional stage recorder (duck-typed like
        :class:`cpzk_tpu.observability.BatchStages`): host prep is timed
        under ``pad_and_pack``, the backend call(s) under
        ``device_dispatch``, and result assembly under ``unpack`` — the
        latency-breakdown seam the serving layer's traces report through.

        Deferred-parse proofs (see :meth:`Proof.from_bytes_batch`) settle
        their postponed commitment decodes here: backends that report
        decode failures tri-state handle them in the same pass as
        verification; otherwise (and always for n == 1 or the combined
        fast path) they are screened eagerly first, so every path yields
        the exact eager-parse error for an undecodable wire.

        Composes :meth:`prepare_batch` (host phase) with
        :meth:`run_prepared` (device phase) — the two-phase seam the
        serving layer's dispatch lane uses to overlap batch N+1's host
        prep with batch N's device compute.  Calling ``verify`` runs both
        phases back-to-back on the current thread.
        """
        st = stages if stages is not None else _NULL_STAGES
        return self.run_prepared(self.prepare_batch(rng, st), st)

    def prepare_batch(self, rng: SecureRng, stages=None) -> "PreparedBatch":
        """Host phase: deferred-parse screening, Fiat-Shamir challenge
        derivation, RLC coefficient draws, and (n == 1) verifier/transcript
        construction — everything that does not touch the backend.  Timed
        under the ``pad_and_pack`` stage.  The returned
        :class:`PreparedBatch` is consumed by :meth:`run_prepared`, on the
        same thread or another one (the dispatch lane's device thread)."""
        if not self.entries:
            raise InvalidParams("Cannot verify empty batch")
        st = stages if stages is not None else _NULL_STAGES
        n = len(self.entries)
        backend = self.backend
        # one pad_and_pack bracket covers the WHOLE host phase — the
        # generator-equality / deferred scans, screening, and row build —
        # so the flight record's stage sum tiles its wall on every path
        with st.stage("pad_and_pack"):
            same_generators = all(
                e.params.generator_g == self.entries[0].params.generator_g
                and e.params.generator_h == self.entries[0].params.generator_h
                for e in self.entries
            )
            has_deferred = any(e.proof.deferred for e in self.entries)
            if has_deferred and (
                n == 1
                or not same_generators
                or not backend.supports_deferred_decode
                or backend.prefers_combined
            ):
                pre_errors = self._screen_deferred()
                if pre_errors:
                    # keep undecodable wires away from the backend:
                    # prepare the survivors as their own batch (null
                    # recorder — this bracket covers their host phase;
                    # run_prepared brackets their device phase);
                    # run_prepared splices results around the errors
                    sub = BatchVerifier(backend=self._backend,
                                        max_size=max(self.max_size, 1))
                    sub.entries = [e for i, e in enumerate(self.entries)
                                   if i not in pre_errors]
                    sub_prepared = (
                        sub.prepare_batch(rng) if sub.entries else None
                    )
                    return PreparedBatch(
                        n=n, pre_errors=pre_errors, sub=sub,
                        sub_prepared=sub_prepared,
                    )

            if n == 1:
                # single-entry batches keep the same stage decomposition
                # so a trace through a lightly-loaded batcher still
                # breaks down
                entry = self.entries[0]
                transcript = Transcript()
                if entry.transcript_context is not None:
                    transcript.append_context(entry.transcript_context)
                verifier = Verifier(entry.params, entry.statement)
                return PreparedBatch(
                    n=1, entry=entry, verifier=verifier,
                    transcript=transcript,
                )

            rows = self.prepare_rows(rng)
            beta = Ristretto255.random_scalar(rng)
        return PreparedBatch(
            n=n, rows=rows, beta=beta, same_generators=same_generators,
        )

    def run_prepared(
        self, prepared: "PreparedBatch", stages=None
    ) -> list[Error | None]:
        """Device phase: backend dispatch (``device_dispatch`` stage) and
        result assembly (``unpack``) for a :meth:`prepare_batch` output.
        Accept/reject semantics are identical to :meth:`verify` — the
        split changes WHERE the phases run, never what they compute."""
        st = stages if stages is not None else _NULL_STAGES
        backend = self.backend

        if prepared.pre_errors is not None:
            # the sub-batch's device phase records into THIS batch's
            # stage recorder, so the splice path keeps the full
            # decomposition (and the stage-sum≈wall invariant)
            sub_results = (
                prepared.sub.run_prepared(prepared.sub_prepared, st)
                if prepared.sub is not None and prepared.sub_prepared is not None
                else []
            )
            results: list[Error | None] = []
            k = 0
            for i in range(prepared.n):
                if i in prepared.pre_errors:
                    results.append(prepared.pre_errors[i])
                else:
                    results.append(sub_results[k])
                    k += 1
            return results

        if prepared.n == 1:
            entry = prepared.entry
            with st.stage("device_dispatch"):
                try:
                    prepared.verifier.verify_with_transcript(
                        entry.proof, prepared.transcript
                    )
                    result: Error | None = None
                except Error as exc:
                    result = exc
            with st.stage("unpack"):
                return [result]

        rows, beta = prepared.rows, prepared.beta
        with st.stage("device_dispatch"):
            if (
                prepared.same_generators
                and backend.prefers_combined
                and backend.verify_combined(rows, beta)
            ):
                statuses = None
            else:
                # Fallback: per-proof ground truth (batch.rs:314-318)
                statuses = backend.verify_each(rows)
        with st.stage("unpack"):
            if statuses is None:
                return [None] * len(rows)
            results = []
            for ok in statuses:
                if ok == 2:  # deferred commitment wire failed to decode
                    results.append(InvalidProofEncoding(
                        "Bytes do not represent a valid Ristretto point"))
                elif ok:
                    results.append(None)
                else:
                    results.append(InvalidParams("Proof verification failed"))
            return results

    def _screen_deferred(self) -> dict[int, Error]:
        """Settle deferred proofs' postponed point decodes eagerly: one
        native deep parse over just the deferred wires.  Survivors are
        promoted to fully-validated (``deferred`` cleared, elements marked
        canonical); failures map to the exact eager-parse error."""
        idxs = [i for i, e in enumerate(self.entries) if e.proof.deferred]
        out: dict[int, Error] = {}
        if not idxs:
            return out
        from ..core import _native

        packed = b"".join(self.entries[i].proof.to_bytes() for i in idxs)
        flags = _native.parse_proofs(packed)  # deep: includes the decodes
        for j, i in enumerate(idxs):
            proof = self.entries[i].proof
            if flags is not None:
                ok = bool(flags[j])
            else:  # no native core: settle through the Python decoder
                try:
                    _ = proof.commitment.r1.point
                    _ = proof.commitment.r2.point
                    ok = True
                except Error:
                    ok = False
            if ok:
                proof.deferred = False
                proof.commitment.r1._validated = True
                proof.commitment.r2._validated = True
            else:
                out[i] = InvalidProofEncoding(
                    "Bytes do not represent a valid Ristretto point")
        return out
