"""Single-proof verifier — reference ``src/verifier/mod.rs`` twin.

Checks ``g^s == r1 * y1^c`` and ``h^s == r2 * y2^c``; the transcript variant
validates the statement first and mirrors the prover's Fiat-Shamir ordering
(``verifier/mod.rs:120-171``).
"""

from __future__ import annotations

from ..errors import InvalidParams, InvalidProofEncoding
from ..core.ristretto import Ristretto255, Scalar
from ..core.transcript import Transcript
from .gadgets import Parameters, Proof, Statement


class Verifier:
    def __init__(self, params: Parameters, statement: Statement):
        self.params = params
        self.statement = statement

    def verify(self, proof: Proof) -> None:
        """NIZK verification with a fresh transcript (verifier/mod.rs:85-88)."""
        self.verify_with_transcript(proof, Transcript())

    def verify_with_transcript(self, proof: Proof, transcript: Transcript) -> None:
        """Context-bound verification (verifier/mod.rs:120-139). Raises on failure."""
        self.statement.validate()

        transcript.append_parameters(
            Ristretto255.element_to_bytes(self.params.generator_g),
            Ristretto255.element_to_bytes(self.params.generator_h),
        )
        transcript.append_statement(
            Ristretto255.element_to_bytes(self.statement.y1),
            Ristretto255.element_to_bytes(self.statement.y2),
        )
        transcript.append_commitment(
            Ristretto255.element_to_bytes(proof.commitment.r1),
            Ristretto255.element_to_bytes(proof.commitment.r2),
        )

        challenge = transcript.challenge_scalar()
        self.verify_response(challenge, proof)

    def verify_response(self, challenge: Scalar, proof: Proof) -> None:
        """Interactive fourth message check (verifier/mod.rs:144-171).

        Routes through the C++ host core (native/ristretto.cpp,
        ~30x the pure-Python group ops) when the library is available;
        bit-exact parity is enforced by tests/test_native.py.
        """
        g = self.params.generator_g
        h = self.params.generator_h
        y1 = self.statement.y1
        y2 = self.statement.y2
        r1 = proof.commitment.r1
        r2 = proof.commitment.r2
        s = proof.response.s

        from ..core import _native

        eb = Ristretto255.element_to_bytes
        native = _native.verify_rows(
            eb(g), eb(h), eb(y1), eb(y2), eb(r1), eb(r2),
            Ristretto255.scalar_to_bytes(s),
            Ristretto255.scalar_to_bytes(challenge),
            threads=1,
        )
        if native is not None:
            if native[0] == 2:
                # a deferred-parse proof whose commitment wire never
                # decoded: keep eager-parse error parity even at this
                # single-proof entry point
                raise InvalidProofEncoding(
                    "Bytes do not represent a valid Ristretto point")
            if native[0] != 1:
                raise InvalidParams("Proof verification failed")
            return

        lhs1 = Ristretto255.scalar_mul(g, s)
        rhs1 = Ristretto255.element_mul(r1, Ristretto255.scalar_mul(y1, challenge))
        lhs2 = Ristretto255.scalar_mul(h, s)
        rhs2 = Ristretto255.element_mul(r2, Ristretto255.scalar_mul(y2, challenge))

        if not (lhs1 == rhs1 and lhs2 == rhs2):
            raise InvalidParams("Proof verification failed")
