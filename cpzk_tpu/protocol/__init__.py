"""Protocol layer: gadgets, prover, verifier, batch verification.

Reference parity: ``src/primitives/gadgets.rs``, ``src/prover/mod.rs``,
``src/verifier/mod.rs``, ``src/verifier/batch.rs``.
"""
