"""Prover — reference ``src/prover/mod.rs`` twin.

NIZK (Fiat-Shamir) and interactive flows. Transcript append order is
normative and mirrors ``prover/mod.rs:86-110``: [context (caller)] →
parameters → statement → commitment → challenge.
"""

from __future__ import annotations

from ..core.ristretto import Ristretto255, Scalar
from ..core.rng import SecureRng
from ..core.transcript import Transcript
from .gadgets import Commitment, Parameters, Proof, Response, Statement, Witness


class Nonce:
    """Secret commitment nonce k (prover/mod.rs:137-152)."""

    __slots__ = ("_k",)

    def __init__(self, k: Scalar):
        self._k = k

    def k(self) -> Scalar:
        return self._k

    def clear(self) -> None:
        self._k = Scalar(0)

    def __repr__(self) -> str:
        # redaction guard: leaking k leaks the witness (s = k + c*x), so
        # reprs must never emit its encoding (docs/security.md LEAK-001)
        return "Nonce(<secret scalar redacted>)"

    __str__ = __repr__


class Prover:
    """Generates proofs of knowledge of x with y1 = g^x, y2 = h^x."""

    def __init__(self, params: Parameters, witness: Witness, statement: Statement | None = None):
        self.params = params
        self.witness = witness
        self.statement = statement if statement is not None else Statement.from_witness(params, witness)

    def prove(self, rng: SecureRng) -> Proof:
        """NIZK proof with a fresh protocol transcript (prover/mod.rs:78-81)."""
        return self.prove_with_transcript(rng, Transcript())

    def prove_with_transcript(self, rng: SecureRng, transcript: Transcript) -> Proof:
        """NIZK proof over a caller-prepared transcript (prover/mod.rs:86-110)."""
        commitment, nonce = self.commit(rng)

        transcript.append_parameters(
            Ristretto255.element_to_bytes(self.params.generator_g),
            Ristretto255.element_to_bytes(self.params.generator_h),
        )
        transcript.append_statement(
            Ristretto255.element_to_bytes(self.statement.y1),
            Ristretto255.element_to_bytes(self.statement.y2),
        )
        transcript.append_commitment(
            Ristretto255.element_to_bytes(commitment.r1),
            Ristretto255.element_to_bytes(commitment.r2),
        )

        challenge = transcript.challenge_scalar()
        response = self.respond(nonce, challenge)
        nonce.clear()
        return Proof(commitment, response)

    def commit(self, rng: SecureRng) -> tuple[Commitment, Nonce]:
        """Interactive first message: k ← rng, r1 = g^k, r2 = h^k (prover/mod.rs:115-121)."""
        k = Ristretto255.random_scalar(rng)
        # k is secret: constant-time fixed-base path (ADVICE r2)
        r1, r2 = Ristretto255.double_base_mul(
            self.params.generator_g, self.params.generator_h, k
        )
        return Commitment(r1, r2), Nonce(k)

    def respond(self, nonce: Nonce, challenge: Scalar) -> Response:
        """Interactive third message: s = k + c*x (prover/mod.rs:126-131)."""
        cx = Ristretto255.scalar_mul_scalar(challenge, self.witness.secret())
        s = Ristretto255.scalar_add(nonce.k(), cx)
        return Response(s)
