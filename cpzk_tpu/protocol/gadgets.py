"""Protocol gadgets: Parameters, Witness, Statement, Commitment, Response, Proof.

Mirrors the reference ``src/primitives/gadgets.rs`` including the exact
109-byte versioned, length-prefixed proof wire format
(``gadgets.rs:343-361``) and every ``from_bytes`` rejection rule
(``gadgets.rs:364-489``): size caps, truncation, trailing bytes, identity
commitments, zero responses.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..errors import Error, InvalidParams
from ..core import _native
from ..core.ristretto import Element, Ristretto255, Scalar

PROTOCOL_VERSION = 1

# The one wire size a VALID proof can have: ver(1) + 3 × [len u32 + 32-byte
# field].  Other sizes still parse (and fail) through the framing loop so
# the reference's per-field error messages are preserved.
PROOF_WIRE_SIZE = 1 + 3 * (4 + 32)


def frame_fields(version: int, *fields: bytes) -> bytes:
    """The proof wire framing: ``[ver u8]`` then u32-BE length-prefixed
    fields.  Single source of truth for every proof emitter (``Proof``,
    the TPU ``BatchProver``)."""
    out = bytearray([version])
    for field in fields:
        out += len(field).to_bytes(4, "big")
        out += field
    return bytes(out)

MAX_ELEMENT_SIZE = 4096
MAX_SCALAR_SIZE = 512
MIN_PROOF_SIZE = 1 + 4 + 1 + 4 + 1 + 4 + 1


@dataclass(frozen=True)
class Parameters:
    """Public generators (g, h) — gadgets.rs:25-121."""

    generator_g: Element
    generator_h: Element

    @staticmethod
    def new() -> "Parameters":
        return Parameters(Ristretto255.generator_g(), Ristretto255.generator_h())

    @staticmethod
    def with_generators(g: Element, h: Element) -> "Parameters":
        """Custom generators; rejects identity/equal/invalid (gadgets.rs:77-103)."""
        Ristretto255.validate_element(g)
        Ristretto255.validate_element(h)
        if Ristretto255.is_identity(g):
            raise InvalidParams("Generator g cannot be identity")
        if Ristretto255.is_identity(h):
            raise InvalidParams("Generator h cannot be identity")
        if g == h:
            raise InvalidParams("Generators g and h must be different")
        return Parameters(g, h)


class Witness:
    """Secret discrete log x (gadgets.rs:125-164).

    Best-effort zeroization: ``clear()`` wipes the value; Python cannot
    guarantee copies are destroyed (documented trust boundary, see
    docs/security.md).
    """

    __slots__ = ("_x",)

    def __init__(self, x: Scalar):
        self._x = x

    def secret(self) -> Scalar:
        return self._x

    def clear(self) -> None:
        self._x = Scalar(0)

    def __repr__(self) -> str:
        # redaction guard: a Witness in a log line / traceback / debugger
        # must never emit the scalar encoding (docs/security.md LEAK-001)
        return "Witness(<secret scalar redacted>)"

    __str__ = __repr__


@dataclass(frozen=True)
class Statement:
    """Public values y1 = g^x, y2 = h^x (gadgets.rs:168-238)."""

    y1: Element
    y2: Element

    @staticmethod
    def from_witness(params: Parameters, witness: Witness) -> "Statement":
        # x is secret: constant-time fixed-base path (ADVICE r2)
        y1, y2 = Ristretto255.double_base_mul(
            params.generator_g, params.generator_h, witness.secret()
        )
        return Statement(y1, y2)

    def validate(self) -> None:
        Ristretto255.validate_element(self.y1)
        Ristretto255.validate_element(self.y2)


@dataclass(frozen=True)
class Commitment:
    """First prover message r1 = g^k, r2 = h^k (gadgets.rs:244-265)."""

    r1: Element
    r2: Element


class Response:
    """Prover response s = k + c*x (gadgets.rs:270-286)."""

    __slots__ = ("_s",)

    def __init__(self, s: Scalar):
        self._s = s

    @property
    def s(self) -> Scalar:
        return self._s

    def clear(self) -> None:
        self._s = Scalar(0)

    def __repr__(self) -> str:
        # redaction guard: the response scalar is bound to the witness;
        # reprs must never emit its encoding (docs/security.md LEAK-001)
        return "Response(<secret scalar redacted>)"

    __str__ = __repr__


class Proof:
    """Complete NIZK proof: version + commitment + response (gadgets.rs:306-489).

    ``deferred`` marks a proof built by the frame-only fast parse
    (:meth:`from_bytes_batch` with ``defer_point_validation=True``): the
    framing, scalar, and identity rules are already enforced, but the two
    commitment point decodes are postponed to the batch-verify stage,
    which decodes them anyway (one decode per point across ingress+verify
    instead of two).  ``BatchVerifier`` screens or tri-state-maps deferred
    proofs so accept/reject and error messages are identical to eager
    parsing."""

    __slots__ = ("version", "commitment", "response", "deferred")

    def __init__(self, commitment: Commitment, response: Response, version: int = PROTOCOL_VERSION):
        self.version = version
        self.commitment = commitment
        self.response = response
        self.deferred = False

    def to_bytes(self) -> bytes:
        """Wire format: ``[ver u8][len u32_be|r1][len|r2][len|s]`` = 109 bytes."""
        return frame_fields(
            self.version,
            Ristretto255.element_to_bytes(self.commitment.r1),
            Ristretto255.element_to_bytes(self.commitment.r2),
            Ristretto255.scalar_to_bytes(self.response.s),
        )

    @staticmethod
    def _from_validated_wire(data: bytes) -> "Proof":
        """Construct from a PROOF_WIRE_SIZE wire that the native fast-path
        parser already validated end to end (framing, canonical non-identity
        points, canonical nonzero scalar).  Skips re-validation and the
        ``Scalar.__init__`` reduction — the parser guarantees s < l."""
        s = Scalar.__new__(Scalar)
        s.value = int.from_bytes(data[77:109], "little")
        resp = Response.__new__(Response)
        resp._s = s
        return Proof(
            Commitment(Element(wire=data[5:37], validated=True),
                       Element(wire=data[41:73], validated=True)),
            resp,
        )

    @staticmethod
    def _from_framed_wire(data: bytes) -> "Proof":
        """Construct from a frame-checked wire whose POINT decodes are
        deferred to the verify stage (commitment elements stay
        unvalidated; the scalar is already proven canonical)."""
        s = Scalar.__new__(Scalar)
        s.value = int.from_bytes(data[77:109], "little")
        resp = Response.__new__(Response)
        resp._s = s
        p = Proof(
            Commitment(Element(wire=data[5:37]), Element(wire=data[41:73])),
            resp,
        )
        p.deferred = True
        return p

    @staticmethod
    def from_bytes_batch(
        items: "list[bytes]",
        defer_point_validation: bool = False,
        packed: bytes | None = None,
    ) -> "list[Proof | Error]":
        """Parse n proof wires with ONE native validation call for the
        whole batch (``cpzk_parse_proofs`` worker pool) instead of per-item
        decode round-trips — the serving path's ingress cost.  Per-item
        result is a :class:`Proof` or the :class:`~cpzk_tpu.errors.Error`
        that :meth:`from_bytes` raises for it: items the fast path rejects
        (wrong size, bad framing, invalid point/scalar) re-parse on the
        Python slow path so error-message parity with the reference
        (gadgets.rs:364-489) is byte-exact.

        ``defer_point_validation=True`` skips the two commitment point
        decodes here and returns ``deferred`` proofs (see :class:`Proof`);
        only hand those to a :class:`~cpzk_tpu.protocol.batch.BatchVerifier`,
        which settles the postponed decodes with exact error parity.

        ``packed``, when provided, MUST be the concatenation of ``items``
        with every item at the canonical ``PROOF_WIRE_SIZE`` — the native
        wire path's C-gathered staging buffer.  The batched native
        validation then runs over it directly, skipping the per-item
        ``bytes()`` + join this method otherwise pays (zero copies
        between the socket bytes and the parse pass).  Results are
        identical either way; a mismatched length falls back to the
        normal path."""
        n = len(items)
        results: list = [None] * n
        if packed is not None and n and len(packed) == PROOF_WIRE_SIZE * n:
            sized = range(n)
        else:
            packed = None
            sized = [i for i in range(n) if len(items[i]) == PROOF_WIRE_SIZE]
        if sized:
            if packed is None:
                packed = b"".join(bytes(items[i]) for i in sized)
            flags = _native.parse_proofs(packed, deep=not defer_point_validation)
            if flags is not None:
                build = (Proof._from_framed_wire if defer_point_validation
                         else Proof._from_validated_wire)
                for j, i in enumerate(sized):
                    if flags[j]:
                        results[i] = build(bytes(items[i]))
        for i in range(n):
            if results[i] is None:
                # straight to the slow parser: the batched native pass
                # already rejected (or never applies to) this item, so
                # from_bytes' fast path would just repeat that work
                try:
                    results[i] = Proof._from_bytes_slow(items[i])
                except Error as e:
                    results[i] = e
        return results

    @staticmethod
    def from_bytes(data: bytes) -> "Proof":
        """Full adversarial-input validation (gadgets.rs:364-489)."""
        if len(data) == PROOF_WIRE_SIZE:
            # one native call validates everything; a 0 flag falls through
            # to the framing loop for the exact error message
            flags = _native.parse_proofs(bytes(data), threads=1)
            if flags == b"\x01":
                return Proof._from_validated_wire(bytes(data))
        return Proof._from_bytes_slow(data)

    @staticmethod
    def _from_bytes_slow(data: bytes) -> "Proof":
        """The Python reference parser: full per-field validation with the
        reference's exact error messages.  ``from_bytes`` minus the native
        fast path — call directly when the native pass already rejected
        this wire (avoids re-running its two point decodes)."""
        if len(data) < MIN_PROOF_SIZE:
            raise InvalidParams(f"Proof too small: {len(data)} bytes")

        version = data[0]
        if version != PROTOCOL_VERSION:
            raise InvalidParams(f"Unsupported proof version: {version}")

        pos = 1
        fields = []
        for name, cap in (("r1", MAX_ELEMENT_SIZE), ("r2", MAX_ELEMENT_SIZE), ("s", MAX_SCALAR_SIZE)):
            if pos + 4 > len(data):
                raise InvalidParams(f"Truncated proof: missing {name} length")
            flen = int.from_bytes(data[pos : pos + 4], "big")
            pos += 4
            if flen == 0 or flen > cap:
                raise InvalidParams(f"Invalid {name} length: {flen}")
            if pos + flen > len(data):
                raise InvalidParams(f"Truncated proof: incomplete {name} data")
            fields.append(data[pos : pos + flen])
            pos += flen

        if pos != len(data):
            raise InvalidParams(f"Proof has {len(data) - pos} trailing bytes")

        r1 = Ristretto255.element_from_bytes(fields[0])
        r2 = Ristretto255.element_from_bytes(fields[1])
        s = Ristretto255.scalar_from_bytes(fields[2])

        Ristretto255.validate_element(r1)
        Ristretto255.validate_element(r2)

        if Ristretto255.is_identity(r1) or Ristretto255.is_identity(r2):
            raise InvalidParams("Commitment contains identity element")
        if Ristretto255.scalar_is_zero(s):
            raise InvalidParams("Response scalar is zero")

        return Proof(Commitment(r1, r2), Response(s), version)
