"""Bulk offline audit: replay a proof log through the batch engine.

``python -m cpzk_tpu.audit run`` turns the serving plane's proof log
(:mod:`cpzk_tpu.audit.log`) back into TPU-sized work: records stream
through :class:`~cpzk_tpu.protocol.batch.BatchVerifier` via the SAME
dispatch seam the serving path uses
(:meth:`~cpzk_tpu.server.dispatch.DispatchLane.verify_once`) at a full
batch quantum per dispatch — and through the
:mod:`~cpzk_tpu.parallel.mesh`-sharded TPU backend when more than one
device is visible — then emits a Schnorr-signed report
(:mod:`cpzk_tpu.audit.sign`) stating what it found.

Resumability contract (the SIGKILL test pins it exactly):

- After every quantum the pipeline atomically checkpoints a **cursor**
  (byte offset, last sequence number, running totals, running transcript
  digest) via write-to-temp + rename — a crash leaves either the old or
  the new cursor, never a torn one.
- The running digest is a SHA-256 chain folded over every record IN
  ORDER (canonical record JSON + the audit outcome byte), so a resumed
  run recomputes the identical digest — and because report signing is
  deterministic (:func:`cpzk_tpu.audit.sign._nonce`), a run that is
  SIGKILLed at ANY point and resumed produces a byte-exact-identical
  signed report to an uninterrupted run.

Audit semantics per record:

- frame fails CRC/parse/sequence rules -> the scan stops (WAL prefix
  contract); everything before the violation is still audited and the
  report carries the valid byte count.
- record parses but is not a well-formed ``proof`` record (unknown type,
  missing/oversized/non-hex fields, bad statement encoding) ->
  **skipped**, never handed to the backend.
- proof wire malformed -> **rejected** (an invalid proof is a
  verification outcome, exactly as the serving path answers it).
- otherwise the batch engine decides: **verified** or **rejected**; a
  computed verdict that contradicts the recorded one increments
  **mismatched** (the number an auditor actually cares about).
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile

from .. import errors
from ..core.ristretto import Ristretto255
from ..core.rng import SecureRng
from ..protocol.batch import BatchEntry, BatchVerifier
from ..protocol.gadgets import Parameters, Proof, Statement
from .log import scan_records, validate_proof_record
from .sign import load_or_create_key, sign_report

SCHEMA = "cpzk-audit-report/1"
CURSOR_SCHEMA = "cpzk-audit-cursor/1"
DEFAULT_QUANTUM = 4096

#: Audit outcome bytes folded into the digest chain (one per record, in
#: record order) — part of the signed transcript, so a tampered log that
#: still parses but audits differently changes the digest.
OUTCOME_VERIFIED = b"V"
OUTCOME_REJECTED = b"R"
OUTCOME_SKIPPED = b"S"

_ZERO_CHAIN = "0" * 64


def _fold(chain_hex: str, rec: dict, outcome: bytes) -> str:
    h = hashlib.sha256()
    h.update(bytes.fromhex(chain_hex))
    h.update(json.dumps(rec, separators=(",", ":"), sort_keys=True).encode())
    h.update(outcome)
    return h.hexdigest()


class AuditState:
    """Running totals + digest chain — everything the cursor persists.

    Pure fold state: :meth:`note` consumes records in order with their
    audit outcomes; the fuzz harness drives it directly (no crypto) to
    hold the monotonicity/consistency invariants."""

    def __init__(self):
        self.offset = 0
        self.prev_seq: int | None = None
        self.first_seq: int | None = None
        self.records = 0
        self.verified = 0
        self.rejected = 0
        self.mismatched = 0
        self.skipped = 0
        self.chain = _ZERO_CHAIN

    @property
    def audited(self) -> int:
        return self.verified + self.rejected

    def note(self, rec: dict, outcome: bytes, mismatch: bool = False) -> None:
        self.records += 1
        seq = rec.get("seq")
        if isinstance(seq, int) and not isinstance(seq, bool):
            if self.first_seq is None:
                self.first_seq = seq
            self.prev_seq = seq
        if outcome == OUTCOME_VERIFIED:
            self.verified += 1
        elif outcome == OUTCOME_REJECTED:
            self.rejected += 1
        else:
            self.skipped += 1
        if mismatch:
            self.mismatched += 1
        self.chain = _fold(self.chain, rec, outcome)

    # -- cursor (de)serialization -------------------------------------------

    def to_cursor(self, log_path: str) -> dict:
        return {
            "schema": CURSOR_SCHEMA,
            "log_path": os.path.basename(log_path),
            "offset": self.offset,
            "prev_seq": self.prev_seq,
            "first_seq": self.first_seq,
            "records": self.records,
            "verified": self.verified,
            "rejected": self.rejected,
            "mismatched": self.mismatched,
            "skipped": self.skipped,
            "chain": self.chain,
        }

    @classmethod
    def from_cursor(cls, cur: dict, log_path: str) -> "AuditState":
        if cur.get("schema") != CURSOR_SCHEMA:
            raise ValueError(f"unknown cursor schema: {cur.get('schema')!r}")
        if cur.get("log_path") != os.path.basename(log_path):
            raise ValueError(
                f"cursor belongs to {cur.get('log_path')!r}, "
                f"not {os.path.basename(log_path)!r}"
            )
        st = cls()
        st.offset = int(cur["offset"])
        st.prev_seq = cur["prev_seq"]
        st.first_seq = cur["first_seq"]
        st.records = int(cur["records"])
        st.verified = int(cur["verified"])
        st.rejected = int(cur["rejected"])
        st.mismatched = int(cur["mismatched"])
        st.skipped = int(cur["skipped"])
        chain = str(cur["chain"])
        bytes.fromhex(chain)  # ValueError on a tampered cursor
        if len(chain) != 64:
            raise ValueError("cursor chain must be 32 hex bytes")
        st.chain = chain
        return st


def _atomic_write_json(path: str, obj: dict) -> None:
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix="." + os.path.basename(path) + ".", dir=d)
    try:
        payload = json.dumps(obj, separators=(",", ":"), sort_keys=True)
        os.write(fd, payload.encode() + b"\n")
        os.fsync(fd)
        os.close(fd)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.close(fd)
        except OSError:
            pass
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def log_files(log_path: str) -> list[str]:
    """The ordered file list of one logical proof log: the path itself,
    or — when it is a rotated-segment **directory** — every sealed
    ``*.seg`` file in name order (zero-padded names sort in sequence
    order) followed by the active log file(s) they rotated out of.
    Sequence numbers strictly increase across that concatenation, so the
    WAL prefix scan treats it as one log."""
    if not os.path.isdir(log_path):
        return [log_path]
    from .log import _SEG_RE

    names = sorted(os.listdir(log_path))
    segs = [n for n in names if _SEG_RE.search(n)]
    bases: list[str] = []
    for n in segs:
        base = _SEG_RE.sub("", n)
        if base not in bases:
            bases.append(base)
    files = [os.path.join(log_path, n) for n in segs]
    files += [
        os.path.join(log_path, b) for b in sorted(bases)
        if os.path.isfile(os.path.join(log_path, b))
    ]
    if not files:
        raise ValueError(
            f"{log_path} is a directory with no proof-log segments "
            "(*.seg) in it"
        )
    return files


def _read_log_bytes(log_path: str) -> bytes:
    parts = []
    for path in log_files(log_path):
        with open(path, "rb") as f:
            parts.append(f.read())
    return b"".join(parts)


def build_backend(backend_name: str, mesh_devices: int = 0):
    """The audit compute plane: the CPU oracle, or the mesh-sharded TPU
    backend (``mesh_devices`` semantics shared with serving: 0 = all
    visible devices — :func:`cpzk_tpu.parallel.mesh.resolve_mesh_devices`
    decides whether a real mesh is built)."""
    if backend_name == "tpu":
        from ..ops.backend import TpuBackend

        return TpuBackend(mesh_devices=mesh_devices)
    from ..protocol.batch import CpuBackend

    return CpuBackend()


def build_router(backend_name: str, lanes: int, quantum: int):
    """The audit pipeline's multi-lane compute plane — the SAME
    :class:`~cpzk_tpu.server.router.LaneRouter` the serving daemon
    places batches on, attached via its synchronous seam
    (``verify_blocking``): each quantum fans out across every lane, so
    a bulk replay is the first consumer that can saturate all chips.

    ``lanes`` semantics match ``[tpu] lanes``: 1 = no router (the
    single-engine path), -1 = one lane per local device (tpu backend) or
    per host core (cpu backend), k = exactly k lanes.  Returns None when
    one lane resolves — the caller keeps the direct ``verify_once``
    path.  The per-lane prewarm runs here (tpu backend) so the replay's
    first quantum per lane books jit HITs like serving traffic."""
    if lanes == 1:
        return None
    from ..server.router import LaneRouter

    if backend_name == "tpu":
        from ..ops.backend import TpuBackend, prewarm_executables
        from ..parallel import resolve_lane_devices

        devices = resolve_lane_devices(lanes)
        if devices is None:
            return None
        prewarm_executables([quantum], devices=devices)
        return LaneRouter(
            [TpuBackend(device=d) for d in devices], devices=devices,
        )
    from ..protocol.batch import CpuBackend

    n = lanes if lanes > 0 else (os.cpu_count() or 1)
    if n <= 1:
        return None
    # CPU lanes: the native verify releases the GIL, so N lanes = real
    # host-core parallelism through the identical router seam
    return LaneRouter([CpuBackend() for _ in range(n)])


def _record_entry(rec: dict) -> tuple[BatchEntry | None, str | None]:
    """(entry, skip_reason): decode one validated proof record into a
    batch entry, or say why it cannot be audited.  A proof wire that
    parses as *malformed proof* is NOT a skip — the caller maps it to a
    rejected outcome via the entry-less ``(None, None)`` convention plus
    ``rec['_parse_error']``."""
    reason = validate_proof_record(rec)
    if reason is not None:
        return None, reason
    try:
        y1 = Ristretto255.element_from_bytes(bytes.fromhex(rec["y1"]))
        y2 = Ristretto255.element_from_bytes(bytes.fromhex(rec["y2"]))
        statement = Statement(y1, y2)
        statement.validate()
        if Ristretto255.is_identity(y1) or Ristretto255.is_identity(y2):
            return None, "bad-statement"
    except errors.Error:
        return None, "bad-statement"
    return (
        BatchEntry(
            Parameters.new(), statement,
            None,  # type: ignore[arg-type]  # proof attached after bulk parse
            bytes.fromhex(rec["ctx"]),
        ),
        None,
    )


def run_audit(
    log_path: str,
    report_path: str,
    cursor_path: str | None = None,
    key_path: str | None = None,
    quantum: int = DEFAULT_QUANTUM,
    backend: str = "cpu",
    mesh_devices: int = 0,
    lanes: int = 1,
    resume: bool = True,
    max_batches: int | None = None,
    progress=None,
) -> dict | None:
    """Replay ``log_path`` through the batch engine and write a signed
    report to ``report_path``.  Returns the report dict, or ``None`` when
    ``max_batches`` stopped the run early (checkpoint saved — rerun with
    ``resume=True`` to continue; the test harness uses this to model a
    SIGKILL between checkpoints).

    ``cursor_path`` defaults to ``<report_path>.cursor``; ``key_path``
    defaults to ``<report_path>.key`` (minted 0600 when absent).

    ``log_path`` may be a **rotated-segment directory** (a log written
    with ``[audit] segment_bytes`` — or a standby's shipped copy): the
    sealed ``*.seg`` files plus the active tail replay as one logical
    log, cursor offsets indexing into their concatenation (stable:
    sealing only renames bytes in place within the order).

    ``lanes != 1`` replays through the serving plane's
    :class:`~cpzk_tpu.server.router.LaneRouter` — each quantum fans out
    across every per-device lane concurrently.  Outcomes fold into the
    digest chain in record order regardless of which lane computed them,
    so the signed report is byte-identical to a single-lane run
    (test-pinned).
    """
    if quantum < 1:
        raise ValueError("audit quantum must be positive")
    cursor_path = cursor_path or report_path + ".cursor"
    key_path = key_path or report_path + ".key"
    state = AuditState()
    if resume and os.path.exists(cursor_path):
        with open(cursor_path, encoding="utf-8") as f:
            state = AuditState.from_cursor(json.load(f), log_path)

    buf = _read_log_bytes(log_path)
    if state.offset > len(buf):
        raise ValueError(
            f"cursor offset {state.offset} is beyond the log "
            f"({len(buf)} bytes) — wrong log file?"
        )

    router = build_router(backend, lanes, quantum)
    engine = None if router is not None else build_backend(
        backend, mesh_devices=mesh_devices
    )
    rng = SecureRng()
    # ONE scan of the remaining suffix (the parse cost is linear in what
    # is left, not quadratic in batch count); quanta then slice the
    # parsed records, with the cursor offset advanced frame-wise
    records, valid = scan_records(
        buf, offset=state.offset, prev_seq=state.prev_seq
    )
    batches = 0
    idx = 0
    if router is not None:
        router.start_in_thread()
    try:
        while idx < len(records):
            batch = records[idx: idx + quantum]
            idx += len(batch)
            _audit_batch(batch, state, engine, rng, router=router)
            state.offset = _advance(buf, state.offset, len(batch))
            batches += 1
            _atomic_write_json(cursor_path, state.to_cursor(log_path))
            if progress is not None:
                progress(state)
            if (
                max_batches is not None and batches >= max_batches
                and idx < len(records)
            ):
                return None
    finally:
        if router is not None:
            router.stop_thread()
    state.offset = max(state.offset, valid)

    report = _build_report(
        log_path, state, valid_bytes=state.offset,
        file_bytes=len(buf), backend=backend, quantum=quantum,
    )
    sign_report(report, load_or_create_key(key_path))
    _atomic_write_json(report_path, report)
    # the run is complete: the cursor has served its purpose (keeping it
    # would make a LATER run against an appended-to log resume silently)
    try:
        os.unlink(cursor_path)
    except OSError:
        pass
    return report


def _advance(buf: bytes, offset: int, n_frames: int) -> int:
    """Byte offset after ``n_frames`` well-formed frames from ``offset``
    (frame sizes only — the frames were already validated this scan)."""
    from ..durability.wal import _HEADER, HEADER_BYTES

    off = offset
    for _ in range(n_frames):
        length, _crc = _HEADER.unpack_from(buf, off)
        off += HEADER_BYTES + length
    return off


def _audit_batch(
    records: list[dict], state: AuditState, engine, rng, router=None
) -> None:
    """Verify one quantum of records through the serving dispatch seam —
    the direct ``verify_once`` engine, or the lane router's synchronous
    fan-out (``verify_blocking``) — and fold the outcomes into ``state``
    IN RECORD ORDER (lane placement never reorders the fold)."""
    from ..server.dispatch import DispatchLane

    entries: list[BatchEntry] = []
    plan: list[tuple[dict, str | None, bool]] = []  # (rec, skip, parse_fail)
    wires: list[bytes] = []
    for rec in records:
        entry, skip = _record_entry(rec)
        if skip is not None:
            plan.append((rec, skip, False))
            continue
        wires.append(bytes.fromhex(rec["p"]))
        entries.append(entry)
        plan.append((rec, None, False))
    # bulk proof parse (deferred point decodes settle inside the batch
    # engine with exact eager-parse semantics, like the serving path)
    parsed = Proof.from_bytes_batch(wires, defer_point_validation=True)
    live: list[BatchEntry] = []
    k = 0
    for i, (rec, skip, _) in enumerate(plan):
        if skip is not None:
            continue
        proof = parsed[k]
        entry = entries[k]
        k += 1
        if isinstance(proof, errors.Error):
            plan[i] = (rec, None, True)  # malformed proof -> rejected
            continue
        entry.proof = proof
        live.append(entry)
    if not live:
        results = []
    elif router is not None:
        results = router.verify_blocking(live)
    else:
        results = DispatchLane.verify_once(engine, rng, live)
    it = iter(results)
    for rec, skip, parse_fail in plan:
        if skip is not None:
            state.note(rec, OUTCOME_SKIPPED)
            continue
        if parse_fail:
            computed = False
        else:
            computed = next(it) is None
        outcome = OUTCOME_VERIFIED if computed else OUTCOME_REJECTED
        mismatch = bool(rec.get("v", 0)) != computed
        state.note(rec, outcome, mismatch=mismatch)


def _build_report(
    log_path: str,
    state: AuditState,
    valid_bytes: int,
    file_bytes: int,
    backend: str,
    quantum: int,
) -> dict:
    """The deterministic (pre-signature) report body: no wall-clock
    timestamps, no absolute paths — two runs over the same log bytes
    produce the same bytes here, which is what makes SIGKILL-resume
    equivalence byte-exact."""
    return {
        "schema": SCHEMA,
        "log": {
            "name": os.path.basename(log_path),
            "valid_bytes": valid_bytes,
            "file_bytes": file_bytes,
            "first_seq": state.first_seq,
            "last_seq": state.prev_seq,
        },
        "engine": {"backend": backend, "quantum": quantum},
        "totals": {
            "records": state.records,
            "audited": state.audited,
            "verified": state.verified,
            "rejected": state.rejected,
            "mismatched": state.mismatched,
            "skipped": state.skipped,
        },
        "digest": state.chain,
    }


def verify_report_file(report_path: str) -> tuple[bool, str, dict | None]:
    """Offline ``--verify-report``: ``(ok, reason, report)``.  Total over
    arbitrary files — a tampered report answers False, never raises."""
    from .sign import verify_report

    try:
        with open(report_path, encoding="utf-8") as f:
            report = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"unreadable report: {e}", None
    if not isinstance(report, dict):
        return False, "report is not a JSON object", None
    if report.get("schema") != SCHEMA:
        return False, f"unknown report schema: {report.get('schema')!r}", report
    ok, reason = verify_report(report)
    return ok, reason, report
