"""Proof log: an append-only, CRC-framed record of verification outcomes.

The serving plane's audit trail (ROADMAP item 5): when ``[audit]`` is
enabled, the service appends one record per verified proof — the
statement halves, the challenge context, the proof wire, and the verdict
the serving path returned — using the SAME framing discipline as the
durability write-ahead log (:mod:`cpzk_tpu.durability.wal`): length +
CRC32 header, compact key-sorted JSON payload with a strictly increasing
``seq``, torn tails and mid-log corruption indistinguishable and never
surfaced as records.  The bulk audit pipeline
(:mod:`cpzk_tpu.audit.pipeline`) later replays the log through the batch
engine at full device quantum and signs what it found.

Record schema (type ``"proof"``)::

    {"seq": n, "type": "proof", "u": user_id, "y1": hex, "y2": hex,
     "ctx": hex-challenge-id, "p": hex-proof-wire, "v": 0|1, "t": unix}

Unknown record types parse cleanly and are skipped by the replayer (a
durability WAL therefore *parses* as a proof log — its records simply
audit to zero proofs), so the two log families can share tooling.

The writer mirrors :class:`~cpzk_tpu.durability.wal.WriteAheadLog`'s
threading contract — sync, cheap ``append_proofs`` (one ``os.write`` into
the page cache, callable from the event loop), fsync policy applied in
``sync()`` off-thread — but keeps its own metrics namespace
(``audit.log.*``) and has no compaction: an audit trail is append-only
by design; rotate by pointing ``[audit] log_path`` somewhere new.
"""

from __future__ import annotations

import os
import re
import threading
import time

from ..durability.wal import (
    MAX_FRAME_PAYLOAD,
    WAL_FORMAT_VERSION,
    NewerFormatError,
    check_record_format,
    encode_record,
    iter_frames,
)
from ..server import metrics

__all__ = [
    "MAX_FRAME_PAYLOAD",
    "ProofLogWriter",
    "proof_record",
    "read_log",
    "scan_records",
    "sealed_segments",
    "segment_name",
    "validate_proof_record",
]

#: Field caps mirroring the service-side wire limits (service.py): a log
#: written by the service can never violate these, so a record that does
#: is tampered and is skipped by the replayer, never verified.
MAX_CTX_HEX = 64 * 2
MAX_PROOF_HEX = 8192 * 2
MAX_ELEMENT_HEX = 32 * 2
MAX_USER_ID = 256


def proof_record(
    user_id: str,
    y1: bytes,
    y2: bytes,
    context: bytes,
    proof_wire: bytes,
    verdict: bool,
    now: int | None = None,
) -> dict:
    """One proof-log payload (everything but ``seq``, which the writer
    assigns under its lock)."""
    return {
        "u": user_id,
        "y1": y1.hex(),
        "y2": y2.hex(),
        "ctx": context.hex(),
        "p": proof_wire.hex(),
        "v": 1 if verdict else 0,
        "t": int(time.time()) if now is None else int(now),
    }


def validate_proof_record(rec: dict) -> str | None:
    """``None`` when ``rec`` is a well-formed ``proof`` record the
    replayer may verify; else a short reason string.  Total over
    arbitrary parsed JSON (the fuzz invariant) — never raises."""
    try:
        if rec.get("type") != "proof":
            return "not-a-proof-record"
        u = rec.get("u")
        if not isinstance(u, str) or len(u) > MAX_USER_ID:
            return "bad-user"
        for key, cap in (("y1", MAX_ELEMENT_HEX), ("y2", MAX_ELEMENT_HEX),
                         ("ctx", MAX_CTX_HEX), ("p", MAX_PROOF_HEX)):
            value = rec.get(key)
            if not isinstance(value, str) or not value or len(value) > cap:
                return f"bad-{key}"
            if len(value) % 2:
                return f"bad-{key}"
            try:
                bytes.fromhex(value)
            except ValueError:
                return f"bad-{key}"
        v = rec.get("v")
        if v not in (0, 1) or isinstance(v, bool):
            return "bad-verdict"
        return None
    except Exception:  # pragma: no cover - dict subclass shenanigans
        return "bad-record"


def scan_records(
    buf: bytes, offset: int = 0, prev_seq: int | None = None
) -> tuple[list[dict], int]:
    """``(records, valid_bytes)`` from ``offset`` in a proof-log buffer —
    the WAL prefix contract (:func:`cpzk_tpu.durability.wal.iter_frames`)
    with resumable offset/seq, shared by the pipeline and the fuzz
    harness."""
    return iter_frames(buf, offset=offset, prev_seq=prev_seq)


def read_log(path: str) -> tuple[list[dict], int, int]:
    """``(records, valid_bytes, file_bytes)`` for the log at ``path``."""
    with open(path, "rb") as f:
        raw = f.read()
    records, valid = scan_records(raw)
    return records, valid, len(raw)


#: Sealed-segment name template: zero-padded first/last sequence numbers
#: so lexicographic order equals sequence order.
_SEG_WIDTH = 12
_SEG_SUFFIX = ".seg"
_SEG_RE = re.compile(r"\.(\d{12})-(\d{12})\.seg$")


def segment_name(path: str, first_seq: int, last_seq: int) -> str:
    return (
        f"{path}.{first_seq:0{_SEG_WIDTH}d}-{last_seq:0{_SEG_WIDTH}d}"
        f"{_SEG_SUFFIX}"
    )


def _segment_seq_range(seg_path: str) -> tuple[int, int]:
    m = _SEG_RE.search(seg_path)
    if m is None:
        raise ValueError(f"not a sealed proof-log segment name: {seg_path!r}")
    return int(m.group(1)), int(m.group(2))


def sealed_segments(path: str) -> list[str]:
    """Sealed-segment files rotated out of the log at ``path``, sequence
    order (their zero-padded names sort that way)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path)
    try:
        names = os.listdir(d)
    except OSError:
        return []
    out = [
        os.path.join(d, n)
        for n in names
        if n.startswith(base + ".") and _SEG_RE.search(n)
    ]
    out.sort()
    return out


class ProofLogWriter:
    """Append-only framed proof log with a configurable fsync policy.

    ``append_proofs`` is synchronous and cheap (one ``os.write`` for the
    whole batch of frames) so the service can call it on the event loop
    right after a batch of verdicts settles; the fsync — when the policy
    wants one — happens in :meth:`sync` on a worker thread.  Created
    0600: the log carries statements and challenge ids (public-ish), but
    an audit trail's integrity expectations match the WAL's.

    **Rotation** (``segment_bytes > 0``): once the active file reaches
    the threshold it is force-synced and atomically renamed to
    ``<path>.<first_seq>-<last_seq>.seg`` (zero-padded, so lexicographic
    order IS sequence order) and a fresh active file opens.  Sealed
    segments are immutable; the replication plane ships them to the warm
    standby (``SegmentShipper``), so a machine death loses at most the
    unsealed active tail — the proof log survives hardware the way the
    WAL does.  ``python -m cpzk_tpu.audit run`` accepts the directory of
    rotated segments directly.
    """

    def __init__(
        self,
        path: str,
        fsync: str = "off",
        fsync_interval_ms: float = 200.0,
        segment_bytes: int = 0,
    ):
        if fsync not in ("always", "interval", "off"):
            raise ValueError(f"unknown proof-log fsync policy: {fsync!r}")
        if segment_bytes < 0:
            raise ValueError("segment_bytes cannot be negative")
        self.path = path
        self.policy = fsync
        self.interval_s = fsync_interval_ms / 1000.0
        self.segment_bytes = segment_bytes
        self._lock = threading.Lock()
        self._fd: int | None = os.open(
            path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o600
        )
        os.chmod(path, 0o600)
        self.size = os.fstat(self._fd).st_size
        # resume numbering past an existing log so an appended-to log
        # still satisfies the strictly-increasing-seq prefix contract.
        # With rotation, sealed segments hold the earlier history — the
        # active file resumes past the LAST sealed segment too.
        self.seq = 0
        for seg in self.sealed_segments():
            _first, last = _segment_seq_range(seg)
            self.seq = max(self.seq, last)
        self.file_first_seq = self.seq + 1
        if self.size:
            try:
                records, _, _ = read_log(path)
                # format gate (same contract as WAL recovery): refuse to
                # append after records stamped newer than this build
                # writes — naming both versions and the file
                for rec in records:
                    try:
                        check_record_format(rec)
                    except NewerFormatError as e:
                        raise NewerFormatError(
                            f"proof log {path}: {e}"
                        ) from None
                if records:
                    self.file_first_seq = int(records[0]["seq"])
                    self.seq = max(self.seq, int(records[-1]["seq"]))
            except OSError:  # pragma: no cover - racing rotation
                pass
        self.records = 0
        self.rotations = 0
        self._pending = 0
        self._last_fsync = time.monotonic()

    # -- append / sync -------------------------------------------------------

    def append_proofs(self, payloads: list[dict]) -> int:
        """Frame and write a batch of proof records in ONE ``os.write``;
        returns the last assigned sequence number.  Records land in the
        OS page cache; call :meth:`sync` (off-thread) afterwards when the
        policy wants durability."""
        if not payloads:
            return self.seq
        with self._lock:
            if self._fd is None:
                raise OSError("proof log is closed")
            frames = bytearray()
            for payload in payloads:
                self.seq += 1
                rec = dict(payload)
                # assigned AFTER the payload merge: a replayed record (or
                # hostile payload) carrying its own seq/type/fmt must
                # never override the writer's numbering or format stamp
                rec["seq"] = self.seq
                rec["type"] = "proof"
                rec["fmt"] = WAL_FORMAT_VERSION
                frames += encode_record(rec)
            os.write(self._fd, frames)
            self.size += len(frames)
            self.records += len(payloads)
            self._pending += len(payloads)
            metrics.counter("audit.log.appends").inc(len(payloads))
            metrics.counter("audit.log.bytes").inc(len(frames))
            if self.segment_bytes and self.size >= self.segment_bytes:
                self._rotate_locked()
            return self.seq

    def _rotate_locked(self) -> None:
        """Seal the active file (fsync + atomic rename to
        ``<path>.<first>-<last>.seg``) and open a fresh one.  Caller
        holds ``_lock``.  Zero-padded seq range in the name keeps
        lexicographic order equal to sequence order — the shipper and
        the audit pipeline both lean on that."""
        assert self._fd is not None
        os.fsync(self._fd)  # a sealed segment is durable by definition
        os.close(self._fd)
        self._fd = None
        sealed = segment_name(self.path, self.file_first_seq, self.seq)
        os.replace(self.path, sealed)
        self._fd = os.open(
            self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o600
        )
        os.chmod(self.path, 0o600)
        self.size = 0
        self._pending = 0
        self._last_fsync = time.monotonic()
        self.file_first_seq = self.seq + 1
        self.rotations += 1
        metrics.counter("audit.log.rotations").inc()

    def sealed_segments(self) -> list[str]:
        """Sealed-segment paths for this log, sequence order (the
        shipper's work list; survives restarts — it is a directory
        scan, not in-memory state)."""
        return sealed_segments(self.path)

    def needs_sync(self) -> bool:
        """Whether :meth:`sync` would fsync right now under the policy —
        lets the async caller skip the worker-thread hop entirely."""
        if self._pending == 0 or self.policy == "off":
            return False
        if self.policy == "always":
            return True
        return time.monotonic() - self._last_fsync >= self.interval_s

    def sync(self, force: bool = False) -> bool:
        """Fsync pending appends per the policy (``force`` overrides);
        returns whether an fsync happened."""
        with self._lock:
            if self._fd is None or self._pending == 0:
                return False
            if not force:
                if self.policy == "off":
                    return False
                if (
                    self.policy == "interval"
                    and time.monotonic() - self._last_fsync < self.interval_s
                ):
                    return False
            os.fsync(self._fd)
            self._pending = 0
            self._last_fsync = time.monotonic()
            metrics.counter("audit.log.fsyncs").inc()
            return True

    @property
    def pending(self) -> int:
        return self._pending

    def status(self) -> dict:
        """Operator view behind the REPL ``/audit``."""
        with self._lock:
            return {
                "path": self.path,
                "bytes": self.size,
                "seq": self.seq,
                "records_this_boot": self.records,
                "pending_appends": self._pending,
                "fsync_policy": self.policy,
                "segment_bytes": self.segment_bytes,
                "rotations_this_boot": self.rotations,
                "sealed_segments": len(self.sealed_segments()),
            }

    def close(self) -> None:
        """Force-sync pending appends and release the fd (idempotent)."""
        self.sync(force=True)
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None
