"""Proof-log subsystem: streaming-adjacent audit surface for the batch
engine (ROADMAP item 5).

- :mod:`.log` — the append-only, CRC-framed proof log the service writes
  behind ``[audit]`` (WAL framing discipline, own metrics namespace);
- :mod:`.pipeline` — the bulk replay pipeline (``python -m
  cpzk_tpu.audit run``): proof log -> batch engine at full device
  quantum, resumable cursor, deterministic digest chain;
- :mod:`.sign` — Schnorr-signed (ristretto255 + Merlin) audit reports
  with a fully offline ``verify-report`` mode.
"""

from .log import ProofLogWriter, proof_record, read_log, scan_records
from .pipeline import AuditState, run_audit, verify_report_file
from .sign import load_or_create_key, sign_report, verify_report

__all__ = [
    "AuditState",
    "ProofLogWriter",
    "load_or_create_key",
    "proof_record",
    "read_log",
    "run_audit",
    "scan_records",
    "sign_report",
    "verify_report",
    "verify_report_file",
]
