"""Bulk audit CLI: generate / replay / verify proof logs offline.

Subcommands::

    python -m cpzk_tpu.audit generate --n 100000 --out proofs.log
    python -m cpzk_tpu.audit run --log proofs.log --report report.json
    python -m cpzk_tpu.audit verify-report --report report.json

``run`` checkpoints a resumable cursor next to the report after every
batch quantum: SIGKILL it at any point, rerun the same command, and the
final signed report is byte-identical to an uninterrupted run (the CI
``audit-smoke`` job does exactly that).  ``verify-report`` needs ONLY the
report file — the Schnorr signature and totals-consistency checks run
fully offline.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def cmd_generate(args) -> int:
    """A synthetic proof log: ``--n`` records over ``--users`` synthetic
    statements, ``--reject-frac`` of them with corrupted proofs (logged
    verdict 0, audit agrees) and ``--mismatch-frac`` with a LYING logged
    verdict (what a tampered or buggy serving plane would leave behind —
    the audit's whole reason to exist)."""
    from .. import Parameters, Prover, SecureRng, Transcript, Witness
    from ..core.ristretto import Ristretto255
    from .log import ProofLogWriter, proof_record

    rng = SecureRng()
    params = Parameters.new()
    eb = Ristretto255.element_to_bytes
    provers = [
        Prover(params, Witness(Ristretto255.random_scalar(rng)))
        for _ in range(max(1, args.users))
    ]
    writer = ProofLogWriter(args.out, fsync="off")
    t0 = time.monotonic()
    pending: list[dict] = []
    n_reject = n_mismatch = 0
    for i in range(args.n):
        prover = provers[i % len(provers)]
        ctx = rng.fill_bytes(32)
        t = Transcript()
        t.append_context(ctx)
        wire = prover.prove_with_transcript(rng, t).to_bytes()
        verdict = True
        if args.reject_frac > 0 and (i % max(1, int(1 / args.reject_frac))) == 1:
            # corrupt the response scalar: parses fine, verifies False
            wire = wire[:-1] + bytes([wire[-1] ^ 1])
            verdict = False
            n_reject += 1
        if args.mismatch_frac > 0 and (
            i % max(1, int(1 / args.mismatch_frac))
        ) == 2:
            verdict = not verdict  # the log lies; the audit must notice
            n_mismatch += 1
        pending.append(proof_record(
            f"u{i % len(provers)}",
            eb(prover.statement.y1), eb(prover.statement.y2),
            ctx, wire, verdict,
        ))
        if len(pending) >= 1024:
            writer.append_proofs(pending)
            pending.clear()
    writer.append_proofs(pending)
    writer.close()
    dt = time.monotonic() - t0
    print(json.dumps({
        "generated": args.n, "path": args.out, "bytes": writer.size,
        "rejects": n_reject, "mismatches": n_mismatch,
        "seconds": round(dt, 2),
        "records_per_s": round(args.n / dt, 1) if dt > 0 else None,
    }))
    return 0


def cmd_run(args) -> int:
    from .pipeline import run_audit

    t0 = time.monotonic()

    # optional ops plane: a long bulk replay is a fleet workload too —
    # expose /metrics (audit.* counters incl. the proof-log families),
    # /healthz, and the ring dumps on a daemon-thread HTTP server while
    # the synchronous pipeline runs
    ops_plane = None
    if args.opsplane_port is not None:
        from ..observability.opsplane import OpsPlane, OpsSources

        ops_plane = OpsPlane(
            OpsSources(role="audit"),
            host=args.opsplane_host, port=args.opsplane_port,
        )
        bound = ops_plane.start_in_thread()
        print(
            f"# ops plane on http://{args.opsplane_host}:{bound} "
            "(/metrics /healthz /statusz)",
            file=sys.stderr, flush=True,
        )

    def progress(state) -> None:
        if not args.quiet:
            dt = time.monotonic() - t0
            rate = state.records / dt if dt > 0 else 0.0
            print(
                f"# audited {state.audited} (+{state.skipped} skipped, "
                f"{state.mismatched} mismatched) @ {rate:,.0f} rec/s",
                file=sys.stderr, flush=True,
            )

    try:
        report = run_audit(
            args.log, args.report,
            cursor_path=args.cursor,
            key_path=args.key,
            quantum=args.quantum,
            backend=args.backend,
            mesh_devices=args.mesh_devices,
            lanes=args.lanes,
            resume=not args.fresh,
            max_batches=args.max_batches,
            progress=progress,
        )
    finally:
        if ops_plane is not None:
            ops_plane.stop_thread()
    if report is None:
        print(json.dumps({"status": "checkpointed", "report": None}))
        return 0
    out = {"status": "complete", "report_path": args.report,
           "totals": report["totals"], "digest": report["digest"]}
    print(json.dumps(out))
    # a mismatch means the log's recorded verdicts and the re-verification
    # disagree — the audit FOUND something; exit nonzero so operators and
    # CI cannot miss it
    return 3 if report["totals"]["mismatched"] else 0


def cmd_verify_report(args) -> int:
    from .pipeline import verify_report_file

    ok, reason, report = verify_report_file(args.report)
    print(json.dumps({
        "ok": ok, "reason": reason,
        "totals": (report or {}).get("totals"),
        "digest": (report or {}).get("digest"),
    }))
    return 0 if ok else 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m cpzk_tpu.audit",
        description="bulk offline proof-log audit pipeline",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("generate", help="write a synthetic proof log")
    g.add_argument("--n", type=int, required=True)
    g.add_argument("--out", required=True)
    g.add_argument("--users", type=int, default=16)
    g.add_argument("--reject-frac", type=float, default=0.0)
    g.add_argument("--mismatch-frac", type=float, default=0.0)
    g.set_defaults(fn=cmd_generate)

    r = sub.add_parser("run", help="replay a proof log, write a signed report")
    r.add_argument("--log", required=True,
                   help="the proof log file, or a rotated-segment "
                        "directory (sealed *.seg files + active tail "
                        "replay as one log)")
    r.add_argument("--report", required=True)
    r.add_argument("--cursor", default=None,
                   help="checkpoint path (default <report>.cursor)")
    r.add_argument("--key", default=None,
                   help="signing-key path (default <report>.key; minted "
                        "0600 when absent)")
    r.add_argument("--quantum", type=int, default=4096,
                   help="records per device batch (the serving batch "
                        "quantum; mesh-sharded when >1 device)")
    r.add_argument("--backend", choices=("cpu", "tpu"), default="cpu")
    r.add_argument("--mesh-devices", type=int, default=0,
                   help="0 = all visible devices (tpu backend)")
    r.add_argument("--lanes", type=int, default=1,
                   help="replay through the serving LaneRouter: -1 = one "
                        "dispatch lane per local device (tpu) or host "
                        "core (cpu), k = exactly k lanes, 1 = direct "
                        "single-engine replay (each quantum fans out "
                        "across the lanes; the signed report is "
                        "byte-identical either way)")
    r.add_argument("--fresh", action="store_true",
                   help="ignore an existing cursor and restart from byte 0")
    r.add_argument("--max-batches", type=int, default=None,
                   help="stop (checkpointed) after this many quanta — "
                        "test hook modelling a crash between checkpoints")
    r.add_argument("--quiet", action="store_true")
    r.add_argument("--opsplane-port", type=int, default=None,
                   help="serve the HTTP ops plane (/metrics /healthz "
                        "/statusz) on this port while the replay runs "
                        "(0 = OS-assigned)")
    r.add_argument("--opsplane-host", default="127.0.0.1")
    r.set_defaults(fn=cmd_run)

    v = sub.add_parser("verify-report", help="offline signed-report check")
    v.add_argument("--report", required=True)
    v.set_defaults(fn=cmd_verify_report)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "run" and args.quantum < 1:
        print("audit quantum must be positive", file=sys.stderr)
        return 2
    try:
        return args.fn(args)
    except (OSError, ValueError) as e:
        print(f"audit: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
