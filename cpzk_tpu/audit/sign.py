"""Schnorr-signed audit reports over the existing Ristretto + Merlin core.

The audit pipeline's output must be tamper-evident offline: a report
consumer who never saw the proof log can check that (a) the report body
was not altered after signing and (b) it was signed by the holder of the
audit key.  Standard Schnorr over ristretto255 with a Merlin transcript
as the Fiat-Shamir hash — entirely built from the primitives the proof
system already ships (:class:`~cpzk_tpu.core.ristretto.Ristretto255`,
:class:`~cpzk_tpu.core.transcript.MerlinTranscript`), no new crypto
dependencies:

    sign(x, m):  k = H_nonce(x, m)        (deterministic, RFC6979-style)
                 R = k*G
                 c = H_sig(m, P, R)       (Merlin transcript challenge)
                 s = k + c*x  (mod l)
                 signature = (R, s)
    verify:      s*G == R + c*P

The deterministic nonce makes signing a pure function of (key, message):
an audit run that resumes after SIGKILL reproduces the byte-exact report,
signature included — the resume-equivalence property the pipeline tests
pin.  ``message`` here is the report's transcript digest (the running
SHA-256 chain over every audited frame), so flipping a single byte of the
log or the report body changes ``m`` and the signature check fails.
"""

from __future__ import annotations

import hashlib
import json
import os

from ..core.ristretto import Element, Ristretto255, Scalar
from ..core.scalars import L, sc_from_bytes_mod_order_wide
from ..core.transcript import MerlinTranscript
from ..errors import Error

SIGN_DOMAIN = b"cpzk-audit-report/1"
NONCE_DOMAIN = b"cpzk-audit-nonce/1"


def generate_key(rng=None) -> Scalar:
    """A fresh audit signing scalar (CSPRNG unless ``rng`` is injected)."""
    if rng is None:
        from ..core.rng import SecureRng

        rng = SecureRng()
    return Ristretto255.random_scalar(rng)


def public_key(key: Scalar) -> bytes:
    """Wire encoding of ``key * G``."""
    return Ristretto255.element_to_bytes(
        Ristretto255.scalar_mul(Ristretto255.generator_g(), key)
    )


def load_or_create_key(path: str) -> Scalar:
    """The 64-hex signing scalar at ``path``, minted (0600) when absent."""
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            text = f.read().strip()
        try:
            raw = bytes.fromhex(text)
        except ValueError:
            raise ValueError(f"audit key file {path} is not hex") from None
        if len(raw) != 32:
            raise ValueError(
                f"audit key file {path} must hold 32 hex-encoded bytes"
            )
        return Ristretto255.scalar_from_bytes(raw)
    key = generate_key()
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
    try:
        os.write(fd, Ristretto255.scalar_to_bytes(key).hex().encode())
    finally:
        os.close(fd)
    return key


def _challenge(message: bytes, pub: bytes, r_bytes: bytes) -> Scalar:
    t = MerlinTranscript(SIGN_DOMAIN)
    t.append_message(b"message", message)
    t.append_message(b"pubkey", pub)
    t.append_message(b"nonce-commitment", r_bytes)
    return Scalar(
        sc_from_bytes_mod_order_wide(t.challenge_bytes(b"challenge", 64))
    )


def _nonce(key: Scalar, message: bytes) -> Scalar:
    """Deterministic per-(key, message) nonce: never reused across
    messages, never random (resume-equivalence needs sign() pure)."""
    t = MerlinTranscript(NONCE_DOMAIN)
    t.append_message(b"key", Ristretto255.scalar_to_bytes(key))
    t.append_message(b"message", message)
    k = Scalar(
        sc_from_bytes_mod_order_wide(t.challenge_bytes(b"nonce", 64))
    )
    if k.value == 0:  # pragma: no cover - probability 1/l
        k = Scalar(1)
    return k


def sign(key: Scalar, message: bytes) -> tuple[bytes, bytes]:
    """``(R_bytes, s_bytes)`` Schnorr signature on ``message``."""
    k = _nonce(key, message)
    r_bytes = Ristretto255.element_to_bytes(
        Ristretto255.scalar_mul(Ristretto255.generator_g(), k)
    )
    c = _challenge(message, public_key(key), r_bytes)
    s = Scalar((k.value + c.value * key.value) % L)
    return r_bytes, Ristretto255.scalar_to_bytes(s)


def verify(pub: bytes, message: bytes, r_bytes: bytes, s_bytes: bytes) -> bool:
    """Offline signature check; False on any malformed input (total —
    the verify-report CLI must answer, not crash, on a tampered file)."""
    try:
        p = Ristretto255.element_from_bytes(pub)
        r = Ristretto255.element_from_bytes(r_bytes)
        if len(s_bytes) != 32:
            return False
        s = Ristretto255.scalar_from_bytes(s_bytes)
    except (Error, ValueError, TypeError):
        return False
    c = _challenge(message, pub, r_bytes)
    lhs = Ristretto255.scalar_mul(Ristretto255.generator_g(), s)
    rhs = Ristretto255.element_mul(r, Ristretto255.scalar_mul(p, c))
    return _eq(lhs, rhs)


def _eq(a: Element, b: Element) -> bool:
    return Ristretto255.element_to_bytes(a) == Ristretto255.element_to_bytes(b)


# -- report body canonicalization ------------------------------------------


def report_message(body: dict) -> bytes:
    """The signed message for a report body: SHA-256 over the canonical
    (compact, key-sorted) JSON encoding of every field EXCEPT the
    signature block itself."""
    scrubbed = {k: v for k, v in body.items() if k != "signature"}
    canon = json.dumps(
        scrubbed, separators=(",", ":"), sort_keys=True
    ).encode()
    return hashlib.sha256(canon).digest()


def sign_report(body: dict, key: Scalar) -> dict:
    """Attach a ``signature`` block to a report body (returns ``body``)."""
    message = report_message(body)
    r_bytes, s_bytes = sign(key, message)
    body["signature"] = {
        "scheme": "schnorr-ristretto255-merlin/1",
        "public_key": public_key(key).hex(),
        "r": r_bytes.hex(),
        "s": s_bytes.hex(),
    }
    return body


def verify_report(body: dict) -> tuple[bool, str]:
    """``(ok, reason)`` for a signed report dict — signature over the
    canonical body, plus the internal totals-consistency checks a
    flipped byte anywhere in the body would break."""
    sig = body.get("signature")
    if not isinstance(sig, dict):
        return False, "missing signature block"
    if sig.get("scheme") != "schnorr-ristretto255-merlin/1":
        return False, f"unknown signature scheme: {sig.get('scheme')!r}"
    try:
        pub = bytes.fromhex(sig["public_key"])
        r_bytes = bytes.fromhex(sig["r"])
        s_bytes = bytes.fromhex(sig["s"])
    except (KeyError, ValueError, TypeError):
        return False, "malformed signature fields"
    message = report_message(body)
    if not verify(pub, message, r_bytes, s_bytes):
        return False, "signature check failed"
    totals = body.get("totals", {})
    try:
        audited = int(totals["audited"])
        parts = (
            int(totals["verified"]) + int(totals["rejected"])
        )
        if audited != parts:
            return False, "totals inconsistent: audited != verified+rejected"
        if int(totals["records"]) != audited + int(totals["skipped"]):
            return False, "totals inconsistent: records != audited+skipped"
    except (KeyError, ValueError, TypeError):
        return False, "malformed totals block"
    return True, "ok"
