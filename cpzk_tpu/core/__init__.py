"""Host-plane cryptographic core.

Pure-Python, integer-exact implementations of the ristretto255 group
(RFC 9496), the scalar ring mod ℓ, Keccak-f[1600]/STROBE-128 transcripts,
and the OS CSPRNG wrapper. This is the *oracle* against which the TPU data
plane (``cpzk_tpu.ops``) and the C++ host library (``core/cpp``) are
differential-tested, and the trusted path for single-proof operations.

Reference parity: ``src/primitives/`` in /root/reference.
"""
