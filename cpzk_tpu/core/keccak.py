"""Keccak-f[1600] permutation (host reference implementation).

Validated against hashlib's SHA3-256 by the test suite (we build SHA3 on top
of this permutation and compare digests). Serves STROBE-128 below, which in
turn serves the Merlin-style Fiat-Shamir transcript.

Reference parity: the Keccak core inside the ``merlin`` crate
(SURVEY.md §2.2, ``primitives/transcript.rs``).
"""

_MASK = (1 << 64) - 1

_ROUND_CONSTANTS = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# rotation offsets r[x + 5y] for lane (x, y)
_RHO = [
    0, 1, 62, 28, 27,
    36, 44, 6, 55, 20,
    3, 10, 43, 25, 39,
    41, 45, 15, 21, 8,
    18, 2, 61, 56, 14,
]


def _rotl(v: int, n: int) -> int:
    n %= 64
    return ((v << n) | (v >> (64 - n))) & _MASK


def keccak_f1600(lanes: list[int]) -> list[int]:
    """Apply Keccak-f[1600] to 25 64-bit lanes (lane index = x + 5y)."""
    a = list(lanes)
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] ^= d[x]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(a[x + 5 * y], _RHO[x + 5 * y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] = b[x + 5 * y] ^ ((~b[(x + 1) % 5 + 5 * y] & _MASK) & b[(x + 2) % 5 + 5 * y])
        # iota
        a[0] ^= rc
    return a


def keccak_f1600_bytes(state: bytes | bytearray) -> bytearray:
    """Apply the permutation to a 200-byte state (little-endian lanes)."""
    assert len(state) == 200
    lanes = [int.from_bytes(state[8 * i : 8 * i + 8], "little") for i in range(25)]
    lanes = keccak_f1600(lanes)
    out = bytearray(200)
    for i, lane in enumerate(lanes):
        out[8 * i : 8 * i + 8] = lane.to_bytes(8, "little")
    return out


def sha3_256(data: bytes) -> bytes:
    """SHA3-256 built on keccak_f1600 — used only to validate the permutation
    against hashlib in tests."""
    rate = 136
    state = bytearray(200)
    # absorb with pad10*1 (domain 0x06)
    padded = bytearray(data)
    pad_len = rate - (len(padded) % rate)
    padded += bytes(pad_len)
    padded[len(data)] ^= 0x06
    padded[-1] ^= 0x80
    for off in range(0, len(padded), rate):
        for i in range(rate):
            state[i] ^= padded[off + i]
        state = keccak_f1600_bytes(state)
    return bytes(state[:32])
