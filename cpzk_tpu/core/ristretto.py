"""Ristretto255 group API — reference ``src/primitives/ristretto.rs`` twin.

``Scalar`` and ``Element`` are immutable newtypes over the integer/extended-
coordinate representations in :mod:`cpzk_tpu.core.scalars` and
:mod:`cpzk_tpu.core.edwards`. ``Ristretto255`` is the static namespace whose
method set mirrors the reference line for line (generators, canonical
(de)serialization, random scalars via 64-byte wide reduction, group ops,
recompression validation).
"""

from __future__ import annotations

import hashlib
import hmac

from ..errors import InvalidGroupElement, InvalidScalar
from . import _native, edwards, scalars
from .rng import SecureRng

RISTRETTO_BYTES = 32
WIDE_REDUCTION_BYTES = 64

# Domain separation tag for the second generator h (ristretto.rs:27).
GENERATOR_H_DST = b"chaum-pedersen-zkp-v1.0.0-generator-h"

# one-shot flag: warn the first time a SECRET scalar multiplication has to
# fall back to the variable-time Python ladder (native core unavailable)
_WARNED_VARTIME_FALLBACK = False


class Scalar:
    """Scalar mod ℓ. Equality is constant-time on the canonical encoding."""

    __slots__ = ("value",)

    def __init__(self, value: int):
        self.value = value % scalars.L

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Scalar):
            return NotImplemented
        # constant-time compare of canonical encodings (subtle::ConstantTimeEq twin)
        return hmac.compare_digest(scalars.sc_to_bytes(self.value), scalars.sc_to_bytes(other.value))

    def __hash__(self) -> int:
        return hash(self.value)

    def __repr__(self) -> str:
        return f"Scalar(0x{self.value:064x})"


class Element:
    """Ristretto255 group element (point coset).

    Dual representation, each computed lazily from the other and cached:

    - ``point`` — extended Edwards coordinates for the pure-Python ops;
    - ``wire()`` — the canonical 32-byte encoding, which is what the C++
      host core and the TPU data plane consume.

    Elements entering from the network carry both (decode validates);
    elements produced by the native group ops carry wire bytes only and
    decode on first ``.point`` access (rare: only the pure-Python fallback
    paths need coordinates).
    """

    __slots__ = ("_point", "_wire", "_validated")

    def __init__(
        self,
        point: edwards.Point | None = None,
        wire: bytes | None = None,
        validated: bool = False,
    ):
        if point is None and wire is None:
            raise ValueError("Element needs a point or wire bytes")
        self._point = point
        self._wire = wire
        # True when the wire bytes are known canonical: they passed the
        # canonical decode (element_from_bytes) or came out of an internal
        # group op whose encode is canonical by construction — then
        # recompression validation is a no-op re-check and is skipped.
        # The default stays False so wire bytes handed to this public
        # constructor WITHOUT a canonical decode still get validated
        # (fail-closed); internal construction sites opt in explicitly.
        self._validated = validated

    @property
    def point(self) -> edwards.Point:
        if self._point is None:
            self._point = edwards.ristretto_decode(self._wire)
            if self._point is None:
                # Adversarially reachable: a deferred-parse proof's
                # commitment wire (frame-checked, point decode postponed)
                # can be undecodable — CpuBackend.verify_each catches this
                # and maps it to row status 2.  For internally-produced
                # wires it remains impossible.
                raise InvalidGroupElement(
                    "Bytes do not represent a valid Ristretto point")
        return self._point

    def wire(self) -> bytes:
        """Canonical encoding, cached after first computation."""
        if self._wire is None:
            self._wire = edwards.ristretto_encode(self._point)
        return self._wire

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Element):
            return NotImplemented
        if self._wire is not None and other._wire is not None:
            return self._wire == other._wire
        return edwards.pt_eq(self.point, other.point)

    def __hash__(self) -> int:
        return hash(self.wire())

    def __repr__(self) -> str:
        return f"Element({self.wire().hex()})"


class Ristretto255:
    """Static namespace mirroring the reference group API."""

    _GENERATOR_G_CACHE: Element | None = None
    _GENERATOR_H_CACHE: Element | None = None

    @classmethod
    def generator_g(cls) -> Element:
        if cls._GENERATOR_G_CACHE is None:
            cls._GENERATOR_G_CACHE = Element(edwards.BASEPOINT, validated=True)
        return cls._GENERATOR_G_CACHE

    @classmethod
    def generator_h(cls) -> Element:
        """Second generator: SHA-512(DST) → one-way map (ristretto.rs:86-91)."""
        if cls._GENERATOR_H_CACHE is None:
            digest = hashlib.sha512(GENERATOR_H_DST).digest()
            cls._GENERATOR_H_CACHE = Element(edwards.ristretto_from_uniform_bytes(digest), validated=True)
        return cls._GENERATOR_H_CACHE

    @staticmethod
    def scalar_from_bytes(data: bytes) -> Scalar:
        if len(data) != RISTRETTO_BYTES:
            raise InvalidScalar(f"Expected {RISTRETTO_BYTES} bytes, got {len(data)}")
        v = scalars.sc_from_bytes_canonical(data)
        if v is None:
            raise InvalidScalar("Bytes do not represent a valid scalar")
        return Scalar(v)

    @staticmethod
    def scalar_to_bytes(scalar: Scalar) -> bytes:
        return scalars.sc_to_bytes(scalar.value)

    @staticmethod
    def element_from_bytes(data: bytes) -> Element:
        if len(data) != RISTRETTO_BYTES:
            raise InvalidGroupElement(f"Expected {RISTRETTO_BYTES} bytes, got {len(data)}")
        # Native fast path: ge_decode applies the same canonical rules as
        # the Python decoder (tests/test_native.py differential), and the
        # RFC 9496 decode rejects every non-canonical encoding, so decode
        # success alone is validity — no re-encode (and no field
        # inversion) on the hot ingress path.  Coordinates are then
        # materialized lazily — most wire elements (proof parsing, server
        # ingress) never need them.
        ok = _native.point_validate(bytes(data))
        if ok is not None:
            if not ok:
                raise InvalidGroupElement("Bytes do not represent a valid Ristretto point")
            return Element(wire=bytes(data), validated=True)
        point = edwards.ristretto_decode(data)
        if point is None:
            raise InvalidGroupElement("Bytes do not represent a valid Ristretto point")
        return Element(point, bytes(data), validated=True)

    @staticmethod
    def element_to_bytes(element: Element) -> bytes:
        return element.wire()

    @staticmethod
    def random_scalar(rng: SecureRng) -> Scalar:
        return Scalar(scalars.sc_from_bytes_mod_order_wide(rng.fill_bytes(WIDE_REDUCTION_BYTES)))

    @staticmethod
    def random_scalars(rng: SecureRng, n: int) -> list[Scalar]:
        """``n`` independent uniform scalars from ONE CSPRNG draw.  Each
        per-scalar ``fill_bytes`` is a getrandom(2) syscall; the batch
        verifier draws one RLC coefficient per row, so at device batch
        sizes the per-row syscall (not the wide reduction) dominates the
        host prep — one pooled draw sliced into 64-byte windows keeps the
        distribution identical and the syscall count at 1."""
        pool = rng.fill_bytes(WIDE_REDUCTION_BYTES * n)
        return [
            Scalar(scalars.sc_from_bytes_mod_order_wide(
                pool[WIDE_REDUCTION_BYTES * i: WIDE_REDUCTION_BYTES * (i + 1)]
            ))
            for i in range(n)
        ]

    @staticmethod
    def scalar_mul(element: Element, scalar: Scalar) -> Element:
        """scalar * element for PUBLIC inputs, through the C++ host core
        when available (bit-exact vs the Python path per
        tests/test_native.py).  Both paths are variable-time — callers
        with SECRET scalars (prover nonce, witness) must use
        :meth:`double_base_mul`, which runs the native constant-time
        fixed-base comb — see docs/security.md."""
        if scalar.value == 0:
            return Ristretto255.identity()
        out = _native.scalarmul(element.wire(), scalars.sc_to_bytes(scalar.value))
        if out:  # None = no library; b"" = decode failure (fall through)
            return Element(wire=out, validated=True)
        return Element(edwards.pt_scalar_mul(element.point, scalar.value), validated=True)

    @staticmethod
    def double_base_mul(g: Element, h: Element, scalar: Scalar) -> tuple[Element, Element]:
        """(scalar*g, scalar*h) for SECRET scalars — the prover's nonce
        commitment (prover/mod.rs:115-121) and the statement derivation
        (gadgets.rs:217-221) are the only places the protocol multiplies a
        secret.  Uses the native constant-time fixed-base comb (signed
        radix-16, masked table scan, no secret-dependent branches); falls
        back to the pure-Python ladder when the native core is absent —
        Python big-int timing is best-effort, disclosed in
        docs/security.md."""
        if scalar.value == 0:
            return Ristretto255.identity(), Ristretto255.identity()
        sc = scalars.sc_to_bytes(scalar.value)
        out = _native.double_basemul(g.wire(), h.wire(), sc)
        if out is None and _native.basemul_init(g.wire(), h.wire()):
            # None can also mean the rare comb-table churn race (another
            # thread swapped the generator pair between build and read);
            # one explicit rebuild + retry resolves it without giving up
            # the constant-time path
            out = _native.double_basemul(g.wire(), h.wire(), sc)
        if out is not None:
            return Element(wire=out[0], validated=True), Element(wire=out[1], validated=True)
        global _WARNED_VARTIME_FALLBACK
        if not _WARNED_VARTIME_FALLBACK:
            _WARNED_VARTIME_FALLBACK = True
            import logging

            logging.getLogger("cpzk_tpu").warning(
                "native constant-time fixed-base comb unavailable; secret-"
                "scalar multiplications are using the variable-time Python "
                "ladder (see docs/security.md)"
            )
        return (
            Element(edwards.pt_scalar_mul(g.point, scalar.value), validated=True),
            Element(edwards.pt_scalar_mul(h.point, scalar.value), validated=True),
        )

    @staticmethod
    def element_mul(a: Element, b: Element) -> Element:
        """Group operation (written multiplicatively in the protocol; the
        curve implementation is additive) — ristretto.rs:158-160."""
        out = _native.point_add(a.wire(), b.wire())
        if out:
            return Element(wire=out, validated=True)
        return Element(edwards.pt_add(a.point, b.point), validated=True)

    @staticmethod
    def identity() -> Element:
        return Element(edwards.IDENTITY, bytes(RISTRETTO_BYTES), validated=True)

    @staticmethod
    def is_identity(element: Element) -> bool:
        if element._wire is not None:
            return element._wire == bytes(RISTRETTO_BYTES)
        return edwards.pt_is_identity(element.point)

    @staticmethod
    def validate_element(element: Element) -> None:
        """Recompression validation (ristretto.rs:173-185): identity is valid;
        otherwise encode→decode must round-trip to the same coset.  Uses the
        C++ core's decode+encode when available (same canonical rules,
        enforced bit-exact by tests/test_native.py)."""
        if element._validated:
            return  # parse-time canonical decode already proved validity
        if Ristretto255.is_identity(element):
            return
        compressed = element.wire()
        rt = _native.point_roundtrip(compressed)
        if rt is not None:
            if rt != compressed:
                raise InvalidGroupElement("Element failed recompression validation")
            return
        point = edwards.ristretto_decode(compressed)
        if point is None or not edwards.pt_eq(point, element.point):
            raise InvalidGroupElement("Element failed recompression validation")

    @staticmethod
    def scalar_add(a: Scalar, b: Scalar) -> Scalar:
        return Scalar(scalars.sc_add(a.value, b.value))

    @staticmethod
    def scalar_sub(a: Scalar, b: Scalar) -> Scalar:
        return Scalar(scalars.sc_sub(a.value, b.value))

    @staticmethod
    def scalar_mul_scalar(a: Scalar, b: Scalar) -> Scalar:
        return Scalar(scalars.sc_mul(a.value, b.value))

    @staticmethod
    def scalar_negate(scalar: Scalar) -> Scalar:
        return Scalar(scalars.sc_neg(scalar.value))

    @staticmethod
    def scalar_invert(scalar: Scalar) -> Scalar | None:
        if scalar.value == 0:
            return None
        return Scalar(scalars.sc_invert(scalar.value))

    @staticmethod
    def scalar_is_zero(scalar: Scalar) -> bool:
        return scalar.value == 0
