"""Fiat-Shamir transcript — Merlin-protocol twin for Chaum-Pedersen.

Message framing follows the ``merlin`` crate exactly:

- ``Transcript::new(label)``: STROBE-128 init with protocol label
  ``b"Merlin v1.0"`` then ``append_message(b"dom-sep", label)``.
- ``append_message(label, msg)``: ``meta_AD(label) || meta_AD(LE32(len))``
  then ``AD(msg)``.
- ``challenge_bytes(label, n)``: ``meta_AD(label) || meta_AD(LE32(n))`` then
  ``PRF(n)``.

The protocol-level labels and append order mirror the reference
``src/primitives/transcript.rs:11-71`` byte for byte: protocol label
``"Chaum-Pedersen ZKP v1.0.0"``, protocol DST ``"chaum-pedersen-ristretto255"``,
challenge DST ``"challenge"``, and the 64-byte wide challenge reduction.
"""

from . import _native
from .scalars import sc_from_bytes_mod_order_wide
from .strobe import Strobe128

MERLIN_PROTOCOL_LABEL = b"Merlin v1.0"
PROTOCOL_LABEL = b"Chaum-Pedersen ZKP v1.0.0"
PROTOCOL_DST = b"chaum-pedersen-ristretto255"
CHALLENGE_DST = b"challenge"
WIDE_REDUCTION_BYTES = 64


class MerlinTranscript:
    """General Merlin transcript (crate-level twin)."""

    def __init__(self, label: bytes):
        self.strobe = Strobe128(MERLIN_PROTOCOL_LABEL)
        self.append_message(b"dom-sep", label)

    def append_message(self, label: bytes, message: bytes) -> None:
        data_len = len(message).to_bytes(4, "little")
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(data_len, True)
        self.strobe.ad(message, False)

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        data_len = n.to_bytes(4, "little")
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(data_len, True)
        return self.strobe.prf(n, False)


class Transcript:
    """Chaum-Pedersen protocol transcript (reference ``Transcript`` twin).

    Mirrors ``src/primitives/transcript.rs:29-71``: construction appends the
    protocol DST under label ``"protocol"``; context/parameters/statement/
    commitment appends use the same labels; ``challenge_scalar`` squeezes 64
    bytes under ``"challenge"`` and wide-reduces mod ℓ.
    """

    def __init__(self) -> None:
        # native C++ core when built (byte-identical; tests/test_native.py),
        # pure-Python twin otherwise
        if _native.load() is not None:
            self._t = _native.NativeMerlin(PROTOCOL_LABEL)
        else:
            self._t = MerlinTranscript(PROTOCOL_LABEL)
        self._t.append_message(b"protocol", PROTOCOL_DST)

    def append_context(self, context: bytes) -> None:
        self._t.append_message(b"context", context)

    def append_parameters(self, generator_g: bytes, generator_h: bytes) -> None:
        self._t.append_message(b"generator-g", generator_g)
        self._t.append_message(b"generator-h", generator_h)

    def append_statement(self, y1: bytes, y2: bytes) -> None:
        self._t.append_message(b"y1", y1)
        self._t.append_message(b"y2", y2)

    def append_commitment(self, r1: bytes, r2: bytes) -> None:
        self._t.append_message(b"r1", r1)
        self._t.append_message(b"r2", r2)

    def challenge_scalar(self):
        from .ristretto import Scalar

        buf = self._t.challenge_bytes(CHALLENGE_DST, WIDE_REDUCTION_BYTES)
        return Scalar(sc_from_bytes_mod_order_wide(buf))


_DEVICE_CHALLENGES_WARNED = False


def _warn_device_challenges_removed() -> None:
    """One-time deprecation notice: deployments still setting
    CPZK_DEVICE_CHALLENGES=1 silently fall through to the host pool (the
    device-Keccak path was removed after round-5 calibration measured it
    18-37x slower than the threaded native derivation) — say so once
    instead of letting the knob rot unnoticed in a config template."""
    global _DEVICE_CHALLENGES_WARNED
    if _DEVICE_CHALLENGES_WARNED:
        return
    _DEVICE_CHALLENGES_WARNED = True
    import os

    if os.environ.get("CPZK_DEVICE_CHALLENGES") == "1":
        import warnings

        warnings.warn(
            "CPZK_DEVICE_CHALLENGES=1 is set, but the device-challenge "
            "path was removed after hardware calibration (18-37x slower "
            "than the threaded host pool at every measured tier); "
            "challenges derive on the host pool — drop the env var",
            stacklevel=3,
        )


def derive_challenges_batch(
    contexts: list[bytes | None],
    gs: list[bytes],
    hs: list[bytes],
    y1s: list[bytes],
    y2s: list[bytes],
    r1s: list[bytes],
    r2s: list[bytes],
):
    """Fiat-Shamir challenges for a whole batch (host hot loop of batch
    verification; reference analog ``src/verifier/batch.rs:239-260``).

    Uses the threaded C++ core when available, else per-row Python
    transcripts. Returns a list of Scalars.
    """
    from .ristretto import Scalar

    # A device (batched-Keccak) path existed here behind
    # CPZK_DEVICE_CHALLENGES=1 and was REMOVED after round-5 hardware
    # calibration: on TPU v5 lite the device Keccak measured 10.3 kchal/s
    # at n=4096 and 23.3 kchal/s at n=65536 vs 383-443 kchal/s for the
    # threaded native pool below — 18-37x slower at every tier, with no
    # projected crossover (the serving plane needs ~25 kchal/s per 25k
    # proofs/s, which one host core already triples).  The kernel itself
    # survives as :mod:`cpzk_tpu.ops.challenge` (device Keccak-f[1600]
    # twin, differential-tested) for silicon where the trade flips.
    _warn_device_challenges_removed()
    out = _native.challenge_batch(
        contexts,
        b"".join(gs), b"".join(hs),
        b"".join(y1s), b"".join(y2s),
        b"".join(r1s), b"".join(r2s),
    )
    if out is not None:
        return [
            Scalar(sc_from_bytes_mod_order_wide(out[64 * i : 64 * i + 64]))
            for i in range(len(contexts))
        ]

    scalars = []
    for i in range(len(contexts)):
        t = Transcript()
        if contexts[i] is not None:
            t.append_context(contexts[i])
        t.append_parameters(gs[i], hs[i])
        t.append_statement(y1s[i], y2s[i])
        t.append_commitment(r1s[i], r2s[i])
        scalars.append(t.challenge_scalar())
    return scalars
