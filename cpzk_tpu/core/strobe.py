"""STROBE-128 duplex construction (the subset Merlin uses).

Implements ``meta_AD`` / ``AD`` / ``PRF`` over Keccak-f[1600] with rate
R = 166, matching the STROBE v1.0.2 lite implementation vendored by the
``merlin`` crate (reference dependency of ``src/primitives/transcript.rs``).

Only the operations Merlin needs are provided; there is no transport mode.
"""

from .keccak import keccak_f1600_bytes

STROBE_R = 166  # 200 - 2*16 - 2 bytes: keccak capacity for 128-bit security

FLAG_I = 0x01
FLAG_A = 0x02
FLAG_C = 0x04
FLAG_T = 0x08
FLAG_M = 0x10
FLAG_K = 0x20


class Strobe128:
    """STROBE-128 state machine (merlin's strobe.rs twin)."""

    def __init__(self, protocol_label: bytes):
        st = bytearray(200)
        st[0:6] = bytes([1, STROBE_R + 2, 1, 0, 1, 12 * 8])
        st[6:18] = b"STROBEv1.0.2"
        self.state = keccak_f1600_bytes(st)
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(protocol_label, False)

    # --- internals ---
    def _run_f(self) -> None:
        self.state[self.pos] ^= self.pos_begin
        self.state[self.pos + 1] ^= 0x04
        self.state[STROBE_R + 1] ^= 0x80
        self.state = keccak_f1600_bytes(self.state)
        self.pos = 0
        self.pos_begin = 0

    def _absorb(self, data: bytes) -> None:
        for byte in data:
            self.state[self.pos] ^= byte
            self.pos += 1
            if self.pos == STROBE_R:
                self._run_f()

    def _squeeze(self, n: int) -> bytes:
        out = bytearray(n)
        for i in range(n):
            out[i] = self.state[self.pos]
            self.state[self.pos] = 0
            self.pos += 1
            if self.pos == STROBE_R:
                self._run_f()
        return bytes(out)

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            if flags != self.cur_flags:
                raise ValueError(
                    f"continued op with different flags: {flags} != {self.cur_flags}"
                )
            return
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        force_f = (flags & (FLAG_C | FLAG_K)) != 0
        if force_f and self.pos != 0:
            self._run_f()

    # --- merlin-facing operations ---
    def meta_ad(self, data: bytes, more: bool) -> None:
        self._begin_op(FLAG_M | FLAG_A, more)
        self._absorb(data)

    def ad(self, data: bytes, more: bool) -> None:
        self._begin_op(FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool) -> bytes:
        self._begin_op(FLAG_I | FLAG_A | FLAG_C, more)
        return self._squeeze(n)
