"""Edwards25519 points (extended coordinates) and the ristretto255 functions.

Implements RFC 9496: ENCODE, DECODE, the Elligator-based one-way map
(FROM_UNIFORM_BYTES), and equality in the quotient group. Points are
immutable ``(X, Y, Z, T)`` tuples with x = X/Z, y = Y/Z, T = XY/Z.

Reference parity: the point layer of curve25519-dalek used by
``src/primitives/ristretto.rs`` (compress/decompress/identity/add/scalar-mul).
"""

from __future__ import annotations

from .field import (
    D,
    D_MINUS_ONE_SQ,
    INVSQRT_A_MINUS_D,
    ONE_MINUS_D_SQ,
    P,
    SQRT_AD_MINUS_ONE,
    SQRT_M1,
    fabs,
    fe_to_bytes,
    finv,
    is_negative,
    sqrt_ratio_m1,
)

Point = tuple[int, int, int, int]  # (X, Y, Z, T) extended coordinates

IDENTITY: Point = (0, 1, 1, 0)


def pt_add(p: Point, q: Point) -> Point:
    """Unified extended-coordinate addition for a = -1 (HWCD'08 add-2008-hwcd-3)."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = T1 * (2 * D % P) % P * T2 % P
    Dd = Z1 * 2 * Z2 % P
    E = B - A
    F = Dd - C
    G = Dd + C
    H = B + A
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def pt_double(p: Point) -> Point:
    """Extended-coordinate doubling for a = -1 (dbl-2008-hwcd)."""
    X1, Y1, Z1, _ = p
    A = X1 * X1 % P
    B = Y1 * Y1 % P
    C = 2 * Z1 * Z1 % P
    H = A + B
    E = (H - (X1 + Y1) * (X1 + Y1)) % P
    G = A - B
    F = C + G
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def pt_neg(p: Point) -> Point:
    X, Y, Z, T = p
    return ((-X) % P, Y, Z, (-T) % P)


def pt_sub(p: Point, q: Point) -> Point:
    return pt_add(p, pt_neg(q))


def pt_scalar_mul(p: Point, n: int) -> Point:
    """Double-and-add scalar multiplication (host path; not constant-time —
    host secret-scalar paths use this only where the reference also accepts
    vartime, and the threat model is documented in docs/security.md)."""
    acc = IDENTITY
    addend = p
    while n > 0:
        if n & 1:
            acc = pt_add(acc, addend)
        addend = pt_double(addend)
        n >>= 1
    return acc


def pt_eq(p: Point, q: Point) -> bool:
    """Equality in the ristretto quotient group: X1*Y2 == Y1*X2 or Y1*Y2 == X1*X2
    (dalek RistrettoPoint::eq — OR, to identify the 4-torsion cosets)."""
    X1, Y1, _, _ = p
    X2, Y2, _, _ = q
    return (X1 * Y2 - Y1 * X2) % P == 0 or (Y1 * Y2 - X1 * X2) % P == 0


def pt_is_identity(p: Point) -> bool:
    return pt_eq(p, IDENTITY)


def ristretto_encode(p: Point) -> bytes:
    """RFC 9496 §4.3.2 ENCODE."""
    X0, Y0, Z0, T0 = p
    u1 = (Z0 + Y0) * (Z0 - Y0) % P
    u2 = X0 * Y0 % P
    _, invsqrt = sqrt_ratio_m1(1, u1 * u2 % P * u2 % P)
    den1 = invsqrt * u1 % P
    den2 = invsqrt * u2 % P
    z_inv = den1 * den2 % P * T0 % P

    ix0 = X0 * SQRT_M1 % P
    iy0 = Y0 * SQRT_M1 % P
    enchanted_denominator = den1 * INVSQRT_A_MINUS_D % P
    rotate = is_negative(T0 * z_inv % P)

    x = iy0 if rotate else X0
    y = ix0 if rotate else Y0
    z = Z0
    den_inv = enchanted_denominator if rotate else den2

    if is_negative(x * z_inv % P):
        y = (-y) % P
    s = fabs(den_inv * ((z - y) % P) % P)
    return fe_to_bytes(s)


def ristretto_decode(b: bytes) -> Point | None:
    """RFC 9496 §4.3.1 DECODE. Returns None on any non-canonical/invalid input."""
    if len(b) != 32:
        return None
    s = int.from_bytes(b, "little")
    if s >= P:  # non-canonical field encoding
        return None
    if s & 1:  # negative s
        return None

    ss = s * s % P
    u1 = (1 - ss) % P
    u2 = (1 + ss) % P
    u2_sqr = u2 * u2 % P
    v = (-(D * u1 % P * u1 % P) - u2_sqr) % P
    was_square, invsqrt = sqrt_ratio_m1(1, v * u2_sqr % P)
    den_x = invsqrt * u2 % P
    den_y = invsqrt * den_x % P * v % P
    x = fabs(2 * s % P * den_x % P)
    y = u1 * den_y % P
    t = x * y % P

    if (not was_square) or is_negative(t) or y == 0:
        return None
    return (x, y, 1, t)


def _elligator_map(t: int) -> Point:
    """RFC 9496 §4.3.4 MAP: one field element → point."""
    r = SQRT_M1 * t % P * t % P
    u = (r + 1) * ONE_MINUS_D_SQ % P
    v = ((-1 - r * D) % P) * ((r + D) % P) % P

    was_square, s = sqrt_ratio_m1(u, v)
    s_prime = (-fabs(s * t % P)) % P
    if not was_square:
        s = s_prime
        c = r
    else:
        c = (-1) % P

    n = (c * ((r - 1) % P) % P * D_MINUS_ONE_SQ - v) % P

    w0 = 2 * s * v % P
    w1 = n * SQRT_AD_MINUS_ONE % P
    w2 = (1 - s * s) % P
    w3 = (1 + s * s) % P
    return (w0 * w3 % P, w2 * w1 % P, w1 * w3 % P, w0 * w2 % P)


def ristretto_from_uniform_bytes(b: bytes) -> Point:
    """RFC 9496 one-way map on 64 uniform bytes (dalek from_uniform_bytes).

    Used for generator_h derivation (reference ``ristretto.rs:86-91``)."""
    if len(b) != 64:
        raise ValueError("from_uniform_bytes needs 64 bytes")
    t1 = int.from_bytes(b[:32], "little") & ((1 << 255) - 1)
    t2 = int.from_bytes(b[32:], "little") & ((1 << 255) - 1)
    return pt_add(_elligator_map(t1 % P), _elligator_map(t2 % P))


def _derive_basepoint() -> Point:
    """Ed25519 basepoint: y = 4/5, x the even root of (y²-1)/(d y²+1)."""
    y = 4 * finv(5) % P
    u = (y * y - 1) % P
    v = (D * y % P * y + 1) % P
    ok, x = sqrt_ratio_m1(u, v)
    assert ok
    # fabs already returned the even representative
    t = x * y % P
    return (x, y, 1, t)


BASEPOINT: Point = _derive_basepoint()


def pt_normalize(p: Point) -> Point:
    """Affine-normalize to Z = 1 (for stable coordinate comparisons)."""
    X, Y, Z, _ = p
    zi = finv(Z)
    x = X * zi % P
    y = Y * zi % P
    return (x, y, 1, x * y % P)
