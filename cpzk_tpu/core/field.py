"""GF(2^255 - 19) field arithmetic and ristretto255 field constants.

Integer-exact host implementation. All ristretto constants are *derived*
(not hardcoded) from the curve definition, then cross-checked by the test
suite against RFC 9496 test vectors.

Reference parity: the field layer that curve25519-dalek provides underneath
``src/primitives/ristretto.rs`` (see SURVEY.md §2.2).
"""

P = 2**255 - 19

# Edwards curve: -x^2 + y^2 = 1 + d x^2 y^2  (a = -1)
D = (-121665 * pow(121666, P - 2, P)) % P

# sqrt(-1) mod p  (p ≡ 5 mod 8)
SQRT_M1 = pow(2, (P - 1) // 4, P)


def fadd(a: int, b: int) -> int:
    return (a + b) % P


def fsub(a: int, b: int) -> int:
    return (a - b) % P


def fmul(a: int, b: int) -> int:
    return (a * b) % P


def fneg(a: int) -> int:
    return (-a) % P


def finv(a: int) -> int:
    """Multiplicative inverse by Fermat's little theorem (a != 0)."""
    return pow(a, P - 2, P)


def is_negative(a: int) -> bool:
    """RFC 9496 'negative' = odd canonical representative."""
    return (a % P) & 1 == 1


def fabs(a: int) -> int:
    """CT_ABS: the non-negative (even) representative of ±a."""
    a %= P
    return P - a if a & 1 else a


def sqrt_ratio_m1(u: int, v: int) -> tuple[bool, int]:
    """Compute (was_square, sqrt(u/v)) per RFC 9496 §3.1 (SQRT_RATIO_M1).

    Returns the non-negative square root of u/v if it exists; otherwise the
    non-negative square root of SQRT_M1 * u / v. ``(u, v) = (0, 0)`` returns
    ``(True, 0)``; ``v = 0, u != 0`` returns ``(False, 0)``.
    """
    u %= P
    v %= P
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P

    correct_sign = check == u
    flipped_sign = check == (P - u) % P
    flipped_sign_i = check == (P - u) * SQRT_M1 % P

    if flipped_sign or flipped_sign_i:
        r = r * SQRT_M1 % P

    r = fabs(r)
    return (correct_sign or flipped_sign, r)


def fsqrt(a: int) -> int:
    """Non-negative square root of a (raises if a is not a QR)."""
    ok, r = sqrt_ratio_m1(a % P, 1)
    if not ok:
        raise ValueError("not a square")
    return r


# --- ristretto255 derived constants (RFC 9496 §4.1) ---
ONE_MINUS_D_SQ = (1 - D * D) % P
D_MINUS_ONE_SQ = (D - 1) * (D - 1) % P
# sqrt(a*d - 1) with a = -1 → sqrt(-(d+1)). RFC 9496 fixes the ODD root
# (fsqrt returns the even one); the sign propagates into the Elligator map
# output, so using the even root would yield negated points and break
# interop with the reference's generator_h.
SQRT_AD_MINUS_ONE = P - fsqrt((-(D + 1)) % P)
assert SQRT_AD_MINUS_ONE & 1 == 1
# 1/sqrt(a - d) with a = -1 → invsqrt(-1 - d); RFC fixes the even root.
INVSQRT_A_MINUS_D = sqrt_ratio_m1(1, (-1 - D) % P)[1]
assert INVSQRT_A_MINUS_D & 1 == 0


def fe_to_bytes(a: int) -> bytes:
    """Canonical 32-byte little-endian encoding."""
    return (a % P).to_bytes(32, "little")
