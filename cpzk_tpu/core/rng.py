"""Cryptographically secure RNG (reference ``src/primitives/rng.rs`` twin).

Wraps the OS CSPRNG (``os.urandom`` → getrandom(2)). All protocol randomness
— witnesses, nonces, batch-verification coefficients, challenge IDs — is
drawn on the host through this class; the TPU never generates secrets.
"""

import os


class SecureRng:
    """OS-backed CSPRNG with the reference's RngCore-ish surface."""

    def fill_bytes(self, n: int) -> bytes:
        return os.urandom(n)

    def next_u32(self) -> int:
        return int.from_bytes(os.urandom(4), "little")

    def next_u64(self) -> int:
        return int.from_bytes(os.urandom(8), "little")
