"""Scalar ring arithmetic mod ℓ (the ristretto255 group order).

ℓ = 2^252 + 27742317777372353535851937790883648493.

Mirrors the scalar behaviors the reference gets from curve25519-dalek
(``src/primitives/ristretto.rs:94-112,146-150,188-222``): canonical 32-byte
decode, 64-byte wide reduction, ring ops, inversion.
"""

L = 2**252 + 27742317777372353535851937790883648493


def sc_add(a: int, b: int) -> int:
    return (a + b) % L


def sc_sub(a: int, b: int) -> int:
    return (a - b) % L


def sc_mul(a: int, b: int) -> int:
    return (a * b) % L


def sc_neg(a: int) -> int:
    return (-a) % L


def sc_invert(a: int) -> int:
    """Multiplicative inverse mod ℓ (a != 0)."""
    return pow(a, L - 2, L)


def sc_from_bytes_canonical(b: bytes) -> int | None:
    """Canonical decode: 32 LE bytes; None if >= ℓ (dalek from_canonical_bytes)."""
    if len(b) != 32:
        return None
    v = int.from_bytes(b, "little")
    return v if v < L else None


def sc_from_bytes_mod_order_wide(b: bytes) -> int:
    """64-byte wide reduction (dalek from_bytes_mod_order_wide)."""
    if len(b) != 64:
        raise ValueError("wide reduction needs 64 bytes")
    return int.from_bytes(b, "little") % L


def sc_to_bytes(a: int) -> bytes:
    return (a % L).to_bytes(32, "little")
