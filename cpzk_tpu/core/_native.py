"""ctypes binding for the C++ host crypto core (native/merlin.cpp).

Loads ``cpzk_tpu/_lib/libcpzk_native.so``, building it on first import when
missing and a C++ toolchain is available. Every consumer falls back to the
pure-Python twins when the library cannot be loaded — the native core is an
accelerator, never a requirement (SURVEY.md §2.2 rebuild strategy).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

_LIB_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "_lib")
_LIB_PATH = os.path.join(_LIB_DIR, "libcpzk_native.so")
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")

_lib: ctypes.CDLL | None = None
_tried = False
_build_warned = False

log = logging.getLogger("cpzk_tpu.core.native")


def _warn_build_failure(exc: Exception) -> None:
    """One-time WARNING when the native build fails: before this, every
    failure was swallowed silently and a box with a broken toolchain was
    indistinguishable from a deliberate ``CPZK_NO_NATIVE_BUILD=1`` — the
    operator had no signal they were serving on the pure-Python slow
    path.  The compiler/make stderr rides in the message, so the root
    cause (missing g++, bad flags, read-only tree) is in the log line
    itself, not on a box someone has to ssh into."""
    global _build_warned
    if _build_warned:
        return
    _build_warned = True
    detail = str(exc)
    stderr = getattr(exc, "stderr", None)
    if stderr:
        if isinstance(stderr, bytes):
            stderr = stderr.decode("utf-8", "replace")
        detail = f"{exc}: {stderr.strip()}"
    log.warning(
        "native core build failed — falling back to the pure-Python slow "
        "path (set CPZK_NO_NATIVE_BUILD=1 to silence this if intentional). "
        "make -C %s said: %s", _SRC_DIR, detail,
    )


def _build(force: bool = False) -> bool:
    if os.environ.get("CPZK_NO_NATIVE_BUILD"):
        return False
    try:
        subprocess.run(
            ["make", "-s"] + (["-B"] if force else []),
            cwd=_SRC_DIR,
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except Exception as exc:
        _warn_build_failure(exc)
        return False


def _declare(lib: ctypes.CDLL) -> None:
    """Attach restype/argtypes for every symbol the library exports."""
    lib.cpzk_transcript_new.restype = ctypes.c_void_p
    lib.cpzk_transcript_new.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.cpzk_transcript_free.argtypes = [ctypes.c_void_p]
    lib.cpzk_transcript_append.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.cpzk_transcript_challenge.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.cpzk_challenge_batch.argtypes = [
        ctypes.c_size_t, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int,
    ]
    if hasattr(lib, "cpzk_verify_rows"):
        lib.cpzk_verify_rows.restype = ctypes.c_int
        lib.cpzk_verify_rows.argtypes = [
            ctypes.c_size_t, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.cpzk_point_roundtrip.restype = ctypes.c_int
        lib.cpzk_point_roundtrip.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    if hasattr(lib, "cpzk_point_validate"):
        lib.cpzk_point_validate.restype = ctypes.c_int
        lib.cpzk_point_validate.argtypes = [ctypes.c_char_p]
    if hasattr(lib, "cpzk_batch_decode"):
        lib.cpzk_batch_decode.restype = ctypes.c_int
        lib.cpzk_batch_decode.argtypes = [
            ctypes.c_size_t, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_int,
        ]
    if hasattr(lib, "cpzk_parse_proofs"):
        lib.cpzk_parse_proofs.restype = ctypes.c_int
        lib.cpzk_parse_proofs.argtypes = [
            ctypes.c_size_t, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_int,
        ]
    if hasattr(lib, "cpzk_sc_mul_beta"):
        lib.cpzk_sc_mul_beta.restype = ctypes.c_int
        lib.cpzk_sc_mul_beta.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ]
    if hasattr(lib, "cpzk_scalarmul"):
        lib.cpzk_scalarmul.restype = ctypes.c_int
        lib.cpzk_scalarmul.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ]
        lib.cpzk_point_add.restype = ctypes.c_int
        lib.cpzk_point_add.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ]
    if hasattr(lib, "cpzk_wire_scan"):
        lib.cpzk_wire_scan.restype = ctypes.c_int
        lib.cpzk_wire_scan.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.cpzk_wire_fill.restype = ctypes.c_int
        lib.cpzk_wire_fill.argtypes = (
            [ctypes.c_int, ctypes.c_char_p, ctypes.c_size_t]
            + [ctypes.POINTER(ctypes.c_uint64)] * 7
            + [ctypes.c_char_p]
        )
        lib.cpzk_wire_gather.restype = ctypes.c_size_t
        lib.cpzk_wire_gather.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_size_t, ctypes.c_char_p,
        ]
    if hasattr(lib, "cpzk_double_basemul"):
        lib.cpzk_basemul_init.restype = ctypes.c_int
        lib.cpzk_basemul_init.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.cpzk_double_basemul.restype = ctypes.c_int
        lib.cpzk_double_basemul.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char_p,
        ]


# Expected cpzk_abi_version(); must match ristretto.cpp.  The loader
# force-rebuilds once on mismatch — keyed on an explicit generation number
# rather than symbol presence, because a changed signature or changed
# semantics behind an existing symbol is invisible to hasattr.
_ABI_EXPECTED = 3


def _abi(lib: ctypes.CDLL) -> int:
    if not hasattr(lib, "cpzk_abi_version"):
        return 0
    lib.cpzk_abi_version.restype = ctypes.c_int
    lib.cpzk_abi_version.argtypes = []
    return int(lib.cpzk_abi_version())


def load() -> ctypes.CDLL | None:
    """The native library, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH) and not _build():
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None

    # Force-rebuild once if the .so predates the newest symbols, but never
    # discard a working (older) library — a failed rebuild keeps the old
    # file and the old capabilities.  Keyed to the NEWEST export so every
    # symbol generation triggers exactly one refresh.
    if _abi(lib) != _ABI_EXPECTED and _build(force=True):
        try:
            relib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            relib = None
        if relib is not None and _abi(relib) == _ABI_EXPECTED:
            lib = relib

    _declare(lib)
    _lib = lib
    return _lib


def _ristretto_lib():
    """The library iff it exports the ristretto verification core."""
    lib = load()
    if lib is None or not hasattr(lib, "cpzk_verify_rows"):
        return None
    return lib


def challenge_batch(
    contexts: list[bytes | None],
    gs: bytes,
    hs: bytes,
    y1s: bytes,
    y2s: bytes,
    r1s: bytes,
    r2s: bytes,
    threads: int = 0,
) -> bytes | None:
    """Derive n 64-byte challenges natively; None if the library is absent.

    Point args are n*32-byte concatenations; ``contexts[i] is None`` means
    "no context append" for row i (distinct from ``b""``).
    """
    lib = load()
    if lib is None:
        return None
    n = len(contexts)
    for name, col in (("gs", gs), ("hs", hs), ("y1s", y1s), ("y2s", y2s), ("r1s", r1s), ("r2s", r2s)):
        if len(col) != 32 * n:
            raise ValueError(f"{name} must be {32 * n} bytes (n*32), got {len(col)}")
    has_ctx = bytes(0 if c is None else 1 for c in contexts)
    blob = b"".join(c or b"" for c in contexts)
    offsets = (ctypes.c_uint32 * (n + 1))()
    off = 0
    for i, c in enumerate(contexts):
        offsets[i] = off
        off += len(c or b"")
    offsets[n] = off
    out = ctypes.create_string_buffer(64 * n)
    lib.cpzk_challenge_batch(
        n, blob, offsets, has_ctx, gs, hs, y1s, y2s, r1s, r2s, out, threads
    )
    return out.raw


def verify_rows(
    g: bytes,
    h: bytes,
    y1s: bytes,
    y2s: bytes,
    r1s: bytes,
    r2s: bytes,
    ss: bytes,
    cs: bytes,
    threads: int = 0,
) -> list[int] | None:
    """Verify n Chaum-Pedersen rows natively (s*G == R1 + c*Y1 and the H/Y2
    twin; reference ``verifier/mod.rs:144-171``); None if the library is
    absent.  ``g``/``h`` are the shared 32-byte generators; the six column
    args are n*32-byte concatenations of wire encodings.  Per-row status:
    1 = pass, 0 = fail, 2 = commitment wire failed to decode (only
    reachable with deferred-parse proofs; maps back to the parse error)."""
    lib = _ristretto_lib()
    if lib is None:
        return None
    if len(g) != 32 or len(h) != 32:
        raise ValueError("g and h must be 32-byte encodings")
    if len(ss) % 32 != 0:
        raise ValueError(f"ss must be a multiple of 32 bytes, got {len(ss)}")
    n = len(ss) // 32
    for name, col in (("y1s", y1s), ("y2s", y2s), ("r1s", r1s),
                      ("r2s", r2s), ("ss", ss), ("cs", cs)):
        if len(col) != 32 * n:
            raise ValueError(f"{name} must be {32 * n} bytes (n*32), got {len(col)}")
    if threads <= 0:
        threads = min(os.cpu_count() or 1, max(1, n))
    out = ctypes.create_string_buffer(n)
    lib.cpzk_verify_rows(n, g, h, y1s, y2s, r1s, r2s, ss, cs, out, threads)
    return list(out.raw)


def batch_decode(wires: bytes, threads: int = 0) -> tuple[bytes, bytes] | None:
    """Decode n concatenated 32-byte wires to extended coordinates on the
    native worker pool; returns (coords, ok) with coords n*128 bytes
    (X|Y|Z|T, canonical LE field bytes each) and ok n flag bytes.  None
    when the library is unavailable.  The device data plane uses this to
    marshal points without per-point Python big-int decodes."""
    lib = _ristretto_lib()
    if lib is None or not hasattr(lib, "cpzk_batch_decode"):
        return None
    if len(wires) % 32:
        raise ValueError("wires must be a multiple of 32 bytes")
    n = len(wires) // 32
    coords = ctypes.create_string_buffer(128 * n)
    ok = ctypes.create_string_buffer(n)
    if threads <= 0:
        threads = min(os.cpu_count() or 1, max(1, n // 256 + 1))
    lib.cpzk_batch_decode(n, wires, coords, ok, threads)
    return coords.raw, ok.raw


def batch_decode_into(wires: bytes, coords, ok, threads: int = 0) -> bool | None:
    """Allocation-free variant of :func:`batch_decode`: the coordinate and
    flag outputs land directly in caller-provided writable C-contiguous
    buffers (numpy uint8 arrays), so a hot marshal loop can reuse one
    staging buffer per batch shape instead of paying two
    ``create_string_buffer`` allocations plus two ``.raw`` copies (129
    bytes/point) per call.  ``coords`` must hold >= 128*n bytes and ``ok``
    >= n bytes for n = len(wires)/32.  Returns True on dispatch, None when
    the library is unavailable (caller falls back)."""
    lib = _ristretto_lib()
    if lib is None or not hasattr(lib, "cpzk_batch_decode"):
        return None
    if len(wires) % 32:
        raise ValueError("wires must be a multiple of 32 bytes")
    n = len(wires) // 32
    if coords.nbytes < 128 * n or ok.nbytes < n:
        raise ValueError("staging buffers too small for the wire count")
    cbuf = (ctypes.c_char * (128 * n)).from_buffer(coords)
    obuf = (ctypes.c_char * n).from_buffer(ok)
    if threads <= 0:
        threads = min(os.cpu_count() or 1, max(1, n // 256 + 1))
    lib.cpzk_batch_decode(n, wires, cbuf, obuf, threads)
    return True


def parse_proofs(packed: bytes, deep: bool = True,
                 threads: int = 0) -> bytes | None:
    """Fast-path validation of n packed 109-byte proof wires (the only
    layout a valid proof can have); returns n flag bytes — 1 means the
    item passed, 0 means "re-parse on the Python slow path for the exact
    error".  ``deep=True`` is complete validity (framing, canonical
    non-identity points, canonical nonzero scalar); ``deep=False`` skips
    the two point decodes for the deferred-parse serving path, where the
    verify stage decodes commitments anyway and reports failures
    tri-state.  None when the library is absent."""
    lib = _ristretto_lib()
    if lib is None or not hasattr(lib, "cpzk_parse_proofs"):
        return None
    if len(packed) % 109:
        raise ValueError("packed must be a multiple of 109 bytes")
    n = len(packed) // 109
    ok = ctypes.create_string_buffer(n)
    if threads <= 0:
        threads = 1 if not deep else min(os.cpu_count() or 1, max(1, n // 512 + 1))
    lib.cpzk_parse_proofs(n, packed, ok, 1 if deep else 0, threads)
    return ok.raw


def point_validate(wire: bytes) -> bool | None:
    """Canonical-validity check via the native decoder (no re-encode, so
    no field inversion — the cheap ingress-path variant); None when the
    library is unavailable."""
    lib = _ristretto_lib()
    if lib is None or not hasattr(lib, "cpzk_point_validate"):
        return None
    if len(wire) != 32:
        return False
    return bool(lib.cpzk_point_validate(wire))


def sc_mul_beta(beta16: bytes, scalar: bytes) -> bytes | None:
    """(beta * scalar) mod l with a 16-byte little-endian beta, via the
    native vartime scalar unit; None when the library is unavailable.
    Exposed for differential testing of the merged-verify weight math."""
    lib = _ristretto_lib()
    if lib is None or not hasattr(lib, "cpzk_sc_mul_beta"):
        return None
    if len(beta16) != 16 or len(scalar) != 32:
        raise ValueError("beta must be 16 bytes and scalar 32 bytes")
    out = ctypes.create_string_buffer(32)
    if not lib.cpzk_sc_mul_beta(beta16, scalar, out):
        raise ValueError("scalar out of domain (must be < 2^253)")
    return out.raw


def point_roundtrip(wire: bytes) -> bytes | None:
    """Decode+re-encode via the native core; None if unavailable, b"" if
    the encoding is rejected."""
    lib = _ristretto_lib()
    if lib is None or len(wire) != 32:
        return None if lib is None else b""
    out = ctypes.create_string_buffer(32)
    if not lib.cpzk_point_roundtrip(wire, out):
        return b""  # decode rejected
    return out.raw


def scalarmul(point: bytes, scalar: bytes) -> bytes | None:
    lib = _ristretto_lib()
    if lib is None:
        return None
    if len(point) != 32 or len(scalar) != 32:
        raise ValueError("point and scalar must be 32 bytes")
    out = ctypes.create_string_buffer(32)
    if not lib.cpzk_scalarmul(point, scalar, out):
        return b""
    return out.raw


def basemul_init(g: bytes, h: bytes) -> bool:
    """Explicitly (re)build the comb tables for a generator pair; used to
    retry once after a churn-race ``double_basemul`` failure.  False when
    the library is absent or a generator fails to decode."""
    lib = _ristretto_lib()
    if lib is None or not hasattr(lib, "cpzk_double_basemul"):
        return False
    if len(g) != 32 or len(h) != 32:
        raise ValueError("g and h must be 32 bytes")
    return bool(lib.cpzk_basemul_init(g, h))


def double_basemul(g: bytes, h: bytes, scalar: bytes) -> tuple[bytes, bytes] | None:
    """Constant-time (s*G, s*H) via the native fixed-base comb; None when
    the library (or the symbol) is unavailable, a generator fails to
    decode, or concurrent callers churn the table's generator pair (rare;
    the caller then uses its fallback path).  Table (re)builds and reads
    are serialized by a rwlock on the C side — ctypes releases the GIL
    around foreign calls, so the GIL alone would not be enough."""
    lib = _ristretto_lib()
    if lib is None or not hasattr(lib, "cpzk_double_basemul"):
        return None
    if len(g) != 32 or len(h) != 32 or len(scalar) != 32:
        raise ValueError("g, h and scalar must be 32 bytes")
    out1 = ctypes.create_string_buffer(32)
    out2 = ctypes.create_string_buffer(32)
    if not lib.cpzk_double_basemul(g, h, scalar, out1, out2):
        return None
    return out1.raw, out2.raw


def point_add(a: bytes, b: bytes) -> bytes | None:
    lib = _ristretto_lib()
    if lib is None:
        return None
    if len(a) != 32 or len(b) != 32:
        raise ValueError("points must be 32 bytes")
    out = ctypes.create_string_buffer(32)
    if not lib.cpzk_point_add(a, b, out):
        return b""
    return out.raw


# --- native request-wire parse (native/wire.cpp) ---------------------------

#: Message kinds, mirroring the enum in native/wire.cpp.
WIRE_CHALLENGE = 1       # auth.ChallengeRequest
WIRE_BATCH_VERIFY = 2    # auth.BatchVerificationRequest
WIRE_STREAM_CHUNK = 3    # auth.StreamVerifyRequest


def wire_lib():
    """The library iff it exports the wire parser; None otherwise."""
    lib = load()
    if lib is None or not hasattr(lib, "cpzk_wire_scan"):
        return None
    return lib


def wire_index(kind: int, data: bytes):
    """Index one request message's known fields natively.

    Returns ``(counts, offs, lens, vals, mint)`` — per-bucket counts
    ``(n0, n1, n2, n_vals)``, per-bucket ctypes uint64 offset/length
    arrays into ``data``, the decoded uint64 ``ids`` values, and the
    final ``mint_sessions`` bool — or ``None`` when the library is
    absent or the message is outside the parser's recognized subset
    (the caller then falls back to the Python protobuf runtime, which
    makes accept/reject and field values identical by construction)."""
    lib = wire_lib()
    if lib is None:
        return None
    counts = (ctypes.c_size_t * 4)()
    if not lib.cpzk_wire_scan(kind, data, len(data), counts):
        return None
    n0, n1, n2, nv = counts[0], counts[1], counts[2], counts[3]
    offs = tuple((ctypes.c_uint64 * max(n, 1))() for n in (n0, n1, n2))
    lens = tuple((ctypes.c_uint64 * max(n, 1))() for n in (n0, n1, n2))
    vals = (ctypes.c_uint64 * max(nv, 1))()
    flags = ctypes.create_string_buffer(1)
    if not lib.cpzk_wire_fill(
        kind, data, len(data),
        offs[0], lens[0], offs[1], lens[1], offs[2], lens[2], vals, flags,
    ):
        return None  # unreachable in practice: same walk as the scan
    return (n0, n1, n2, nv), offs, lens, vals, flags.raw[0:1] == b"\x01"


def wire_gather(data: bytes, offs, lens, n: int, total: int, out=None):
    """Concatenate ``n`` (offset, length) ranges of ``data`` into ``out``
    (a writable buffer of >= ``total`` bytes — the per-thread staging
    buffer on the hot path) or a fresh bytes object when ``out`` is
    None.  Returns the buffer written (``out`` itself, or the new
    bytes); None when the library is unavailable."""
    lib = wire_lib()
    if lib is None:
        return None
    if out is None:
        buf = ctypes.create_string_buffer(total)
        written = lib.cpzk_wire_gather(data, len(data), offs, lens, n, buf)
        if written != total:
            raise ValueError("wire gather ranges out of bounds")
        return buf.raw
    if len(out) < total:
        raise ValueError("staging buffer too small for the gathered ranges")
    cbuf = (ctypes.c_char * len(out)).from_buffer(out)
    written = lib.cpzk_wire_gather(data, len(data), offs, lens, n, cbuf)
    if written != total:
        raise ValueError("wire gather ranges out of bounds")
    return out


class NativeMerlin:
    """Incremental Merlin transcript over the native core (Strobe128 twin)."""

    __slots__ = ("_h", "_lib")

    def __init__(self, label: bytes):
        lib = load()
        if lib is None:
            raise RuntimeError("native core unavailable")
        self._lib = lib
        self._h = lib.cpzk_transcript_new(label, len(label))

    def append_message(self, label: bytes, message: bytes) -> None:
        self._lib.cpzk_transcript_append(self._h, label, len(label), message, len(message))

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        out = ctypes.create_string_buffer(n)
        self._lib.cpzk_transcript_challenge(self._h, label, len(label), out, n)
        return out.raw

    def __del__(self):
        try:
            self._lib.cpzk_transcript_free(self._h)
        except Exception:
            pass
