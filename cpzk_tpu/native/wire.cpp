// Native protobuf wire scanner for the three hot request messages
// (cpzk_tpu/server/wire.py is the Python owner of this seam).
//
// This is NOT a general protobuf decoder.  It recognizes exactly the
// field layouts of auth.ChallengeRequest, auth.BatchVerificationRequest
// and auth.StreamVerifyRequest, and it reports "punt" (return 0) for
// ANYTHING it is not bit-for-bit sure the Python protobuf runtime would
// decode the same way: unknown field numbers, unexpected wire types,
// truncated varints, over-long varints, lengths past the buffer, and
// invalid UTF-8 in string fields.  On punt the Python caller re-parses
// with the real protobuf runtime, so accept/reject semantics and field
// values are definitionally identical — the differential fuzzer
// (fuzz/fuzz_wire_parse.py) holds the accepted-path equivalence.
//
// Two-pass protocol (per message):
//   cpzk_wire_scan(kind, buf, len, counts[4])  -> 1 ok / 0 punt
//   cpzk_wire_fill(kind, buf, len, offs0, lens0, offs1, lens1,
//                  offs2, lens2, vals, flags)
// Length-delimited occurrences of the known fields land in up to three
// per-field BUCKETS of (offset, length) rows in document order (repeated
// append order; a singular string field simply takes the last row):
//
//   kind 1 ChallengeRequest:        bucket 0 = user_id (field 1)
//   kind 2 BatchVerificationRequest: 0 = user_ids (1), 1 = challenge_ids
//                                    (2), 2 = proofs (3)
//   kind 3 StreamVerifyRequest:      0 = user_ids (2), 1 = challenge_ids
//                                    (3), 2 = proofs (4); the uint64 ids
//                                    (field 1, packed or not) decode into
//                                    vals, and flags[0] carries the final
//                                    mint_sessions bool (field 5)
//
// counts[0..2] are the bucket sizes, counts[3] the vals count.  The fill
// pass re-runs the same walk, so its verdict can never diverge from the
// scan's.
//
// cpzk_wire_gather concatenates (offset, length) ranges into a caller
// buffer — the zero-copy hop from socket bytes into the per-thread proof
// staging buffer the parse/marshal stages reuse.

#include <cstddef>
#include <cstdint>
#include <cstring>

extern "C" {

// message kinds (mirrored in cpzk_tpu/core/_native.py)
enum {
    WIRE_CHALLENGE = 1,       // auth.ChallengeRequest
    WIRE_BATCH_VERIFY = 2,    // auth.BatchVerificationRequest
    WIRE_STREAM_CHUNK = 3,    // auth.StreamVerifyRequest
};

// wire types we understand; anything else punts
static const int WT_VARINT = 0;
static const int WT_LEN = 2;

// Strict RFC 3629 UTF-8 validation: rejects overlong encodings,
// surrogates and > U+10FFFF — exactly the byte strings CPython's utf-8
// decoder (and the protobuf runtime's string fields) accept.
static int utf8_valid(const uint8_t *s, size_t len) {
    size_t i = 0;
    while (i < len) {
        uint8_t c = s[i];
        if (c < 0x80) { i += 1; continue; }
        if (c < 0xC2) return 0;  // continuation byte or overlong 2-byte
        if (c < 0xE0) {          // 2-byte
            if (i + 1 >= len || (s[i + 1] & 0xC0) != 0x80) return 0;
            i += 2; continue;
        }
        if (c < 0xF0) {          // 3-byte
            if (i + 2 >= len) return 0;
            uint8_t c1 = s[i + 1], c2 = s[i + 2];
            if ((c1 & 0xC0) != 0x80 || (c2 & 0xC0) != 0x80) return 0;
            if (c == 0xE0 && c1 < 0xA0) return 0;          // overlong
            if (c == 0xED && c1 >= 0xA0) return 0;         // surrogate
            i += 3; continue;
        }
        if (c < 0xF5) {          // 4-byte
            if (i + 3 >= len) return 0;
            uint8_t c1 = s[i + 1], c2 = s[i + 2], c3 = s[i + 3];
            if ((c1 & 0xC0) != 0x80 || (c2 & 0xC0) != 0x80 ||
                (c3 & 0xC0) != 0x80) return 0;
            if (c == 0xF0 && c1 < 0x90) return 0;          // overlong
            if (c == 0xF4 && c1 >= 0x90) return 0;         // > U+10FFFF
            i += 4; continue;
        }
        return 0;
    }
    return 1;
}

// Decode one varint at buf[*pos]; advances *pos.  Returns 1 on success,
// 0 on truncation or a value that does not fit uint64 exactly (a 10th
// byte above 0x01 encodes bits past 2^64 — the runtimes disagree on
// those, so we punt).
static int read_varint(const uint8_t *buf, size_t len, size_t *pos,
                       uint64_t *out) {
    uint64_t v = 0;
    int shift = 0;
    size_t i = *pos;
    for (int k = 0; k < 10; ++k) {
        if (i >= len) return 0;
        uint8_t b = buf[i++];
        if (k == 9 && b > 0x01) return 0;
        v |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) { *pos = i; *out = v; return 1; }
        shift += 7;
    }
    return 0;  // 10 continuation bytes: malformed
}

// (field, wiretype) -> bucket index for one kind; -2 = punt,
// -1 = handled elsewhere (ids / mint varint paths).
static int classify(int kind, uint64_t field, int wt,
                    int *is_string, int *is_ids, int *is_mint) {
    *is_string = 0; *is_ids = 0; *is_mint = 0;
    if (kind == WIRE_CHALLENGE) {
        if (field == 1 && wt == WT_LEN) { *is_string = 1; return 0; }
        return -2;
    }
    if (kind == WIRE_BATCH_VERIFY) {
        if (field == 1 && wt == WT_LEN) { *is_string = 1; return 0; }
        if (field == 2 && wt == WT_LEN) return 1;
        if (field == 3 && wt == WT_LEN) return 2;
        return -2;
    }
    if (kind == WIRE_STREAM_CHUNK) {
        if (field == 1 && (wt == WT_LEN || wt == WT_VARINT)) {
            *is_ids = 1; return -1;
        }
        if (field == 2 && wt == WT_LEN) { *is_string = 1; return 0; }
        if (field == 3 && wt == WT_LEN) return 1;
        if (field == 4 && wt == WT_LEN) return 2;
        if (field == 5 && wt == WT_VARINT) { *is_mint = 1; return -1; }
        return -2;
    }
    return -2;
}

// One scan over a message.  When counting (offs[0] == nullptr) it only
// tallies; when filling it writes the bucket rows/vals.  1 ok / 0 punt.
static int wire_walk(int kind, const uint8_t *buf, size_t len,
                     size_t counts[4],
                     uint64_t *offs[3], uint64_t *lens[3],
                     uint64_t *vals, uint8_t *flags) {
    size_t pos = 0, nb[3] = {0, 0, 0}, nv = 0;
    uint64_t mint = 0;
    int fill = offs != nullptr && offs[0] != nullptr;
    while (pos < len) {
        uint64_t tag;
        if (!read_varint(buf, len, &pos, &tag)) return 0;
        uint64_t field = tag >> 3;
        int wt = (int)(tag & 7);
        if (field == 0 || field > 0x1FFFFFFF) return 0;

        int is_string, is_ids, is_mint;
        int bucket = classify(kind, field, wt, &is_string, &is_ids, &is_mint);
        if (bucket == -2) return 0;

        if (is_mint) {
            uint64_t v;
            if (!read_varint(buf, len, &pos, &v)) return 0;
            mint = v;  // last occurrence wins (proto3 singular)
            continue;
        }
        if (is_ids && wt == WT_VARINT) {
            uint64_t v;
            if (!read_varint(buf, len, &pos, &v)) return 0;
            if (fill) vals[nv] = v;
            nv++;
            continue;
        }
        // length-delimited payload (string / bytes / packed ids)
        uint64_t flen;
        if (!read_varint(buf, len, &pos, &flen)) return 0;
        if (flen > len - pos) return 0;  // truncated payload
        if (is_ids) {  // packed varint block: must consume flen exactly
            size_t end = pos + (size_t)flen;
            while (pos < end) {
                uint64_t v;
                if (!read_varint(buf, end, &pos, &v)) return 0;
                if (fill) vals[nv] = v;
                nv++;
            }
            continue;
        }
        if (is_string && !utf8_valid(buf + pos, (size_t)flen)) return 0;
        if (fill) {
            offs[bucket][nb[bucket]] = (uint64_t)pos;
            lens[bucket][nb[bucket]] = flen;
        }
        nb[bucket]++;
        pos += (size_t)flen;
    }
    if (counts) {
        counts[0] = nb[0]; counts[1] = nb[1]; counts[2] = nb[2];
        counts[3] = nv;
    }
    if (flags) flags[0] = mint ? 1 : 0;
    return 1;
}

// Pass 1: bucket counts.  1 = the message is in this parser's recognized
// subset (counts filled), 0 = punt to the Python protobuf runtime.
int cpzk_wire_scan(int kind, const uint8_t *buf, size_t len,
                   size_t counts[4]) {
    return wire_walk(kind, buf, len, counts, nullptr, nullptr,
                     nullptr, nullptr);
}

// Pass 2: fill the arrays sized by pass 1.  Same walk, same verdict.
int cpzk_wire_fill(int kind, const uint8_t *buf, size_t len,
                   uint64_t *offs0, uint64_t *lens0,
                   uint64_t *offs1, uint64_t *lens1,
                   uint64_t *offs2, uint64_t *lens2,
                   uint64_t *vals, uint8_t *flags) {
    uint64_t *offs[3] = {offs0, offs1, offs2};
    uint64_t *lens[3] = {lens0, lens1, lens2};
    return wire_walk(kind, buf, len, nullptr, offs, lens, vals, flags);
}

// Concatenate n (offset, length) ranges of buf into out (caller sized
// it as the sum of lengths); returns bytes written.  The ranges come
// from cpzk_wire_fill, so they are in-bounds by construction — but the
// bound is re-checked anyway (buf_len) so a confused caller cannot
// make this read out of bounds.
size_t cpzk_wire_gather(const uint8_t *buf, size_t buf_len,
                        const uint64_t *offs, const uint64_t *lens,
                        size_t n, uint8_t *out) {
    size_t w = 0;
    for (size_t i = 0; i < n; ++i) {
        uint64_t off = offs[i], l = lens[i];
        if (off > buf_len || l > buf_len - off) return w;
        memcpy(out + w, buf + off, (size_t)l);
        w += (size_t)l;
    }
    return w;
}

}  // extern "C"
