// Host crypto core: Keccak-f[1600] + STROBE-128 + Merlin transcript framing.
//
// Byte-identical twin of the Python implementation in
// cpzk_tpu/core/{keccak,strobe,transcript}.py, which itself mirrors the
// merlin 3.0.0 crate used by the reference (src/primitives/transcript.rs,
// SURVEY.md §2.2). The batch entry point derives Fiat-Shamir challenges for
// whole proof batches on a thread pool — the host hot loop of batch
// verification (reference analog: src/verifier/batch.rs:239-260).
//
// C ABI only; bound from Python via ctypes (cpzk_tpu/core/_native.py).

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <thread>
#include <vector>

namespace {

constexpr int kStrobeR = 166;

constexpr uint64_t kRoundConstants[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808AULL,
    0x8000000080008000ULL, 0x000000000000808BULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008AULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000AULL,
    0x000000008000808BULL, 0x800000000000008BULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800AULL, 0x800000008000000AULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
};

constexpr int kRho[25] = {
    0, 1, 62, 28, 27, 36, 44, 6, 55, 20, 3, 10, 43,
    25, 39, 41, 45, 15, 21, 8, 18, 2, 61, 56, 14,
};

inline uint64_t rotl64(uint64_t v, int n) {
  n &= 63;
  return n == 0 ? v : (v << n) | (v >> (64 - n));
}

void keccak_f1600(uint64_t a[25]) {
  uint64_t b[25], c[5], d[5];
  for (uint64_t rc : kRoundConstants) {
    for (int x = 0; x < 5; x++)
      c[x] = a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20];
    for (int x = 0; x < 5; x++)
      d[x] = c[(x + 4) % 5] ^ rotl64(c[(x + 1) % 5], 1);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++) a[x + 5 * y] ^= d[x];
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        b[y + 5 * ((2 * x + 3 * y) % 5)] = rotl64(a[x + 5 * y], kRho[x + 5 * y]);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        a[x + 5 * y] = b[x + 5 * y] ^ (~b[(x + 1) % 5 + 5 * y] & b[(x + 2) % 5 + 5 * y]);
    a[0] ^= rc;
  }
}

struct Strobe128 {
  uint8_t state[200];
  uint8_t pos = 0;
  uint8_t pos_begin = 0;
  uint8_t cur_flags = 0;

  static constexpr uint8_t FLAG_I = 0x01, FLAG_A = 0x02, FLAG_C = 0x04,
                           FLAG_M = 0x10, FLAG_K = 0x20;

  explicit Strobe128(const uint8_t* label, size_t label_len) {
    std::memset(state, 0, sizeof(state));
    const uint8_t init[6] = {1, kStrobeR + 2, 1, 0, 1, 12 * 8};
    std::memcpy(state, init, 6);
    std::memcpy(state + 6, "STROBEv1.0.2", 12);
    permute();
    meta_ad(label, label_len, false);
  }

  void permute() {
    uint64_t lanes[25];
    for (int i = 0; i < 25; i++) {
      uint64_t v = 0;
      for (int j = 7; j >= 0; j--) v = (v << 8) | state[8 * i + j];
      lanes[i] = v;
    }
    keccak_f1600(lanes);
    for (int i = 0; i < 25; i++)
      for (int j = 0; j < 8; j++) state[8 * i + j] = (lanes[i] >> (8 * j)) & 0xFF;
  }

  void run_f() {
    state[pos] ^= pos_begin;
    state[pos + 1] ^= 0x04;
    state[kStrobeR + 1] ^= 0x80;
    permute();
    pos = 0;
    pos_begin = 0;
  }

  void absorb(const uint8_t* data, size_t n) {
    for (size_t i = 0; i < n; i++) {
      state[pos] ^= data[i];
      if (++pos == kStrobeR) run_f();
    }
  }

  void squeeze(uint8_t* out, size_t n) {
    for (size_t i = 0; i < n; i++) {
      out[i] = state[pos];
      state[pos] = 0;
      if (++pos == kStrobeR) run_f();
    }
  }

  void begin_op(uint8_t flags, bool more) {
    if (more) return;  // flag mismatch is a programming error; callers fixed
    uint8_t old_begin = pos_begin;
    pos_begin = pos + 1;
    cur_flags = flags;
    const uint8_t hdr[2] = {old_begin, flags};
    absorb(hdr, 2);
    if ((flags & (FLAG_C | FLAG_K)) != 0 && pos != 0) run_f();
  }

  void meta_ad(const uint8_t* data, size_t n, bool more) {
    begin_op(FLAG_M | FLAG_A, more);
    absorb(data, n);
  }
  void ad(const uint8_t* data, size_t n, bool more) {
    begin_op(FLAG_A, more);
    absorb(data, n);
  }
  void prf(uint8_t* out, size_t n) {
    begin_op(FLAG_I | FLAG_A | FLAG_C, false);
    squeeze(out, n);
  }
};

struct MerlinTranscript {
  Strobe128 strobe;

  explicit MerlinTranscript(const uint8_t* label, size_t label_len)
      : strobe(reinterpret_cast<const uint8_t*>("Merlin v1.0"), 11) {
    append_message(reinterpret_cast<const uint8_t*>("dom-sep"), 7, label, label_len);
  }

  void append_message(const uint8_t* label, size_t label_len,
                      const uint8_t* msg, size_t msg_len) {
    uint8_t len_le[4] = {
        static_cast<uint8_t>(msg_len & 0xFF),
        static_cast<uint8_t>((msg_len >> 8) & 0xFF),
        static_cast<uint8_t>((msg_len >> 16) & 0xFF),
        static_cast<uint8_t>((msg_len >> 24) & 0xFF),
    };
    strobe.meta_ad(label, label_len, false);
    strobe.meta_ad(len_le, 4, true);
    strobe.ad(msg, msg_len, false);
  }

  void challenge_bytes(const uint8_t* label, size_t label_len,
                       uint8_t* out, size_t n) {
    uint8_t len_le[4] = {
        static_cast<uint8_t>(n & 0xFF),
        static_cast<uint8_t>((n >> 8) & 0xFF),
        static_cast<uint8_t>((n >> 16) & 0xFF),
        static_cast<uint8_t>((n >> 24) & 0xFF),
    };
    strobe.meta_ad(label, label_len, false);
    strobe.meta_ad(len_le, 4, true);
    strobe.prf(out, n);
  }
};

constexpr char kProtocolLabel[] = "Chaum-Pedersen ZKP v1.0.0";
constexpr char kProtocolDst[] = "chaum-pedersen-ristretto255";

// One full Chaum-Pedersen challenge derivation
// (reference transcript sequence, src/primitives/transcript.rs:29-71).
void derive_one(const uint8_t* ctx, size_t ctx_len, bool has_ctx,
                const uint8_t* g, const uint8_t* h, const uint8_t* y1,
                const uint8_t* y2, const uint8_t* r1, const uint8_t* r2,
                uint8_t out[64]) {
  auto B = [](const char* s) { return reinterpret_cast<const uint8_t*>(s); };
  MerlinTranscript t(B(kProtocolLabel), sizeof(kProtocolLabel) - 1);
  t.append_message(B("protocol"), 8, B(kProtocolDst), sizeof(kProtocolDst) - 1);
  if (has_ctx) t.append_message(B("context"), 7, ctx, ctx_len);
  t.append_message(B("generator-g"), 11, g, 32);
  t.append_message(B("generator-h"), 11, h, 32);
  t.append_message(B("y1"), 2, y1, 32);
  t.append_message(B("y2"), 2, y2, 32);
  t.append_message(B("r1"), 2, r1, 32);
  t.append_message(B("r2"), 2, r2, 32);
  t.challenge_bytes(B("challenge"), 9, out, 64);
}

}  // namespace

extern "C" {

// --- incremental transcript API (ctypes handles) ---

void* cpzk_transcript_new(const uint8_t* protocol_label, size_t label_len) {
  return new MerlinTranscript(protocol_label, label_len);
}

void cpzk_transcript_free(void* t) {
  delete static_cast<MerlinTranscript*>(t);
}

void cpzk_transcript_append(void* t, const uint8_t* label, size_t label_len,
                            const uint8_t* msg, size_t msg_len) {
  static_cast<MerlinTranscript*>(t)->append_message(label, label_len, msg, msg_len);
}

void cpzk_transcript_challenge(void* t, const uint8_t* label, size_t label_len,
                               uint8_t* out, size_t n) {
  static_cast<MerlinTranscript*>(t)->challenge_bytes(label, label_len, out, n);
}

// --- batched Chaum-Pedersen challenge derivation (thread pool) ---
//
// ctxs: concatenated context bytes with ctx_offsets[n+1] prefix offsets;
// ctx_offsets == nullptr means "no context" for every row.  Point args are
// [n*32] contiguous compressed encodings; out is [n*64].
void cpzk_challenge_batch(size_t n, const uint8_t* ctxs,
                          const uint32_t* ctx_offsets, const uint8_t* has_ctx,
                          const uint8_t* gs, const uint8_t* hs,
                          const uint8_t* y1s, const uint8_t* y2s,
                          const uint8_t* r1s, const uint8_t* r2s,
                          uint8_t* out, int threads) {
  if (threads <= 0) {
    threads = static_cast<int>(std::thread::hardware_concurrency());
    if (threads <= 0) threads = 1;
  }
  if (static_cast<size_t>(threads) > n) threads = static_cast<int>(n ? n : 1);

  auto worker = [&](size_t lo, size_t hi) {
    for (size_t i = lo; i < hi; i++) {
      const uint8_t* ctx = nullptr;
      size_t ctx_len = 0;
      bool hc = false;
      if (ctx_offsets != nullptr && has_ctx != nullptr && has_ctx[i]) {
        ctx = ctxs + ctx_offsets[i];
        ctx_len = ctx_offsets[i + 1] - ctx_offsets[i];
        hc = true;
      }
      derive_one(ctx, ctx_len, hc, gs + 32 * i, hs + 32 * i, y1s + 32 * i,
                 y2s + 32 * i, r1s + 32 * i, r2s + 32 * i, out + 64 * i);
    }
  };

  if (threads == 1) {
    worker(0, n);
    return;
  }
  std::vector<std::thread> pool;
  size_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; t++) {
    size_t lo = t * chunk;
    size_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back(worker, lo, hi);
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"
