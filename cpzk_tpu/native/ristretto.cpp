// Host ristretto255 verification core (C++17, no dependencies).
//
// From-scratch implementation of the group arithmetic the reference gets
// from curve25519-dalek (SURVEY.md §2.2: field mod 2^255-19, extended
// Edwards points, RFC 9496 decode/encode, vartime scalar multiplication),
// specialised for the Chaum-Pedersen verification equations
//   s*G == R1 + c*Y1   and   s*H == R2 + c*Y2
// (reference analog: src/verifier/mod.rs:144-171).  Exposed as a C ABI with
// a pthread pool for batch rows; bit-exactness vs the integer-exact Python
// oracle is enforced by tests/test_native.py differential tests.
//
// Verification inputs are PUBLIC (statements, commitments, challenges,
// responses), so the variable-time paths (ge_scalarmul, cp_check_eq) leak
// nothing secret (docs/security.md).  Secret-scalar work — the prover's
// nonce commitment r1 = k*G, r2 = k*H and the statement derivation
// y1 = x*G, y2 = x*H — goes through the CONSTANT-TIME fixed-base comb
// (cpzk_basemul_init / cpzk_double_basemul below): signed radix-16 digits,
// full-table masked selection, mask-based conditional negation, no
// secret-dependent branches or memory addressing.

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <pthread.h>

extern "C" {

// ---------------------------------------------------------------------------
// field arithmetic mod p = 2^255 - 19, radix 2^51, 5 limbs
// ---------------------------------------------------------------------------

typedef unsigned __int128 u128;

struct fe {
    uint64_t v[5];
};

static const uint64_t MASK51 = (1ULL << 51) - 1;

static const fe FE_ZERO = {{0, 0, 0, 0, 0}};
static const fe FE_ONE = {{1, 0, 0, 0, 0}};
static const fe FE_D = {{929955233495203ULL, 466365720129213ULL, 1662059464998953ULL, 2033849074728123ULL, 1442794654840575ULL}};
static const fe FE_D2 = {{1859910466990425ULL, 932731440258426ULL, 1072319116312658ULL, 1815898335770999ULL, 633789495995903ULL}};
static const fe FE_SQRT_M1 = {{1718705420411056ULL, 234908883556509ULL, 2233514472574048ULL, 2117202627021982ULL, 765476049583133ULL}};
static const fe FE_INVSQRT_A_MINUS_D = {{278908739862762ULL, 821645201101625ULL, 8113234426968ULL, 1777959178193151ULL, 2118520810568447ULL}};

static void fe_add(fe &h, const fe &f, const fe &g) {
    for (int i = 0; i < 5; i++) h.v[i] = f.v[i] + g.v[i];
}

// h = f - g, assuming limbs of f, g < 2^52; adds 16p to keep limbs positive
static void fe_sub(fe &h, const fe &f, const fe &g) {
    const uint64_t p0 = 0x7FFFFFFFFFFEDULL * 16;  // 16 * (2^51 - 19)
    const uint64_t pi = 0x7FFFFFFFFFFFFULL * 16;  // 16 * (2^51 - 1)
    h.v[0] = f.v[0] + p0 - g.v[0];
    h.v[1] = f.v[1] + pi - g.v[1];
    h.v[2] = f.v[2] + pi - g.v[2];
    h.v[3] = f.v[3] + pi - g.v[3];
    h.v[4] = f.v[4] + pi - g.v[4];
}

// weak carry: brings limbs to < 2^52 (value unchanged mod p)
static void fe_carry(fe &h) {
    uint64_t c;
    for (int i = 0; i < 4; i++) {
        c = h.v[i] >> 51;
        h.v[i] &= MASK51;
        h.v[i + 1] += c;
    }
    c = h.v[4] >> 51;
    h.v[4] &= MASK51;
    h.v[0] += 19 * c;
    c = h.v[0] >> 51;
    h.v[0] &= MASK51;
    h.v[1] += c;
}

static void fe_mul(fe &h, const fe &f, const fe &g) {
    u128 t[5] = {0, 0, 0, 0, 0};
    for (int i = 0; i < 5; i++) {
        for (int j = 0; j < 5; j++) {
            int k = i + j;
            u128 prod = (u128)f.v[i] * g.v[j];
            if (k >= 5) {
                k -= 5;
                prod *= 19;
            }
            t[k] += prod;
        }
    }
    uint64_t c;
    uint64_t r[5];
    c = 0;
    for (int i = 0; i < 5; i++) {
        u128 acc = t[i] + c;
        r[i] = (uint64_t)acc & MASK51;
        c = (uint64_t)(acc >> 51);
    }
    r[0] += 19 * c;
    c = r[0] >> 51;
    r[0] &= MASK51;
    r[1] += c;
    for (int i = 0; i < 5; i++) h.v[i] = r[i];
}

static void fe_sq(fe &h, const fe &f) {
    // dedicated squaring: cross terms doubled, wrap terms folded by 19
    u128 f0 = f.v[0], f1 = f.v[1], f2 = f.v[2], f3 = f.v[3], f4 = f.v[4];
    u128 t0 = f0 * f0 + 38 * (f1 * f4 + f2 * f3);
    u128 t1 = 2 * f0 * f1 + 38 * (f2 * f4) + 19 * (f3 * f3);
    u128 t2 = 2 * f0 * f2 + f1 * f1 + 38 * (f3 * f4);
    u128 t3 = 2 * (f0 * f3 + f1 * f2) + 19 * (f4 * f4);
    u128 t4 = 2 * (f0 * f4 + f1 * f3) + f2 * f2;
    u128 t[5] = {t0, t1, t2, t3, t4};
    uint64_t c = 0, r[5];
    for (int i = 0; i < 5; i++) {
        u128 acc = t[i] + c;
        r[i] = (uint64_t)acc & MASK51;
        c = (uint64_t)(acc >> 51);
    }
    r[0] += 19 * c;
    c = r[0] >> 51;
    r[0] &= MASK51;
    r[1] += c;
    for (int i = 0; i < 5; i++) h.v[i] = r[i];
}

static void fe_neg(fe &h, const fe &f) { fe_sub(h, FE_ZERO, f); fe_carry(h); }

// canonical little-endian bytes
static void fe_tobytes(uint8_t *s, const fe &f) {
    fe t = f;
    fe_carry(t);
    // freeze: add 19, carry, subtract 2^255 - 19 via top-bit trick
    uint64_t q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51;
    t.v[0] += 19 * q;
    uint64_t c;
    for (int i = 0; i < 4; i++) {
        c = t.v[i] >> 51;
        t.v[i] &= MASK51;
        t.v[i + 1] += c;
    }
    t.v[4] &= MASK51;
    uint64_t lo[4];
    lo[0] = t.v[0] | (t.v[1] << 51);
    lo[1] = (t.v[1] >> 13) | (t.v[2] << 38);
    lo[2] = (t.v[2] >> 26) | (t.v[3] << 25);
    lo[3] = (t.v[3] >> 39) | (t.v[4] << 12);
    memcpy(s, lo, 32);
}

static void fe_frombytes(fe &h, const uint8_t *s) {
    uint64_t lo[4];
    memcpy(lo, s, 32);
    h.v[0] = lo[0] & MASK51;
    h.v[1] = ((lo[0] >> 51) | (lo[1] << 13)) & MASK51;
    h.v[2] = ((lo[1] >> 38) | (lo[2] << 26)) & MASK51;
    h.v[3] = ((lo[2] >> 25) | (lo[3] << 39)) & MASK51;
    h.v[4] = (lo[3] >> 12) & MASK51;  // drops bit 255
}

static int fe_isnegative(const fe &f) {
    uint8_t s[32];
    fe_tobytes(s, f);
    return s[0] & 1;
}

static int fe_iszero(const fe &f) {
    uint8_t s[32];
    fe_tobytes(s, f);
    uint8_t r = 0;
    for (int i = 0; i < 32; i++) r |= s[i];
    return r == 0;
}

static int fe_eq(const fe &f, const fe &g) {
    uint8_t a[32], b[32];
    fe_tobytes(a, f);
    fe_tobytes(b, g);
    return memcmp(a, b, 32) == 0;
}

static void fe_abs(fe &h, const fe &f) {
    if (fe_isnegative(f)) fe_neg(h, f); else h = f;
}

// h = f^(2^252 - 3)  ((p-5)/8 exponent), standard chain
static void fe_pow2523(fe &h, const fe &f) {
    fe t0, t1, t2;
    fe_sq(t0, f);                                      // 2
    fe_sq(t1, t0); fe_sq(t1, t1);                      // 8
    fe_mul(t1, f, t1);                                 // 9
    fe_mul(t0, t0, t1);                                // 11
    fe_sq(t0, t0);                                     // 22
    fe_mul(t0, t1, t0);                                // 31 = 2^5-1
    fe_sq(t1, t0);
    for (int i = 1; i < 5; i++) fe_sq(t1, t1);
    fe_mul(t0, t1, t0);                                // 2^10-1
    fe_sq(t1, t0);
    for (int i = 1; i < 10; i++) fe_sq(t1, t1);
    fe_mul(t1, t1, t0);                                // 2^20-1
    fe_sq(t2, t1);
    for (int i = 1; i < 20; i++) fe_sq(t2, t2);
    fe_mul(t1, t2, t1);                                // 2^40-1
    fe_sq(t1, t1);
    for (int i = 1; i < 10; i++) fe_sq(t1, t1);
    fe_mul(t0, t1, t0);                                // 2^50-1
    fe_sq(t1, t0);
    for (int i = 1; i < 50; i++) fe_sq(t1, t1);
    fe_mul(t1, t1, t0);                                // 2^100-1
    fe_sq(t2, t1);
    for (int i = 1; i < 100; i++) fe_sq(t2, t2);
    fe_mul(t1, t2, t1);                                // 2^200-1
    fe_sq(t1, t1);
    for (int i = 1; i < 50; i++) fe_sq(t1, t1);
    fe_mul(t0, t1, t0);                                // 2^250-1
    fe_sq(t0, t0); fe_sq(t0, t0);                      // 2^252-4
    fe_mul(h, t0, f);                                  // 2^252-3
}

// (was_square, r) = SQRT_RATIO_M1(u, v)  (RFC 9496 §3.1)
static int fe_sqrt_ratio_m1(fe &r, const fe &u, const fe &v) {
    fe v3, v7, t, check, neg_u, neg_u_i;
    fe_sq(v3, v); fe_mul(v3, v3, v);          // v^3
    fe_sq(v7, v3); fe_mul(v7, v7, v);         // v^7
    fe_mul(t, u, v7);
    fe_pow2523(t, t);                          // (u v^7)^((p-5)/8)
    fe_mul(t, t, v3);
    fe_mul(r, t, u);                           // u v^3 (u v^7)^((p-5)/8)
    fe_sq(check, r); fe_mul(check, check, v);  // v r^2
    fe_neg(neg_u, u);
    fe_mul(neg_u_i, neg_u, FE_SQRT_M1);
    int correct = fe_eq(check, u);
    int flipped = fe_eq(check, neg_u);
    int flipped_i = fe_eq(check, neg_u_i);
    if (flipped || flipped_i) fe_mul(r, r, FE_SQRT_M1);
    fe_abs(r, r);
    return correct | flipped;
}

// ---------------------------------------------------------------------------
// extended Edwards points (a = -1), unified formulas
// ---------------------------------------------------------------------------

struct ge {
    fe X, Y, Z, T;
};

static void ge_identity(ge &p) {
    p.X = FE_ZERO;
    p.Y = FE_ONE;
    p.Z = FE_ONE;
    p.T = FE_ZERO;
}

// add-2008-hwcd-3 (twin of cpzk_tpu.core.edwards.pt_add)
static void ge_add(ge &r, const ge &p, const ge &q) {
    fe a, b, c, d, e, f, g, h, t;
    fe_sub(t, p.Y, p.X); fe_carry(t);
    fe_sub(a, q.Y, q.X); fe_carry(a);
    fe_mul(a, t, a);
    fe_add(t, p.Y, p.X);
    fe_add(b, q.Y, q.X);
    fe_mul(b, t, b);
    fe_mul(c, p.T, FE_D2);
    fe_mul(c, c, q.T);
    fe_mul(d, p.Z, q.Z);
    fe_add(d, d, d);
    fe_carry(d);
    fe_sub(e, b, a); fe_carry(e);
    fe_sub(f, d, c); fe_carry(f);
    fe_add(g, d, c); fe_carry(g);
    fe_add(h, b, a); fe_carry(h);
    fe_mul(r.X, e, f);
    fe_mul(r.Y, g, h);
    fe_mul(r.Z, f, g);
    fe_mul(r.T, e, h);
}

// dbl-2008-hwcd (twin of cpzk_tpu.core.edwards.pt_double)
static void ge_double(ge &r, const ge &p) {
    fe a, b, c, e, f, g, h, t;
    fe_sq(a, p.X);
    fe_sq(b, p.Y);
    fe_sq(c, p.Z);
    fe_add(c, c, c);
    fe_carry(c);
    fe_add(h, a, b); fe_carry(h);
    fe_add(t, p.X, p.Y); fe_carry(t);
    fe_sq(t, t);
    fe_sub(e, h, t); fe_carry(e);
    fe_sub(g, a, b); fe_carry(g);
    fe_add(f, c, g); fe_carry(f);
    fe_mul(r.X, e, f);
    fe_mul(r.Y, g, h);
    fe_mul(r.Z, f, g);
    fe_mul(r.T, e, h);
}

static void ge_neg(ge &r, const ge &p) {
    fe_neg(r.X, p.X);
    r.Y = p.Y;
    r.Z = p.Z;
    fe_neg(r.T, p.T);
}

static int ge_is_identity(const ge &p) {
    return fe_iszero(p.X) || fe_iszero(p.Y);
}

// RFC 9496 §4.3.1 DECODE; returns 0 on invalid encodings
static int ge_decode(ge &p, const uint8_t *bytes) {
    fe s;
    fe_frombytes(s, bytes);
    // canonical check: re-encode must reproduce (also catches bit 255)
    uint8_t check[32];
    fe_tobytes(check, s);
    if (memcmp(check, bytes, 32) != 0) return 0;
    if (bytes[0] & 1) return 0;  // negative s

    fe ss, u1, u2, u2s, v, i, dx, dy, x, y, t, tmp;
    fe_sq(ss, s);
    fe_sub(u1, FE_ONE, ss); fe_carry(u1);          // 1 - s^2
    fe_add(u2, FE_ONE, ss); fe_carry(u2);          // 1 + s^2
    fe_sq(u2s, u2);                                 // u2^2
    fe_sq(tmp, u1);
    fe_mul(tmp, tmp, FE_D);
    fe_neg(tmp, tmp);                               // -d u1^2
    fe_sub(v, tmp, u2s); fe_carry(v);               // -(d u1^2) - u2^2
    fe_mul(tmp, v, u2s);                            // v u2^2
    int was_square = fe_sqrt_ratio_m1(i, FE_ONE, tmp);
    fe_mul(dx, i, u2);                              // den_x
    fe_mul(dy, i, dx);
    fe_mul(dy, dy, v);                              // den_y
    fe_add(tmp, s, s);
    fe_carry(tmp);
    fe_mul(x, tmp, dx);                             // 2 s den_x
    fe_abs(x, x);
    fe_mul(y, u1, dy);
    fe_mul(t, x, y);
    if (!was_square || fe_isnegative(t) || fe_iszero(y)) return 0;
    p.X = x;
    p.Y = y;
    p.Z = FE_ONE;
    p.T = t;
    return 1;
}

// RFC 9496 §4.3.2 ENCODE
static void ge_encode(uint8_t *out, const ge &p) {
    fe u1, u2, isr, d1, d2, zinv, ix, iy, eden, tz, x, y, dinv, s, tmp;
    fe_add(tmp, p.Z, p.Y); fe_carry(tmp);
    fe_sub(u1, p.Z, p.Y); fe_carry(u1);
    fe_mul(u1, tmp, u1);                    // (Z+Y)(Z-Y)
    fe_mul(u2, p.X, p.Y);                   // XY
    fe_sq(tmp, u2);
    fe_mul(tmp, u1, tmp);                   // u1 u2^2
    fe_sqrt_ratio_m1(isr, FE_ONE, tmp);
    fe_mul(d1, isr, u1);
    fe_mul(d2, isr, u2);
    fe_mul(zinv, d1, d2);
    fe_mul(zinv, zinv, p.T);                // den1 den2 T
    fe_mul(ix, p.X, FE_SQRT_M1);
    fe_mul(iy, p.Y, FE_SQRT_M1);
    fe_mul(eden, d1, FE_INVSQRT_A_MINUS_D);
    fe_mul(tz, p.T, zinv);
    int rotate = fe_isnegative(tz);
    if (rotate) {
        x = iy;
        y = ix;
        dinv = eden;
    } else {
        x = p.X;
        y = p.Y;
        dinv = d2;
    }
    fe_mul(tmp, x, zinv);
    if (fe_isnegative(tmp)) fe_neg(y, y);
    fe_sub(s, p.Z, y); fe_carry(s);
    fe_mul(s, dinv, s);
    fe_abs(s, s);
    fe_tobytes(out, s);
}

// ---------------------------------------------------------------------------
// constant-time fixed-base comb for the generators G and H
// ---------------------------------------------------------------------------
//
// Per base: tbl[i][j] = (j+1) * 16^i * B for i in 0..63, j in 0..7.  A
// canonical scalar (< L < 2^253) recodes to 64 signed radix-16 digits in
// [-8, 8); the product is a sum of 64 table entries — no doublings at all.
// Selection scans the full 8-entry window with arithmetic masks; negation
// is mask-based.  The adds use the same unified formulas as the vartime
// path (identity-safe), so a zero digit simply adds the masked-in identity.

static void fe_cmov(fe &f, const fe &g, uint64_t mask) {
    for (int i = 0; i < 5; i++) f.v[i] ^= mask & (f.v[i] ^ g.v[i]);
}

static void ge_cmov(ge &r, const ge &p, uint64_t mask) {
    fe_cmov(r.X, p.X, mask);
    fe_cmov(r.Y, p.Y, mask);
    fe_cmov(r.Z, p.Z, mask);
    fe_cmov(r.T, p.T, mask);
}

// all-ones when a == b (a, b in [0, 255]); branchless
static uint64_t ct_eq_mask(uint64_t a, uint64_t b) {
    uint64_t d = a ^ b;
    return (uint64_t)0 - (((d - 1) & ~d) >> 63);
}

struct comb_table {
    ge tbl[64][8];
    uint8_t wire[32];   // which generator this table is for
    int ready;
};

static comb_table COMB_G = {{}, {0}, 0};
static comb_table COMB_H = {{}, {0}, 0};
// Guards the global tables: ctypes releases the GIL around foreign calls,
// so concurrent Python threads CAN race a rebuild against a multiply.
// Rebuilds take the write lock, multiplies the read lock.
static pthread_rwlock_t COMB_LOCK = PTHREAD_RWLOCK_INITIALIZER;

static void comb_build(comb_table &t, const ge &base, const uint8_t *wire) {
    ge cur = base;                       // 16^i * B
    for (int i = 0; i < 64; i++) {
        t.tbl[i][0] = cur;
        for (int j = 1; j < 8; j++) ge_add(t.tbl[i][j], t.tbl[i][j - 1], cur);
        ge next = t.tbl[i][7];           // 8 * 16^i * B
        ge_double(next, next);           // 16^(i+1) * B
        cur = next;
    }
    memcpy(t.wire, wire, 32);
    t.ready = 1;
}

// signed radix-16 recoding of a canonical (< 2^253) little-endian scalar
static void recode_radix16(int8_t digits[64], const uint8_t *s) {
    for (int i = 0; i < 32; i++) {
        digits[2 * i] = (int8_t)(s[i] & 15);
        digits[2 * i + 1] = (int8_t)((s[i] >> 4) & 15);
    }
    int8_t carry = 0;
    for (int i = 0; i < 63; i++) {
        digits[i] = (int8_t)(digits[i] + carry);
        carry = (int8_t)((digits[i] + 8) >> 4);
        digits[i] = (int8_t)(digits[i] - (carry << 4));
    }
    digits[63] = (int8_t)(digits[63] + carry);  // < 8 since s < 2^253
}

// constant-time: r = sum_i digits[i] * 16^i * B via masked table scan
static void comb_mul(ge &r, const comb_table &t, const int8_t digits[64]) {
    ge_identity(r);
    for (int i = 0; i < 64; i++) {
        int8_t d = digits[i];
        uint64_t neg = (uint64_t)0 - (uint64_t)(((uint8_t)d) >> 7);
        uint8_t babs = (uint8_t)((d ^ (d >> 7)) - (d >> 7));
        ge sel;
        ge_identity(sel);
        for (int j = 0; j < 8; j++)
            ge_cmov(sel, t.tbl[i][j], ct_eq_mask(babs, (uint64_t)j + 1));
        ge nsel;
        ge_neg(nsel, sel);
        ge_cmov(sel, nsel, neg);
        ge s2;
        ge_add(s2, r, sel);
        r = s2;
    }
}

// tables ready for this generator pair? (caller holds COMB_LOCK)
static int comb_current(const uint8_t *g_wire, const uint8_t *h_wire) {
    return COMB_G.ready && COMB_H.ready &&
           memcmp(COMB_G.wire, g_wire, 32) == 0 &&
           memcmp(COMB_H.wire, h_wire, 32) == 0;
}

// Build (or rebuild) the comb tables for the generator pair.  Returns 1 on
// success, 0 if either encoding fails to decode.  Thread-safe: rebuilds
// run under the table write lock.
int cpzk_basemul_init(const uint8_t *g_wire, const uint8_t *h_wire) {
    pthread_rwlock_rdlock(&COMB_LOCK);
    int current = comb_current(g_wire, h_wire);
    pthread_rwlock_unlock(&COMB_LOCK);
    if (current) return 1;
    ge G, H;
    if (!ge_decode(G, g_wire) || !ge_decode(H, h_wire)) return 0;
    pthread_rwlock_wrlock(&COMB_LOCK);
    if (!comb_current(g_wire, h_wire)) {
        comb_build(COMB_G, G, g_wire);
        comb_build(COMB_H, H, h_wire);
    }
    pthread_rwlock_unlock(&COMB_LOCK);
    return 1;
}

// out1 = s*G, out2 = s*H (wire bytes), constant time in s.  Builds the
// tables when missing or built for different generators (one atomic call —
// no init-then-mul race window); returns 0 only when a generator encoding
// is invalid.
int cpzk_double_basemul(const uint8_t *g_wire, const uint8_t *h_wire,
                        const uint8_t *scalar, uint8_t *out1, uint8_t *out2) {
    pthread_rwlock_rdlock(&COMB_LOCK);
    if (!comb_current(g_wire, h_wire)) {
        pthread_rwlock_unlock(&COMB_LOCK);
        if (!cpzk_basemul_init(g_wire, h_wire)) return 0;
        pthread_rwlock_rdlock(&COMB_LOCK);
        if (!comb_current(g_wire, h_wire)) {
            // another thread swapped in a different pair between our build
            // and this read lock; give up rather than loop unboundedly
            pthread_rwlock_unlock(&COMB_LOCK);
            return 0;
        }
    }
    int8_t digits[64];
    recode_radix16(digits, scalar);
    ge r1, r2;
    comb_mul(r1, COMB_G, digits);
    comb_mul(r2, COMB_H, digits);
    pthread_rwlock_unlock(&COMB_LOCK);
    ge_encode(out1, r1);
    ge_encode(out2, r2);
    return 1;
}

// variable-base, variable-time scalar mul: 4-bit fixed windows, scalar is
// 32 canonical little-endian bytes (public verification input)
static void ge_scalarmul(ge &r, const ge &p, const uint8_t *scalar) {
    ge table[16];
    ge_identity(table[0]);
    table[1] = p;
    for (int i = 2; i < 16; i++) ge_add(table[i], table[i - 1], p);
    ge_identity(r);
    for (int i = 63; i >= 0; i--) {
        int byte = scalar[i >> 1];
        int nib = (i & 1) ? (byte >> 4) : (byte & 0x0F);
        ge_double(r, r);
        ge_double(r, r);
        ge_double(r, r);
        ge_double(r, r);
        if (nib) {
            ge t;
            ge_add(t, r, table[nib]);
            r = t;
        }
    }
}

// ---------------------------------------------------------------------------
// Chaum-Pedersen row verification + threaded batch entry point
// ---------------------------------------------------------------------------

// 1..15 multiples table for the Straus ladder (slot 0 = identity)
static void straus_table(ge tb[16], const ge &B) {
    ge_identity(tb[0]);
    tb[1] = B;
    for (int i = 2; i < 16; i++) ge_add(tb[i], tb[i - 1], B);
}

// one equation: s*B == R + c*Y  <=>  s*B + c*(-Y) - R == identity.
// Straus shared-doubling: one 255-double ladder with two 4-bit tables
// (~half the doublings of two independent scalar muls).  The base table
// ``tb`` ({1..15}*B) is precomputed once per batch — B is the shared
// generator G or H, so rebuilding it per row would waste 15 adds/row.
static int cp_check_eq(const ge tb[16], const ge &Y, const ge &R,
                       const uint8_t *s, const uint8_t *c) {
    ge ty[16], nY, acc, nR;
    ge_neg(nY, Y);
    straus_table(ty, nY);
    ge_identity(acc);
    for (int i = 63; i >= 0; i--) {
        int sb = s[i >> 1], cb = c[i >> 1];
        int ns = (i & 1) ? (sb >> 4) : (sb & 0x0F);
        int nc = (i & 1) ? (cb >> 4) : (cb & 0x0F);
        ge_double(acc, acc);
        ge_double(acc, acc);
        ge_double(acc, acc);
        ge_double(acc, acc);
        if (ns) {
            ge t;
            ge_add(t, acc, tb[ns]);
            acc = t;
        }
        if (nc) {
            ge t;
            ge_add(t, acc, ty[nc]);
            acc = t;
        }
    }
    ge_neg(nR, R);
    ge_add(acc, acc, nR);
    return ge_is_identity(acc);
}

struct row_job {
    const uint8_t *g, *h;          // 32B each (shared generators)
    const uint8_t *y1, *y2, *r1, *r2, *s, *c;  // n x 32B arrays
    uint8_t *out;
    size_t n;
    size_t next;           // work index (mutex-guarded)
    pthread_mutex_t lock;
    ge tbG[16], tbH[16];   // shared Straus tables for the generators
    int gh_ok;
};

static void *row_worker(void *arg) {
    row_job *job = (row_job *)arg;
    for (;;) {
        pthread_mutex_lock(&job->lock);
        size_t i = job->next++;
        pthread_mutex_unlock(&job->lock);
        if (i >= job->n) return nullptr;

        ge y1, y2, r1, r2;
        if (!job->gh_ok ||
            !ge_decode(y1, job->y1 + 32 * i) || !ge_decode(y2, job->y2 + 32 * i) ||
            !ge_decode(r1, job->r1 + 32 * i) || !ge_decode(r2, job->r2 + 32 * i)) {
            job->out[i] = 0;
            continue;
        }
        const uint8_t *s = job->s + 32 * i;
        const uint8_t *c = job->c + 32 * i;
        job->out[i] = cp_check_eq(job->tbG, y1, r1, s, c) &&
                      cp_check_eq(job->tbH, y2, r2, s, c);
    }
}

// Verify n Chaum-Pedersen rows; returns 0 on success, out[i] in {0,1}.
// All inputs are 32-byte wire encodings; g/h are shared across the batch.
int cpzk_verify_rows(size_t n, const uint8_t *g, const uint8_t *h,
                     const uint8_t *y1, const uint8_t *y2,
                     const uint8_t *r1, const uint8_t *r2,
                     const uint8_t *s, const uint8_t *c,
                     uint8_t *out, int n_threads) {
    row_job job;
    job.g = g; job.h = h;
    job.y1 = y1; job.y2 = y2; job.r1 = r1; job.r2 = r2;
    job.s = s; job.c = c;
    job.out = out;
    job.n = n;
    job.next = 0;
    pthread_mutex_init(&job.lock, nullptr);
    ge G, H;
    job.gh_ok = ge_decode(G, g) && ge_decode(H, h);
    if (job.gh_ok) {
        straus_table(job.tbG, G);
        straus_table(job.tbH, H);
    }

    if (n_threads < 1) n_threads = 1;
    if ((size_t)n_threads > n) n_threads = (int)n;
    if (n_threads == 1) {
        row_worker(&job);
    } else {
        pthread_t *tids = (pthread_t *)malloc(sizeof(pthread_t) * n_threads);
        int spawned = 0;
        if (tids != nullptr) {
            for (int t = 0; t < n_threads - 1; t++) {
                if (pthread_create(&tids[spawned], nullptr, row_worker, &job) != 0)
                    break;  // thread exhaustion: keep whatever we got
                spawned++;
            }
        }
        row_worker(&job);  // this thread always participates
        for (int t = 0; t < spawned; t++) pthread_join(tids[t], nullptr);
        free(tids);
    }
    pthread_mutex_destroy(&job.lock);
    return 0;
}

// --- small self-check helpers exposed for differential tests ---------------

// decode -> encode round trip; returns 1 if input decodes validly
int cpzk_point_roundtrip(const uint8_t *in, uint8_t *out) {
    ge p;
    if (!ge_decode(p, in)) return 0;
    ge_encode(out, p);
    return 1;
}

// out = scalar * P (all wire bytes); returns 0 on decode failure
int cpzk_scalarmul(const uint8_t *point, const uint8_t *scalar, uint8_t *out) {
    ge p, r;
    if (!ge_decode(p, point)) return 0;
    ge_scalarmul(r, p, scalar);
    ge_encode(out, r);
    return 1;
}

// out = P + Q (wire bytes); returns 0 on decode failure
int cpzk_point_add(const uint8_t *a, const uint8_t *b, uint8_t *out) {
    ge p, q, r;
    if (!ge_decode(p, a) || !ge_decode(q, b)) return 0;
    ge_add(r, p, q);
    ge_encode(out, r);
    return 1;
}

}  // extern "C"
