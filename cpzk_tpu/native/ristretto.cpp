// Host ristretto255 verification core (C++17, no dependencies).
//
// From-scratch implementation of the group arithmetic the reference gets
// from curve25519-dalek (SURVEY.md §2.2: field mod 2^255-19, extended
// Edwards points, RFC 9496 decode/encode, vartime scalar multiplication),
// specialised for the Chaum-Pedersen verification equations
//   s*G == R1 + c*Y1   and   s*H == R2 + c*Y2
// (reference analog: src/verifier/mod.rs:144-171).  Exposed as a C ABI with
// a pthread pool for batch rows; bit-exactness vs the integer-exact Python
// oracle is enforced by tests/test_native.py differential tests.
//
// Verification inputs are PUBLIC (statements, commitments, challenges,
// responses), so the variable-time paths (ge_scalarmul, cp_check_eq) leak
// nothing secret (docs/security.md).  Secret-scalar work — the prover's
// nonce commitment r1 = k*G, r2 = k*H and the statement derivation
// y1 = x*G, y2 = x*H — goes through the CONSTANT-TIME fixed-base comb
// (cpzk_basemul_init / cpzk_double_basemul below): signed radix-16 digits,
// full-table masked selection, mask-based conditional negation, no
// secret-dependent branches or memory addressing.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <fcntl.h>
#include <pthread.h>
#include <unistd.h>
#if defined(__linux__)
#include <sys/random.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// field arithmetic mod p = 2^255 - 19, radix 2^51, 5 limbs
// ---------------------------------------------------------------------------

typedef unsigned __int128 u128;

struct fe {
    uint64_t v[5];
};

static const uint64_t MASK51 = (1ULL << 51) - 1;

static const fe FE_ZERO = {{0, 0, 0, 0, 0}};
static const fe FE_ONE = {{1, 0, 0, 0, 0}};
static const fe FE_D = {{929955233495203ULL, 466365720129213ULL, 1662059464998953ULL, 2033849074728123ULL, 1442794654840575ULL}};
static const fe FE_D2 = {{1859910466990425ULL, 932731440258426ULL, 1072319116312658ULL, 1815898335770999ULL, 633789495995903ULL}};
static const fe FE_SQRT_M1 = {{1718705420411056ULL, 234908883556509ULL, 2233514472574048ULL, 2117202627021982ULL, 765476049583133ULL}};
static const fe FE_INVSQRT_A_MINUS_D = {{278908739862762ULL, 821645201101625ULL, 8113234426968ULL, 1777959178193151ULL, 2118520810568447ULL}};

static void fe_add(fe &h, const fe &f, const fe &g) {
    for (int i = 0; i < 5; i++) h.v[i] = f.v[i] + g.v[i];
}

// h = f - g, assuming limbs of f, g < 2^52; adds 16p to keep limbs positive
static void fe_sub(fe &h, const fe &f, const fe &g) {
    const uint64_t p0 = 0x7FFFFFFFFFFEDULL * 16;  // 16 * (2^51 - 19)
    const uint64_t pi = 0x7FFFFFFFFFFFFULL * 16;  // 16 * (2^51 - 1)
    h.v[0] = f.v[0] + p0 - g.v[0];
    h.v[1] = f.v[1] + pi - g.v[1];
    h.v[2] = f.v[2] + pi - g.v[2];
    h.v[3] = f.v[3] + pi - g.v[3];
    h.v[4] = f.v[4] + pi - g.v[4];
}

// weak carry: brings limbs to < 2^52 (value unchanged mod p)
static void fe_carry(fe &h) {
    uint64_t c;
    for (int i = 0; i < 4; i++) {
        c = h.v[i] >> 51;
        h.v[i] &= MASK51;
        h.v[i + 1] += c;
    }
    c = h.v[4] >> 51;
    h.v[4] &= MASK51;
    h.v[0] += 19 * c;
    c = h.v[0] >> 51;
    h.v[0] &= MASK51;
    h.v[1] += c;
}

static void fe_mul(fe &h, const fe &f, const fe &g) {
    // donna-style: fold the 19x wrap into pre-scaled u64 factors.  Real
    // headroom (not the tight reduced-form bound): callers routinely pass
    // uncarried fe_add/fe_sub outputs as g (e.g. ge_add's fe_add(b, q.Y,
    // q.X) with limbs up to ~2^56), so the requirement is g[j] < 2^59
    // (19*g[j] < 2^64 stays a u64) and f[j] < ~2^57 (each of the 5
    // products per accumulator < 2^123, so the u128 sums cannot wrap).
    const uint64_t f0 = f.v[0], f1 = f.v[1], f2 = f.v[2], f3 = f.v[3], f4 = f.v[4];
    const uint64_t g0 = g.v[0], g1 = g.v[1], g2 = g.v[2], g3 = g.v[3], g4 = g.v[4];
    const uint64_t g1_19 = 19 * g1, g2_19 = 19 * g2, g3_19 = 19 * g3, g4_19 = 19 * g4;
    u128 t0 = (u128)f0 * g0 + (u128)f1 * g4_19 + (u128)f2 * g3_19 + (u128)f3 * g2_19 + (u128)f4 * g1_19;
    u128 t1 = (u128)f0 * g1 + (u128)f1 * g0 + (u128)f2 * g4_19 + (u128)f3 * g3_19 + (u128)f4 * g2_19;
    u128 t2 = (u128)f0 * g2 + (u128)f1 * g1 + (u128)f2 * g0 + (u128)f3 * g4_19 + (u128)f4 * g3_19;
    u128 t3 = (u128)f0 * g3 + (u128)f1 * g2 + (u128)f2 * g1 + (u128)f3 * g0 + (u128)f4 * g4_19;
    u128 t4 = (u128)f0 * g4 + (u128)f1 * g3 + (u128)f2 * g2 + (u128)f3 * g1 + (u128)f4 * g0;
    u128 t[5] = {t0, t1, t2, t3, t4};
    uint64_t c = 0, r[5];
    for (int i = 0; i < 5; i++) {
        u128 acc = t[i] + c;
        r[i] = (uint64_t)acc & MASK51;
        c = (uint64_t)(acc >> 51);
    }
    r[0] += 19 * c;
    c = r[0] >> 51;
    r[0] &= MASK51;
    r[1] += c;
    for (int i = 0; i < 5; i++) h.v[i] = r[i];
}

static void fe_sq(fe &h, const fe &f) {
    // dedicated squaring: cross terms doubled, wrap terms folded by 19
    u128 f0 = f.v[0], f1 = f.v[1], f2 = f.v[2], f3 = f.v[3], f4 = f.v[4];
    u128 t0 = f0 * f0 + 38 * (f1 * f4 + f2 * f3);
    u128 t1 = 2 * f0 * f1 + 38 * (f2 * f4) + 19 * (f3 * f3);
    u128 t2 = 2 * f0 * f2 + f1 * f1 + 38 * (f3 * f4);
    u128 t3 = 2 * (f0 * f3 + f1 * f2) + 19 * (f4 * f4);
    u128 t4 = 2 * (f0 * f4 + f1 * f3) + f2 * f2;
    u128 t[5] = {t0, t1, t2, t3, t4};
    uint64_t c = 0, r[5];
    for (int i = 0; i < 5; i++) {
        u128 acc = t[i] + c;
        r[i] = (uint64_t)acc & MASK51;
        c = (uint64_t)(acc >> 51);
    }
    r[0] += 19 * c;
    c = r[0] >> 51;
    r[0] &= MASK51;
    r[1] += c;
    for (int i = 0; i < 5; i++) h.v[i] = r[i];
}

static void fe_neg(fe &h, const fe &f) { fe_sub(h, FE_ZERO, f); fe_carry(h); }

// canonical little-endian bytes
static void fe_tobytes(uint8_t *s, const fe &f) {
    fe t = f;
    fe_carry(t);
    // freeze: add 19, carry, subtract 2^255 - 19 via top-bit trick
    uint64_t q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51;
    t.v[0] += 19 * q;
    uint64_t c;
    for (int i = 0; i < 4; i++) {
        c = t.v[i] >> 51;
        t.v[i] &= MASK51;
        t.v[i + 1] += c;
    }
    t.v[4] &= MASK51;
    uint64_t lo[4];
    lo[0] = t.v[0] | (t.v[1] << 51);
    lo[1] = (t.v[1] >> 13) | (t.v[2] << 38);
    lo[2] = (t.v[2] >> 26) | (t.v[3] << 25);
    lo[3] = (t.v[3] >> 39) | (t.v[4] << 12);
    memcpy(s, lo, 32);
}

static void fe_frombytes(fe &h, const uint8_t *s) {
    uint64_t lo[4];
    memcpy(lo, s, 32);
    h.v[0] = lo[0] & MASK51;
    h.v[1] = ((lo[0] >> 51) | (lo[1] << 13)) & MASK51;
    h.v[2] = ((lo[1] >> 38) | (lo[2] << 26)) & MASK51;
    h.v[3] = ((lo[2] >> 25) | (lo[3] << 39)) & MASK51;
    h.v[4] = (lo[3] >> 12) & MASK51;  // drops bit 255
}

static int fe_isnegative(const fe &f) {
    uint8_t s[32];
    fe_tobytes(s, f);
    return s[0] & 1;
}

static int fe_iszero(const fe &f) {
    uint8_t s[32];
    fe_tobytes(s, f);
    uint8_t r = 0;
    for (int i = 0; i < 32; i++) r |= s[i];
    return r == 0;
}

static int fe_eq(const fe &f, const fe &g) {
    uint8_t a[32], b[32];
    fe_tobytes(a, f);
    fe_tobytes(b, g);
    return memcmp(a, b, 32) == 0;
}

static void fe_abs(fe &h, const fe &f) {
    if (fe_isnegative(f)) fe_neg(h, f); else h = f;
}

// h = f^(2^252 - 3)  ((p-5)/8 exponent), standard chain
static void fe_pow2523(fe &h, const fe &f) {
    fe t0, t1, t2;
    fe_sq(t0, f);                                      // 2
    fe_sq(t1, t0); fe_sq(t1, t1);                      // 8
    fe_mul(t1, f, t1);                                 // 9
    fe_mul(t0, t0, t1);                                // 11
    fe_sq(t0, t0);                                     // 22
    fe_mul(t0, t1, t0);                                // 31 = 2^5-1
    fe_sq(t1, t0);
    for (int i = 1; i < 5; i++) fe_sq(t1, t1);
    fe_mul(t0, t1, t0);                                // 2^10-1
    fe_sq(t1, t0);
    for (int i = 1; i < 10; i++) fe_sq(t1, t1);
    fe_mul(t1, t1, t0);                                // 2^20-1
    fe_sq(t2, t1);
    for (int i = 1; i < 20; i++) fe_sq(t2, t2);
    fe_mul(t1, t2, t1);                                // 2^40-1
    fe_sq(t1, t1);
    for (int i = 1; i < 10; i++) fe_sq(t1, t1);
    fe_mul(t0, t1, t0);                                // 2^50-1
    fe_sq(t1, t0);
    for (int i = 1; i < 50; i++) fe_sq(t1, t1);
    fe_mul(t1, t1, t0);                                // 2^100-1
    fe_sq(t2, t1);
    for (int i = 1; i < 100; i++) fe_sq(t2, t2);
    fe_mul(t1, t2, t1);                                // 2^200-1
    fe_sq(t1, t1);
    for (int i = 1; i < 50; i++) fe_sq(t1, t1);
    fe_mul(t0, t1, t0);                                // 2^250-1
    fe_sq(t0, t0); fe_sq(t0, t0);                      // 2^252-4
    fe_mul(h, t0, f);                                  // 2^252-3
}

// h = f^(p-2) = 1/f (standard ed25519 inversion chain); only used for
// one-time table normalization, never on a hot path
static void fe_invert(fe &h, const fe &f) {
    fe t0, t1, t2, t3;
    fe_sq(t0, f);                                      // 2
    fe_sq(t1, t0); fe_sq(t1, t1);                      // 8
    fe_mul(t1, f, t1);                                 // 9
    fe_mul(t0, t0, t1);                                // 11
    fe_sq(t2, t0);                                     // 22
    fe_mul(t1, t1, t2);                                // 31 = 2^5-1
    fe_sq(t2, t1);
    for (int i = 1; i < 5; i++) fe_sq(t2, t2);
    fe_mul(t1, t2, t1);                                // 2^10-1
    fe_sq(t2, t1);
    for (int i = 1; i < 10; i++) fe_sq(t2, t2);
    fe_mul(t2, t2, t1);                                // 2^20-1
    fe_sq(t3, t2);
    for (int i = 1; i < 20; i++) fe_sq(t3, t3);
    fe_mul(t2, t3, t2);                                // 2^40-1
    fe_sq(t2, t2);
    for (int i = 1; i < 10; i++) fe_sq(t2, t2);
    fe_mul(t1, t2, t1);                                // 2^50-1
    fe_sq(t2, t1);
    for (int i = 1; i < 50; i++) fe_sq(t2, t2);
    fe_mul(t2, t2, t1);                                // 2^100-1
    fe_sq(t3, t2);
    for (int i = 1; i < 100; i++) fe_sq(t3, t3);
    fe_mul(t2, t3, t2);                                // 2^200-1
    fe_sq(t2, t2);
    for (int i = 1; i < 50; i++) fe_sq(t2, t2);
    fe_mul(t1, t2, t1);                                // 2^250-1
    fe_sq(t1, t1);
    for (int i = 1; i < 5; i++) fe_sq(t1, t1);         // 2^255-32
    fe_mul(h, t1, t0);                                 // 2^255-21 = p-2
}

// (was_square, r) = SQRT_RATIO_M1(u, v)  (RFC 9496 §3.1)
static int fe_sqrt_ratio_m1(fe &r, const fe &u, const fe &v) {
    fe v3, v7, t, check, neg_u, neg_u_i;
    fe_sq(v3, v); fe_mul(v3, v3, v);          // v^3
    fe_sq(v7, v3); fe_mul(v7, v7, v);         // v^7
    fe_mul(t, u, v7);
    fe_pow2523(t, t);                          // (u v^7)^((p-5)/8)
    fe_mul(t, t, v3);
    fe_mul(r, t, u);                           // u v^3 (u v^7)^((p-5)/8)
    fe_sq(check, r); fe_mul(check, check, v);  // v r^2
    fe_neg(neg_u, u);
    fe_mul(neg_u_i, neg_u, FE_SQRT_M1);
    int correct = fe_eq(check, u);
    int flipped = fe_eq(check, neg_u);
    int flipped_i = fe_eq(check, neg_u_i);
    if (flipped || flipped_i) fe_mul(r, r, FE_SQRT_M1);
    fe_abs(r, r);
    return correct | flipped;
}

// ---------------------------------------------------------------------------
// extended Edwards points (a = -1), unified formulas
// ---------------------------------------------------------------------------

struct ge {
    fe X, Y, Z, T;
};

static void ge_identity(ge &p) {
    p.X = FE_ZERO;
    p.Y = FE_ONE;
    p.Z = FE_ONE;
    p.T = FE_ZERO;
}

// add-2008-hwcd-3 (twin of cpzk_tpu.core.edwards.pt_add)
static void ge_add(ge &r, const ge &p, const ge &q) {
    fe a, b, c, d, e, f, g, h, t;
    fe_sub(t, p.Y, p.X); fe_carry(t);
    fe_sub(a, q.Y, q.X); fe_carry(a);
    fe_mul(a, t, a);
    fe_add(t, p.Y, p.X);
    fe_add(b, q.Y, q.X);
    fe_mul(b, t, b);
    fe_mul(c, p.T, FE_D2);
    fe_mul(c, c, q.T);
    fe_mul(d, p.Z, q.Z);
    fe_add(d, d, d);
    fe_carry(d);
    fe_sub(e, b, a); fe_carry(e);
    fe_sub(f, d, c); fe_carry(f);
    fe_add(g, d, c); fe_carry(g);
    fe_add(h, b, a); fe_carry(h);
    fe_mul(r.X, e, f);
    fe_mul(r.Y, g, h);
    fe_mul(r.Z, f, g);
    fe_mul(r.T, e, h);
}

// dbl-2008-hwcd (twin of cpzk_tpu.core.edwards.pt_double)
static void ge_double(ge &r, const ge &p) {
    fe a, b, c, e, f, g, h, t;
    fe_sq(a, p.X);
    fe_sq(b, p.Y);
    fe_sq(c, p.Z);
    fe_add(c, c, c);
    fe_carry(c);
    fe_add(h, a, b); fe_carry(h);
    fe_add(t, p.X, p.Y); fe_carry(t);
    fe_sq(t, t);
    fe_sub(e, h, t); fe_carry(e);
    fe_sub(g, a, b); fe_carry(g);
    fe_add(f, c, g); fe_carry(f);
    fe_mul(r.X, e, f);
    fe_mul(r.Y, g, h);
    fe_mul(r.Z, f, g);
    fe_mul(r.T, e, h);
}

static void ge_neg(ge &r, const ge &p) {
    fe_neg(r.X, p.X);
    r.Y = p.Y;
    r.Z = p.Z;
    fe_neg(r.T, p.T);
}

static int ge_is_identity(const ge &p) {
    return fe_iszero(p.X) || fe_iszero(p.Y);
}

// affine precomputed form (y+x, y-x, 2d*x*y) for table entries: the
// mixed add below is 7 muls vs ge_add's 9, and entries shrink 160->120B
struct gep {
    fe ypx, ymx, t2d;
};

// r = p + q with q affine-precomputed (madd-2008-hwcd, unified: a zero
// t2d/unit ypx+ymx entry is the identity and adds as a no-op)
static void ge_madd(ge &r, const ge &p, const gep &q) {
    fe a, b, c, d, e, f, g, h, t;
    fe_sub(t, p.Y, p.X); fe_carry(t);
    fe_mul(a, t, q.ymx);
    fe_add(t, p.Y, p.X);
    fe_mul(b, t, q.ypx);
    fe_mul(c, p.T, q.t2d);
    fe_add(d, p.Z, p.Z);
    fe_carry(d);
    fe_sub(e, b, a); fe_carry(e);
    fe_sub(f, d, c); fe_carry(f);
    fe_add(g, d, c); fe_carry(g);
    fe_add(h, b, a); fe_carry(h);
    fe_mul(r.X, e, f);
    fe_mul(r.Y, g, h);
    fe_mul(r.Z, f, g);
    fe_mul(r.T, e, h);
}

static void gep_neg(gep &r, const gep &q) {
    r.ypx = q.ymx;
    r.ymx = q.ypx;
    fe_neg(r.t2d, q.t2d);
}

// RFC 9496 §4.3.1 DECODE; returns 0 on invalid encodings
static int ge_decode(ge &p, const uint8_t *bytes) {
    fe s;
    fe_frombytes(s, bytes);
    // canonical check: re-encode must reproduce (also catches bit 255)
    uint8_t check[32];
    fe_tobytes(check, s);
    if (memcmp(check, bytes, 32) != 0) return 0;
    if (bytes[0] & 1) return 0;  // negative s

    fe ss, u1, u2, u2s, v, i, dx, dy, x, y, t, tmp;
    fe_sq(ss, s);
    fe_sub(u1, FE_ONE, ss); fe_carry(u1);          // 1 - s^2
    fe_add(u2, FE_ONE, ss); fe_carry(u2);          // 1 + s^2
    fe_sq(u2s, u2);                                 // u2^2
    fe_sq(tmp, u1);
    fe_mul(tmp, tmp, FE_D);
    fe_neg(tmp, tmp);                               // -d u1^2
    fe_sub(v, tmp, u2s); fe_carry(v);               // -(d u1^2) - u2^2
    fe_mul(tmp, v, u2s);                            // v u2^2
    int was_square = fe_sqrt_ratio_m1(i, FE_ONE, tmp);
    fe_mul(dx, i, u2);                              // den_x
    fe_mul(dy, i, dx);
    fe_mul(dy, dy, v);                              // den_y
    fe_add(tmp, s, s);
    fe_carry(tmp);
    fe_mul(x, tmp, dx);                             // 2 s den_x
    fe_abs(x, x);
    fe_mul(y, u1, dy);
    fe_mul(t, x, y);
    if (!was_square || fe_isnegative(t) || fe_iszero(y)) return 0;
    p.X = x;
    p.Y = y;
    p.Z = FE_ONE;
    p.T = t;
    return 1;
}

// RFC 9496 §4.3.2 ENCODE
static void ge_encode(uint8_t *out, const ge &p) {
    fe u1, u2, isr, d1, d2, zinv, ix, iy, eden, tz, x, y, dinv, s, tmp;
    fe_add(tmp, p.Z, p.Y); fe_carry(tmp);
    fe_sub(u1, p.Z, p.Y); fe_carry(u1);
    fe_mul(u1, tmp, u1);                    // (Z+Y)(Z-Y)
    fe_mul(u2, p.X, p.Y);                   // XY
    fe_sq(tmp, u2);
    fe_mul(tmp, u1, tmp);                   // u1 u2^2
    fe_sqrt_ratio_m1(isr, FE_ONE, tmp);
    fe_mul(d1, isr, u1);
    fe_mul(d2, isr, u2);
    fe_mul(zinv, d1, d2);
    fe_mul(zinv, zinv, p.T);                // den1 den2 T
    fe_mul(ix, p.X, FE_SQRT_M1);
    fe_mul(iy, p.Y, FE_SQRT_M1);
    fe_mul(eden, d1, FE_INVSQRT_A_MINUS_D);
    fe_mul(tz, p.T, zinv);
    int rotate = fe_isnegative(tz);
    if (rotate) {
        x = iy;
        y = ix;
        dinv = eden;
    } else {
        x = p.X;
        y = p.Y;
        dinv = d2;
    }
    fe_mul(tmp, x, zinv);
    if (fe_isnegative(tmp)) fe_neg(y, y);
    fe_sub(s, p.Z, y); fe_carry(s);
    fe_mul(s, dinv, s);
    fe_abs(s, s);
    fe_tobytes(out, s);
}

// ---------------------------------------------------------------------------
// constant-time fixed-base comb for the generators G and H
// ---------------------------------------------------------------------------
//
// Per base: tbl[i][j] = (j+1) * 16^i * B for i in 0..63, j in 0..7.  A
// canonical scalar (< L < 2^253) recodes to 64 signed radix-16 digits in
// [-8, 8); the product is a sum of 64 table entries — no doublings at all.
// Selection scans the full 8-entry window with arithmetic masks; negation
// is mask-based.  The adds use the same unified formulas as the vartime
// path (identity-safe), so a zero digit simply adds the masked-in identity.

static void fe_cmov(fe &f, const fe &g, uint64_t mask) {
    for (int i = 0; i < 5; i++) f.v[i] ^= mask & (f.v[i] ^ g.v[i]);
}

static void ge_cmov(ge &r, const ge &p, uint64_t mask) {
    fe_cmov(r.X, p.X, mask);
    fe_cmov(r.Y, p.Y, mask);
    fe_cmov(r.Z, p.Z, mask);
    fe_cmov(r.T, p.T, mask);
}

// all-ones when a == b (a, b in [0, 255]); branchless
static uint64_t ct_eq_mask(uint64_t a, uint64_t b) {
    uint64_t d = a ^ b;
    return (uint64_t)0 - (((d - 1) & ~d) >> 63);
}

struct comb_table {
    ge tbl[64][8];
    uint8_t wire[32];   // which generator this table is for
    int ready;
};

static comb_table COMB_G = {{}, {0}, 0};
static comb_table COMB_H = {{}, {0}, 0};
// Guards the global tables: ctypes releases the GIL around foreign calls,
// so concurrent Python threads CAN race a rebuild against a multiply.
// Rebuilds take the write lock, multiplies the read lock.
static pthread_rwlock_t COMB_LOCK = PTHREAD_RWLOCK_INITIALIZER;

static void comb_build(comb_table &t, const ge &base, const uint8_t *wire) {
    ge cur = base;                       // 16^i * B
    for (int i = 0; i < 64; i++) {
        t.tbl[i][0] = cur;
        for (int j = 1; j < 8; j++) ge_add(t.tbl[i][j], t.tbl[i][j - 1], cur);
        ge next = t.tbl[i][7];           // 8 * 16^i * B
        ge_double(next, next);           // 16^(i+1) * B
        cur = next;
    }
    memcpy(t.wire, wire, 32);
    t.ready = 1;
}

// signed radix-16 recoding of a canonical (< 2^253) little-endian scalar
static void recode_radix16(int8_t digits[64], const uint8_t *s) {
    for (int i = 0; i < 32; i++) {
        digits[2 * i] = (int8_t)(s[i] & 15);
        digits[2 * i + 1] = (int8_t)((s[i] >> 4) & 15);
    }
    int8_t carry = 0;
    for (int i = 0; i < 63; i++) {
        digits[i] = (int8_t)(digits[i] + carry);
        carry = (int8_t)((digits[i] + 8) >> 4);
        digits[i] = (int8_t)(digits[i] - (carry << 4));
    }
    digits[63] = (int8_t)(digits[63] + carry);  // < 8 since s < 2^253
}

// constant-time: r = sum_i digits[i] * 16^i * B via masked table scan
static void comb_mul(ge &r, const comb_table &t, const int8_t digits[64]) {
    ge_identity(r);
    for (int i = 0; i < 64; i++) {
        int8_t d = digits[i];
        uint64_t neg = (uint64_t)0 - (uint64_t)(((uint8_t)d) >> 7);
        uint8_t babs = (uint8_t)((d ^ (d >> 7)) - (d >> 7));
        ge sel;
        ge_identity(sel);
        for (int j = 0; j < 8; j++)
            ge_cmov(sel, t.tbl[i][j], ct_eq_mask(babs, (uint64_t)j + 1));
        ge nsel;
        ge_neg(nsel, sel);
        ge_cmov(sel, nsel, neg);
        ge s2;
        ge_add(s2, r, sel);
        r = s2;
    }
}

// tables ready for this generator pair? (caller holds COMB_LOCK)
static int comb_current(const uint8_t *g_wire, const uint8_t *h_wire) {
    return COMB_G.ready && COMB_H.ready &&
           memcmp(COMB_G.wire, g_wire, 32) == 0 &&
           memcmp(COMB_H.wire, h_wire, 32) == 0;
}

// Build (or rebuild) the comb tables for the generator pair.  Returns 1 on
// success, 0 if either encoding fails to decode.  Thread-safe: rebuilds
// run under the table write lock.
int cpzk_basemul_init(const uint8_t *g_wire, const uint8_t *h_wire) {
    pthread_rwlock_rdlock(&COMB_LOCK);
    int current = comb_current(g_wire, h_wire);
    pthread_rwlock_unlock(&COMB_LOCK);
    if (current) return 1;
    ge G, H;
    if (!ge_decode(G, g_wire) || !ge_decode(H, h_wire)) return 0;
    pthread_rwlock_wrlock(&COMB_LOCK);
    if (!comb_current(g_wire, h_wire)) {
        comb_build(COMB_G, G, g_wire);
        comb_build(COMB_H, H, h_wire);
    }
    pthread_rwlock_unlock(&COMB_LOCK);
    return 1;
}

// out1 = s*G, out2 = s*H (wire bytes), constant time in s.  Builds the
// tables when missing or built for different generators (one atomic call —
// no init-then-mul race window); returns 0 only when a generator encoding
// is invalid.
int cpzk_double_basemul(const uint8_t *g_wire, const uint8_t *h_wire,
                        const uint8_t *scalar, uint8_t *out1, uint8_t *out2) {
    pthread_rwlock_rdlock(&COMB_LOCK);
    if (!comb_current(g_wire, h_wire)) {
        pthread_rwlock_unlock(&COMB_LOCK);
        if (!cpzk_basemul_init(g_wire, h_wire)) return 0;
        pthread_rwlock_rdlock(&COMB_LOCK);
        if (!comb_current(g_wire, h_wire)) {
            // another thread swapped in a different pair between our build
            // and this read lock; give up rather than loop unboundedly
            pthread_rwlock_unlock(&COMB_LOCK);
            return 0;
        }
    }
    int8_t digits[64];
    recode_radix16(digits, scalar);
    ge r1, r2;
    comb_mul(r1, COMB_G, digits);
    comb_mul(r2, COMB_H, digits);
    pthread_rwlock_unlock(&COMB_LOCK);
    ge_encode(out1, r1);
    ge_encode(out2, r2);
    return 1;
}

// variable-base, variable-time scalar mul: 4-bit fixed windows, scalar is
// 32 canonical little-endian bytes (public verification input)
static void ge_scalarmul(ge &r, const ge &p, const uint8_t *scalar) {
    ge table[16];
    ge_identity(table[0]);
    table[1] = p;
    for (int i = 2; i < 16; i++) ge_add(table[i], table[i - 1], p);
    ge_identity(r);
    for (int i = 63; i >= 0; i--) {
        int byte = scalar[i >> 1];
        int nib = (i & 1) ? (byte >> 4) : (byte & 0x0F);
        ge_double(r, r);
        ge_double(r, r);
        ge_double(r, r);
        ge_double(r, r);
        if (nib) {
            ge t;
            ge_add(t, r, table[nib]);
            r = t;
        }
    }
}

// ---------------------------------------------------------------------------
// scalar arithmetic mod l = 2^252 + q (vartime; verification inputs are
// public).  Needed for the beta-merged verification equation below.
// ---------------------------------------------------------------------------

struct sc4 { uint64_t v[4]; };  // 256-bit little-endian

// q = l - 2^252 = 27742317777372353535851937790883648493 (125 bits)
static const uint64_t SC_Q[2] = {0x5812631A5CF5D3EDULL, 0x14DEF9DEA2F79CD6ULL};
// l itself: bit 252 set in word 3
static const uint64_t SC_L[4] = {0x5812631A5CF5D3EDULL, 0x14DEF9DEA2F79CD6ULL,
                                 0, 0x1000000000000000ULL};

static uint64_t load64le(const uint8_t *b) {
    uint64_t r = 0;
    for (int i = 7; i >= 0; i--) r = (r << 8) | b[i];
    return r;
}

static void store64le(uint8_t *b, uint64_t v) {
    for (int i = 0; i < 8; i++) { b[i] = (uint8_t)v; v >>= 8; }
}

// r >= l ?
static int sc_geq_l(const uint64_t r[4]) {
    for (int i = 3; i >= 0; i--) {
        if (r[i] > SC_L[i]) return 1;
        if (r[i] < SC_L[i]) return 0;
    }
    return 1;
}

static void sc_sub_l(uint64_t r[4]) {
    uint64_t borrow = 0;
    for (int i = 0; i < 4; i++) {
        uint64_t d = r[i] - SC_L[i] - borrow;
        borrow = (r[i] < SC_L[i] + borrow) || (SC_L[i] + borrow < SC_L[i]);
        r[i] = d;
    }
}

static void sc_add_l(uint64_t r[4]) {
    u128 c = 0;
    for (int i = 0; i < 4; i++) {
        c += (u128)r[i] + SC_L[i];
        r[i] = (uint64_t)c;
        c >>= 64;
    }
}

// out[na+nb] = a * b, row-wise schoolbook (no intermediate overflow:
// each step is product + word + carry < 2^128).  out must be zeroed to
// na+nb words by the caller's sizing; we do it here.
static void mul_words(uint64_t *out, const uint64_t *a, int na,
                      const uint64_t *b, int nb) {
    memset(out, 0, (size_t)(na + nb) * 8);
    for (int i = 0; i < na; i++) {
        uint64_t carry = 0;
        for (int j = 0; j < nb; j++) {
            u128 cur = (u128)a[i] * b[j] + out[i + j] + carry;
            out[i + j] = (uint64_t)cur;
            carry = (uint64_t)(cur >> 64);
        }
        out[i + nb] = carry;  // untouched so far in row-wise order
    }
}

// r = p mod l for p < 2^381 (6 words), via 2^252 === -q (mod l) twice
static void sc_reduce384(uint64_t r[4], const uint64_t p[6]) {
    const uint64_t MASK60 = 0x0FFFFFFFFFFFFFFFULL;
    // split: lo = p mod 2^252 (4 words), hi = p >> 252 (< 2^129, 3 words)
    uint64_t lo[4] = {p[0], p[1], p[2], p[3] & MASK60};
    uint64_t hi[3];
    hi[0] = (p[3] >> 60) | (p[4] << 4);
    hi[1] = (p[4] >> 60) | (p[5] << 4);
    hi[2] = p[5] >> 60;
    // t = hi * q  (< 2^254, 4 words after the drop of the zero top word)
    uint64_t t5[5];
    mul_words(t5, hi, 3, SC_Q, 2);
    uint64_t t[4] = {t5[0], t5[1], t5[2], t5[3]};
    // t = t_hi * 2^252 + t_lo with t_hi < 4;  p === lo - t_lo + t_hi*q
    uint64_t thi = t[3] >> 60;
    uint64_t tlo[4] = {t[0], t[1], t[2], t[3] & MASK60};
    // u = thi * q (2 words + carry)
    uint64_t u[3];
    u128 uc = (u128)thi * SC_Q[0];
    u[0] = (uint64_t)uc;
    uc = (uc >> 64) + (u128)thi * SC_Q[1];
    u[1] = (uint64_t)uc;
    u[2] = (uint64_t)(uc >> 64);
    // r = lo + u (< 2^252 + 2^131, fits 4 words)
    u128 ac = 0;
    for (int i = 0; i < 4; i++) {
        ac += (u128)lo[i] + (i < 3 ? u[i] : 0);
        r[i] = (uint64_t)ac;
        ac >>= 64;
    }
    // r -= tlo; on borrow add l back (single add suffices: deficit < 2^252 < l)
    uint64_t borrow = 0;
    for (int i = 0; i < 4; i++) {
        uint64_t bi = tlo[i] + borrow;
        uint64_t carry_in = borrow && bi == 0;  // tlo[i]+borrow wrapped
        borrow = carry_in || r[i] < bi;
        r[i] = r[i] - bi;
    }
    if (borrow) sc_add_l(r);
    while (sc_geq_l(r)) sc_sub_l(r);
}

// out = (beta * s) mod l; beta is 16 bytes LE (128-bit weight), s is a
// canonical 32-byte scalar.  Vartime — both operands are public.
int cpzk_sc_mul_beta(const uint8_t *beta16, const uint8_t *s32, uint8_t *out32) {
    // domain: s < 2^253 (every canonical scalar is) — beyond that the
    // 384-bit reduction's dropped top word goes nonzero and the result
    // would be silently wrong; reject instead
    if (s32[31] & 0xE0) return 0;
    uint64_t b[2] = {load64le(beta16), load64le(beta16 + 8)};
    uint64_t s[4];
    for (int i = 0; i < 4; i++) s[i] = load64le(s32 + 8 * i);
    uint64_t p[6];
    mul_words(p, b, 2, s, 4);
    uint64_t r[4];
    sc_reduce384(r, p);
    for (int i = 0; i < 4; i++) store64le(out32 + 8 * i, r[i]);
    return 1;
}

// ---------------------------------------------------------------------------
// vartime scalar-mul building blocks for verification
// ---------------------------------------------------------------------------

// width-5 NAF recoding: digits odd in [-15, 15] or 0; scalar < 2^253.
// naf must hold 258 entries.
static void recode_wnaf5(int8_t *naf, const uint8_t *s32) {
    memset(naf, 0, 258);
    uint64_t x[5] = {load64le(s32), load64le(s32 + 8), load64le(s32 + 16),
                     load64le(s32 + 24), 0};
    int i = 0;
    while (i < 253) {  // canonical scalars are < 2^253; carries may push
                       // digits past this index, handled below the loop
        if (((x[i >> 6] >> (i & 63)) & 1) == 0) { i++; continue; }
        // take 5 bits starting at i (straddles at most two words)
        int w = (int)((x[i >> 6] >> (i & 63)) & 31);
        if ((i & 63) > 59) w = (w | (int)(x[(i >> 6) + 1] << (64 - (i & 63)))) & 31;
        if (w & 16) {
            naf[i] = (int8_t)(w - 32);
            // carry: add 2^(i+5) (bits i..i+4 are consumed by the digit)
            int wi = (i + 5) >> 6;
            uint64_t add = 1ULL << ((i + 5) & 63);
            while (wi < 5) {
                uint64_t nv = x[wi] + add;
                x[wi] = nv;
                if (nv >= add) break;  // no wrap -> carry absorbed
                add = 1;
                wi++;
            }
        } else {
            naf[i] = (int8_t)w;
        }
        i += 5;
    }
    // bits at or above 253 (original top bits or ripple from a carry) are
    // emitted as single +1 digits — always below 2^258 for our inputs
    for (; i < 258; i++)
        if ((x[i >> 6] >> (i & 63)) & 1) naf[i] = 1;
}

// odd multiples {1,3,...,15} * P for the wNAF5 ladder
static void wnaf_table(ge T[8], const ge &P) {
    T[0] = P;
    ge P2;
    ge_double(P2, P);
    for (int k = 1; k < 8; k++) ge_add(T[k], T[k - 1], P2);
}

// signed radix-256 recoding: 32 digits in [-128, 127]; scalar < 2^253 so
// the top digit absorbs the final carry without overflow
static void recode_s256(int16_t d[32], const uint8_t *s32) {
    int carry = 0;
    for (int i = 0; i < 32; i++) {
        int v = s32[i] + carry;
        if (v >= 128 && i < 31) {
            d[i] = (int16_t)(v - 256);
            carry = 1;
        } else {
            d[i] = (int16_t)v;
            carry = 0;
        }
    }
}

// ---------------------------------------------------------------------------
// cached verification context for a generator pair
// ---------------------------------------------------------------------------
//
// Built once per (g, h) pair and reused across calls: decoded points,
// 4-bit Straus tables for the exact per-equation path, and vartime
// signed radix-256 comb tables (tbl[i][j] = (j+1) * 256^i * B, j in
// 0..127) that evaluate the fixed-base terms s*G and (beta*s)*H in ~32
// adds each with ZERO doublings.  ~1.3 MiB static — a server working set,
// built in ~2 ms on first use.

struct vcomb {
    gep tbl[32][128];
};

struct verify_ctx {
    uint8_t gw[32], hw[32];
    ge G, H;
    ge tbG16[16], tbH16[16];
    vcomb combG, combH;
    int ready;
};

static verify_ctx VCTX = {};
static pthread_rwlock_t VCTX_LOCK = PTHREAD_RWLOCK_INITIALIZER;

static void vcomb_build(vcomb &t, const ge &base) {
    const int N = 32 * 128;
    ge *tmp = (ge *)malloc(sizeof(ge) * N);
    fe *prefix = (fe *)malloc(sizeof(fe) * N);
    ge cur = base;  // 256^i * B
    for (int i = 0; i < 32; i++) {
        tmp[i * 128] = cur;
        for (int j = 1; j < 128; j++)
            ge_add(tmp[i * 128 + j], tmp[i * 128 + j - 1], cur);
        if (i < 31) {
            for (int k = 0; k < 8; k++) ge_double(cur, cur);  // 256^(i+1) * B
        }
    }
    // batch-normalize to affine (one inversion via Montgomery's trick),
    // then store the precomputed (y+x, y-x, 2d*x*y) form
    prefix[0] = tmp[0].Z;
    for (int k = 1; k < N; k++) fe_mul(prefix[k], prefix[k - 1], tmp[k].Z);
    fe inv;
    fe_invert(inv, prefix[N - 1]);
    for (int k = N - 1; k >= 0; k--) {
        fe zinv;
        if (k > 0) {
            fe_mul(zinv, inv, prefix[k - 1]);
            fe_mul(inv, inv, tmp[k].Z);
        } else {
            zinv = inv;
        }
        fe x, y, xy;
        fe_mul(x, tmp[k].X, zinv);
        fe_mul(y, tmp[k].Y, zinv);
        gep &o = t.tbl[k / 128][k % 128];
        fe_add(o.ypx, y, x);
        fe_carry(o.ypx);
        fe_sub(o.ymx, y, x);
        fe_carry(o.ymx);
        fe_mul(xy, x, y);
        fe_mul(o.t2d, xy, FE_D2);
    }
    free(prefix);
    free(tmp);
}

// vartime read: acc += sum_i digits[i] * 256^i * B
static void vcomb_accum(ge &acc, const vcomb &t, const uint8_t *s32) {
    int16_t d[32];
    recode_s256(d, s32);
    for (int i = 0; i < 32; i++) {
        if (d[i] == 0) continue;
        int mag = d[i] < 0 ? -d[i] : d[i];
        ge r;
        if (d[i] < 0) {
            gep n;
            gep_neg(n, t.tbl[i][mag - 1]);
            ge_madd(r, acc, n);
        } else {
            ge_madd(r, acc, t.tbl[i][mag - 1]);
        }
        acc = r;
    }
}

// 1..15 multiples table for the Straus ladder (slot 0 = identity)
static void straus_table(ge tb[16], const ge &B) {
    ge_identity(tb[0]);
    tb[1] = B;
    for (int i = 2; i < 16; i++) ge_add(tb[i], tb[i - 1], B);
}

// one equation: s*B == R + c*Y  <=>  s*B + c*(-Y) - R == identity.
// Straus shared-doubling: one 255-double ladder with two 4-bit tables
// (~half the doublings of two independent scalar muls).  The base table
// ``tb`` ({1..15}*B) is precomputed once per batch — B is the shared
// generator G or H, so rebuilding it per row would waste 15 adds/row.
static int cp_check_eq(const ge tb[16], const ge &Y, const ge &R,
                       const uint8_t *s, const uint8_t *c) {
    ge ty[16], nY, acc, nR;
    ge_neg(nY, Y);
    straus_table(ty, nY);
    ge_identity(acc);
    for (int i = 63; i >= 0; i--) {
        int sb = s[i >> 1], cb = c[i >> 1];
        int ns = (i & 1) ? (sb >> 4) : (sb & 0x0F);
        int nc = (i & 1) ? (cb >> 4) : (cb & 0x0F);
        ge_double(acc, acc);
        ge_double(acc, acc);
        ge_double(acc, acc);
        ge_double(acc, acc);
        if (ns) {
            ge t;
            ge_add(t, acc, tb[ns]);
            acc = t;
        }
        if (nc) {
            ge t;
            ge_add(t, acc, ty[nc]);
            acc = t;
        }
    }
    ge_neg(nR, R);
    ge_add(acc, acc, nR);
    return ge_is_identity(acc);
}

// OS entropy for the merge weight; not security-critical beyond batch
// soundness (a failed draw just disables the merged fast path).  This is
// on the single-verify hot path, so: getrandom(2)/arc4random first, and
// the /dev/urandom fallback keeps one unbuffered fd for the process.
static int fill_random16(uint8_t out[16]) {
#if defined(__APPLE__)
    arc4random_buf(out, 16);
    return 1;
#else
#if defined(__linux__)
    if (getrandom(out, 16, 0) == 16) return 1;
#endif
    // fallback only (getrandom absent/failed): mutex-guarded lazy fd —
    // concurrent verify_rows callers run GIL-free, so an unguarded
    // lazy-init would race (leaked fds + a data race on the flag int)
    static int urandom_fd = -2;  // -2 unopened, -1 failed
    static pthread_mutex_t URANDOM_LOCK = PTHREAD_MUTEX_INITIALIZER;
    pthread_mutex_lock(&URANDOM_LOCK);
    if (urandom_fd == -2) urandom_fd = open("/dev/urandom", O_RDONLY);
    int fd = urandom_fd;
    int ok = fd >= 0 && read(fd, out, 16) == 16;
    pthread_mutex_unlock(&URANDOM_LOCK);
    return ok;
#endif
}

// one ladder step for a wNAF digit against an odd-multiples table
static void wnaf_step(ge &acc, const ge T[8], int8_t d) {
    if (!d) return;
    ge t;
    const ge &e = T[(d < 0 ? -d : d) >> 1];
    if (d > 0) {
        ge_add(t, acc, e);
    } else {
        ge n;
        ge_neg(n, e);
        ge_add(t, acc, n);
    }
    acc = t;
}

// Merged verification of one proof with a random 128-bit weight beta:
//     s*G + (beta*s)*H - c*Y1 - (beta*c)*Y2 - R1 - beta*R2 == identity
// which is eq1 + beta*eq2 for the two Chaum-Pedersen equations.  A proof
// failing either equation passes only with probability ~2^-128 over beta
// (the caller re-checks failures with the exact per-equation path, so the
// observable accept/reject verdicts match the reference's).  Cost: ONE
// shared-doubling ladder for the whole proof — the fixed-base terms read
// the cached radix-256 combs with no doublings at all.
static int cp_check_merged(const verify_ctx &ctx, const ge &Y1, const ge &Y2,
                           const ge &R1, const ge &R2,
                           const uint8_t *s, const uint8_t *c,
                           const uint8_t beta16[16]) {
    // the radix-256/wNAF recoders assume scalars < 2^253; canonical
    // inputs always are, but this ABI is callable with arbitrary bytes —
    // defer those to the exact path (which handles any 256-bit value)
    // rather than index past a comb-table row
    if ((s[31] & 0xE0) || (c[31] & 0xE0)) return 0;
    uint8_t beta32[32] = {0};
    memcpy(beta32, beta16, 16);
    uint8_t bs[32], bc[32];
    cpzk_sc_mul_beta(beta16, s, bs);
    cpzk_sc_mul_beta(beta16, c, bc);

    // fixed-base part: s*G + (beta*s)*H, adds only
    ge fixed;
    ge_identity(fixed);
    vcomb_accum(fixed, ctx.combG, s);
    vcomb_accum(fixed, ctx.combH, bs);

    // variable-base part: one ladder over c*(-Y1) + bc*(-Y2) + beta*(-R2)
    ge nY1, nY2, nR2;
    ge_neg(nY1, Y1);
    ge_neg(nY2, Y2);
    ge_neg(nR2, R2);
    ge TY1[8], TY2[8], TR2[8];
    wnaf_table(TY1, nY1);
    wnaf_table(TY2, nY2);
    wnaf_table(TR2, nR2);
    int8_t nc[258], nbc[258], nb[258];
    recode_wnaf5(nc, c);
    recode_wnaf5(nbc, bc);
    recode_wnaf5(nb, beta32);

    int top = 257;
    while (top >= 0 && !nc[top] && !nbc[top] && !nb[top]) top--;
    ge acc;
    ge_identity(acc);
    for (int i = top; i >= 0; i--) {
        ge_double(acc, acc);
        wnaf_step(acc, TY1, nc[i]);
        wnaf_step(acc, TY2, nbc[i]);
        wnaf_step(acc, TR2, nb[i]);
    }
    ge nR1;
    ge_neg(nR1, R1);
    ge_add(acc, acc, fixed);
    ge_add(acc, acc, nR1);
    return ge_is_identity(acc);
}

// Ensure VCTX matches this generator pair; returns 1 when the cached
// context is usable (caller then reads it under its own read lock).
// The ~4 ms table build only happens for a pair seen on two consecutive
// misses — a one-off (or alternating) foreign pair takes the per-call
// local-table path instead of thrashing the shared context.
static int vctx_ensure(const uint8_t *g_wire, const uint8_t *h_wire) {
    static uint8_t last_miss[64];
    static int have_miss = 0;
    static pthread_mutex_t MISS_LOCK = PTHREAD_MUTEX_INITIALIZER;
    pthread_rwlock_rdlock(&VCTX_LOCK);
    int ok = VCTX.ready && memcmp(VCTX.gw, g_wire, 32) == 0 &&
             memcmp(VCTX.hw, h_wire, 32) == 0;
    pthread_rwlock_unlock(&VCTX_LOCK);
    if (ok) {
        // a hit clears the miss-streak: "two CONSECUTIVE misses" is what
        // promotes a pair, so alternating pairs (hit between misses)
        // never rebuild and keep taking the per-call local-table path.
        // (Unconditional lock: once per cpzk_verify_rows call, not per
        // row — and a bare flag read would race the miss-path writes.)
        pthread_mutex_lock(&MISS_LOCK);
        have_miss = 0;
        pthread_mutex_unlock(&MISS_LOCK);
        return 1;
    }
    ge G, H;
    if (!ge_decode(G, g_wire) || !ge_decode(H, h_wire)) return 0;
    pthread_rwlock_wrlock(&VCTX_LOCK);
    // re-check under the write lock (another thread may have built it)
    if (VCTX.ready && memcmp(VCTX.gw, g_wire, 32) == 0 &&
        memcmp(VCTX.hw, h_wire, 32) == 0) {
        pthread_rwlock_unlock(&VCTX_LOCK);
        return 1;
    }
    pthread_mutex_lock(&MISS_LOCK);
    int repeat = have_miss && memcmp(last_miss, g_wire, 32) == 0 &&
                 memcmp(last_miss + 32, h_wire, 32) == 0;
    if (!repeat && VCTX.ready) {
        memcpy(last_miss, g_wire, 32);
        memcpy(last_miss + 32, h_wire, 32);
        have_miss = 1;
        pthread_mutex_unlock(&MISS_LOCK);
        pthread_rwlock_unlock(&VCTX_LOCK);
        return 0;  // caller uses per-call tables this time
    }
    have_miss = 0;
    pthread_mutex_unlock(&MISS_LOCK);
    VCTX.ready = 0;
    VCTX.G = G;
    VCTX.H = H;
    straus_table(VCTX.tbG16, G);
    straus_table(VCTX.tbH16, H);
    vcomb_build(VCTX.combG, G);
    vcomb_build(VCTX.combH, H);
    memcpy(VCTX.gw, g_wire, 32);
    memcpy(VCTX.hw, h_wire, 32);
    VCTX.ready = 1;
    pthread_rwlock_unlock(&VCTX_LOCK);
    return 1;
}

// Small decode cache for repeat statements — the serving pattern is the
// same user's y1/y2 decoding on every login, and a decode costs a full
// field exponentiation.  Direct-mapped, consulted only for small-n calls
// (large batches have mostly-distinct users and would just thrash it).
struct dcache_slot {
    uint8_t wire[32];
    ge p;
    int valid;
};
static dcache_slot DCACHE[64];
static pthread_mutex_t DCACHE_LOCK = PTHREAD_MUTEX_INITIALIZER;

static int ge_decode_cached(ge &out, const uint8_t *wire) {
    int idx = wire[0] & 63;
    pthread_mutex_lock(&DCACHE_LOCK);
    if (DCACHE[idx].valid && memcmp(DCACHE[idx].wire, wire, 32) == 0) {
        out = DCACHE[idx].p;
        pthread_mutex_unlock(&DCACHE_LOCK);
        return 1;
    }
    pthread_mutex_unlock(&DCACHE_LOCK);
    if (!ge_decode(out, wire)) return 0;
    pthread_mutex_lock(&DCACHE_LOCK);
    memcpy(DCACHE[idx].wire, wire, 32);
    DCACHE[idx].p = out;
    DCACHE[idx].valid = 1;
    pthread_mutex_unlock(&DCACHE_LOCK);
    return 1;
}

struct row_job {
    const uint8_t *g, *h;          // 32B each (shared generators)
    const uint8_t *y1, *y2, *r1, *r2, *s, *c;  // n x 32B arrays
    uint8_t *out;
    size_t n;
    size_t next;           // work index (mutex-guarded)
    pthread_mutex_t lock;
    ge tbG[16], tbH[16];   // per-call Straus tables (fallback path, lazy)
    int tb_built;
    int gh_ok;
    int use_ctx;           // cached verify_ctx matches this g/h pair
    int have_beta;
    uint8_t beta[16];
};

// Fallback when the cached context is unavailable (build failure or
// generator churn mid-batch): per-call tables, built once under the lock.
static int ensure_local_tables(row_job *job) {
    pthread_mutex_lock(&job->lock);
    if (!job->tb_built) {
        ge G, H;
        job->gh_ok = ge_decode(G, job->g) && ge_decode(H, job->h);
        if (job->gh_ok) {
            straus_table(job->tbG, G);
            straus_table(job->tbH, H);
        }
        job->tb_built = 1;
    }
    pthread_mutex_unlock(&job->lock);
    return job->gh_ok;
}

static void *row_worker(void *arg) {
    row_job *job = (row_job *)arg;
    for (;;) {
        pthread_mutex_lock(&job->lock);
        size_t i = job->next++;
        pthread_mutex_unlock(&job->lock);
        if (i >= job->n) return nullptr;

        ge y1, y2, r1, r2;
        // statements repeat across logins -> cached decode for small
        // calls; commitments are fresh randomness every proof
        int small = job->n <= 4;
        int ok_y = small
            ? ge_decode_cached(y1, job->y1 + 32 * i) &&
              ge_decode_cached(y2, job->y2 + 32 * i)
            : ge_decode(y1, job->y1 + 32 * i) && ge_decode(y2, job->y2 + 32 * i);
        if (!ok_y) {
            job->out[i] = 0;
            continue;
        }
        // tri-state: 2 = commitment wire failed to decode — the deferred-
        // parse serving path maps this back to the exact parse error
        // (statement wires come from registration and are always valid, so
        // only r1/r2 can be unvalidated here)
        if (!ge_decode(r1, job->r1 + 32 * i) || !ge_decode(r2, job->r2 + 32 * i)) {
            job->out[i] = 2;
            continue;
        }
        const uint8_t *s = job->s + 32 * i;
        const uint8_t *c = job->c + 32 * i;

        if (job->use_ctx) {
            pthread_rwlock_rdlock(&VCTX_LOCK);
            if (VCTX.ready && memcmp(VCTX.gw, job->g, 32) == 0 &&
                memcmp(VCTX.hw, job->h, 32) == 0) {
                int ok = 0;
                if (job->have_beta)
                    ok = cp_check_merged(VCTX, y1, y2, r1, r2, s, c, job->beta);
                if (!ok)  // merged miss (or disabled): exact per-equation
                    ok = cp_check_eq(VCTX.tbG16, y1, r1, s, c) &&
                         cp_check_eq(VCTX.tbH16, y2, r2, s, c);
                pthread_rwlock_unlock(&VCTX_LOCK);
                job->out[i] = (uint8_t)ok;
                continue;
            }
            pthread_rwlock_unlock(&VCTX_LOCK);  // churned away mid-batch
        }
        if (!ensure_local_tables(job)) {
            job->out[i] = 0;
            continue;
        }
        job->out[i] = cp_check_eq(job->tbG, y1, r1, s, c) &&
                      cp_check_eq(job->tbH, y2, r2, s, c);
    }
}

// Verify n Chaum-Pedersen rows; returns 0 on success, out[i] in {0,1,2}
// (2 = commitment decode failure, see row_worker).
// All inputs are 32-byte wire encodings; g/h are shared across the batch.
int cpzk_verify_rows(size_t n, const uint8_t *g, const uint8_t *h,
                     const uint8_t *y1, const uint8_t *y2,
                     const uint8_t *r1, const uint8_t *r2,
                     const uint8_t *s, const uint8_t *c,
                     uint8_t *out, int n_threads) {
    row_job job;
    job.g = g; job.h = h;
    job.y1 = y1; job.y2 = y2; job.r1 = r1; job.r2 = r2;
    job.s = s; job.c = c;
    job.out = out;
    job.n = n;
    job.next = 0;
    pthread_mutex_init(&job.lock, nullptr);
    job.tb_built = 0;
    job.gh_ok = 0;
    job.use_ctx = vctx_ensure(g, h);
    if (!job.use_ctx && !ensure_local_tables(&job)) {
        // generators fail to decode: every row is invalid
        memset(out, 0, n);
        pthread_mutex_destroy(&job.lock);
        return 0;
    }
    job.have_beta = fill_random16(job.beta);
    if (job.have_beta) {
        int nz = 0;
        for (int b = 0; b < 16; b++) nz |= job.beta[b];
        job.have_beta = nz != 0;  // beta = 0 would ignore the h-side equation
    }

    if (n_threads < 1) n_threads = 1;
    if ((size_t)n_threads > n) n_threads = (int)n;
    if (n_threads == 1) {
        row_worker(&job);
    } else {
        pthread_t *tids = (pthread_t *)malloc(sizeof(pthread_t) * n_threads);
        int spawned = 0;
        if (tids != nullptr) {
            for (int t = 0; t < n_threads - 1; t++) {
                if (pthread_create(&tids[spawned], nullptr, row_worker, &job) != 0)
                    break;  // thread exhaustion: keep whatever we got
                spawned++;
            }
        }
        row_worker(&job);  // this thread always participates
        for (int t = 0; t < spawned; t++) pthread_join(tids[t], nullptr);
        free(tids);
    }
    pthread_mutex_destroy(&job.lock);
    return 0;
}

// --- batched wire decode for the device data plane -------------------------
//
// The TPU backend marshals proof/statement points from wire bytes into
// limb arrays; Python-side decode costs ~340 us/point (big-int inverse
// square root), which dwarfs device compute at batch scale.  This decodes
// n wires to extended coordinates (X|Y|Z|T, 32 canonical LE bytes each)
// on the worker pool instead.

struct decode_job {
    const uint8_t *wires;
    uint8_t *coords;  // n * 128 bytes
    uint8_t *ok;      // n flags
    size_t n;
    size_t next;
    pthread_mutex_t lock;
};

static void *decode_worker(void *arg) {
    decode_job *job = (decode_job *)arg;
    for (;;) {
        pthread_mutex_lock(&job->lock);
        size_t i = job->next++;
        pthread_mutex_unlock(&job->lock);
        if (i >= job->n) return nullptr;
        ge p;
        if (ge_decode(p, job->wires + 32 * i)) {
            uint8_t *o = job->coords + 128 * i;
            fe_tobytes(o, p.X);
            fe_tobytes(o + 32, p.Y);
            fe_tobytes(o + 64, p.Z);
            fe_tobytes(o + 96, p.T);
            job->ok[i] = 1;
        } else {
            memset(job->coords + 128 * i, 0, 128);
            job->ok[i] = 0;
        }
    }
}

int cpzk_batch_decode(size_t n, const uint8_t *wires, uint8_t *coords,
                      uint8_t *ok, int n_threads) {
    decode_job job;
    job.wires = wires;
    job.coords = coords;
    job.ok = ok;
    job.n = n;
    job.next = 0;
    pthread_mutex_init(&job.lock, nullptr);
    if (n_threads < 1) n_threads = 1;
    if ((size_t)n_threads > n) n_threads = (int)n;
    if (n_threads == 1) {
        decode_worker(&job);
    } else {
        pthread_t *tids = (pthread_t *)malloc(sizeof(pthread_t) * n_threads);
        int spawned = 0;
        if (tids != nullptr) {
            for (int t = 0; t < n_threads - 1; t++) {
                if (pthread_create(&tids[spawned], nullptr, decode_worker, &job) != 0)
                    break;
                spawned++;
            }
        }
        decode_worker(&job);
        for (int t = 0; t < spawned; t++) pthread_join(tids[t], nullptr);
        free(tids);
    }
    pthread_mutex_destroy(&job.lock);
    return 0;
}

// ABI generation for the Python loader's staleness gate: bump on ANY
// exported-signature or exported-semantics change (not just new symbols —
// a symbol-presence check cannot see a changed signature).
// 2: cpzk_parse_proofs gained `deep`; cpzk_verify_rows out[] went tri-state.
// 3: wire.cpp added cpzk_wire_scan/fill/gather (native request parse).
int cpzk_abi_version(void) { return 3; }

// --- small self-check helpers exposed for differential tests ---------------

// decode -> encode round trip; returns 1 if input decodes validly
int cpzk_point_roundtrip(const uint8_t *in, uint8_t *out) {
    ge p;
    if (!ge_decode(p, in)) return 0;
    ge_encode(out, p);
    return 1;
}

// validity check only — RFC 9496 decode already rejects every
// non-canonical encoding, so no re-encode (and no field inversion) is
// needed just to validate wire bytes (the hot ingress path: proof and
// statement parsing).  Differential tests vs the Python oracle own the
// decoder's correctness; cpzk_point_roundtrip stays for them.
int cpzk_point_validate(const uint8_t *in) {
    ge p;
    return ge_decode(p, in);
}

// out = scalar * P (all wire bytes); returns 0 on decode failure
int cpzk_scalarmul(const uint8_t *point, const uint8_t *scalar, uint8_t *out) {
    ge p, r;
    if (!ge_decode(p, point)) return 0;
    ge_scalarmul(r, p, scalar);
    ge_encode(out, r);
    return 1;
}

// out = P + Q (wire bytes); returns 0 on decode failure
int cpzk_point_add(const uint8_t *a, const uint8_t *b, uint8_t *out) {
    ge p, q, r;
    if (!ge_decode(p, a) || !ge_decode(q, b)) return 0;
    ge_add(r, p, q);
    ge_encode(out, r);
    return 1;
}

// --- batch proof parse fast path -------------------------------------------
// Validates n candidate proof wires, each exactly PROOF_WIRE=109 bytes,
// packed contiguously.  Wire layout (gadgets.py framing; the only layout a
// valid proof can have, since every field must be exactly 32 bytes):
//   [ver=1][00 00 00 20][r1:32][00 00 00 20][r2:32][00 00 00 20][s:32]
// ok[i]=1 only when item i is a COMPLETE valid proof: exact framing, both
// commitment points decode (RFC 9496 canonical — decode success is
// validity) and are not the identity, response scalar canonical mod l and
// nonzero.  ok[i]=0 means "re-parse on the slow path" — the Python parser
// reproduces the reference's exact per-field error message
// (gadgets.rs:364-489); this function only has to agree on accept/reject,
// which tests/test_protocol.py pins differentially against Proof.from_bytes.

#define PROOF_WIRE 109

// deep=1: full validation including point decodes.  deep=0: frame-only —
// everything EXCEPT the two point decodes, for the deferred-parse serving
// path where the batch-verify stage decodes the commitments anyway (one
// decode per point across the whole ingress+verify pipeline instead of
// two).  A frame-only pass guarantees that the ONLY way the item can
// still be invalid is a commitment decode failure, which is what lets the
// verify stage's tri-state (row_worker out[i]=2) map back to the exact
// parse error message.
static int parse_one_proof(const uint8_t *p, int deep) {
    static const uint8_t LEN32[4] = {0, 0, 0, 32};
    static const uint8_t ZERO32[32] = {0};
    if (p[0] != 1) return 0;  // PROTOCOL_VERSION (gadgets.py:17)
    if (memcmp(p + 1, LEN32, 4) != 0 || memcmp(p + 37, LEN32, 4) != 0 ||
        memcmp(p + 73, LEN32, 4) != 0)
        return 0;
    const uint8_t *r1 = p + 5, *r2 = p + 41, *s = p + 77;
    // identity's canonical encoding is all-zero; decode would accept it
    if (memcmp(r1, ZERO32, 32) == 0 || memcmp(r2, ZERO32, 32) == 0) return 0;
    uint64_t sv[4];
    for (int i = 0; i < 4; i++) sv[i] = load64le(s + 8 * i);
    if (sc_geq_l(sv)) return 0;                    // non-canonical scalar
    if ((sv[0] | sv[1] | sv[2] | sv[3]) == 0) return 0;  // zero response
    if (deep) {
        ge t;
        if (!ge_decode(t, r1)) return 0;
        if (!ge_decode(t, r2)) return 0;
    }
    return 1;
}

struct parse_job {
    const uint8_t *wires;
    uint8_t *ok;
    size_t n;
    size_t next;
    int deep;
    pthread_mutex_t lock;
};

static void *parse_worker(void *arg) {
    parse_job *job = (parse_job *)arg;
    for (;;) {
        pthread_mutex_lock(&job->lock);
        size_t i = job->next++;
        pthread_mutex_unlock(&job->lock);
        if (i >= job->n) return nullptr;
        job->ok[i] = (uint8_t)parse_one_proof(job->wires + PROOF_WIRE * i,
                                              job->deep);
    }
}

int cpzk_parse_proofs(size_t n, const uint8_t *wires, uint8_t *ok,
                      int deep, int n_threads) {
    parse_job job;
    job.wires = wires;
    job.ok = ok;
    job.n = n;
    job.next = 0;
    job.deep = deep;
    pthread_mutex_init(&job.lock, nullptr);
    if (n_threads < 1) n_threads = 1;
    if ((size_t)n_threads > n) n_threads = (int)n;
    if (n_threads == 1) {
        parse_worker(&job);
    } else {
        pthread_t *tids = (pthread_t *)malloc(sizeof(pthread_t) * n_threads);
        int spawned = 0;
        if (tids != nullptr) {
            for (int t = 0; t < n_threads - 1; t++) {
                if (pthread_create(&tids[spawned], nullptr, parse_worker, &job) != 0)
                    break;
                spawned++;
            }
        }
        parse_worker(&job);
        for (int t = 0; t < spawned; t++) pthread_join(tids[t], nullptr);
        free(tids);
    }
    pthread_mutex_destroy(&job.lock);
    return 0;
}

}  // extern "C"
