// Threaded-workload driver for sanitizer runs (CI runs this under
// -fsanitize=thread; a clean exit with no TSAN report is the gate).
//
// Exercises every concurrent path in the native core:
//   1. cpzk_verify_rows      — work-stealing pthread row pool
//   2. cpzk_challenge_batch  — threaded Merlin challenge derivation
//   3. cpzk_double_basemul   — comb-table rwlock under generator churn
//
// Inputs are synthetic: the ristretto basepoint encoding for points and
// small scalars.  Correctness of the outputs is asserted loosely (the
// differential tests own exactness); the sanitizer owns the memory model.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <pthread.h>

extern "C" {
int cpzk_verify_rows(size_t n, const uint8_t *g, const uint8_t *h,
                     const uint8_t *y1, const uint8_t *y2,
                     const uint8_t *r1, const uint8_t *r2,
                     const uint8_t *s, const uint8_t *c,
                     uint8_t *out, int n_threads);
void cpzk_challenge_batch(size_t n, const uint8_t *ctx_blob,
                          const uint32_t *ctx_offsets, const uint8_t *has_ctx,
                          const uint8_t *gs, const uint8_t *hs,
                          const uint8_t *y1s, const uint8_t *y2s,
                          const uint8_t *r1s, const uint8_t *r2s,
                          uint8_t *out, int n_threads);
int cpzk_basemul_init(const uint8_t *g_wire, const uint8_t *h_wire);
int cpzk_double_basemul(const uint8_t *g_wire, const uint8_t *h_wire,
                        const uint8_t *scalar, uint8_t *out1, uint8_t *out2);
int cpzk_scalarmul(const uint8_t *point, const uint8_t *scalar, uint8_t *out);
}

// ristretto255 basepoint, canonical encoding
static const uint8_t BP[32] = {
    0xe2, 0xf2, 0xae, 0x0a, 0x6a, 0xbc, 0x4e, 0x71, 0xa8, 0x84, 0xa9, 0x61,
    0xc5, 0x00, 0x51, 0x5f, 0x58, 0xe3, 0x0b, 0x6a, 0xa5, 0x82, 0xdd, 0x8d,
    0xb6, 0xa6, 0x59, 0x45, 0xe0, 0x8d, 0x2d, 0x76};

struct churn_arg {
    const uint8_t *g2;
    const uint8_t *h2;
    int which;
    int ok;
};

static void *churn_worker(void *p) {
    churn_arg *a = (churn_arg *)p;
    uint8_t s[32] = {0}, o1[32], o2[32];
    a->ok = 1;
    for (int i = 0; i < 40; i++) {
        s[0] = (uint8_t)(i + 1);
        s[1] = (uint8_t)a->which;
        const uint8_t *g = (i + a->which) % 2 ? a->g2 : BP;
        const uint8_t *h = (i + a->which) % 2 ? a->h2 : a->g2;
        // 0 is a legal transient result under churn (pair swapped between
        // build and read) — the Python caller falls back; no race either way
        cpzk_double_basemul(g, h, s, o1, o2);
    }
    return nullptr;
}

int main() {
    const size_t n = 64;
    uint8_t cols[6][64 * 32];
    for (int c = 0; c < 6; c++)
        for (size_t i = 0; i < n; i++) memcpy(cols[c] + 32 * i, BP, 32);
    uint8_t scal[64 * 32];
    memset(scal, 0, sizeof scal);
    for (size_t i = 0; i < n; i++) scal[32 * i] = (uint8_t)(i + 1);

    // 1. row pool (4 workers racing the shared cursor)
    uint8_t out[64];
    cpzk_verify_rows(n, BP, BP, cols[0], cols[1], cols[2], cols[3],
                     scal, scal, out, 4);

    // 2. threaded challenge derivation
    uint32_t offs[65];
    for (size_t i = 0; i <= n; i++) offs[i] = (uint32_t)i;  // 1-byte contexts
    uint8_t ctx[64], has[64], ch[64 * 64];
    memset(ctx, 0x5a, sizeof ctx);
    memset(has, 1, sizeof has);
    cpzk_challenge_batch(n, ctx, offs, has, cols[0], cols[1], cols[2],
                         cols[3], cols[4], cols[5], ch, 4);

    // 3. comb rwlock churn: two generator pairs, 4 threads
    uint8_t g2[32], h2[32], two[32] = {2}, three[32] = {3};
    if (!cpzk_scalarmul(BP, two, g2) || !cpzk_scalarmul(BP, three, h2)) {
        fprintf(stderr, "setup scalarmul failed\n");
        return 1;
    }
    pthread_t tids[4];
    churn_arg args[4];
    for (int t = 0; t < 4; t++) {
        args[t] = {g2, h2, t, 0};
        pthread_create(&tids[t], nullptr, churn_worker, &args[t]);
    }
    for (int t = 0; t < 4; t++) pthread_join(tids[t], nullptr);

    printf("tsan driver done: rows[0]=%d ch[0]=%02x\n", out[0], ch[0]);
    return 0;
}
