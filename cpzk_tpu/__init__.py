"""cpzk_tpu — TPU-native Chaum-Pedersen zero-knowledge proof framework.

A ground-up re-design of the capabilities of the reference Rust crate
``chaum-pedersen-zkp`` (see /root/reference) for TPU hardware:

- **Host plane** (this package's ``core/`` + ``protocol/``): bit-exact
  ristretto255 group arithmetic, Merlin-style Fiat-Shamir transcripts, the
  109-byte proof codec, and single-proof prove/verify — the trusted,
  constant-time-disciplined path (reference: ``src/primitives/``,
  ``src/prover/``, ``src/verifier/mod.rs``).
- **TPU data plane** (``ops/`` + ``parallel/``): batched limb-vector field
  arithmetic, extended-coordinate point kernels, windowed scalar
  multiplication and batch verification as JAX/XLA programs, sharded over
  ``jax.sharding.Mesh`` for multi-chip scale (reference analog:
  ``src/verifier/batch.rs``, re-designed — not translated).
- **Serving plane** (``server/`` + ``client/``): the gRPC auth system
  (reference: ``src/verifier/service.rs``, ``src/bin/``).

Public facade mirrors the reference's ``src/lib.rs:79-88`` re-export set.
"""

from .errors import Error, InvalidGroupElement, InvalidParams, InvalidScalar
from .core.ristretto import Element, Ristretto255, Scalar
from .core.rng import SecureRng
from .core.transcript import Transcript
from .protocol.gadgets import (
    Commitment,
    Parameters,
    Proof,
    Response,
    Statement,
    Witness,
)
from .protocol.prover import Nonce, Prover
from .protocol.verifier import Verifier
from .protocol.batch import BatchVerifier

__version__ = "1.0.0"

__all__ = [
    "BatchVerifier",
    "Commitment",
    "Element",
    "Error",
    "InvalidGroupElement",
    "InvalidParams",
    "InvalidScalar",
    "Nonce",
    "Parameters",
    "Proof",
    "Prover",
    "Response",
    "Ristretto255",
    "Scalar",
    "SecureRng",
    "Statement",
    "Transcript",
    "Verifier",
    "Witness",
]
