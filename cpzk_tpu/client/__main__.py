"""Interactive auth client REPL (reference ``src/bin/client.rs`` twin).

Commands (+ short aliases, client.rs:47-123): /register /r, /login /l,
/batch-register /br, /batch-login /bl, /status /st, /help /h /?,
/quit /exit /q.  Passwords never leave the client; registration sends the
statement (y1, y2) derived via the Argon2id KDF and login proves knowledge
of the derived scalar against a single-use server challenge.

Run: ``python -m cpzk_tpu.client --server 127.0.0.1:50051``
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys

import grpc

from .. import Parameters, Prover, SecureRng, Transcript, Witness
from ..core.ristretto import Ristretto255
from .kdf import password_to_scalar
from .rpc import AuthClient


def _c(color: str, text: str) -> str:
    codes = {"green": "32", "red": "31", "yellow": "33", "cyan": "36", "white": "37"}
    if not sys.stdout.isatty():
        return text
    return f"\x1b[{codes[color]}m{text}\x1b[0m"


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(prog="cpzk-client", description="Chaum-Pedersen auth client")
    p.add_argument(
        "-s", "--server", default=os.environ.get("AUTH_SERVER", "127.0.0.1:50051")
    )
    p.add_argument(
        "--no-retry", action="store_true",
        help="disable transient-error retries (backoff + budget; "
             "idempotent-safe RPCs only — logins are never retried)",
    )
    return p.parse_args(argv)


def build_retry_policy(args):
    """Retry policy from the resolved [retry] config (SERVER_RETRY_* env /
    server.toml) unless --no-retry; None = straight-through calls."""
    if args.no_retry:
        return None
    from ..server.config import ServerConfig

    return ServerConfig.from_env().retry.build_policy()


async def do_register(client: AuthClient, user: str, password: str) -> str:
    """client.rs:206-233."""
    x = password_to_scalar(password, user)
    prover = Prover(Parameters.new(), Witness(x))
    st = prover.statement
    try:
        resp = await client.register(
            user,
            Ristretto255.element_to_bytes(st.y1),
            Ristretto255.element_to_bytes(st.y2),
        )
    except grpc.aio.AioRpcError as e:
        return _c("red", f"Failed: {e.details()}")
    color = "green" if resp.success else "red"
    word = "Registered" if resp.success else "Failed"
    return _c(color, f"{word}: {resp.message}")


async def do_login(client: AuthClient, user: str, password: str) -> str:
    """client.rs:235-285: challenge -> prove with challenge-id context -> verify."""
    try:
        ch = await client.create_challenge(user)
        cid = bytes(ch.challenge_id)
        x = password_to_scalar(password, user)
        prover = Prover(Parameters.new(), Witness(x))
        transcript = Transcript()
        transcript.append_context(cid)
        proof = prover.prove_with_transcript(SecureRng(), transcript)
        resp = await client.verify_proof(user, cid, proof.to_bytes())
    except grpc.aio.AioRpcError as e:
        return _c("red", f"Login failed: {e.details()}")
    if resp.success:
        return _c("green", f"Login OK: {resp.message}\n  session: {resp.session_token}")
    return _c("red", f"Login failed: {resp.message}")


async def do_batch_register(client: AuthClient, users: list[str], passwords: list[str]) -> str:
    """client.rs:287-340."""
    y1s, y2s = [], []
    for user, password in zip(users, passwords, strict=True):
        prover = Prover(Parameters.new(), Witness(password_to_scalar(password, user)))
        y1s.append(Ristretto255.element_to_bytes(prover.statement.y1))
        y2s.append(Ristretto255.element_to_bytes(prover.statement.y2))
    try:
        resp = await client.register_batch(users, y1s, y2s)
    except grpc.aio.AioRpcError as e:
        return _c("red", f"Batch register failed: {e.details()}")
    lines = []
    for user, r in zip(users, resp.results):
        color = "green" if r.success else "red"
        lines.append(_c(color, f"  {user}: {r.message}"))
    ok = sum(1 for r in resp.results if r.success)
    lines.append(_c("cyan", f"{ok}/{len(users)} registered"))
    return "\n".join(lines)


async def do_batch_login(client: AuthClient, users: list[str], passwords: list[str]) -> str:
    """client.rs:342-411: per-user challenges, one batch verification RPC."""
    rng = SecureRng()
    ids, cids, proofs = [], [], []
    errors = {}
    for user, password in zip(users, passwords, strict=True):
        try:
            ch = await client.create_challenge(user)
        except grpc.aio.AioRpcError as e:
            errors[user] = e.details()
            continue
        cid = bytes(ch.challenge_id)
        prover = Prover(Parameters.new(), Witness(password_to_scalar(password, user)))
        transcript = Transcript()
        transcript.append_context(cid)
        proofs.append(prover.prove_with_transcript(rng, transcript).to_bytes())
        ids.append(user)
        cids.append(cid)
    lines = [_c("red", f"  {u}: challenge failed: {msg}") for u, msg in errors.items()]
    if ids:
        try:
            resp = await client.verify_proof_batch(ids, cids, proofs)
        except grpc.aio.AioRpcError as e:
            return _c("red", f"Batch login failed: {e.details()}")
        for user, r in zip(ids, resp.results):
            if r.success:
                lines.append(_c("green", f"  {user}: OK session={r.session_token[:16]}..."))
            else:
                lines.append(_c("red", f"  {user}: {r.message}"))
    return "\n".join(lines) if lines else _c("yellow", "nothing to do")


async def do_status(client: AuthClient, server_addr: str) -> str:
    """client.rs:497-528: probe the server with a timeout'd RPC."""
    try:
        resp = await client.health_check(timeout=2.0)
        if resp.status == 1:
            return _c("green", f"Server {server_addr}: SERVING")
        return _c("yellow", f"Server {server_addr}: NOT SERVING (status={resp.status})")
    except Exception:
        pass
    try:
        await client.create_challenge("__status_probe__", timeout=2.0)
        return _c("green", f"Server {server_addr}: reachable")
    except grpc.aio.AioRpcError as e:
        if e.code() in (grpc.StatusCode.NOT_FOUND, grpc.StatusCode.INVALID_ARGUMENT,
                        grpc.StatusCode.RESOURCE_EXHAUSTED):
            return _c("green", f"Server {server_addr}: reachable")
        return _c("red", f"Server {server_addr}: unreachable ({e.code().name})")


HELP = """Available commands:
  /register <user> <password>            (/r)   register a new user
  /login <user> <password>               (/l)   authenticate
  /batch-register <u1,u2> <p1,p2>        (/br)  register several users
  /batch-login <u1,u2> <p1,p2>           (/bl)  authenticate several users
  /status                                (/st)  probe the server
  /help                                  (/h)   this help
  /quit                                  (/q)   exit"""


async def handle_line(line: str, client: AuthClient, server_addr: str) -> tuple[str, bool]:
    line = line.strip()
    if not line:
        return "", False
    if not line.startswith("/"):
        return "Commands must start with '/'. Type /help for available commands.", False
    parts = line.split(" ", 3)
    cmd = parts[0].lower()

    def two_args(usage: str):
        if len(parts) < 3:
            return None
        return parts[1], parts[2]

    if cmd in ("/register", "/r"):
        args = two_args("/register")
        if args is None:
            return "Usage: /register <user_id> <password>", False
        return await do_register(client, *args), False
    if cmd in ("/login", "/l"):
        args = two_args("/login")
        if args is None:
            return "Usage: /login <user_id> <password>", False
        return await do_login(client, *args), False
    if cmd in ("/batch-register", "/br", "/batch-login", "/bl"):
        args = two_args(cmd)
        if args is None:
            return f"Usage: {cmd} <user1,user2,...> <pass1,pass2,...>", False
        users = [u.strip() for u in args[0].split(",")]
        passwords = [p.strip() for p in args[1].split(",")]
        if len(users) != len(passwords):
            return (
                f"Number of users ({len(users)}) must match number of passwords ({len(passwords)})",
                False,
            )
        if cmd in ("/batch-register", "/br"):
            return await do_batch_register(client, users, passwords), False
        return await do_batch_login(client, users, passwords), False
    if cmd in ("/status", "/st"):
        return await do_status(client, server_addr), False
    if cmd in ("/help", "/h", "/?"):
        return HELP, False
    if cmd in ("/quit", "/exit", "/q"):
        return "bye", True
    return f"Unknown command: {cmd}. Type /help for available commands.", False


async def amain(args) -> None:
    async with AuthClient(args.server, retry=build_retry_policy(args)) as client:
        print(_c("cyan", f"Connected to {args.server}. Type /help for commands."))
        while True:
            try:
                line = await asyncio.to_thread(input, "> ")
            except (EOFError, KeyboardInterrupt):
                print()
                return
            out, quit_ = await handle_line(line, client, args.server)
            if out:
                print(out)
            if quit_:
                return


def main() -> None:
    asyncio.run(amain(parse_args()))


if __name__ == "__main__":
    main()
