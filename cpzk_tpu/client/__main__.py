"""Interactive auth client REPL + bulk subcommands.

REPL commands (+ short aliases, client.rs:47-123): /register /r,
/login /l, /batch-register /br, /batch-login /bl, /stream-login /sl,
/status /st, /help /h /?, /quit /exit /q.  Passwords never leave the
client; registration sends the statement (y1, y2) derived via the
Argon2id KDF and login proves knowledge of the derived scalar against a
single-use server challenge.

Subcommands (the two bulk workload surfaces, drivable end to end):

- ``python -m cpzk_tpu.client stream --proofs 10000``: register
  ephemeral users, then push proofs through the ``VerifyProofStream``
  bidi RPC and report throughput + verdict counts;
- ``python -m cpzk_tpu.client audit run|verify-report|generate ...``:
  the bulk offline audit pipeline (forwards to ``cpzk_tpu.audit``).

Run the REPL: ``python -m cpzk_tpu.client --server 127.0.0.1:50051``
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys

import grpc

from .. import Parameters, Prover, SecureRng, Transcript, Witness
from ..core.ristretto import Ristretto255
from .kdf import password_to_scalar
from .rpc import AuthClient


def _c(color: str, text: str) -> str:
    codes = {"green": "32", "red": "31", "yellow": "33", "cyan": "36", "white": "37"}
    if not sys.stdout.isatty():
        return text
    return f"\x1b[{codes[color]}m{text}\x1b[0m"


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(prog="cpzk-client", description="Chaum-Pedersen auth client")
    p.add_argument(
        "-s", "--server", default=os.environ.get("AUTH_SERVER", "127.0.0.1:50051")
    )
    p.add_argument(
        "--no-retry", action="store_true",
        help="disable transient-error retries (backoff + budget; "
             "idempotent-safe RPCs only — logins are never retried)",
    )
    sub = p.add_subparsers(dest="cmd")
    st = sub.add_parser(
        "stream",
        help="bulk streaming verification: register ephemeral users, "
             "push proofs through VerifyProofStream, report throughput",
    )
    st.add_argument("--users", type=int, default=64)
    st.add_argument("--proofs", type=int, default=1024)
    st.add_argument("--chunk", type=int, default=512,
                    help="entries packed per stream message")
    st.add_argument("--mint-sessions", action="store_true",
                    help="mint a session per verified proof (unary login "
                         "parity; bulk runs usually skip it)")
    st.add_argument("--client-id", default=None,
                    help="cpzk-client-id for keyed fair admission")
    au = sub.add_parser(
        "audit",
        help="bulk offline audit pipeline (see python -m cpzk_tpu.audit)",
    )
    au.add_argument("rest", nargs=argparse.REMAINDER,
                    help="arguments forwarded to cpzk_tpu.audit "
                         "(run / verify-report / generate ...)")
    return p.parse_args(argv)


def build_retry_policy(args):
    """Retry policy from the resolved [retry] config (SERVER_RETRY_* env /
    server.toml) unless --no-retry; None = straight-through calls."""
    if args.no_retry:
        return None
    from ..server.config import ServerConfig

    return ServerConfig.from_env().retry.build_policy()


async def do_register(client: AuthClient, user: str, password: str) -> str:
    """client.rs:206-233."""
    x = password_to_scalar(password, user)
    prover = Prover(Parameters.new(), Witness(x))
    st = prover.statement
    try:
        resp = await client.register(
            user,
            Ristretto255.element_to_bytes(st.y1),
            Ristretto255.element_to_bytes(st.y2),
        )
    except grpc.aio.AioRpcError as e:
        return _c("red", f"Failed: {e.details()}")
    color = "green" if resp.success else "red"
    word = "Registered" if resp.success else "Failed"
    return _c(color, f"{word}: {resp.message}")


async def do_login(client: AuthClient, user: str, password: str) -> str:
    """client.rs:235-285: challenge -> prove with challenge-id context -> verify."""
    try:
        ch = await client.create_challenge(user)
        cid = bytes(ch.challenge_id)
        x = password_to_scalar(password, user)
        prover = Prover(Parameters.new(), Witness(x))
        transcript = Transcript()
        transcript.append_context(cid)
        proof = prover.prove_with_transcript(SecureRng(), transcript)
        resp = await client.verify_proof(user, cid, proof.to_bytes())
    except grpc.aio.AioRpcError as e:
        return _c("red", f"Login failed: {e.details()}")
    if resp.success:
        return _c("green", f"Login OK: {resp.message}\n  session: {resp.session_token}")
    return _c("red", f"Login failed: {resp.message}")


async def do_batch_register(client: AuthClient, users: list[str], passwords: list[str]) -> str:
    """client.rs:287-340."""
    y1s, y2s = [], []
    for user, password in zip(users, passwords, strict=True):
        prover = Prover(Parameters.new(), Witness(password_to_scalar(password, user)))
        y1s.append(Ristretto255.element_to_bytes(prover.statement.y1))
        y2s.append(Ristretto255.element_to_bytes(prover.statement.y2))
    try:
        resp = await client.register_batch(users, y1s, y2s)
    except grpc.aio.AioRpcError as e:
        return _c("red", f"Batch register failed: {e.details()}")
    lines = []
    for user, r in zip(users, resp.results):
        color = "green" if r.success else "red"
        lines.append(_c(color, f"  {user}: {r.message}"))
    ok = sum(1 for r in resp.results if r.success)
    lines.append(_c("cyan", f"{ok}/{len(users)} registered"))
    return "\n".join(lines)


async def do_batch_login(client: AuthClient, users: list[str], passwords: list[str]) -> str:
    """client.rs:342-411: per-user challenges, one batch verification RPC."""
    rng = SecureRng()
    ids, cids, proofs = [], [], []
    errors = {}
    for user, password in zip(users, passwords, strict=True):
        try:
            ch = await client.create_challenge(user)
        except grpc.aio.AioRpcError as e:
            errors[user] = e.details()
            continue
        cid = bytes(ch.challenge_id)
        prover = Prover(Parameters.new(), Witness(password_to_scalar(password, user)))
        transcript = Transcript()
        transcript.append_context(cid)
        proofs.append(prover.prove_with_transcript(rng, transcript).to_bytes())
        ids.append(user)
        cids.append(cid)
    lines = [_c("red", f"  {u}: challenge failed: {msg}") for u, msg in errors.items()]
    if ids:
        try:
            resp = await client.verify_proof_batch(ids, cids, proofs)
        except grpc.aio.AioRpcError as e:
            return _c("red", f"Batch login failed: {e.details()}")
        for user, r in zip(ids, resp.results):
            if r.success:
                lines.append(_c("green", f"  {user}: OK session={r.session_token[:16]}..."))
            else:
                lines.append(_c("red", f"  {user}: {r.message}"))
    return "\n".join(lines) if lines else _c("yellow", "nothing to do")


async def do_stream_login(client: AuthClient, users: list[str], passwords: list[str]) -> str:
    """Authenticate several users over ONE VerifyProofStream (the
    streaming twin of /batch-login): per-user challenges, proofs pushed
    down the stream, sessions minted per verified entry."""
    rng = SecureRng()
    entries = []
    order: list[str] = []
    errors = {}
    for user, password in zip(users, passwords, strict=True):
        try:
            ch = await client.create_challenge(user)
        except grpc.aio.AioRpcError as e:
            errors[user] = e.details()
            continue
        cid = bytes(ch.challenge_id)
        prover = Prover(Parameters.new(), Witness(password_to_scalar(password, user)))
        transcript = Transcript()
        transcript.append_context(cid)
        proof = prover.prove_with_transcript(rng, transcript)
        entries.append((user, cid, proof.to_bytes()))
        order.append(user)
    lines = [_c("red", f"  {u}: challenge failed: {msg}")
             for u, msg in errors.items()]
    if entries:
        try:
            k = 0
            async for v in client.verify_proof_stream(
                entries, mint_sessions=True
            ):
                user = order[k]
                k += 1
                if v.ok:
                    token = (v.session_token or "")[:16]
                    lines.append(_c("green", f"  {user}: OK session={token}..."))
                else:
                    lines.append(_c("red", f"  {user}: {v.message}"))
        except grpc.aio.AioRpcError as e:
            return _c("red", f"Stream login failed: {e.details()}")
    return "\n".join(lines) if lines else _c("yellow", "nothing to do")


async def do_status(client: AuthClient, server_addr: str) -> str:
    """client.rs:497-528: probe the server with a timeout'd RPC."""
    try:
        resp = await client.health_check(timeout=2.0)
        if resp.status == 1:
            return _c("green", f"Server {server_addr}: SERVING")
        return _c("yellow", f"Server {server_addr}: NOT SERVING (status={resp.status})")
    except Exception:
        pass
    try:
        await client.create_challenge("__status_probe__", timeout=2.0)
        return _c("green", f"Server {server_addr}: reachable")
    except grpc.aio.AioRpcError as e:
        if e.code() in (grpc.StatusCode.NOT_FOUND, grpc.StatusCode.INVALID_ARGUMENT,
                        grpc.StatusCode.RESOURCE_EXHAUSTED):
            return _c("green", f"Server {server_addr}: reachable")
        return _c("red", f"Server {server_addr}: unreachable ({e.code().name})")


HELP = """Available commands:
  /register <user> <password>            (/r)   register a new user
  /login <user> <password>               (/l)   authenticate
  /batch-register <u1,u2> <p1,p2>        (/br)  register several users
  /batch-login <u1,u2> <p1,p2>           (/bl)  authenticate several users
  /stream-login <u1,u2> <p1,p2>          (/sl)  authenticate over ONE
                                                VerifyProofStream
  /status                                (/st)  probe the server
  /help                                  (/h)   this help
  /quit                                  (/q)   exit"""


async def handle_line(line: str, client: AuthClient, server_addr: str) -> tuple[str, bool]:
    line = line.strip()
    if not line:
        return "", False
    if not line.startswith("/"):
        return "Commands must start with '/'. Type /help for available commands.", False
    parts = line.split(" ", 3)
    cmd = parts[0].lower()

    def two_args(usage: str):
        if len(parts) < 3:
            return None
        return parts[1], parts[2]

    if cmd in ("/register", "/r"):
        args = two_args("/register")
        if args is None:
            return "Usage: /register <user_id> <password>", False
        return await do_register(client, *args), False
    if cmd in ("/login", "/l"):
        args = two_args("/login")
        if args is None:
            return "Usage: /login <user_id> <password>", False
        return await do_login(client, *args), False
    if cmd in ("/batch-register", "/br", "/batch-login", "/bl",
               "/stream-login", "/sl"):
        args = two_args(cmd)
        if args is None:
            return f"Usage: {cmd} <user1,user2,...> <pass1,pass2,...>", False
        users = [u.strip() for u in args[0].split(",")]
        passwords = [p.strip() for p in args[1].split(",")]
        if len(users) != len(passwords):
            return (
                f"Number of users ({len(users)}) must match number of passwords ({len(passwords)})",
                False,
            )
        if cmd in ("/batch-register", "/br"):
            return await do_batch_register(client, users, passwords), False
        if cmd in ("/stream-login", "/sl"):
            return await do_stream_login(client, users, passwords), False
        return await do_batch_login(client, users, passwords), False
    if cmd in ("/status", "/st"):
        return await do_status(client, server_addr), False
    if cmd in ("/help", "/h", "/?"):
        return HELP, False
    if cmd in ("/quit", "/exit", "/q"):
        return "bye", True
    return f"Unknown command: {cmd}. Type /help for available commands.", False


async def stream_main(args) -> int:
    """Bulk streaming verification driver: ephemeral users, per-proof
    challenges (untimed setup), then one timed ``VerifyProofStream``
    pass.  Prints a JSON summary line — the CLI face of the workload
    ``benches/bench_e2e_curve.py`` measures."""
    import json
    import time as _time

    from .. import SecureRng
    from ..core.ristretto import Ristretto255

    rng = SecureRng()
    n_users = max(1, args.users)
    provers = [
        Prover(Parameters.new(), Witness(Ristretto255.random_scalar(rng)))
        for _ in range(n_users)
    ]
    eb = Ristretto255.element_to_bytes
    run_tag = os.urandom(4).hex()
    names = [f"stream-{run_tag}-{i}" for i in range(n_users)]
    async with AuthClient(
        args.server, retry=build_retry_policy(args), client_id=args.client_id
    ) as client:
        resp = await client.register_batch(
            names,
            [eb(p.statement.y1) for p in provers],
            [eb(p.statement.y2) for p in provers],
        )
        if not all(r.success for r in resp.results):
            print(_c("red", "ephemeral user registration failed"), file=sys.stderr)
            return 1
        # proofs are prepared per wave (the per-user outstanding-challenge
        # cap bounds how many can be pending at once) so each timed pass
        # measures the streaming path, not client-side proving
        ok = bad = shed = 0
        dt = 0.0
        done = 0
        wave_cap = n_users * 3  # MAX_CHALLENGES_PER_USER parity
        while done < args.proofs:
            wave = min(args.proofs - done, wave_cap)
            entries = []
            for k in range(wave):
                u = k % n_users
                ch = await client.create_challenge(names[u])
                cid = bytes(ch.challenge_id)
                t = Transcript()
                t.append_context(cid)
                entries.append(
                    (names[u], cid,
                     provers[u].prove_with_transcript(rng, t).to_bytes())
                )
            t0 = _time.perf_counter()
            async for v in client.verify_proof_stream(
                entries, chunk=args.chunk, mint_sessions=args.mint_sessions
            ):
                if v.ok:
                    ok += 1
                elif v.retry_after_ms:
                    shed += 1
                else:
                    bad += 1
            dt += _time.perf_counter() - t0
            done += wave
        print(json.dumps({
            "metric": "stream_cli",
            "proofs": args.proofs,
            "verified": ok,
            "rejected": bad,
            "shed": shed,
            "seconds": round(dt, 3),
            "proofs_per_s": round(args.proofs / dt, 1) if dt > 0 else None,
        }))
        return 0 if bad == 0 else 1


async def amain(args) -> None:
    async with AuthClient(args.server, retry=build_retry_policy(args)) as client:
        print(_c("cyan", f"Connected to {args.server}. Type /help for commands."))
        while True:
            try:
                line = await asyncio.to_thread(input, "> ")
            except (EOFError, KeyboardInterrupt):
                print()
                return
            out, quit_ = await handle_line(line, client, args.server)
            if out:
                print(out)
            if quit_:
                return


def main() -> None:
    args = parse_args()
    if args.cmd == "stream":
        sys.exit(asyncio.run(stream_main(args)))
    if args.cmd == "audit":
        from ..audit.__main__ import main as audit_main

        sys.exit(audit_main(args.rest))
    asyncio.run(amain(args))


if __name__ == "__main__":
    main()
