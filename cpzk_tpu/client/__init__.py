"""Client plane: gRPC auth client, password KDF, REPL CLI.

Reference analog: ``src/bin/client.rs`` (SURVEY.md §2.1 #15). The KDF is
byte-compatible so statements registered by either implementation verify
against the other.
"""

from .kdf import password_to_scalar
from .rpc import AuthClient

__all__ = ["AuthClient", "password_to_scalar"]
