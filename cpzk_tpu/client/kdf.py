"""Password -> secret scalar derivation (client.rs:179-204 twin).

Pipeline: salt = SHA-256("chaum-pedersen-v1.0.0-{user}")[0:16];
okm = Argon2id(password, salt) with the RustCrypto argon2 crate's default
parameters (m=19456 KiB, t=2, p=1, 32-byte output, version 0x13);
scalar = wide_reduce(SHA-512(okm || "chaum-pedersen-zkp-scalar-derivation")).
Parameters must not drift — interoperable statements depend on it
(SURVEY.md §2.2 argon2 row).
"""

from __future__ import annotations

import hashlib

from ..core.ristretto import Scalar
from ..core.scalars import sc_from_bytes_mod_order_wide

SALT_PREFIX = "chaum-pedersen-v1.0.0-"
SCALAR_DST = b"chaum-pedersen-zkp-scalar-derivation"

ARGON2_MEMORY_KIB = 19456
ARGON2_TIME_COST = 2
ARGON2_PARALLELISM = 1
ARGON2_HASH_LEN = 32


def _argon2id(password: bytes, salt: bytes) -> bytes:
    from argon2.low_level import Type, hash_secret_raw

    return hash_secret_raw(
        secret=password,
        salt=salt,
        time_cost=ARGON2_TIME_COST,
        memory_cost=ARGON2_MEMORY_KIB,
        parallelism=ARGON2_PARALLELISM,
        hash_len=ARGON2_HASH_LEN,
        type=Type.ID,
        version=19,
    )


def derive_salt(user_id: str) -> bytes:
    """Per-user Argon2 salt: SHA-256(prefix || user)[0:16] (client.rs:181-183)."""
    return hashlib.sha256((SALT_PREFIX + user_id).encode()).digest()[:16]


def password_to_scalar(password: str, user_id: str) -> Scalar:
    okm = _argon2id(password.encode(), derive_salt(user_id))
    digest = hashlib.sha512(okm + SCALAR_DST).digest()
    return Scalar(sc_from_bytes_mod_order_wide(digest))
