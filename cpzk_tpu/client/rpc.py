"""Async gRPC client for the auth service (hand-wired stubs).

Mirrors the RPC surface the reference client drives through its generated
``AuthServiceClient`` (``src/bin/client.rs``); method paths and message
types come straight from ``proto/auth.proto``.

Resilience: pass a :class:`~cpzk_tpu.resilience.retry.RetryPolicy` to get
exponential backoff with full jitter and a shared retry budget on
transient failures (``UNAVAILABLE``, ``RESOURCE_EXHAUSTED``).  Only
idempotent-safe RPCs are ever retried — ``VerifyProof`` /
``VerifyProofBatch`` are excluded because the server consumes their
challenges on FIRST receipt (even on failure): a resend can never
succeed, it just burns the challenge, so those errors surface
immediately and the caller restarts from ``CreateChallenge``.
"""

from __future__ import annotations

import asyncio
import random

import grpc

from ..observability.context import RequestContext
from ..resilience.retry import RETRY_PUSHBACK_KEY, RetryPolicy
from ..server.proto import SERVICE_NAME, load_pb2, method_types

#: RPCs safe to resend on a transient failure.  Register re-sent after an
#: unreported success fails loudly with ALREADY_EXISTS (never silently
#: corrupts); CreateChallenge just mints a fresh nonce; health is pure.
_RETRY_SAFE = frozenset({"Register", "RegisterBatch", "CreateChallenge", "HealthCheck"})

#: Metadata tag carrying the caller's self-chosen identity for per-client
#: fair admission (see cpzk_tpu.admission.limiter.client_key).
CLIENT_ID_KEY = "cpzk-client-id"


def _pushback_ms(err) -> float | None:
    """Server retry pushback from an RpcError's trailing metadata
    (``cpzk-retry-after-ms``), or None when absent/unparseable.  Negative
    values are returned as-is — they mean "do not retry" (gRFC A6)."""
    try:
        trailing = err.trailing_metadata()
    except Exception:
        return None
    for key, value in trailing or ():
        if str(key).lower() != RETRY_PUSHBACK_KEY:
            continue
        if isinstance(value, bytes):
            value = value.decode("ascii", "replace")
        try:
            return float(value)
        except (TypeError, ValueError):
            return None
    return None


class AuthClient:
    """Thin unary-unary stub set over a grpc.aio channel."""

    def __init__(
        self,
        target: str,
        credentials: grpc.ChannelCredentials | None = None,
        retry: RetryPolicy | None = None,
        retry_rng: random.Random | None = None,
        client_id: str | None = None,
    ):
        self.pb2 = load_pb2()
        self.retry = retry
        #: sent as ``cpzk-client-id`` metadata on every RPC so the server
        #: keys fair admission to this identity rather than the peer
        #: address (useful behind proxies / NAT).
        self.client_id = client_id
        #: trace context of the most recent RPC attempt (observability).
        self.last_context: RequestContext | None = None
        # injectable RNG so chaos tests get deterministic jitter
        self._retry_rng = retry_rng or random.Random()
        if credentials is not None:
            self.channel = grpc.aio.secure_channel(target, credentials)
        else:
            self.channel = grpc.aio.insecure_channel(target)
        types = method_types(self.pb2)
        self._stubs = {
            name: self.channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            )
            for name, (req, resp) in types.items()
        }

    async def close(self) -> None:
        await self.channel.close()

    async def __aenter__(self) -> "AuthClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # --- retry plumbing ---

    async def _call(self, name: str, stub, request, timeout: float | None):
        """One RPC through the retry policy.  Non-idempotent methods (and
        clients with no policy) go straight through; the rest retry only
        on the policy's transient codes, sleeping full-jitter backoff,
        until attempts or the shared budget run out.

        Every attempt carries a trace context in its gRPC metadata: the
        trace id is minted ONCE per logical call and stays stable across
        retries while the attempt number increments, so the server-side
        trace ring shows a retried request as one trace with several
        completions.  The most recent context is kept on
        ``self.last_context`` for callers that want to correlate their
        own logs with the server's.

        Server pushback (gRFC A6): a rejection carrying
        ``cpzk-retry-after-ms`` trailing metadata overrides the jittered
        backoff — the sleep is exactly the server-advertised delay
        (sized from its queue drain rate).  Negative pushback means the
        server asked us not to retry at all.  The retry budget and
        attempt cap still apply either way."""
        rctx = RequestContext()
        self.last_context = rctx
        policy = self.retry
        if policy is None or name not in _RETRY_SAFE:
            return await stub(
                request, timeout=timeout, metadata=self._metadata(rctx)
            )
        while True:
            try:
                response = await stub(
                    request, timeout=timeout, metadata=self._metadata(rctx)
                )
            except grpc.RpcError as e:
                code = e.code()
                code_name = code.name if code is not None else ""
                pushback = _pushback_ms(e)
                if pushback is not None and pushback < 0:
                    raise  # server pushback: do not retry
                if not policy.should_retry(code_name, rctx.attempt):
                    raise
                await asyncio.sleep(
                    policy.sleep_s(
                        rctx.attempt, pushback_ms=pushback,
                        rng=self._retry_rng,
                    )
                )
                rctx = rctx.child()  # same trace id, attempt + 1
                self.last_context = rctx
                continue
            policy.note_success()
            return response

    def _metadata(self, rctx: RequestContext):
        md = rctx.to_metadata()
        if self.client_id:
            md += ((CLIENT_ID_KEY, self.client_id),)
        return md

    # --- RPCs ---

    async def register(self, user_id: str, y1: bytes, y2: bytes, timeout: float | None = None):
        return await self._call(
            "Register",
            self._stubs["Register"],
            self.pb2.RegistrationRequest(user_id=user_id, y1=y1, y2=y2),
            timeout,
        )

    async def register_batch(
        self, user_ids: list[str], y1_values: list[bytes], y2_values: list[bytes],
        timeout: float | None = None,
    ):
        return await self._call(
            "RegisterBatch",
            self._stubs["RegisterBatch"],
            self.pb2.BatchRegistrationRequest(
                user_ids=user_ids, y1_values=y1_values, y2_values=y2_values
            ),
            timeout,
        )

    async def create_challenge(self, user_id: str, timeout: float | None = None):
        return await self._call(
            "CreateChallenge",
            self._stubs["CreateChallenge"],
            self.pb2.ChallengeRequest(user_id=user_id),
            timeout,
        )

    async def verify_proof(
        self, user_id: str, challenge_id: bytes, proof: bytes, timeout: float | None = None
    ):
        # never retried: the challenge is consumed server-side on first
        # receipt, so a resend is guaranteed PERMISSION_DENIED
        return await self._call(
            "VerifyProof",
            self._stubs["VerifyProof"],
            self.pb2.VerificationRequest(
                user_id=user_id, challenge_id=challenge_id, proof=proof
            ),
            timeout,
        )

    async def verify_proof_batch(
        self, user_ids: list[str], challenge_ids: list[bytes], proofs: list[bytes],
        timeout: float | None = None,
    ):
        # never retried (same consumed-challenge semantics as VerifyProof)
        return await self._call(
            "VerifyProofBatch",
            self._stubs["VerifyProofBatch"],
            self.pb2.BatchVerificationRequest(
                user_ids=user_ids, challenge_ids=challenge_ids, proofs=proofs
            ),
            timeout,
        )

    async def health_check(
        self, timeout: float | None = None, service: str = ""
    ):
        # service="" is the liveness probe; service="readiness" (or the
        # auth service name) additionally reports NOT_SERVING while the
        # backend is degraded or WAL recovery is still replaying, so load
        # balancers stop routing to a replica that would only shed.
        from ..server.proto import load_health_pb2

        pb2 = load_health_pb2()
        stub = self.channel.unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=pb2.HealthCheckRequest.SerializeToString,
            response_deserializer=pb2.HealthCheckResponse.FromString,
        )
        return await self._call(
            "HealthCheck", stub, pb2.HealthCheckRequest(service=service),
            timeout,
        )
