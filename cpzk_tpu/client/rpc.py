"""Async gRPC client for the auth service (hand-wired stubs).

Mirrors the RPC surface the reference client drives through its generated
``AuthServiceClient`` (``src/bin/client.rs``); method paths and message
types come straight from ``proto/auth.proto``.

Resilience: pass a :class:`~cpzk_tpu.resilience.retry.RetryPolicy` to get
exponential backoff with full jitter and a shared retry budget on
transient failures (``UNAVAILABLE``, ``RESOURCE_EXHAUSTED``).  Only
idempotent-safe RPCs are ever retried — ``VerifyProof`` /
``VerifyProofBatch`` are excluded because the server consumes their
challenges on FIRST receipt (even on failure): a resend can never
succeed, it just burns the challenge, so those errors surface
immediately and the caller restarts from ``CreateChallenge``.
"""

from __future__ import annotations

import asyncio
import random

import grpc

from ..observability.context import RequestContext
from ..resilience.retry import RetryPolicy
from ..server.proto import SERVICE_NAME, load_pb2, method_types

#: RPCs safe to resend on a transient failure.  Register re-sent after an
#: unreported success fails loudly with ALREADY_EXISTS (never silently
#: corrupts); CreateChallenge just mints a fresh nonce; health is pure.
_RETRY_SAFE = frozenset({"Register", "RegisterBatch", "CreateChallenge", "HealthCheck"})


class AuthClient:
    """Thin unary-unary stub set over a grpc.aio channel."""

    def __init__(
        self,
        target: str,
        credentials: grpc.ChannelCredentials | None = None,
        retry: RetryPolicy | None = None,
        retry_rng: random.Random | None = None,
    ):
        self.pb2 = load_pb2()
        self.retry = retry
        #: trace context of the most recent RPC attempt (observability).
        self.last_context: RequestContext | None = None
        # injectable RNG so chaos tests get deterministic jitter
        self._retry_rng = retry_rng or random.Random()
        if credentials is not None:
            self.channel = grpc.aio.secure_channel(target, credentials)
        else:
            self.channel = grpc.aio.insecure_channel(target)
        types = method_types(self.pb2)
        self._stubs = {
            name: self.channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            )
            for name, (req, resp) in types.items()
        }

    async def close(self) -> None:
        await self.channel.close()

    async def __aenter__(self) -> "AuthClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # --- retry plumbing ---

    async def _call(self, name: str, stub, request, timeout: float | None):
        """One RPC through the retry policy.  Non-idempotent methods (and
        clients with no policy) go straight through; the rest retry only
        on the policy's transient codes, sleeping full-jitter backoff,
        until attempts or the shared budget run out.

        Every attempt carries a trace context in its gRPC metadata: the
        trace id is minted ONCE per logical call and stays stable across
        retries while the attempt number increments, so the server-side
        trace ring shows a retried request as one trace with several
        completions.  The most recent context is kept on
        ``self.last_context`` for callers that want to correlate their
        own logs with the server's."""
        rctx = RequestContext()
        self.last_context = rctx
        policy = self.retry
        if policy is None or name not in _RETRY_SAFE:
            return await stub(
                request, timeout=timeout, metadata=rctx.to_metadata()
            )
        while True:
            try:
                response = await stub(
                    request, timeout=timeout, metadata=rctx.to_metadata()
                )
            except grpc.RpcError as e:
                code = e.code()
                code_name = code.name if code is not None else ""
                if not policy.should_retry(code_name, rctx.attempt):
                    raise
                await asyncio.sleep(
                    policy.backoff_s(rctx.attempt, self._retry_rng)
                )
                rctx = rctx.child()  # same trace id, attempt + 1
                self.last_context = rctx
                continue
            policy.note_success()
            return response

    # --- RPCs ---

    async def register(self, user_id: str, y1: bytes, y2: bytes, timeout: float | None = None):
        return await self._call(
            "Register",
            self._stubs["Register"],
            self.pb2.RegistrationRequest(user_id=user_id, y1=y1, y2=y2),
            timeout,
        )

    async def register_batch(
        self, user_ids: list[str], y1_values: list[bytes], y2_values: list[bytes],
        timeout: float | None = None,
    ):
        return await self._call(
            "RegisterBatch",
            self._stubs["RegisterBatch"],
            self.pb2.BatchRegistrationRequest(
                user_ids=user_ids, y1_values=y1_values, y2_values=y2_values
            ),
            timeout,
        )

    async def create_challenge(self, user_id: str, timeout: float | None = None):
        return await self._call(
            "CreateChallenge",
            self._stubs["CreateChallenge"],
            self.pb2.ChallengeRequest(user_id=user_id),
            timeout,
        )

    async def verify_proof(
        self, user_id: str, challenge_id: bytes, proof: bytes, timeout: float | None = None
    ):
        # never retried: the challenge is consumed server-side on first
        # receipt, so a resend is guaranteed PERMISSION_DENIED
        return await self._call(
            "VerifyProof",
            self._stubs["VerifyProof"],
            self.pb2.VerificationRequest(
                user_id=user_id, challenge_id=challenge_id, proof=proof
            ),
            timeout,
        )

    async def verify_proof_batch(
        self, user_ids: list[str], challenge_ids: list[bytes], proofs: list[bytes],
        timeout: float | None = None,
    ):
        # never retried (same consumed-challenge semantics as VerifyProof)
        return await self._call(
            "VerifyProofBatch",
            self._stubs["VerifyProofBatch"],
            self.pb2.BatchVerificationRequest(
                user_ids=user_ids, challenge_ids=challenge_ids, proofs=proofs
            ),
            timeout,
        )

    async def health_check(self, timeout: float | None = None):
        from ..server.proto import load_health_pb2

        pb2 = load_health_pb2()
        stub = self.channel.unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=pb2.HealthCheckRequest.SerializeToString,
            response_deserializer=pb2.HealthCheckResponse.FromString,
        )
        return await self._call(
            "HealthCheck", stub, pb2.HealthCheckRequest(service=""), timeout
        )
