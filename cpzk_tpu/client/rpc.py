"""Async gRPC client for the auth service (hand-wired stubs).

Mirrors the RPC surface the reference client drives through its generated
``AuthServiceClient`` (``src/bin/client.rs``); method paths and message
types come straight from ``proto/auth.proto``.

Resilience: pass a :class:`~cpzk_tpu.resilience.retry.RetryPolicy` to get
exponential backoff with full jitter and a shared retry budget on
transient failures (``UNAVAILABLE``, ``RESOURCE_EXHAUSTED``).  Only
idempotent-safe RPCs are ever retried — ``VerifyProof`` /
``VerifyProofBatch`` are excluded because the server consumes their
challenges on FIRST receipt (even on failure): a resend can never
succeed, it just burns the challenge, so those errors surface
immediately and the caller restarts from ``CreateChallenge``.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass

import grpc

from ..fleet.partition_map import (
    PARTITION_MAP_VERSION_KEY,
    PARTITION_OWNER_KEY,
    PartitionMap,
)
from ..observability.context import RequestContext
from ..resilience.retry import RETRY_PUSHBACK_KEY, RetryPolicy
from ..server.proto import SERVICE_NAME, load_pb2, method_types, stream_method_types

#: RPCs safe to resend on a transient failure.  Register re-sent after an
#: unreported success fails loudly with ALREADY_EXISTS (never silently
#: corrupts); CreateChallenge just mints a fresh nonce; health is pure.
_RETRY_SAFE = frozenset({"Register", "RegisterBatch", "CreateChallenge", "HealthCheck"})

#: Metadata tag carrying the caller's self-chosen identity for per-client
#: fair admission (see cpzk_tpu.admission.limiter.client_key).
CLIENT_ID_KEY = "cpzk-client-id"

#: Hard cap on wrong-partition re-routes within one logical call: the
#: contract is one refresh + re-route per attempt, and a second redirect
#: in a row means the fleet's maps are churning — surface the error.
_MAX_REDIRECTS = 2


def _pushback_ms(err) -> float | None:
    """Server retry pushback from an RpcError's trailing metadata
    (``cpzk-retry-after-ms``), or None when absent/unparseable.  Negative
    values are returned as-is — they mean "do not retry" (gRFC A6)."""
    try:
        trailing = err.trailing_metadata()
    except Exception:
        return None
    for key, value in trailing or ():
        if str(key).lower() != RETRY_PUSHBACK_KEY:
            continue
        if isinstance(value, bytes):
            value = value.decode("ascii", "replace")
        try:
            return float(value)
        except (TypeError, ValueError):
            return None
    return None


def _redirect_info(err) -> tuple[str | None, int | None]:
    """``(owner_address, map_version)`` from a wrong-partition
    FAILED_PRECONDITION's trailing metadata, or ``(None, None)`` when the
    error is not a fleet redirect.  Both trailers must be present — a
    plain FAILED_PRECONDITION from anything else is never re-routed."""
    try:
        trailing = err.trailing_metadata()
    except Exception:
        return None, None
    owner: str | None = None
    version: int | None = None
    for key, value in trailing or ():
        k = str(key).lower()
        if isinstance(value, bytes):
            value = value.decode("utf-8", "replace")
        if k == PARTITION_OWNER_KEY:
            owner = str(value)
        elif k == PARTITION_MAP_VERSION_KEY:
            try:
                version = int(value)
            except (TypeError, ValueError):
                version = None
    if owner is None or version is None:
        return None, None
    return owner, version


@dataclass(slots=True)
class StreamVerdict:
    """One per-proof outcome from :meth:`AuthClient.verify_proof_stream`.

    ``retry_after_ms`` nonzero marks an entry the server SHED under
    admission pressure (not verified, not rejected) — resend it after the
    delay; the stream itself stayed open."""

    id: int
    ok: bool
    message: str
    session_token: str | None = None
    retry_after_ms: int = 0


class AuthClient:
    """Thin unary-unary stub set over a grpc.aio channel — or, with a
    :class:`~cpzk_tpu.fleet.PartitionMap`, over a **channel pool keyed by
    partition**: user-keyed RPCs route to the owning partition's address,
    batch RPCs fan out per partition, and a wrong-partition redirect
    (``FAILED_PRECONDITION`` + the map-version/owner trailers) triggers
    at most ONE map refresh + re-route per attempt, charged against the
    retry budget.  ``VerifyProof`` — never retried on any other error,
    because its challenge is consumed server-side on first receipt — IS
    safely re-routed here: the server checks ownership *before* touching
    state, so a redirected proof's challenge was never consumed."""

    def __init__(
        self,
        target: str = "",
        credentials: grpc.ChannelCredentials | None = None,
        retry: RetryPolicy | None = None,
        retry_rng: random.Random | None = None,
        client_id: str | None = None,
        partition_map: PartitionMap | None = None,
        map_refresh=None,
        refresh_jitter_s: float = 0.25,
        refresh_min_interval_s: float = 1.0,
        reconnect_damp_s: float = 0.5,
    ):
        self.pb2 = load_pb2()
        self.retry = retry
        #: sent as ``cpzk-client-id`` metadata on every RPC so the server
        #: keys fair admission to this identity rather than the peer
        #: address (useful behind proxies / NAT).
        self.client_id = client_id
        #: trace context of the most recent RPC attempt (observability).
        self.last_context: RequestContext | None = None
        #: the routing map (None = single-target client, exactly as
        #: before); refreshed in place on a server redirect when
        #: ``map_refresh`` is provided.
        self.partition_map = partition_map
        #: zero-arg callable (sync or async) returning a fresh
        #: :class:`PartitionMap` or None — typically a fetch of the ops
        #: plane's ``/partitionmap``; invoked at most once per redirect.
        self.map_refresh = map_refresh
        #: wrong-partition re-routes performed (observability/tests).
        self.redirects = 0
        #: UNAVAILABLE-triggered dials of a partition's warm standby
        #: (v2 maps only; observability/tests).
        self.standby_dials = 0
        # herd damping: N clients waking together (a promotion, a map
        # flip) must not hammer /partitionmap or the new primary in one
        # synchronized wave.  Map refreshes are SINGLE-FLIGHT (concurrent
        # callers share one in-flight fetch) behind a full-jitter delay
        # and a min re-fetch interval; the first RPC to an address that
        # just answered UNAVAILABLE sleeps full jitter before re-dialing.
        self.refresh_jitter_s = refresh_jitter_s
        self.refresh_min_interval_s = refresh_min_interval_s
        self.reconnect_damp_s = reconnect_damp_s
        self._refresh_inflight: asyncio.Task | None = None
        self._refresh_done_at = float("-inf")
        #: address -> loop time of the last UNAVAILABLE from it
        self._addr_down: dict[str, float] = {}
        #: damping observability (tests + bench assertions)
        self.refresh_fetches = 0
        self.refresh_coalesced = 0
        self.reconnects_damped = 0
        # injectable RNG so chaos tests get deterministic jitter
        self._retry_rng = retry_rng or random.Random()
        self._credentials = credentials
        if not target:
            if partition_map is None:
                raise ValueError(
                    "AuthClient needs a target or a partition_map"
                )
            target = partition_map.partitions[0].address
        self._target = target
        # per-partition channel pool; the default target's channel lives
        # in it too, so `self.channel` stays one of the pooled channels
        self._pool: dict[str, grpc.aio.Channel] = {}
        self._unary_stubs: dict[tuple[str, str], object] = {}
        self.channel = self._channel(target)
        types = method_types(self.pb2)
        self._stubs = {
            name: self._stub(target, name) for name in types
        }
        stream_types = stream_method_types(self.pb2)
        req, resp = stream_types["VerifyProofStream"]
        self._stream_stub = self.channel.stream_stream(
            f"/{SERVICE_NAME}/VerifyProofStream",
            request_serializer=req.SerializeToString,
            response_deserializer=resp.FromString,
        )

    # --- the per-partition channel pool ---

    def _channel(self, address: str) -> grpc.aio.Channel:
        ch = self._pool.get(address)
        if ch is None:
            if self._credentials is not None:
                ch = grpc.aio.secure_channel(address, self._credentials)
            else:
                ch = grpc.aio.insecure_channel(address)
            self._pool[address] = ch
        return ch

    def _stub(self, address: str, name: str):
        key = (address, name)
        stub = self._unary_stubs.get(key)
        if stub is None:
            req, resp = method_types(self.pb2)[name]
            stub = self._channel(address).unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            )
            self._unary_stubs[key] = stub
        return stub

    def _route_address(self, user_id: str) -> str:
        """The owning partition's address under the client's map (the
        default target when no map is loaded)."""
        if self.partition_map is None:
            return self._target
        return self.partition_map.partition_for(user_id).address

    def _standby_for(self, address: str | None) -> str | None:
        """The warm-standby address paired with ``address`` under a v2
        map (the failover target when the primary answers UNAVAILABLE),
        or None on v1 maps / unknown addresses.  Symmetric: the map may
        already name the standby as the primary (a flipped entry), in
        which case the *other* address of the pair is returned."""
        pmap = self.partition_map
        if pmap is None or not address:
            return None
        for p in pmap.partitions:
            if not p.standby:
                continue
            if p.address == address:
                return p.standby
            if p.standby == address:
                return p.address
        return None

    async def _refresh_map(self) -> bool:
        """One bounded, HERD-DAMPED map refresh (called on a redirect):
        adopt the fetched map when its version is strictly newer.  A
        refresh failure is non-fatal — the redirect's owner trailer still
        routes this attempt.

        Damping: concurrent callers coalesce onto ONE in-flight fetch
        (single-flight), the fetch itself starts behind a full-jitter
        delay of up to ``refresh_jitter_s``, and a refresh that completed
        within ``refresh_min_interval_s`` answers from that result
        instead of re-fetching — so a thousand clients redirected by the
        same map flip produce a trickle of ``/partitionmap`` hits, not a
        synchronized wave."""
        if self.map_refresh is None:
            return False
        loop = asyncio.get_running_loop()
        task = self._refresh_inflight
        if task is None:
            if (
                loop.time() - self._refresh_done_at
                < self.refresh_min_interval_s
            ):
                return False  # a fresh-enough fetch already answered
            task = loop.create_task(self._do_refresh())
            self._refresh_inflight = task
        else:
            self.refresh_coalesced += 1
        # shield: one caller being cancelled must not kill the fetch the
        # coalesced others are waiting on
        try:
            return await asyncio.shield(task)
        except asyncio.CancelledError:
            raise
        except Exception:
            return False

    async def _do_refresh(self) -> bool:
        try:
            if self.refresh_jitter_s > 0:
                await asyncio.sleep(
                    self._retry_rng.uniform(0.0, self.refresh_jitter_s)
                )
            self.refresh_fetches += 1
            fresh = self.map_refresh()
            if asyncio.iscoroutine(fresh):
                fresh = await fresh
        except Exception:
            fresh = None
        finally:
            self._refresh_done_at = asyncio.get_running_loop().time()
            self._refresh_inflight = None
        if fresh is None or self.partition_map is None:
            return False
        if fresh.version > self.partition_map.version:
            self.partition_map = fresh
            return True
        return False

    def _mark_down(self, address: str | None) -> None:
        if address:
            self._addr_down[address] = asyncio.get_running_loop().time()

    async def _damp_reconnect(self, address: str | None) -> None:
        """Full-jitter sleep before the first RPC back to an address that
        just answered UNAVAILABLE, so N clients reconnecting after a
        failover spread their re-dials over ``reconnect_damp_s`` instead
        of landing on the new primary as one thundering herd.  One damped
        attempt per down-mark: the mark clears after the sleep (steady
        traffic is never taxed) and a still-down address re-marks on the
        next failure."""
        if not address or self.reconnect_damp_s <= 0:
            return
        since = self._addr_down.get(address)
        if since is None:
            return
        loop = asyncio.get_running_loop()
        if loop.time() - since > self.reconnect_damp_s:
            # the outage mark is stale — the herd window has passed
            self._addr_down.pop(address, None)
            return
        self._addr_down.pop(address, None)
        self.reconnects_damped += 1
        await asyncio.sleep(
            self._retry_rng.uniform(0.0, self.reconnect_damp_s)
        )

    async def close(self) -> None:
        for ch in self._pool.values():
            await ch.close()

    async def __aenter__(self) -> "AuthClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # --- retry plumbing ---

    async def _call(
        self, name: str, stub, request, timeout: float | None,
        user_id: str | None = None, address: str | None = None,
    ):
        """One RPC through the routing + retry stack.

        **Routing** (fleet mode only — a ``partition_map`` is loaded):
        ``user_id``-keyed RPCs resolve the owning partition's address per
        attempt and go out on that partition's pooled channel.  A
        wrong-partition rejection (``FAILED_PRECONDITION`` carrying the
        map-version + owner trailers) triggers at most one map refresh +
        re-route per attempt — charged against the shared retry budget,
        capped at ``_MAX_REDIRECTS`` per logical call — which is how a
        stale-map client converges in one extra round trip.  This applies
        to EVERY routed RPC including ``VerifyProof``: the server checks
        ownership before consuming anything, so a redirected proof is not
        a replay.

        **Retries**: non-idempotent methods (and clients with no policy)
        go straight through; the rest retry only on the policy's
        transient codes, sleeping full-jitter backoff, until attempts or
        the shared budget run out.

        Every attempt carries a trace context in its gRPC metadata: the
        trace id is minted ONCE per logical call and stays stable across
        retries/redirects while the attempt number increments, so the
        server-side trace ring shows a retried request as one trace with
        several completions.  The most recent context is kept on
        ``self.last_context`` for callers that want to correlate their
        own logs with the server's.

        Server pushback (gRFC A6): a rejection carrying
        ``cpzk-retry-after-ms`` trailing metadata overrides the jittered
        backoff — the sleep is exactly the server-advertised delay
        (sized from its queue drain rate).  Negative pushback means the
        server asked us not to retry at all.  The retry budget and
        attempt cap still apply either way."""
        rctx = RequestContext()
        self.last_context = rctx
        policy = self.retry
        routed = self.partition_map is not None and user_id is not None
        if routed:
            address = self._route_address(user_id)
            stub = self._stub(address, name)
        elif address is None:
            address = self._target
        # post-failover herd damping: jittered hold-off before re-dialing
        # an address whose last answer was UNAVAILABLE
        await self._damp_reconnect(address)
        redirected = 0
        standby_tried = False
        while True:
            try:
                response = await stub(
                    request, timeout=timeout, metadata=self._metadata(rctx)
                )
            except grpc.RpcError as e:
                code = e.code()
                code_name = code.name if code is not None else ""
                if code_name == "UNAVAILABLE":
                    self._mark_down(address)
                    # v2-map failover: dial the partition's warm standby
                    # ONCE per logical call, before any retry budget is
                    # charged — a dead primary mid-handover (or a plain
                    # crash) costs one extra dial, not a backoff ladder
                    if not standby_tried:
                        standby = self._standby_for(address)
                        if standby is not None and standby != address:
                            standby_tried = True
                            self.standby_dials += 1
                            stub = self._stub(standby, name)
                            address = standby
                            rctx = rctx.child()
                            self.last_context = rctx
                            continue
                if (
                    self.partition_map is not None
                    and code_name == "FAILED_PRECONDITION"
                    and redirected < _MAX_REDIRECTS
                ):
                    owner, _version = _redirect_info(e)
                    if owner is not None:
                        # one refresh + re-route, against the retry budget
                        if (
                            policy is not None
                            and policy.budget is not None
                            and not policy.budget.try_withdraw()
                        ):
                            raise
                        redirected += 1
                        self.redirects += 1
                        refreshed = await self._refresh_map()
                        addr = owner
                        if refreshed and user_id is not None:
                            # the fresh map may know better than the
                            # (possibly itself-stale) rejecting server
                            addr = self._route_address(user_id)
                        stub = self._stub(addr, name)
                        address = addr
                        rctx = rctx.child()  # same trace id, attempt + 1
                        self.last_context = rctx
                        continue
                pushback = _pushback_ms(e)
                if policy is None or name not in _RETRY_SAFE:
                    raise
                if pushback is not None and pushback < 0:
                    raise  # server pushback: do not retry
                if not policy.should_retry(code_name, rctx.attempt):
                    raise
                await asyncio.sleep(
                    policy.sleep_s(
                        rctx.attempt, pushback_ms=pushback,
                        rng=self._retry_rng,
                    )
                )
                rctx = rctx.child()  # same trace id, attempt + 1
                self.last_context = rctx
                continue
            if policy is not None and name in _RETRY_SAFE:
                policy.note_success()
            self._addr_down.pop(address, None)
            return response

    def _metadata(self, rctx: RequestContext):
        md = rctx.to_metadata()
        if self.client_id:
            md += ((CLIENT_ID_KEY, self.client_id),)
        return md

    # --- RPCs ---

    async def register(self, user_id: str, y1: bytes, y2: bytes, timeout: float | None = None):
        return await self._call(
            "Register",
            self._stubs["Register"],
            self.pb2.RegistrationRequest(user_id=user_id, y1=y1, y2=y2),
            timeout,
            user_id=user_id,
        )

    def _partition_groups(
        self, user_ids: list[str]
    ) -> list[tuple[str, list[int]]] | None:
        """Batch fan-out plan: ``[(address, [indices]), ...]`` grouping
        the batch by owning partition under the client's map, or ``None``
        when no fan-out is needed (no map, or a single partition)."""
        pmap = self.partition_map
        if pmap is None or len(pmap.partitions) < 2:
            return None
        groups: dict[str, list[int]] = {}
        for i, uid in enumerate(user_ids):
            groups.setdefault(pmap.partition_for(uid).address, []).append(i)
        return list(groups.items())

    async def register_batch(
        self, user_ids: list[str], y1_values: list[bytes], y2_values: list[bytes],
        timeout: float | None = None,
    ):
        groups = self._partition_groups(user_ids)
        if groups is None:
            return await self._call(
                "RegisterBatch",
                self._stubs["RegisterBatch"],
                self.pb2.BatchRegistrationRequest(
                    user_ids=user_ids, y1_values=y1_values, y2_values=y2_values
                ),
                timeout,
            )
        # fleet fan-out: one sub-batch per owning partition, results
        # reassembled in the caller's entry order
        results = [None] * len(user_ids)
        for address, idxs in groups:
            resp = await self._call(
                "RegisterBatch",
                self._stub(address, "RegisterBatch"),
                self.pb2.BatchRegistrationRequest(
                    user_ids=[user_ids[i] for i in idxs],
                    y1_values=[y1_values[i] for i in idxs],
                    y2_values=[y2_values[i] for i in idxs],
                ),
                timeout,
                address=address,
            )
            for k, i in enumerate(idxs):
                results[i] = resp.results[k]
        return self.pb2.BatchRegistrationResponse(results=results)

    async def create_challenge(self, user_id: str, timeout: float | None = None):
        return await self._call(
            "CreateChallenge",
            self._stubs["CreateChallenge"],
            self.pb2.ChallengeRequest(user_id=user_id),
            timeout,
            user_id=user_id,
        )

    async def verify_proof(
        self, user_id: str, challenge_id: bytes, proof: bytes, timeout: float | None = None
    ):
        # never retried: the challenge is consumed server-side on first
        # receipt, so a resend is guaranteed PERMISSION_DENIED.  (A fleet
        # wrong-partition redirect IS re-routed — ownership is checked
        # before the consume, so nothing was burned.)
        return await self._call(
            "VerifyProof",
            self._stubs["VerifyProof"],
            self.pb2.VerificationRequest(
                user_id=user_id, challenge_id=challenge_id, proof=proof
            ),
            timeout,
            user_id=user_id,
        )

    async def verify_proof_batch(
        self, user_ids: list[str], challenge_ids: list[bytes], proofs: list[bytes],
        timeout: float | None = None,
    ):
        # never retried (same consumed-challenge semantics as VerifyProof)
        groups = self._partition_groups(user_ids)
        if groups is None:
            return await self._call(
                "VerifyProofBatch",
                self._stubs["VerifyProofBatch"],
                self.pb2.BatchVerificationRequest(
                    user_ids=user_ids, challenge_ids=challenge_ids, proofs=proofs
                ),
                timeout,
            )
        results = [None] * len(user_ids)
        for address, idxs in groups:
            resp = await self._call(
                "VerifyProofBatch",
                self._stub(address, "VerifyProofBatch"),
                self.pb2.BatchVerificationRequest(
                    user_ids=[user_ids[i] for i in idxs],
                    challenge_ids=[challenge_ids[i] for i in idxs],
                    proofs=[proofs[i] for i in idxs],
                ),
                timeout,
                address=address,
            )
            for k, i in enumerate(idxs):
                results[i] = resp.results[k]
        return self.pb2.BatchVerificationResponse(results=results)

    async def verify_proof_stream(
        self,
        entries,
        timeout: float | None = None,
        mint_sessions: bool = False,
        chunk: int = 512,
    ):
        """Stream proofs, get verdicts: an async iterator of
        :class:`StreamVerdict` over the ``VerifyProofStream`` bidi RPC.

        ``entries`` is a sync or async iterable of ``(user_id,
        challenge_id, proof_bytes)`` tuples.  The client packs up to
        ``chunk`` entries per wire message (amortizing HTTP/2 frame +
        protobuf overhead — the knob that lets one stream keep a device
        batch engine fed) and assigns sequential ids; verdicts stream
        back in entry order as the server's device batches settle.

        Never retried (same consumed-challenge semantics as
        VerifyProof): a transport failure mid-stream surfaces
        immediately — the caller restarts from CreateChallenge for
        whatever entries had no verdict yet.

        Fleet note: a stream rides ONE channel (the default target), so
        in a multi-partition deployment the driver shards its entry
        stream per partition itself (``partition_map.partition_for``)
        and opens one stream per partition; entries for users this
        partition does not own come back as per-entry wrong-partition
        failures, never a dead stream.

        Convenience wrapper over :meth:`verify_proof_stream_chunks` —
        bulk drivers that count outcomes at 10k+ proofs/s should consume
        the chunk iterator directly and skip the per-entry object."""
        async for chunk_v in self.verify_proof_stream_chunks(
            entries, timeout=timeout, mint_sessions=mint_sessions,
            chunk=chunk,
        ):
            ids, succ, msgs, tokens, push = chunk_v
            n_tok = len(tokens)
            n_msg = len(msgs)
            for k in range(len(ids)):
                ok = succ[k]
                yield StreamVerdict(
                    id=ids[k],
                    ok=ok,
                    message=msgs[k] if k < n_msg else "",
                    session_token=(
                        tokens[k] if k < n_tok and tokens[k] else None
                    ),
                    retry_after_ms=0 if ok else push,
                )

    async def verify_proof_stream_chunks(
        self,
        entries,
        timeout: float | None = None,
        mint_sessions: bool = False,
        chunk: int = 512,
    ):
        """The raw chunk-level face of :meth:`verify_proof_stream`:
        yields ``(ids, success, messages, session_tokens,
        retry_after_ms)`` — plain lists materialized once per response
        message — in entry order.  This is the surface bulk pipelines
        and the e2e bench drive: per-verdict Python objects are the
        client's dominant cost at device-batch rates."""
        rctx = RequestContext()
        self.last_context = rctx
        call = self._stream_stub(
            timeout=timeout, metadata=self._metadata(rctx)
        )

        async def _aiter(items):
            if hasattr(items, "__aiter__"):
                async for item in items:
                    yield item
            else:
                for item in items:
                    yield item

        async def _writer():
            step = max(1, chunk)
            if not hasattr(entries, "__aiter__"):
                # list input (the bulk-driver shape): slice whole chunks
                # instead of stepping an async generator per entry — at
                # device-batch rates the per-entry loop is measurable
                # client overhead on the same host
                items = entries if isinstance(entries, list) else list(entries)
                for lo in range(0, len(items), step):
                    part = items[lo:lo + step]
                    users, cids, proofs = zip(*part)
                    await call.write(self.pb2.StreamVerifyRequest(
                        ids=range(lo, lo + len(part)),
                        user_ids=users,
                        challenge_ids=map(bytes, cids),
                        proofs=map(bytes, proofs),
                        mint_sessions=mint_sessions,
                    ))
                await call.done_writing()
                return
            next_id = 0
            ids, users, cids, proofs = [], [], [], []

            async def _flush():
                nonlocal ids, users, cids, proofs
                await call.write(self.pb2.StreamVerifyRequest(
                    ids=ids, user_ids=users, challenge_ids=cids,
                    proofs=proofs, mint_sessions=mint_sessions,
                ))
                ids, users, cids, proofs = [], [], [], []

            async for user_id, challenge_id, proof in _aiter(entries):
                ids.append(next_id)
                next_id += 1
                users.append(user_id)
                cids.append(bytes(challenge_id))
                proofs.append(bytes(proof))
                if len(ids) >= max(1, chunk):
                    await _flush()
            if ids:
                await _flush()
            await call.done_writing()

        writer = asyncio.ensure_future(_writer())
        try:
            async for resp in call:
                # bulk repeated-field materialization (one C call each)
                # instead of per-index proto __getitem__ in a hot loop
                yield (
                    list(resp.ids),
                    list(resp.success),
                    list(resp.messages),
                    list(resp.session_tokens),
                    int(getattr(resp, "retry_after_ms", 0) or 0),
                )
            await writer
        finally:
            if not writer.done():
                writer.cancel()
                await asyncio.gather(writer, return_exceptions=True)
            # abandoned mid-iteration (caller broke out of the loop):
            # cancel the RPC so the server tears the stream down instead
            # of waiting on a reader that will never come back
            try:
                if not call.done():
                    call.cancel()
            except Exception:  # pragma: no cover - non-grpc call stub
                pass

    async def health_check(
        self, timeout: float | None = None, service: str = ""
    ):
        # service="" is the liveness probe; service="readiness" (or the
        # auth service name) additionally reports NOT_SERVING while the
        # backend is degraded or WAL recovery is still replaying, so load
        # balancers stop routing to a replica that would only shed.
        from ..server.proto import load_health_pb2

        pb2 = load_health_pb2()
        stub = self.channel.unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=pb2.HealthCheckRequest.SerializeToString,
            response_deserializer=pb2.HealthCheckResponse.FromString,
        )
        return await self._call(
            "HealthCheck", stub, pb2.HealthCheckRequest(service=service),
            timeout,
        )
