"""Async gRPC client for the auth service (hand-wired stubs).

Mirrors the RPC surface the reference client drives through its generated
``AuthServiceClient`` (``src/bin/client.rs``); method paths and message
types come straight from ``proto/auth.proto``.

Resilience: pass a :class:`~cpzk_tpu.resilience.retry.RetryPolicy` to get
exponential backoff with full jitter and a shared retry budget on
transient failures (``UNAVAILABLE``, ``RESOURCE_EXHAUSTED``).  Only
idempotent-safe RPCs are ever retried — ``VerifyProof`` /
``VerifyProofBatch`` are excluded because the server consumes their
challenges on FIRST receipt (even on failure): a resend can never
succeed, it just burns the challenge, so those errors surface
immediately and the caller restarts from ``CreateChallenge``.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass

import grpc

from ..observability.context import RequestContext
from ..resilience.retry import RETRY_PUSHBACK_KEY, RetryPolicy
from ..server.proto import SERVICE_NAME, load_pb2, method_types, stream_method_types

#: RPCs safe to resend on a transient failure.  Register re-sent after an
#: unreported success fails loudly with ALREADY_EXISTS (never silently
#: corrupts); CreateChallenge just mints a fresh nonce; health is pure.
_RETRY_SAFE = frozenset({"Register", "RegisterBatch", "CreateChallenge", "HealthCheck"})

#: Metadata tag carrying the caller's self-chosen identity for per-client
#: fair admission (see cpzk_tpu.admission.limiter.client_key).
CLIENT_ID_KEY = "cpzk-client-id"


def _pushback_ms(err) -> float | None:
    """Server retry pushback from an RpcError's trailing metadata
    (``cpzk-retry-after-ms``), or None when absent/unparseable.  Negative
    values are returned as-is — they mean "do not retry" (gRFC A6)."""
    try:
        trailing = err.trailing_metadata()
    except Exception:
        return None
    for key, value in trailing or ():
        if str(key).lower() != RETRY_PUSHBACK_KEY:
            continue
        if isinstance(value, bytes):
            value = value.decode("ascii", "replace")
        try:
            return float(value)
        except (TypeError, ValueError):
            return None
    return None


@dataclass(slots=True)
class StreamVerdict:
    """One per-proof outcome from :meth:`AuthClient.verify_proof_stream`.

    ``retry_after_ms`` nonzero marks an entry the server SHED under
    admission pressure (not verified, not rejected) — resend it after the
    delay; the stream itself stayed open."""

    id: int
    ok: bool
    message: str
    session_token: str | None = None
    retry_after_ms: int = 0


class AuthClient:
    """Thin unary-unary stub set over a grpc.aio channel."""

    def __init__(
        self,
        target: str,
        credentials: grpc.ChannelCredentials | None = None,
        retry: RetryPolicy | None = None,
        retry_rng: random.Random | None = None,
        client_id: str | None = None,
    ):
        self.pb2 = load_pb2()
        self.retry = retry
        #: sent as ``cpzk-client-id`` metadata on every RPC so the server
        #: keys fair admission to this identity rather than the peer
        #: address (useful behind proxies / NAT).
        self.client_id = client_id
        #: trace context of the most recent RPC attempt (observability).
        self.last_context: RequestContext | None = None
        # injectable RNG so chaos tests get deterministic jitter
        self._retry_rng = retry_rng or random.Random()
        if credentials is not None:
            self.channel = grpc.aio.secure_channel(target, credentials)
        else:
            self.channel = grpc.aio.insecure_channel(target)
        types = method_types(self.pb2)
        self._stubs = {
            name: self.channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            )
            for name, (req, resp) in types.items()
        }
        stream_types = stream_method_types(self.pb2)
        req, resp = stream_types["VerifyProofStream"]
        self._stream_stub = self.channel.stream_stream(
            f"/{SERVICE_NAME}/VerifyProofStream",
            request_serializer=req.SerializeToString,
            response_deserializer=resp.FromString,
        )

    async def close(self) -> None:
        await self.channel.close()

    async def __aenter__(self) -> "AuthClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # --- retry plumbing ---

    async def _call(self, name: str, stub, request, timeout: float | None):
        """One RPC through the retry policy.  Non-idempotent methods (and
        clients with no policy) go straight through; the rest retry only
        on the policy's transient codes, sleeping full-jitter backoff,
        until attempts or the shared budget run out.

        Every attempt carries a trace context in its gRPC metadata: the
        trace id is minted ONCE per logical call and stays stable across
        retries while the attempt number increments, so the server-side
        trace ring shows a retried request as one trace with several
        completions.  The most recent context is kept on
        ``self.last_context`` for callers that want to correlate their
        own logs with the server's.

        Server pushback (gRFC A6): a rejection carrying
        ``cpzk-retry-after-ms`` trailing metadata overrides the jittered
        backoff — the sleep is exactly the server-advertised delay
        (sized from its queue drain rate).  Negative pushback means the
        server asked us not to retry at all.  The retry budget and
        attempt cap still apply either way."""
        rctx = RequestContext()
        self.last_context = rctx
        policy = self.retry
        if policy is None or name not in _RETRY_SAFE:
            return await stub(
                request, timeout=timeout, metadata=self._metadata(rctx)
            )
        while True:
            try:
                response = await stub(
                    request, timeout=timeout, metadata=self._metadata(rctx)
                )
            except grpc.RpcError as e:
                code = e.code()
                code_name = code.name if code is not None else ""
                pushback = _pushback_ms(e)
                if pushback is not None and pushback < 0:
                    raise  # server pushback: do not retry
                if not policy.should_retry(code_name, rctx.attempt):
                    raise
                await asyncio.sleep(
                    policy.sleep_s(
                        rctx.attempt, pushback_ms=pushback,
                        rng=self._retry_rng,
                    )
                )
                rctx = rctx.child()  # same trace id, attempt + 1
                self.last_context = rctx
                continue
            policy.note_success()
            return response

    def _metadata(self, rctx: RequestContext):
        md = rctx.to_metadata()
        if self.client_id:
            md += ((CLIENT_ID_KEY, self.client_id),)
        return md

    # --- RPCs ---

    async def register(self, user_id: str, y1: bytes, y2: bytes, timeout: float | None = None):
        return await self._call(
            "Register",
            self._stubs["Register"],
            self.pb2.RegistrationRequest(user_id=user_id, y1=y1, y2=y2),
            timeout,
        )

    async def register_batch(
        self, user_ids: list[str], y1_values: list[bytes], y2_values: list[bytes],
        timeout: float | None = None,
    ):
        return await self._call(
            "RegisterBatch",
            self._stubs["RegisterBatch"],
            self.pb2.BatchRegistrationRequest(
                user_ids=user_ids, y1_values=y1_values, y2_values=y2_values
            ),
            timeout,
        )

    async def create_challenge(self, user_id: str, timeout: float | None = None):
        return await self._call(
            "CreateChallenge",
            self._stubs["CreateChallenge"],
            self.pb2.ChallengeRequest(user_id=user_id),
            timeout,
        )

    async def verify_proof(
        self, user_id: str, challenge_id: bytes, proof: bytes, timeout: float | None = None
    ):
        # never retried: the challenge is consumed server-side on first
        # receipt, so a resend is guaranteed PERMISSION_DENIED
        return await self._call(
            "VerifyProof",
            self._stubs["VerifyProof"],
            self.pb2.VerificationRequest(
                user_id=user_id, challenge_id=challenge_id, proof=proof
            ),
            timeout,
        )

    async def verify_proof_batch(
        self, user_ids: list[str], challenge_ids: list[bytes], proofs: list[bytes],
        timeout: float | None = None,
    ):
        # never retried (same consumed-challenge semantics as VerifyProof)
        return await self._call(
            "VerifyProofBatch",
            self._stubs["VerifyProofBatch"],
            self.pb2.BatchVerificationRequest(
                user_ids=user_ids, challenge_ids=challenge_ids, proofs=proofs
            ),
            timeout,
        )

    async def verify_proof_stream(
        self,
        entries,
        timeout: float | None = None,
        mint_sessions: bool = False,
        chunk: int = 512,
    ):
        """Stream proofs, get verdicts: an async iterator of
        :class:`StreamVerdict` over the ``VerifyProofStream`` bidi RPC.

        ``entries`` is a sync or async iterable of ``(user_id,
        challenge_id, proof_bytes)`` tuples.  The client packs up to
        ``chunk`` entries per wire message (amortizing HTTP/2 frame +
        protobuf overhead — the knob that lets one stream keep a device
        batch engine fed) and assigns sequential ids; verdicts stream
        back in entry order as the server's device batches settle.

        Never retried (same consumed-challenge semantics as
        VerifyProof): a transport failure mid-stream surfaces
        immediately — the caller restarts from CreateChallenge for
        whatever entries had no verdict yet.

        Convenience wrapper over :meth:`verify_proof_stream_chunks` —
        bulk drivers that count outcomes at 10k+ proofs/s should consume
        the chunk iterator directly and skip the per-entry object."""
        async for chunk_v in self.verify_proof_stream_chunks(
            entries, timeout=timeout, mint_sessions=mint_sessions,
            chunk=chunk,
        ):
            ids, succ, msgs, tokens, push = chunk_v
            n_tok = len(tokens)
            n_msg = len(msgs)
            for k in range(len(ids)):
                ok = succ[k]
                yield StreamVerdict(
                    id=ids[k],
                    ok=ok,
                    message=msgs[k] if k < n_msg else "",
                    session_token=(
                        tokens[k] if k < n_tok and tokens[k] else None
                    ),
                    retry_after_ms=0 if ok else push,
                )

    async def verify_proof_stream_chunks(
        self,
        entries,
        timeout: float | None = None,
        mint_sessions: bool = False,
        chunk: int = 512,
    ):
        """The raw chunk-level face of :meth:`verify_proof_stream`:
        yields ``(ids, success, messages, session_tokens,
        retry_after_ms)`` — plain lists materialized once per response
        message — in entry order.  This is the surface bulk pipelines
        and the e2e bench drive: per-verdict Python objects are the
        client's dominant cost at device-batch rates."""
        rctx = RequestContext()
        self.last_context = rctx
        call = self._stream_stub(
            timeout=timeout, metadata=self._metadata(rctx)
        )

        async def _aiter(items):
            if hasattr(items, "__aiter__"):
                async for item in items:
                    yield item
            else:
                for item in items:
                    yield item

        async def _writer():
            next_id = 0
            ids, users, cids, proofs = [], [], [], []

            async def _flush():
                nonlocal ids, users, cids, proofs
                await call.write(self.pb2.StreamVerifyRequest(
                    ids=ids, user_ids=users, challenge_ids=cids,
                    proofs=proofs, mint_sessions=mint_sessions,
                ))
                ids, users, cids, proofs = [], [], [], []

            async for user_id, challenge_id, proof in _aiter(entries):
                ids.append(next_id)
                next_id += 1
                users.append(user_id)
                cids.append(bytes(challenge_id))
                proofs.append(bytes(proof))
                if len(ids) >= max(1, chunk):
                    await _flush()
            if ids:
                await _flush()
            await call.done_writing()

        writer = asyncio.ensure_future(_writer())
        try:
            async for resp in call:
                # bulk repeated-field materialization (one C call each)
                # instead of per-index proto __getitem__ in a hot loop
                yield (
                    list(resp.ids),
                    list(resp.success),
                    list(resp.messages),
                    list(resp.session_tokens),
                    int(getattr(resp, "retry_after_ms", 0) or 0),
                )
            await writer
        finally:
            if not writer.done():
                writer.cancel()
                await asyncio.gather(writer, return_exceptions=True)
            # abandoned mid-iteration (caller broke out of the loop):
            # cancel the RPC so the server tears the stream down instead
            # of waiting on a reader that will never come back
            try:
                if not call.done():
                    call.cancel()
            except Exception:  # pragma: no cover - non-grpc call stub
                pass

    async def health_check(
        self, timeout: float | None = None, service: str = ""
    ):
        # service="" is the liveness probe; service="readiness" (or the
        # auth service name) additionally reports NOT_SERVING while the
        # backend is degraded or WAL recovery is still replaying, so load
        # balancers stop routing to a replica that would only shed.
        from ..server.proto import load_health_pb2

        pb2 = load_health_pb2()
        stub = self.channel.unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=pb2.HealthCheckRequest.SerializeToString,
            response_deserializer=pb2.HealthCheckResponse.FromString,
        )
        return await self._call(
            "HealthCheck", stub, pb2.HealthCheckRequest(service=service),
            timeout,
        )
