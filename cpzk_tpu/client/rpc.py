"""Async gRPC client for the auth service (hand-wired stubs).

Mirrors the RPC surface the reference client drives through its generated
``AuthServiceClient`` (``src/bin/client.rs``); method paths and message
types come straight from ``proto/auth.proto``.
"""

from __future__ import annotations

import grpc

from ..server.proto import SERVICE_NAME, load_pb2, method_types


class AuthClient:
    """Thin unary-unary stub set over a grpc.aio channel."""

    def __init__(self, target: str, credentials: grpc.ChannelCredentials | None = None):
        self.pb2 = load_pb2()
        if credentials is not None:
            self.channel = grpc.aio.secure_channel(target, credentials)
        else:
            self.channel = grpc.aio.insecure_channel(target)
        types = method_types(self.pb2)
        self._stubs = {
            name: self.channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            )
            for name, (req, resp) in types.items()
        }

    async def close(self) -> None:
        await self.channel.close()

    async def __aenter__(self) -> "AuthClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # --- RPCs ---

    async def register(self, user_id: str, y1: bytes, y2: bytes, timeout: float | None = None):
        return await self._stubs["Register"](
            self.pb2.RegistrationRequest(user_id=user_id, y1=y1, y2=y2), timeout=timeout
        )

    async def register_batch(
        self, user_ids: list[str], y1_values: list[bytes], y2_values: list[bytes],
        timeout: float | None = None,
    ):
        return await self._stubs["RegisterBatch"](
            self.pb2.BatchRegistrationRequest(
                user_ids=user_ids, y1_values=y1_values, y2_values=y2_values
            ),
            timeout=timeout,
        )

    async def create_challenge(self, user_id: str, timeout: float | None = None):
        return await self._stubs["CreateChallenge"](
            self.pb2.ChallengeRequest(user_id=user_id), timeout=timeout
        )

    async def verify_proof(
        self, user_id: str, challenge_id: bytes, proof: bytes, timeout: float | None = None
    ):
        return await self._stubs["VerifyProof"](
            self.pb2.VerificationRequest(
                user_id=user_id, challenge_id=challenge_id, proof=proof
            ),
            timeout=timeout,
        )

    async def verify_proof_batch(
        self, user_ids: list[str], challenge_ids: list[bytes], proofs: list[bytes],
        timeout: float | None = None,
    ):
        return await self._stubs["VerifyProofBatch"](
            self.pb2.BatchVerificationRequest(
                user_ids=user_ids, challenge_ids=challenge_ids, proofs=proofs
            ),
            timeout=timeout,
        )

    async def health_check(self, timeout: float | None = None):
        from ..server.proto import load_health_pb2

        pb2 = load_health_pb2()
        stub = self.channel.unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=pb2.HealthCheckRequest.SerializeToString,
            response_deserializer=pb2.HealthCheckResponse.FromString,
        )
        return await stub(pb2.HealthCheckRequest(service=""), timeout=timeout)
