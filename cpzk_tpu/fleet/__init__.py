"""Fleet layer: N-partition routing over the replication plane.

ROADMAP item 3: one primary/standby pair (ISSUE 8) scaled out to N
partitions behind a versioned :class:`PartitionMap` — consumed
client-side by :class:`~cpzk_tpu.client.AuthClient`, enforced
server-side by the auth service (wrong-partition RPCs redirect with the
map version + owner address in trailing metadata), served read-only from
the ops plane at ``/partitionmap``, and **grown** by the live split flow
(:mod:`cpzk_tpu.fleet.split`), which moves a hash range's users to a new
partition through the same ``SegmentApplier`` trust boundary promotion
already relies on.

CLI: ``python -m cpzk_tpu.fleet init|show|route|split``.
"""

from .partition_map import (
    HASH_SPACE,
    PARTITION_MAP_VERSION_KEY,
    PARTITION_OWNER_KEY,
    FleetRouter,
    Partition,
    PartitionMap,
    fetch_partition_map,
    user_hash,
)
from .split import SPLIT_CRASH_POINTS, SplitError, run_split

__all__ = [
    "HASH_SPACE",
    "PARTITION_MAP_VERSION_KEY",
    "PARTITION_OWNER_KEY",
    "SPLIT_CRASH_POINTS",
    "FleetRouter",
    "Partition",
    "PartitionMap",
    "SplitError",
    "fetch_partition_map",
    "run_split",
    "user_hash",
]
