"""Fleet CLI: build, inspect, and grow the partition map.

Subcommands::

    python -m cpzk_tpu.fleet init --addresses a:1,b:2,c:3 --out map.json
    python -m cpzk_tpu.fleet show --map map.json
    python -m cpzk_tpu.fleet route --map map.json USER_ID [USER_ID ...]
    python -m cpzk_tpu.fleet split --map map.json --source 0 \\
        --new-address d:4 --source-state p0.json --target-state p3.json

``split`` is crash-resumable: SIGKILL it at any stage and re-running the
identical command completes the split (see ``fleet/split.py`` and the
runbook in docs/operations.md §"Partitioned fleet").
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def cmd_init(args) -> int:
    from .partition_map import PartitionMap

    addresses = [a.strip() for a in args.addresses.split(",") if a.strip()]
    pmap = PartitionMap.uniform(addresses)
    pmap.store(args.out)
    print(json.dumps({
        "path": args.out, "version": pmap.version,
        "partitions": len(pmap.partitions), "digest": pmap.short_digest(),
    }))
    return 0


def cmd_show(args) -> int:
    from .partition_map import PartitionMap

    pmap = PartitionMap.load(args.map)
    print(pmap.to_json(), end="")
    return 0


def cmd_route(args) -> int:
    from .partition_map import PartitionMap, user_hash

    pmap = PartitionMap.load(args.map)
    for uid in args.user_ids:
        p = pmap.partition_for(uid)
        print(json.dumps({
            "user_id": uid, "hash": user_hash(uid),
            "partition": p.index, "address": p.address,
            "map_version": pmap.version,
        }))
    return 0


def cmd_split(args) -> int:
    from .split import SplitError, run_split

    try:
        report = asyncio.run(run_split(
            args.map, args.source, args.new_address,
            args.source_state, args.target_state,
            source_wal=args.source_wal,
            target_wal=args.target_wal,
            source_epoch_file=args.source_epoch,
            target_epoch_file=args.target_epoch,
            segment_bytes=args.segment_bytes,
        ))
    except SplitError as e:
        print(f"split: {e}", file=sys.stderr)
        return 2
    print(json.dumps(report))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m cpzk_tpu.fleet",
        description="partition-map fleet tooling",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    i = sub.add_parser("init", help="write an initial uniform partition map")
    i.add_argument("--addresses", required=True,
                   help="comma-separated partition addresses, index order")
    i.add_argument("--out", required=True)
    i.set_defaults(fn=cmd_init)

    s = sub.add_parser("show", help="print a validated partition map")
    s.add_argument("--map", required=True)
    s.set_defaults(fn=cmd_show)

    r = sub.add_parser("route", help="resolve user ids to partitions")
    r.add_argument("--map", required=True)
    r.add_argument("user_ids", nargs="+")
    r.set_defaults(fn=cmd_route)

    sp = sub.add_parser(
        "split",
        help="move half the source partition's largest hash range onto a "
             "new partition (crash-resumable; see docs/operations.md)",
    )
    sp.add_argument("--map", required=True)
    sp.add_argument("--source", type=int, required=True,
                    help="index of the partition to split")
    sp.add_argument("--new-address", required=True,
                    help="serving address of the new partition")
    sp.add_argument("--source-state", required=True,
                    help="the source partition's state_file")
    sp.add_argument("--target-state", required=True,
                    help="the new partition's state_file (created)")
    sp.add_argument("--source-wal", default=None,
                    help="default <source-state>.wal")
    sp.add_argument("--target-wal", default=None,
                    help="default <target-state>.wal")
    sp.add_argument("--source-epoch", default=None,
                    help="default <source-state>.epoch")
    sp.add_argument("--target-epoch", default=None,
                    help="default <target-state>.epoch")
    sp.add_argument("--segment-bytes", type=int, default=65536)
    sp.set_defaults(fn=cmd_split)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except (OSError, ValueError) as e:
        print(f"fleet: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
