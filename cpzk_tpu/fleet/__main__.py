"""Fleet CLI: build, inspect, and grow the partition map.

Subcommands::

    python -m cpzk_tpu.fleet init --addresses a:1,b:2,c:3 --out map.json
    python -m cpzk_tpu.fleet show --map map.json
    python -m cpzk_tpu.fleet route --map map.json USER_ID [USER_ID ...]
    python -m cpzk_tpu.fleet split --map map.json --source 0 \\
        --new-address d:4 --source-state p0.json --target-state p3.json
    python -m cpzk_tpu.fleet set-standby --map map.json --partition 0 \\
        --standby a2:1
    python -m cpzk_tpu.fleet rolling-restart --map map.json

``split`` is crash-resumable: SIGKILL it at any stage and re-running the
identical command completes the split (see ``fleet/split.py`` and the
runbook in docs/operations.md §"Partitioned fleet").

``rolling-restart`` (ISSUE 18) walks an N-partition replicated fleet one
partition at a time: coordinated handover to the partition's warm
standby (zero acked-write loss, write blackout bounded by one ship RTT +
promotion), verify the new primary serves, flip the map entry
(``swap_standby``), then move on — refusing to touch the next partition
while the previous one is unhealthy.  The deposed primaries are left
draining for the operator to restart (they come back as the standbys the
flipped map already names).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def cmd_init(args) -> int:
    from .partition_map import PartitionMap

    addresses = [a.strip() for a in args.addresses.split(",") if a.strip()]
    standbys = None
    if args.standbys:
        standbys = [
            (s.strip() or None) for s in args.standbys.split(",")
        ]
        if len(standbys) != len(addresses):
            raise ValueError(
                f"--standbys needs {len(addresses)} comma-separated "
                f"entries (blank = no standby), got {len(standbys)}"
            )
    pmap = PartitionMap.uniform(addresses, standbys=standbys)
    pmap.store(args.out)
    print(json.dumps({
        "path": args.out, "version": pmap.version,
        "partitions": len(pmap.partitions), "digest": pmap.short_digest(),
    }))
    return 0


def cmd_set_standby(args) -> int:
    from .partition_map import PartitionMap

    pmap = PartitionMap.load(args.map).set_standby(
        args.partition, args.standby or None
    )
    pmap.store(args.map)
    print(json.dumps({
        "path": args.map, "version": pmap.version,
        "partition": args.partition,
        "standby": pmap.partitions[args.partition].standby,
        "digest": pmap.short_digest(),
    }))
    return 0


def cmd_show(args) -> int:
    from .partition_map import PartitionMap

    pmap = PartitionMap.load(args.map)
    print(pmap.to_json(), end="")
    return 0


def cmd_route(args) -> int:
    from .partition_map import PartitionMap, user_hash

    pmap = PartitionMap.load(args.map)
    for uid in args.user_ids:
        p = pmap.partition_for(uid)
        print(json.dumps({
            "user_id": uid, "hash": user_hash(uid),
            "partition": p.index, "address": p.address,
            "map_version": pmap.version,
        }))
    return 0


def cmd_split(args) -> int:
    from .split import SplitError, run_split

    try:
        report = asyncio.run(run_split(
            args.map, args.source, args.new_address,
            args.source_state, args.target_state,
            source_wal=args.source_wal,
            target_wal=args.target_wal,
            source_epoch_file=args.source_epoch,
            target_epoch_file=args.target_epoch,
            segment_bytes=args.segment_bytes,
        ))
    except SplitError as e:
        print(f"split: {e}", file=sys.stderr)
        return 2
    print(json.dumps(report))
    return 0


async def _replication_status(address: str, timeout: float):
    """One ReplicationStatus probe (no lease renewal) — returns the
    response or raises."""
    import grpc

    from ..replication.wire import ReplicationStub

    channel = grpc.aio.insecure_channel(address)
    try:
        stub = ReplicationStub(channel)
        return await stub.replication_status(
            stub.pb2.ReplicationStatusRequest(), timeout=timeout
        )
    finally:
        await channel.close()


async def _serving_primary(address: str, timeout: float) -> bool:
    try:
        resp = await _replication_status(address, timeout)
    except Exception:
        return False
    return resp.role == "primary"


async def _roll_fleet(args) -> int:
    import grpc

    from ..replication.wire import ReplicationStub
    from .partition_map import PartitionMap

    pmap = PartitionMap.load(args.map)
    rolled = []
    prev_primary: str | None = None
    for index in range(len(pmap.partitions)):
        pmap = PartitionMap.load(args.map)  # pick up our own flips
        p = pmap.partitions[index]
        if not p.standby:
            print(json.dumps({
                "partition": index, "address": p.address,
                "skipped": "no standby in the map",
            }))
            continue
        # the safety rail: never take partition N down while partition
        # N-1's new primary is not verifiably serving
        if prev_primary is not None and not await _serving_primary(
            prev_primary, args.timeout
        ):
            print(
                f"rolling-restart: REFUSING to roll partition {index} — "
                f"previous partition's new primary {prev_primary} is not "
                "healthy; fix it and re-run (completed partitions are "
                "already flipped in the map)",
                file=sys.stderr,
            )
            return 3
        channel = grpc.aio.insecure_channel(p.address)
        try:
            stub = ReplicationStub(channel)
            resp = await stub.handover(
                stub.pb2.HandoverRequest(
                    phase="initiate", reason="rolling-restart"
                ),
                timeout=args.timeout,
            )
        except grpc.aio.AioRpcError as e:
            print(
                f"rolling-restart: partition {index} primary {p.address} "
                f"unreachable ({e.code().name}); stopping",
                file=sys.stderr,
            )
            return 3
        finally:
            await channel.close()
        if not resp.ok:
            print(
                f"rolling-restart: partition {index} handover refused: "
                f"{resp.message}; stopping",
                file=sys.stderr,
            )
            return 3
        # verify the promoted standby actually serves as primary at the
        # new epoch before flipping the map and moving on
        deadline = asyncio.get_running_loop().time() + args.timeout
        promoted = False
        while asyncio.get_running_loop().time() < deadline:
            try:
                st = await _replication_status(p.standby, args.timeout)
                if st.role == "primary" and st.epoch >= resp.epoch:
                    promoted = True
                    break
            except Exception:
                pass
            await asyncio.sleep(0.1)
        if not promoted:
            print(
                f"rolling-restart: partition {index} standby {p.standby} "
                f"did not surface as primary at epoch {resp.epoch}; "
                "stopping (map NOT flipped for this partition)",
                file=sys.stderr,
            )
            return 3
        pmap = pmap.swap_standby(index)
        pmap.store(args.map)
        rolled.append(index)
        prev_primary = pmap.partitions[index].address
        print(json.dumps({
            "partition": index, "new_primary": prev_primary,
            "old_primary": pmap.partitions[index].standby,
            "epoch": int(resp.epoch), "fence_seq": int(resp.fence_seq),
            "handover_ms": round(resp.duration_s * 1000.0, 1),
            "map_version": pmap.version,
        }))
    print(json.dumps({
        "rolled": rolled, "partitions": len(pmap.partitions),
        "map_version": pmap.version,
    }))
    return 0


def cmd_rolling_restart(args) -> int:
    return asyncio.run(_roll_fleet(args))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m cpzk_tpu.fleet",
        description="partition-map fleet tooling",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    i = sub.add_parser("init", help="write an initial uniform partition map")
    i.add_argument("--addresses", required=True,
                   help="comma-separated partition addresses, index order")
    i.add_argument("--standbys", default="",
                   help="comma-separated warm-standby addresses, index "
                        "order (blank entry = no standby); makes a "
                        "schema-v2 map")
    i.add_argument("--out", required=True)
    i.set_defaults(fn=cmd_init)

    s = sub.add_parser("show", help="print a validated partition map")
    s.add_argument("--map", required=True)
    s.set_defaults(fn=cmd_show)

    r = sub.add_parser("route", help="resolve user ids to partitions")
    r.add_argument("--map", required=True)
    r.add_argument("user_ids", nargs="+")
    r.set_defaults(fn=cmd_route)

    sp = sub.add_parser(
        "split",
        help="move half the source partition's largest hash range onto a "
             "new partition (crash-resumable; see docs/operations.md)",
    )
    sp.add_argument("--map", required=True)
    sp.add_argument("--source", type=int, required=True,
                    help="index of the partition to split")
    sp.add_argument("--new-address", required=True,
                    help="serving address of the new partition")
    sp.add_argument("--source-state", required=True,
                    help="the source partition's state_file")
    sp.add_argument("--target-state", required=True,
                    help="the new partition's state_file (created)")
    sp.add_argument("--source-wal", default=None,
                    help="default <source-state>.wal")
    sp.add_argument("--target-wal", default=None,
                    help="default <target-state>.wal")
    sp.add_argument("--source-epoch", default=None,
                    help="default <source-state>.epoch")
    sp.add_argument("--target-epoch", default=None,
                    help="default <target-state>.epoch")
    sp.add_argument("--segment-bytes", type=int, default=65536)
    sp.set_defaults(fn=cmd_split)

    ss = sub.add_parser(
        "set-standby",
        help="stamp (or clear) a partition's warm-standby address in the "
             "map (bumps the version; a standby-free map stays schema v1)",
    )
    ss.add_argument("--map", required=True)
    ss.add_argument("--partition", type=int, required=True)
    ss.add_argument("--standby", default="",
                    help="standby address; empty clears it")
    ss.set_defaults(fn=cmd_set_standby)

    rr = sub.add_parser(
        "rolling-restart",
        help="coordinated handover across the fleet, one partition at a "
             "time (zero acked-write loss; refuses to proceed past an "
             "unhealthy partition)",
    )
    rr.add_argument("--map", required=True)
    rr.add_argument("--timeout", type=float, default=15.0,
                    help="per-step deadline in seconds (handover RPC, "
                         "promotion poll, health probe)")
    rr.set_defaults(fn=cmd_rolling_restart)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except (OSError, ValueError) as e:
        print(f"fleet: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
