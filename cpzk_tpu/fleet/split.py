"""Live partition splitting: grow the fleet one hash range at a time.

``python -m cpzk_tpu.fleet split`` moves the upper half of the source
partition's largest hash range onto a brand-new partition, using the
same machinery lease-based promotion already trusts:

1. **manifest** — the split's own write-ahead intent: the computed new
   map version, the moved ranges, and a fencing epoch (source epoch + 1,
   exactly how promotion fences a deposed primary) are atomically
   written to ``<map>.split.json`` *before anything else changes*, so a
   SIGKILL at any later stage leaves a resumable plan, and a re-run
   continues the SAME split instead of computing a different one.
2. **copy** — the source partition's state is recovered from its
   snapshot + WAL (the ordinary durability boot path, torn tails
   truncated), the moved users' records are exported as a deterministic
   journal-record stream (``ServerState.export_user_records``),
   re-sequenced from 1, sealed into CRC'd segments
   (:func:`~cpzk_tpu.replication.segments.split_records`), and replayed
   into the new partition through the
   :class:`~cpzk_tpu.replication.SegmentApplier` **trust boundary** — a
   tampered source file cannot smuggle into the new partition what a
   live RPC would reject — with every applied frame durable in the new
   partition's own WAL *before* it is applied (the standby's
   persist-then-commit discipline).  The copy is idempotent: a re-run
   truncates the target files and rebuilds them from scratch.
3. **flip** — the new map (version + 1) is atomically renamed over the
   map file.  From this instant the moved range's owner of record is the
   new partition; the old source still *holds* stale copies but
   server-side ownership enforcement refuses to serve them, so the fleet
   never serves one user from two places.
4. **drain** — the moved users are dropped from the source's state, a
   fresh covering snapshot lands, and the source WAL is compacted away
   (the same "snapshot covers everything, replay nothing" state a
   graceful shutdown leaves).  Only then is the manifest removed.

Crash consistency (the chaos suite SIGKILLs every stage): before the
flip, the fleet serves entirely from the source (the target is not in
the map); after the flip, the target is authoritative for the moved
range and enforcement fences the source's stale copies until the drain
lands.  At no point can both partitions serve the same user, and a
re-run of the identical command completes the split from whatever stage
the crash left.

The source partition must be **stopped** (or read-only) while the split
runs — the runbook in docs/operations.md §"Partitioned fleet" walks the
stop → split → restart-with-new-map sequence and the rollback.
"""

from __future__ import annotations

import json
import logging
import os

from ..durability.wal import WriteAheadLog
from ..replication.segments import split_records
from ..replication.standby import SegmentApplier, load_epoch, store_epoch
from .partition_map import PartitionMap, user_hash

log = logging.getLogger("cpzk_tpu.fleet")

#: Schema tag of the split manifest (``<map>.split.json``).
MANIFEST_SCHEMA = "cpzk-split-manifest/1"

#: Deterministic crash sites the chaos suite schedules via a
#: :class:`~cpzk_tpu.resilience.faults.FaultPlan` — each raises
#: :class:`~cpzk_tpu.resilience.faults.CrashPoint` at exactly the file
#: state a SIGKILL at that instruction would leave behind.
SPLIT_CRASH_POINTS = (
    "pre_manifest",   # nothing written: the split never started
    "pre_copy",       # manifest durable, target untouched
    "mid_copy",       # target WAL half-written (next run rebuilds it)
    "pre_flip",       # target complete, map still the old version
    "pre_drain",      # map flipped, source still holds stale copies
    "pre_finish",     # drain done, manifest still present
)


class SplitError(RuntimeError):
    """A split cannot proceed (bad arguments, mismatched resume manifest,
    or a segment the trust boundary refused)."""


def _crash(faults, point: str) -> None:
    if faults is not None and faults.take_crash(point):
        from ..resilience.faults import CrashPoint

        raise CrashPoint(f"{point} during partition split")


def manifest_path(map_path: str) -> str:
    return map_path + ".split.json"


def _write_manifest(path: str, doc: dict) -> None:
    import tempfile

    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix="." + os.path.basename(path) + ".", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


async def _recover_into(state, state_file: str, wal_path: str):
    """Load a stopped partition's durable pair through the ordinary
    recovery path (snapshot + torn-tail truncation + suffix replay)."""
    from ..durability.recovery import recover_state

    return await recover_state(state, state_file, wal_path)


async def run_split(
    map_path: str,
    source: int,
    new_address: str,
    source_state_file: str,
    target_state_file: str,
    *,
    source_wal: str | None = None,
    target_wal: str | None = None,
    source_epoch_file: str | None = None,
    target_epoch_file: str | None = None,
    segment_bytes: int = 65536,
    faults=None,
) -> dict:
    """Run (or resume) one split; returns a report dict.  Idempotent and
    crash-resumable at every :data:`SPLIT_CRASH_POINTS` site — re-invoke
    with the same arguments after any death and it completes.  See the
    module docstring for the stage contract."""
    from ..server.state import ServerState

    source_wal = source_wal or source_state_file + ".wal"
    target_wal = target_wal or target_state_file + ".wal"
    source_epoch_file = source_epoch_file or source_state_file + ".epoch"
    target_epoch_file = target_epoch_file or target_state_file + ".epoch"
    if segment_bytes < 1:
        raise SplitError("segment_bytes must be positive")

    # -- stage 1: the manifest (the split's own write-ahead intent) --------
    mpath = manifest_path(map_path)
    current = PartitionMap.load(map_path)
    if os.path.exists(mpath):
        with open(mpath, encoding="utf-8") as f:
            manifest = json.load(f)
        if manifest.get("schema") != MANIFEST_SCHEMA:
            raise SplitError(
                f"unknown split manifest schema: {manifest.get('schema')!r}"
            )
        if (
            int(manifest["source"]) != source
            or manifest["new_address"] != new_address
        ):
            raise SplitError(
                f"a different split is in progress (source "
                f"{manifest['source']} -> {manifest['new_address']!r}); "
                "finish or remove its manifest first: " + mpath
            )
        log.info(
            "resuming split manifest %s (map v%d -> v%d)",
            mpath, manifest["old_version"], manifest["new_version"],
        )
    else:
        if current.version < 1:  # pragma: no cover - load() validates
            raise SplitError("map failed to load")
        new_map, moved = current.split(source, new_address)
        _crash(faults, "pre_manifest")
        manifest = {
            "schema": MANIFEST_SCHEMA,
            "old_version": current.version,
            "new_version": new_map.version,
            "source": source,
            "new_index": len(current.partitions),
            "new_address": new_address,
            "moved": [[lo, hi] for lo, hi in moved],
            # promotion-style fencing: segments built by THIS split carry
            # source-epoch + 1, so a stale splitter resuming an older
            # manifest is refused by the target's applier
            "epoch": load_epoch(source_epoch_file) + 1,
        }
        _write_manifest(mpath, manifest)

    moved_ranges = [(int(lo), int(hi)) for lo, hi in manifest["moved"]]
    epoch = int(manifest["epoch"])
    new_version = int(manifest["new_version"])

    def moved_user(uid: str) -> bool:
        h = user_hash(uid)
        return any(lo <= h < hi for lo, hi in moved_ranges)

    report = {
        "old_version": int(manifest["old_version"]),
        "new_version": new_version,
        "source": source,
        "new_index": int(manifest["new_index"]),
        "new_address": new_address,
        "moved_ranges": [list(r) for r in moved_ranges],
        "epoch": epoch,
        "copied": False,
        "flipped": False,
        "moved_users": 0,
        "moved_records": 0,
        "segments": 0,
        "dropped_users": 0,
        "dropped_challenges": 0,
        "dropped_sessions": 0,
    }

    flipped = current.version >= new_version

    # -- stage 2: copy the moved subset into the new partition -------------
    if not flipped:
        _crash(faults, "pre_copy")
        src_state = ServerState()
        await _recover_into(src_state, source_state_file, source_wal)
        records = src_state.export_user_records(moved_user)
        for seq, rec in enumerate(records, start=1):
            rec["seq"] = seq
        report["moved_records"] = len(records)
        report["moved_users"] = sum(
            1 for r in records if r["type"] == "register_user"
        )

        # idempotent restart: a half-written target from a crashed
        # attempt is rebuilt from scratch, never appended to
        for stale in (target_state_file, target_wal, target_epoch_file):
            try:
                os.unlink(stale)
            except OSError:
                pass
        tgt_state = ServerState()
        twal = WriteAheadLog(target_wal, fsync="always")

        def sink(frames: bytes, last_seq: int) -> None:
            # durable-before-apply, the standby's persist discipline
            twal.append_frames(frames, last_seq)
            twal.sync(force=True)

        applier = SegmentApplier(tgt_state, epoch=epoch, sink=sink)
        segments = split_records(records, epoch, 0, segment_bytes)
        half = (len(segments) + 1) // 2
        for i, seg in enumerate(segments):
            accepted, message = applier.apply(seg)
            if not accepted:
                raise SplitError(
                    f"target refused segment {seg.index}: {message}"
                )
            if i + 1 == half:
                # the half-copied state: target WAL holds frames but no
                # covering snapshot or epoch file exists yet
                _crash(faults, "mid_copy")
        report["segments"] = len(segments)
        if applier.records_skipped:
            log.warning(
                "split copy: %d records refused by the replay trust "
                "boundary (they would not have survived a reboot either)",
                applier.records_skipped,
            )
        # covering snapshot + fencing epoch: the new partition boots
        # through ordinary durability recovery like any other node
        tgt_state.attach_journal(twal)
        await tgt_state.snapshot(target_state_file)
        twal.close()
        store_epoch(target_epoch_file, epoch)
        report["copied"] = True
        _crash(faults, "pre_flip")

        # -- stage 3: flip the map (atomic rename = the ownership edge) ----
        new_map, moved_again = current.split(source, new_address)
        if (
            new_map.version != new_version
            or [list(r) for r in moved_again] != manifest["moved"]
        ):  # pragma: no cover - split() is deterministic over one map
            raise SplitError("map changed under the manifest; aborting")
        new_map.store(map_path)
        report["flipped"] = True
    else:
        report["copied"] = True
        report["flipped"] = True

    # -- stage 4: drain the moved subset from the source -------------------
    _crash(faults, "pre_drain")
    src_state = ServerState()
    src_report = await _recover_into(src_state, source_state_file, source_wal)
    dropped = src_state.drop_users(moved_user)
    report["dropped_users"], report["dropped_challenges"], \
        report["dropped_sessions"] = dropped
    wal = WriteAheadLog(
        source_wal, fsync="always", start_seq=src_report.next_seq
    )
    src_state.attach_journal(wal)
    src_state._persist_dirty = True  # force a covering snapshot on resume
    await src_state.snapshot(source_state_file)
    # the snapshot covers every record: compact the whole log, exactly the
    # state a graceful shutdown leaves (reboot restores, replays nothing)
    wal.compact(wal.size)
    wal.close()

    _crash(faults, "pre_finish")
    try:
        os.unlink(mpath)
    except OSError:
        pass
    log.info(
        "split complete: map v%d -> v%d, partition %d -> new partition %d "
        "(%s), %d users moved, %d dropped from the source",
        report["old_version"], new_version, source, report["new_index"],
        new_address, report["moved_users"], report["dropped_users"],
    )
    return report
