"""Versioned partition map: the fleet's routing contract (ROADMAP item 3).

One primary/standby pair (ISSUE 8) is still one hot box.  This module is
the layer that turns N such pairs into a fleet: a **partition map** — a
static consistent-hash of the ``user_id`` keyspace onto N partitions —
serialized with a monotonically increasing ``version`` and a content
``digest``, stored as one JSON file every daemon and client loads (and
the ops plane serves read-only at ``/partitionmap``), so the whole fleet
agrees on who owns whom.

Hash scheme
-----------

``user_hash(user_id) = crc32(user_id) over the 32-bit space`` — the SAME
stable hash the state shards use (``server/state.py``), so a user's
placement is identical across processes and languages with a crc32.  The
map carries, per partition, a set of half-open ``[lo, hi)`` ranges over
``[0, 2**32)``; the ranges of all partitions are **disjoint and
exhaustive** (validated on every load — a map with a gap or an overlap
refuses to parse), which makes :meth:`PartitionMap.partition_for` a
total function over arbitrary user ids: every id routes to exactly one
partition, always.

Range-based rather than ring-based on purpose: a **split**
(:meth:`PartitionMap.split`) is then a pure map operation — halve the
source partition's largest range, hand the upper half to a new
partition, bump the version — and "the users that moved" is exactly "the
ids whose hash lands in the moved ranges", which is what the live split
flow (:mod:`cpzk_tpu.fleet.split`) snapshots and replays over the WAL
replication plane.

Versioning and the redirect contract
------------------------------------

The version is the fleet's fencing token for routing: servers enforce
ownership against *their* loaded map and answer wrong-partition requests
with ``FAILED_PRECONDITION`` carrying ``cpzk-partition-map-version`` and
``cpzk-partition-owner`` in trailing metadata (the same trailer
discipline as the admission plane's ``cpzk-retry-after-ms``); clients
route by *their* map and, on a redirect, refresh + re-route **once per
attempt** (``client/rpc.py``).  A stale client therefore converges in
one redirect; two servers disagreeing about a map version is visible in
``/statusz`` (``fleet.map_version`` gauge) rather than silent.

The digest covers the canonical JSON of everything except itself, so two
operators (or a drift monitor) can compare maps by 12 hex chars.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import os
import tempfile
import zlib
from dataclasses import dataclass

#: Schema tags of the serialized map document.  v1 routes to one address
#: per partition; v2 (ISSUE 18) additionally carries the partition's
#: warm-standby address so clients can fail over / follow a handover
#: without a map flip.  A v2 map with no standbys serializes as v1 — old
#: digests (and old readers) stay stable.
SCHEMA = "cpzk-partition-map/1"
SCHEMA_V2 = "cpzk-partition-map/2"
_SCHEMAS = (SCHEMA, SCHEMA_V2)

#: The hash keyspace: crc32 — shared with the state-shard router so one
#: hash places a user both onto a partition and onto a shard within it.
HASH_SPACE = 1 << 32

#: Trailing-metadata keys of the wrong-partition redirect contract.  The
#: version tells the client *why* (its map is stale or the server's is);
#: the owner is the address to re-route to under the server's map.
PARTITION_MAP_VERSION_KEY = "cpzk-partition-map-version"
PARTITION_OWNER_KEY = "cpzk-partition-owner"

#: Sanity cap: partition indexes ride in JSON and per-partition channel
#: pools; a hostile map must not allocate unboundedly.
MAX_PARTITIONS = 4096


def user_hash(user_id: str) -> int:
    """Stable placement hash of one user id (crc32 over the 32-bit
    space; identical across processes — and to the state-shard hash for
    every id the server would accept).  Total over arbitrary Python
    strings: lone surrogates (which strict UTF-8 refuses) hash via
    surrogatepass rather than raising — routing is a total function,
    and the service's own user-id validation rejects such ids long
    before any state is touched."""
    return zlib.crc32(user_id.encode("utf-8", "surrogatepass")) & 0xFFFFFFFF


@dataclass(frozen=True)
class Partition:
    """One partition: an index, the serving address of its primary
    (in a replicated deployment: the pair's stable/VIP address), the
    hash ranges it owns (half-open ``[lo, hi)``), and — in a v2 map —
    the optional address of its warm standby (``None`` on v1 maps and
    unreplicated partitions)."""

    index: int
    address: str
    ranges: tuple[tuple[int, int], ...]
    standby: str | None = None

    def span(self) -> int:
        return sum(hi - lo for lo, hi in self.ranges)

    def covers(self, h: int) -> bool:
        return any(lo <= h < hi for lo, hi in self.ranges)


class PartitionMap:
    """The validated, routable form of one map document."""

    def __init__(self, version: int, partitions: list[Partition]):
        self.version = int(version)
        self.partitions = list(partitions)
        _validate(self.version, self.partitions)
        # routing index: range starts sorted, owner per start — bisect
        # makes partition_for O(log ranges) and allocation-free
        edges: list[tuple[int, int, int]] = []
        for p in self.partitions:
            for lo, hi in p.ranges:
                edges.append((lo, hi, p.index))
        edges.sort()
        self._starts = [lo for lo, _hi, _idx in edges]
        self._owners = [idx for _lo, _hi, idx in edges]

    # -- routing (total over arbitrary user ids) ---------------------------

    def partition_for_hash(self, h: int) -> Partition:
        i = bisect.bisect_right(self._starts, h % HASH_SPACE) - 1
        return self.partitions[self._owners[i]]

    def partition_for(self, user_id: str) -> Partition:
        """The owning partition of ``user_id`` — a total function: the
        ranges are validated disjoint + exhaustive, so every id (any
        unicode, any length) lands on exactly one partition."""
        return self.partition_for_hash(user_hash(user_id))

    def index_of_address(self, address: str) -> int:
        """The partition index serving at ``address`` (boot-time self
        discovery when ``[fleet] partition`` is left at -1)."""
        for p in self.partitions:
            if p.address == address:
                return p.index
        raise ValueError(
            f"address {address!r} is not in the partition map "
            f"(v{self.version}: {[p.address for p in self.partitions]})"
        )

    # -- construction ------------------------------------------------------

    @classmethod
    def uniform(
        cls, addresses: list[str], version: int = 1,
        standbys: list[str | None] | None = None,
    ) -> "PartitionMap":
        """An initial map: the hash space sliced into ``len(addresses)``
        equal contiguous ranges, one per address.  ``standbys`` (same
        length, entries may be ``None``) stamps each partition's warm
        standby for a replicated fleet (a v2 map)."""
        n = len(addresses)
        if n < 1:
            raise ValueError("a partition map needs at least one address")
        if standbys is not None and len(standbys) != n:
            raise ValueError(
                f"standbys must match addresses ({len(standbys)} != {n})"
            )
        bounds = [HASH_SPACE * i // n for i in range(n)] + [HASH_SPACE]
        return cls(version, [
            Partition(
                i, addr, ((bounds[i], bounds[i + 1]),),
                standby=standbys[i] if standbys is not None else None,
            )
            for i, addr in enumerate(addresses)
        ])

    def split(
        self, source: int, new_address: str
    ) -> tuple["PartitionMap", tuple[tuple[int, int], ...]]:
        """``(new_map, moved_ranges)``: halve the source partition's
        largest range, hand the upper half to a new partition appended at
        index N, bump the version.  The moved ranges are what the live
        split flow uses to select the users that change owner."""
        if not 0 <= source < len(self.partitions):
            raise ValueError(f"no partition {source} in map v{self.version}")
        src = self.partitions[source]
        lo, hi = max(src.ranges, key=lambda r: r[1] - r[0])
        if hi - lo < 2:
            raise ValueError(
                f"partition {source} owns no splittable range (largest is "
                f"[{lo}, {hi}))"
            )
        mid = (lo + hi) // 2
        moved = ((mid, hi),)
        kept = tuple(r for r in src.ranges if r != (lo, hi)) + ((lo, mid),)
        parts = list(self.partitions)
        parts[source] = Partition(
            src.index, src.address, kept, standby=src.standby
        )
        parts.append(Partition(len(parts), new_address, moved))
        return PartitionMap(self.version + 1, parts), moved

    def set_standby(self, index: int, standby: str | None) -> "PartitionMap":
        """A copy with partition ``index``'s warm-standby address set (or
        cleared with ``None``), version bumped — the ``fleet set-standby``
        CLI's operation."""
        if not 0 <= index < len(self.partitions):
            raise ValueError(f"no partition {index} in map v{self.version}")
        parts = list(self.partitions)
        p = parts[index]
        parts[index] = Partition(p.index, p.address, p.ranges,
                                 standby=standby)
        return PartitionMap(self.version + 1, parts)

    def swap_standby(self, index: int) -> "PartitionMap":
        """A copy with partition ``index``'s primary and standby addresses
        swapped, version bumped — the map flip after a coordinated
        handover (the old standby now serves; the restarted old primary
        comes back as the standby)."""
        if not 0 <= index < len(self.partitions):
            raise ValueError(f"no partition {index} in map v{self.version}")
        p = self.partitions[index]
        if not p.standby:
            raise ValueError(
                f"partition {index} has no standby to swap with"
            )
        parts = list(self.partitions)
        parts[index] = Partition(p.index, p.standby, p.ranges,
                                 standby=p.address)
        return PartitionMap(self.version + 1, parts)

    # -- (de)serialization -------------------------------------------------

    def to_doc(self) -> dict:
        # the standby key (and the /2 schema tag) appear only when some
        # partition actually has one: a standby-free map round-trips to
        # the exact v1 document, digest included
        has_standby = any(p.standby for p in self.partitions)
        doc = {
            "schema": SCHEMA_V2 if has_standby else SCHEMA,
            "version": self.version,
            "partitions": [
                {
                    "index": p.index,
                    "address": p.address,
                    "ranges": [[lo, hi] for lo, hi in p.ranges],
                    **({"standby": p.standby} if p.standby else {}),
                }
                for p in self.partitions
            ],
        }
        doc["digest"] = _digest(doc)
        return doc

    def to_json(self) -> str:
        return json.dumps(self.to_doc(), indent=2, sort_keys=True) + "\n"

    @classmethod
    def from_doc(cls, doc: dict) -> "PartitionMap":
        """Parse + validate one map document.  The file (and the ops
        plane's ``/partitionmap`` body) is a trust boundary for routing:
        anything structurally off — wrong schema, non-integer version,
        overlapping or non-exhaustive ranges, digest mismatch — raises
        ``ValueError`` (never anything else; the fuzz harness holds
        that)."""
        try:
            if not isinstance(doc, dict):
                raise ValueError("partition map must be a JSON object")
            if doc.get("schema") not in _SCHEMAS:
                raise ValueError(
                    f"unknown partition-map schema: {doc.get('schema')!r}"
                )
            claimed = doc.get("digest")
            if claimed is not None and claimed != _digest(doc):
                raise ValueError("partition map digest mismatch")
            raw = doc.get("partitions")
            if not isinstance(raw, list):
                raise ValueError("partitions must be a list")
            parts = []
            for entry in raw:
                if not isinstance(entry, dict):
                    raise ValueError("partition entry must be an object")
                address = entry.get("address")
                if not isinstance(address, str) or not address:
                    raise ValueError("partition address must be non-empty")
                ranges = entry.get("ranges")
                if not isinstance(ranges, list) or not ranges:
                    raise ValueError("partition ranges must be non-empty")
                standby = entry.get("standby")
                if standby is not None and (
                    not isinstance(standby, str) or not standby
                ):
                    raise ValueError(
                        "partition standby must be a non-empty string "
                        "when present"
                    )
                parts.append(Partition(
                    int(entry.get("index")),
                    address,
                    tuple((int(lo), int(hi)) for lo, hi in ranges),
                    standby=standby,
                ))
            return cls(int(doc.get("version")), parts)
        except ValueError:
            raise
        except Exception as e:  # hostile structure -> one exception type
            raise ValueError(f"malformed partition map: {e!r}") from None

    @classmethod
    def from_json(cls, text: str | bytes) -> "PartitionMap":
        try:
            doc = json.loads(text)
        except Exception as e:
            raise ValueError(f"partition map is not JSON: {e}") from None
        return cls.from_doc(doc)

    @classmethod
    def load(cls, path: str) -> "PartitionMap":
        with open(path, encoding="utf-8") as f:
            return cls.from_json(f.read())

    def store(self, path: str) -> None:
        """Atomic write (tmp + fsync + rename): a reader — or a split
        SIGKILLed mid-flip — sees the old map or the new one, never a
        torn document."""
        d = os.path.dirname(os.path.abspath(path)) or "."
        fd, tmp = tempfile.mkstemp(
            prefix="." + os.path.basename(path) + ".tmp.", dir=d
        )
        try:
            with os.fdopen(fd, "w") as f:
                f.write(self.to_json())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        # the map is routing config, not a secret: world-readable like
        # any deploy manifest
        os.chmod(path, 0o644)

    @property
    def digest(self) -> str:
        return _digest(self.to_doc())

    def short_digest(self) -> str:
        return self.digest[:12]


def fetch_partition_map(url: str, timeout: float = 5.0) -> PartitionMap:
    """Fetch + validate a map from an ops plane's ``/partitionmap`` (or
    any HTTP source).  Synchronous — async callers wrap it in
    ``asyncio.to_thread`` or pass ``lambda: asyncio.to_thread(...)`` as
    ``AuthClient(map_refresh=...)``."""
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r:
        return PartitionMap.from_json(r.read())


def _digest(doc: dict) -> str:
    body = {k: v for k, v in doc.items() if k != "digest"}
    return hashlib.sha256(
        json.dumps(body, separators=(",", ":"), sort_keys=True).encode()
    ).hexdigest()


def _validate(version: int, partitions: list[Partition]) -> None:
    if version < 1:
        raise ValueError(f"partition map version must be >= 1, got {version}")
    if not partitions:
        raise ValueError("a partition map needs at least one partition")
    if len(partitions) > MAX_PARTITIONS:
        raise ValueError(
            f"partition map exceeds {MAX_PARTITIONS} partitions"
        )
    if [p.index for p in partitions] != list(range(len(partitions))):
        raise ValueError(
            "partition indexes must be exactly 0..N-1 in order"
        )
    ranges: list[tuple[int, int, int]] = []
    for p in partitions:
        if not p.address:
            raise ValueError(f"partition {p.index} has an empty address")
        if p.standby is not None and p.standby == p.address:
            raise ValueError(
                f"partition {p.index} standby equals its primary address "
                f"({p.address!r})"
            )
        for lo, hi in p.ranges:
            if not (0 <= lo < hi <= HASH_SPACE):
                raise ValueError(
                    f"partition {p.index} range [{lo}, {hi}) is outside "
                    f"[0, {HASH_SPACE})"
                )
            ranges.append((lo, hi, p.index))
    ranges.sort()
    # disjoint AND exhaustive: sorted ranges must tile [0, HASH_SPACE)
    # exactly — this is what makes routing a total function
    cursor = 0
    for lo, hi, idx in ranges:
        if lo != cursor:
            kind = "overlap" if lo < cursor else "gap"
            raise ValueError(
                f"partition ranges have a {kind} at {min(lo, cursor)} "
                f"(partition {idx})"
            )
        cursor = hi
    if cursor != HASH_SPACE:
        raise ValueError(
            f"partition ranges end at {cursor}, not {HASH_SPACE} (gap at "
            "the top of the hash space)"
        )


class FleetRouter:
    """One daemon's view of the map: *this* partition's index plus the
    loaded :class:`PartitionMap`, with the ownership check the service
    layer runs on every auth RPC.

    The N=1 fast path is structural: a single-partition map makes
    :meth:`owns` a constant ``True`` with **no hash computed** — the CPU
    e2e perf gate runs with fleet routing enabled on a one-partition map
    to pin that routing costs the hot path nothing.
    """

    def __init__(self, pmap: PartitionMap, self_index: int,
                 map_path: str = ""):
        if not 0 <= self_index < len(pmap.partitions):
            raise ValueError(
                f"partition index {self_index} is not in map "
                f"v{pmap.version} ({len(pmap.partitions)} partitions)"
            )
        self.map = pmap
        self.self_index = self_index
        self.map_path = map_path
        self.redirects = 0  # process-lifetime count behind /statusz
        self._single = len(pmap.partitions) == 1
        self._export_gauges()

    def _export_gauges(self) -> None:
        from ..server import metrics

        metrics.gauge("fleet.partition").set(float(self.self_index))
        metrics.gauge("fleet.map_version").set(float(self.map.version))

    # -- the ownership check (the service's hot path) ----------------------

    def owns(self, user_id: str) -> bool:
        """Whether this partition owns ``user_id``.  Single-partition
        maps short-circuit before hashing (the N=1 fast path)."""
        if self._single:
            return True
        return self.map.partition_for(user_id).index == self.self_index

    def owner(self, user_id: str) -> Partition:
        return self.map.partition_for(user_id)

    # -- reload (operator REPL / split runbook) ----------------------------

    def reload(self) -> bool:
        """Re-read the map file; adopt it when its version is strictly
        newer (a split flipped it).  Returns whether the map changed.
        The self partition keeps its index — a reload that drops this
        partition from the map raises rather than silently serving an
        unowned keyspace."""
        if not self.map_path:
            return False
        pmap = PartitionMap.load(self.map_path)
        if pmap.version <= self.map.version:
            return False
        if self.self_index >= len(pmap.partitions):
            raise ValueError(
                f"map v{pmap.version} has {len(pmap.partitions)} "
                f"partitions; this daemon is partition {self.self_index}"
            )
        self.map = pmap
        self._single = len(pmap.partitions) == 1
        self._export_gauges()
        return True

    # -- introspection (/statusz fleet block) ------------------------------

    def status(self) -> dict:
        me = self.map.partitions[self.self_index]
        return {
            "partition": self.self_index,
            "partitions": len(self.map.partitions),
            "map_version": self.map.version,
            "map_digest": self.map.short_digest(),
            "address": me.address,
            "standby": me.standby,
            "owned_ranges": [[lo, hi] for lo, hi in me.ranges],
            "owned_span_fraction": round(me.span() / HASH_SPACE, 6),
            "redirects": self.redirects,
        }
