"""Self-driving fleet: the daemon-resident control loop.

Every signal this controller consumes already exists — SLO burn pages
(``observability/slo.py``), per-shard sizes and lock-wait
(``server/state.py`` + the ``state.shard.lock_wait`` histogram), lane
breaker states and depths (``server/router.py``) — and every actuator it
drives already exists too: the crash-resumable split machinery
(``fleet/split.py``), the lane router's administrative drain, the
admission controller's level cap.  What was missing is the loop that
closes them, so a partition approaching its soak-calibrated capacity
envelope splits itself, a browned-out lane drains and re-admits itself,
and a burning login SLO sheds load before it cascades — with no operator
at the keyboard.

The loop is deliberately boring:

1. **collect** one :class:`Signals` snapshot per tick;
2. **decide** through two-sided hysteresis (a signal must stay hot for
   ``act_ticks`` consecutive ticks to act, and stay clear for
   ``clear_ticks`` to revert) plus per-action cooldowns;
3. **act** through exactly one actuator per tick, never while another
   action is still in flight, never a split while a split manifest or a
   promotion is unfinished — the safety rails are structural, not tuned.

Every decision — including dry-run "would have acted" and every vetoed
intent — lands in the trace ring as a ``controller_decision`` event, in
the ``/statusz`` controller block (last-N ring), and in the
``fleet.controller.decisions`` counter family.  ``dry_run = true`` (the
shipping default) runs the identical decide path — same hysteresis
bookkeeping, same cooldown stamps, same decision stream — and skips only
the actuator call, so an operator can watch what the controller *would*
do for days before arming it.

The **live split** (:func:`run_live_split`) is the one actuator that
needed new machinery: ``fleet/split.py`` recovers the source partition
from its stopped files, but the controller must split a *serving*
daemon.  The live variant writes the same resumable manifest, then runs
export → copy → map-flip as one synchronous critical section on the
event loop — no await between the consistent cut and the ownership flip,
so no handler can observe a half-exported state.  That alone fences only
handlers whose ownership check and mutation share one synchronous
section; a multi-await handler (``VerifyProof`` awaits the batcher
between its entry check and ``create_session``, ``register`` awaits the
shard lock) can straddle the flip, which is why every acknowledged
user-keyed mutation ALSO re-verifies ownership at write time through
``ServerState.owner_fence`` — inside the shard lock, synchronously with
the mutation — and answers a post-flip write with the standard redirect
instead of an ack (see ``server/state.py``).  The serving pause the
critical section buys is proportional to the moved subset, which is
exactly why the controller fires it *before* the capacity cliff rather
than at it.  A crash at any point leaves the standard manifest; the
offline ``fleet split`` resume completes it.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from collections import deque
from dataclasses import dataclass, field

from ..server import metrics
from .partition_map import PartitionMap, user_hash
from .split import MANIFEST_SCHEMA, SplitError, _write_manifest, manifest_path

log = logging.getLogger("cpzk_tpu.fleet.controller")

#: Trace-ring event name carried by every decision (dry-run included).
DECISION_EVENT = "controller_decision"

#: The actions the controller can take (decision ``action`` values).
ACTION_SPLIT = "split"
ACTION_LANE_DRAIN = "lane_drain"
ACTION_LANE_READMIT = "lane_readmit"
ACTION_ADMISSION_SHRINK = "admission_shrink"
ACTION_ADMISSION_RESTORE = "admission_restore"


@dataclass
class Signals:
    """One tick's view of the planes the controller watches.  ``None``
    means the plane is absent on this daemon (no fleet, single lane, no
    SLO engine) — absent planes simply produce no intents."""

    users: int | None = None            # users on THIS partition
    lock_wait_ms: float | None = None   # mean shard lock-wait since last tick
    lanes: list[dict] = field(default_factory=list)
    paging: bool | None = None          # the watched RPC is burn-paging
    manifest: bool = False              # an unfinished split manifest exists
    promoting: bool = False             # this daemon is (or is mid-) standby


@dataclass
class Decision:
    """One decision the controller made — acted, dry-run, or vetoed."""

    action: str
    target: str           # partition index, lane label, or the SLO rpc
    reason: str           # the signal that crossed its envelope
    dry_run: bool
    fired: bool = False   # the actuator actually ran
    veto: str | None = None  # why an eligible intent did NOT act
    at: float = 0.0       # wall-clock time of the decision
    detail: dict = field(default_factory=dict)

    def row(self) -> dict:
        return {
            "action": self.action,
            "target": self.target,
            "reason": self.reason,
            "dry_run": self.dry_run,
            "fired": self.fired,
            "veto": self.veto,
            "at": self.at,
            "detail": self.detail,
        }


class FleetController:
    """The control loop (see module docstring).  All constructor planes
    are optional: a daemon without a fleet router simply never produces a
    split intent, one without a lane router never drains, and tests
    inject exactly the planes a scenario needs.

    ``clock`` is injectable monotonic time (hysteresis, cooldowns) and
    ``wall`` injectable wall time (decision timestamps)."""

    def __init__(
        self,
        settings,
        *,
        state=None,
        router=None,
        admission=None,
        slo=None,
        fleet=None,
        durability=None,
        replica=None,
        epoch_file: str = "",
        segment_bytes: int = 65536,
        clock=time.monotonic,
        wall=time.time,
    ):
        self.settings = settings
        self.state = state
        self.router = router
        self.admission = admission
        self.slo = slo
        self.fleet = fleet
        self.durability = durability
        self.replica = replica
        self.epoch_file = epoch_file
        self.segment_bytes = segment_bytes
        self._clock = clock
        self._wall = wall
        self.ticks = 0
        self.decisions: deque[Decision] = deque(
            maxlen=max(1, settings.decision_ring)
        )
        self.acting = False  # one action in flight at a time (structural)
        # hysteresis state: consecutive hot/clear tick counts per signal
        self._split_hot = 0
        self._paging_hot = 0
        self._paging_clear = 0
        #: lane label -> clock time the breaker was first seen OPEN
        self._lane_open_since: dict[str, float] = {}
        #: lane label -> consecutive CLOSED observations while drained
        self._lane_closed_ticks: dict[str, int] = {}
        #: lane label -> clock time the controller drained it
        self._lane_drained_at: dict[str, float] = {}
        # per-action cooldown stamps (clock time of the last armed action)
        self._cooldown_until: dict[str, float] = {}
        # undo record of THE decision decide() committed this tick, for
        # rollback + error backoff when the live actuator raises
        self._pending_undo: dict | None = None
        # lock-wait histogram baseline for the per-tick delta
        self._lw_count, self._lw_sum = metrics.read_histogram(
            "state.shard.lock_wait"
        )
        metrics.gauge("fleet.controller.dry_run").set(
            1.0 if settings.dry_run else 0.0
        )

    # -- signal collection ---------------------------------------------------

    def collect(self) -> Signals:
        """One snapshot of every attached plane.  Runs on the event loop;
        every read is a synchronous in-process call."""
        sig = Signals()
        if self.state is not None and self.fleet is not None:
            sig.users = sum(
                row["users"] for row in self.state.shard_stats()
            )
            count, total = metrics.read_histogram("state.shard.lock_wait")
            d_count = count - self._lw_count
            d_sum = total - self._lw_sum
            self._lw_count, self._lw_sum = count, total
            sig.lock_wait_ms = (
                (d_sum / d_count) * 1000.0 if d_count > 0 else 0.0
            )
        if self.router is not None:
            sig.lanes = self.router.lane_states()
        if self.slo is not None:
            view = self.slo.snapshot().get("rpcs") or {}
            rpc = view.get(self.settings.slo_rpc)
            sig.paging = bool(rpc and rpc.get("paging"))
        if self.fleet is not None and self.fleet.map_path:
            sig.manifest = os.path.exists(
                manifest_path(self.fleet.map_path)
            )
        if self.replica is not None:
            sig.promoting = getattr(self.replica, "role", "primary") != "primary"
        return sig

    # -- decide (pure over Signals + internal hysteresis state) --------------

    def decide(self, sig: Signals) -> list[Decision]:
        """Turn one signal snapshot into decisions.  Identical in dry-run
        and live mode: hysteresis counters, cooldown stamps, and the
        decision stream never depend on ``dry_run`` — only the actuator
        call (which :meth:`tick` performs) does.

        The ``_decide_*`` helpers are PURE over the arm state: they
        accumulate hysteresis and attach veto reasons but never stamp a
        cooldown or reset a counter.  Only after the single-action rail
        has picked THE action of this tick does :meth:`_commit` consume
        its cooldown + hysteresis — so a same-tick runner-up vetoed as
        ``single-action`` keeps its accumulated eligibility and can fire
        on the very next tick instead of re-paying a full cooldown plus
        ``act_ticks`` of re-accumulation for an action that never ran."""
        now = self._clock()
        out: list[Decision] = []
        self._decide_split(sig, now, out)
        self._decide_lanes(sig, now, out)
        self._decide_admission(sig, now, out)
        # single-action rail: the FIRST armed decision this tick keeps its
        # eligibility; every later armed decision waits for a future tick
        armed = [d for d in out if d.veto is None]
        for d in armed[1:]:
            d.veto = "single-action"
        self._pending_undo = None
        if armed:
            self._pending_undo = self._commit(armed[0], now)
        return out

    def _commit(self, d: Decision, now: float) -> dict:
        """Consume the selected action's cooldown + hysteresis — called
        for exactly ONE decision per tick, after the single-action rail.
        Returns the undo record :meth:`_rollback` needs when the live
        actuator subsequently fails."""
        s = self.settings
        a, t = d.action, d.target
        undo: dict = {"action": a, "target": t}
        if a == ACTION_SPLIT:
            undo["split_hot"] = self._split_hot
            self._arm(a, now, s.split_cooldown_s)
            self._split_hot = 0
        elif a == ACTION_LANE_DRAIN:
            undo["open_since"] = self._lane_open_since.pop(t, None)
            undo["drained_at"] = self._lane_drained_at.get(t)
            undo["closed_ticks"] = self._lane_closed_ticks.get(t, 0)
            self._lane_drained_at[t] = now
            self._lane_closed_ticks[t] = 0
        elif a == ACTION_LANE_READMIT:
            undo["drained_at"] = self._lane_drained_at.pop(t, None)
            undo["closed_ticks"] = self._lane_closed_ticks.get(t, 0)
            self._lane_closed_ticks[t] = 0
        elif a in (ACTION_ADMISSION_SHRINK, ACTION_ADMISSION_RESTORE):
            undo["paging_hot"] = self._paging_hot
            undo["paging_clear"] = self._paging_clear
            self._arm(a, now, s.admission_cooldown_s)
            self._paging_hot = 0
            self._paging_clear = 0
        return undo

    def _rollback(self, d: Decision, undo: dict, now: float) -> None:
        """A live actuator raised: restore the hysteresis/bookkeeping the
        commit consumed (nothing actually changed in the planes) and
        replace the full cooldown with the short ``error_backoff_s`` —
        a transient actuator failure must not block the retry for e.g.
        the 600 s split cooldown, but the very next tick hammering a
        broken actuator helps nobody either."""
        a, t = d.action, d.target
        if a == ACTION_SPLIT:
            self._split_hot = undo["split_hot"]
        elif a == ACTION_LANE_DRAIN:
            if undo["open_since"] is not None:
                self._lane_open_since[t] = undo["open_since"]
            if undo["drained_at"] is None:
                self._lane_drained_at.pop(t, None)   # it is NOT drained
            else:
                self._lane_drained_at[t] = undo["drained_at"]
            self._lane_closed_ticks[t] = undo["closed_ticks"]
        elif a == ACTION_LANE_READMIT:
            if undo["drained_at"] is not None:       # it is STILL drained
                self._lane_drained_at[t] = undo["drained_at"]
            self._lane_closed_ticks[t] = undo["closed_ticks"]
        elif a in (ACTION_ADMISSION_SHRINK, ACTION_ADMISSION_RESTORE):
            self._paging_hot = undo["paging_hot"]
            self._paging_clear = undo["paging_clear"]
        self._arm(a, now, self.settings.error_backoff_s)

    def _cooled(self, kind: str, now: float) -> bool:
        return now >= self._cooldown_until.get(kind, 0.0)

    def _arm(self, kind: str, now: float, cooldown_s: float) -> None:
        self._cooldown_until[kind] = now + cooldown_s

    def _decide_split(
        self, sig: Signals, now: float, out: list[Decision]
    ) -> None:
        s = self.settings
        armed = (
            s.split_target_address
            and (s.split_user_threshold > 0 or s.split_lock_wait_ms > 0)
        )
        if not armed or sig.users is None:
            self._split_hot = 0
            return
        reasons = []
        if 0 < s.split_user_threshold <= sig.users:
            reasons.append(
                f"users {sig.users} >= {s.split_user_threshold}"
            )
        if (
            s.split_lock_wait_ms > 0
            and sig.lock_wait_ms is not None
            and sig.lock_wait_ms >= s.split_lock_wait_ms
        ):
            reasons.append(
                f"lock_wait {sig.lock_wait_ms:.1f}ms >= "
                f"{s.split_lock_wait_ms:.1f}ms"
            )
        if not reasons:
            self._split_hot = 0
            return
        self._split_hot += 1
        if self._split_hot < s.act_ticks:
            return
        d = Decision(
            action=ACTION_SPLIT,
            target=str(self.fleet.self_index if self.fleet else -1),
            reason="; ".join(reasons),
            dry_run=s.dry_run,
            at=self._wall(),
            detail={
                "new_address": s.split_target_address,
                "hot_ticks": self._split_hot,
            },
        )
        if sig.manifest:
            d.veto = "split-manifest"       # never split over an unfinished one
        elif sig.promoting:
            d.veto = "promotion"            # never split during promotion
        elif self.acting:
            d.veto = "action-in-flight"
        elif not self._cooled(ACTION_SPLIT, now):
            d.veto = "cooldown"
        out.append(d)

    def _decide_lanes(
        self, sig: Signals, now: float, out: list[Decision]
    ) -> None:
        s = self.settings
        seen = set()
        for lane in sig.lanes:
            label = lane["lane"]
            seen.add(label)
            is_open = lane["breaker"] == "open"
            if lane["drained"]:
                # recovery path: the breaker re-closes through its probe
                # traffic; clear_ticks consecutive CLOSED observations
                # past the lane cooldown earn re-admission
                if lane["breaker"] == "closed":
                    self._lane_closed_ticks[label] = (
                        self._lane_closed_ticks.get(label, 0) + 1
                    )
                else:
                    self._lane_closed_ticks[label] = 0
                drained_at = self._lane_drained_at.get(label, now)
                if (
                    self._lane_closed_ticks.get(label, 0) >= s.clear_ticks
                    and now - drained_at >= s.lane_cooldown_s
                ):
                    d = Decision(
                        action=ACTION_LANE_READMIT,
                        target=label,
                        reason=(
                            f"breaker closed for {s.clear_ticks} ticks "
                            f"after drain"
                        ),
                        dry_run=s.dry_run,
                        at=self._wall(),
                    )
                    if self.acting:
                        d.veto = "action-in-flight"
                    elif not self._cooled(ACTION_LANE_READMIT, now):
                        d.veto = "cooldown"  # error backoff after a failed
                    out.append(d)            # readmit actuation
                continue
            if not is_open:
                self._lane_open_since.pop(label, None)
                continue
            opened = self._lane_open_since.setdefault(label, now)
            open_for = now - opened
            if open_for < s.lane_open_after_s:
                continue
            d = Decision(
                action=ACTION_LANE_DRAIN,
                target=label,
                reason=(
                    f"breaker OPEN for {open_for:.1f}s >= "
                    f"{s.lane_open_after_s:.1f}s"
                ),
                dry_run=s.dry_run,
                at=self._wall(),
                detail={"pending": lane["pending"]},
            )
            if self.acting:
                d.veto = "action-in-flight"
            elif not self._cooled(ACTION_LANE_DRAIN, now):
                d.veto = "cooldown"          # error backoff after a failed
            out.append(d)                    # drain actuation
        for label in list(self._lane_open_since):
            if label not in seen:
                del self._lane_open_since[label]

    def _decide_admission(
        self, sig: Signals, now: float, out: list[Decision]
    ) -> None:
        s = self.settings
        if sig.paging is None or self.admission is None:
            return
        from ..admission.controller import MIN_LEVEL, N_TIERS

        cap = self.admission.level_cap
        if sig.paging:
            self._paging_clear = 0
            self._paging_hot += 1
            if self._paging_hot < s.act_ticks or cap <= MIN_LEVEL:
                return
            d = Decision(
                action=ACTION_ADMISSION_SHRINK,
                target=s.slo_rpc,
                reason=(
                    f"{s.slo_rpc} burn paging for {self._paging_hot} ticks"
                ),
                dry_run=s.dry_run,
                at=self._wall(),
                detail={"cap": cap, "new_cap": max(MIN_LEVEL, cap - 1.0)},
            )
            if self.acting:
                d.veto = "action-in-flight"
            elif not self._cooled(ACTION_ADMISSION_SHRINK, now):
                d.veto = "cooldown"
            out.append(d)
        else:
            self._paging_hot = 0
            if cap >= float(N_TIERS):
                self._paging_clear = 0
                return
            self._paging_clear += 1
            if self._paging_clear < s.clear_ticks:
                return
            d = Decision(
                action=ACTION_ADMISSION_RESTORE,
                target=s.slo_rpc,
                reason=(
                    f"{s.slo_rpc} burn clear for {self._paging_clear} ticks"
                ),
                dry_run=s.dry_run,
                at=self._wall(),
                detail={"cap": cap, "new_cap": min(float(N_TIERS), cap + 1.0)},
            )
            if self.acting:
                d.veto = "action-in-flight"
            elif not self._cooled(ACTION_ADMISSION_RESTORE, now):
                d.veto = "cooldown"
            out.append(d)

    # -- the tick ------------------------------------------------------------

    async def tick(self) -> list[Decision]:
        """One control-loop iteration: collect, decide, publish every
        decision, and run at most one actuator (live mode only)."""
        self.ticks += 1
        metrics.counter("fleet.controller.ticks").inc()
        decisions = self.decide(self.collect())
        for d in decisions:
            await self._publish_and_act(d)
        return decisions

    async def _publish_and_act(self, d: Decision) -> None:
        eligible = d.veto is None
        if eligible and not self.settings.dry_run:
            self.acting = True
            try:
                await self._act(d)
                d.fired = True
            except Exception as e:
                d.veto = f"actuator-error: {e}"
                # nothing changed in the planes: give the consumed
                # cooldown + hysteresis back and retry after the short
                # error backoff instead of a full action cooldown
                if self._pending_undo is not None:
                    self._rollback(d, self._pending_undo, self._clock())
                    self._pending_undo = None
                log.exception(
                    "controller %s on %s failed", d.action, d.target
                )
            finally:
                self.acting = False
        outcome = (
            "fired" if d.fired
            else "dry_run" if eligible
            else "veto"
        )
        metrics.counter(
            "fleet.controller.decisions", labelnames=("action", "outcome")
        ).labels(action=d.action, outcome=outcome).inc()
        self.decisions.append(d)
        level = logging.INFO if d.fired or eligible else logging.DEBUG
        log.log(
            level, "controller decision: %s %s (%s) -> %s",
            d.action, d.target, d.reason, outcome,
        )
        try:
            from ..observability import get_tracer

            get_tracer().record_event(
                DECISION_EVENT,
                action=d.action, target=d.target, reason=d.reason,
                dry_run=d.dry_run, fired=d.fired, veto=d.veto or "",
            )
        except Exception:  # pragma: no cover - observability optional
            pass

    async def _act(self, d: Decision) -> None:
        if d.action == ACTION_SPLIT:
            report = await run_live_split(
                map_path=self.fleet.map_path,
                source=self.fleet.self_index,
                new_address=self.settings.split_target_address,
                state=self.state,
                fleet=self.fleet,
                durability=self.durability,
                epoch_file=self.epoch_file,
                segment_bytes=self.segment_bytes,
            )
            d.detail["report"] = {
                k: report[k] for k in (
                    "new_version", "new_index", "moved_users",
                    "moved_records", "target_state_file",
                )
            }
        elif d.action == ACTION_LANE_DRAIN:
            self.router.drain_lane(d.target)
        elif d.action == ACTION_LANE_READMIT:
            self.router.readmit_lane(d.target)
        elif d.action == ACTION_ADMISSION_SHRINK:
            self.admission.set_level_cap(d.detail["new_cap"])
        elif d.action == ACTION_ADMISSION_RESTORE:
            self.admission.set_level_cap(d.detail["new_cap"])
        else:  # pragma: no cover - decide() only emits the five above
            raise SplitError(f"unknown controller action {d.action!r}")

    # -- introspection -------------------------------------------------------

    def status(self) -> dict:
        """The ``/statusz`` controller block."""
        now = self._clock()
        return {
            "enabled": self.settings.enabled,
            "dry_run": self.settings.dry_run,
            "ticks": self.ticks,
            "acting": self.acting,
            "cooldowns_s": {
                kind: round(max(0.0, until - now), 1)
                for kind, until in self._cooldown_until.items()
                if until > now
            },
            "drained_lanes": sorted(self._lane_drained_at),
            "decisions": [d.row() for d in self.decisions],
        }


# -- the live split actuator -------------------------------------------------

async def run_live_split(
    *,
    map_path: str,
    source: int,
    new_address: str,
    state,
    fleet=None,
    durability=None,
    epoch_file: str = "",
    segment_bytes: int = 65536,
) -> dict:
    """Split a SERVING partition in-process: same manifest, same segment
    trust boundary, same map flip as ``fleet/split.py``, but the source
    is the daemon's live ``ServerState`` instead of stopped files.

    Correctness hinges on two structural properties that together
    totally order every acknowledged write against the cut:

    1. **export → copy → flip runs with no await point**, so the
       single-threaded event loop guarantees no handler interleaves
       between the consistent cut and the ownership flip;
    2. **every acknowledged user-keyed mutation re-verifies ownership
       at write time** (``ServerState.owner_fence``, checked inside the
       shard lock in the same synchronous section as the mutation) —
       a handler that passed its entry ownership check but resumed
       from a later await (the batcher, a shard lock) after the flip
       is answered with the redirect, not an ack.

    An acknowledged write therefore either precedes the export (and
    ships) or follows the flip (and redirects); nothing acked can land
    on a stale copy for ``drop_users`` to discard.  The drain (drop +
    covering checkpoint) runs after the flip, when both fences already
    reject the moved users.

    A crash at any point leaves the standard resumable manifest; the
    offline ``python -m cpzk_tpu.fleet split`` run completes the split
    from whatever stage the crash left (the controller never starts a
    second split while a manifest exists).
    """
    from ..durability.wal import WriteAheadLog
    from ..replication.segments import split_records
    from ..replication.standby import SegmentApplier, load_epoch, store_epoch
    from ..server.state import ServerState

    if segment_bytes < 1:
        raise SplitError("segment_bytes must be positive")
    mpath = manifest_path(map_path)
    if os.path.exists(mpath):
        raise SplitError(
            f"a split manifest already exists: {mpath} — finish it with "
            "the offline `fleet split` resume first"
        )
    current = PartitionMap.load(map_path)
    new_map, moved = current.split(source, new_address)
    new_index = len(current.partitions)
    target_dir = os.path.dirname(os.path.abspath(map_path)) or "."
    target_state_file = os.path.join(
        target_dir, f"partition-{new_index}.state.json"
    )
    target_wal = target_state_file + ".wal"
    target_epoch_file = target_state_file + ".epoch"
    epoch = (load_epoch(epoch_file) if epoch_file else 0) + 1
    manifest = {
        "schema": MANIFEST_SCHEMA,
        "old_version": current.version,
        "new_version": new_map.version,
        "source": source,
        "new_index": new_index,
        "new_address": new_address,
        "moved": [[lo, hi] for lo, hi in moved],
        "epoch": epoch,
    }
    _write_manifest(mpath, manifest)
    moved_ranges = [(int(lo), int(hi)) for lo, hi in moved]

    def moved_user(uid: str) -> bool:
        h = user_hash(uid)
        return any(lo <= h < hi for lo, hi in moved_ranges)

    # ---- critical section: export -> copy -> flip, NO await ----------------
    # (synchronous on the event loop; the serving pause is the price of a
    # consistent cut + atomic ownership edge without stopping the daemon)
    records = state.export_user_records(moved_user)
    for seq, rec in enumerate(records, start=1):
        rec["seq"] = seq
    for stale in (target_state_file, target_wal, target_epoch_file):
        try:
            os.unlink(stale)
        except OSError:
            pass
    tgt_state = ServerState()
    twal = WriteAheadLog(target_wal, fsync="always")

    def sink(frames: bytes, last_seq: int) -> None:
        twal.append_frames(frames, last_seq)   # durable-before-apply
        twal.sync(force=True)

    applier = SegmentApplier(tgt_state, epoch=epoch, sink=sink)
    segments = split_records(records, epoch, 0, segment_bytes)
    for seg in segments:
        accepted, message = applier.apply(seg)
        if not accepted:
            twal.close()
            raise SplitError(f"target refused segment {seg.index}: {message}")
    new_map.store(map_path)        # the atomic ownership edge
    if fleet is not None:
        fleet.reload()
    # ---- end critical section ----------------------------------------------

    # covering snapshot + fencing epoch for the new partition's first boot
    # (its WAL already holds every frame durably; this is the tidy boot)
    tgt_state.attach_journal(twal)
    await tgt_state.snapshot(target_state_file)
    twal.close()
    store_epoch(target_epoch_file, epoch)

    # drain: the moved users are fenced by ownership enforcement from the
    # flip onward, so dropping their stale copies cannot lose a write
    dropped = state.drop_users(moved_user)
    if durability is not None:
        await durability.checkpoint()
    try:
        os.unlink(mpath)
    except OSError:
        pass
    report = {
        "old_version": current.version,
        "new_version": new_map.version,
        "source": source,
        "new_index": new_index,
        "new_address": new_address,
        "moved_ranges": [list(r) for r in moved_ranges],
        "epoch": epoch,
        "moved_users": sum(
            1 for r in records if r["type"] == "register_user"
        ),
        "moved_records": len(records),
        "segments": len(segments),
        "dropped_users": dropped[0],
        "dropped_challenges": dropped[1],
        "dropped_sessions": dropped[2],
        "target_state_file": target_state_file,
    }
    log.warning(
        "live split complete: map v%d -> v%d, partition %d -> new "
        "partition %d (%s), %d users moved; boot the new daemon from %s",
        report["old_version"], report["new_version"], source, new_index,
        new_address, report["moved_users"], target_state_file,
    )
    return report
