"""DurabilityManager: the snapshot + WAL pair as one serving-stack unit.

Owns the whole persistence lifecycle the daemon wires up when
``[durability] enabled = true``:

- :meth:`recover` — boot: snapshot load (quarantine-safe), torn-tail
  truncation, WAL-suffix replay, then opens the log for append and
  attaches it to ``ServerState`` as the journal hook;
- :meth:`checkpoint` — each cleanup sweep: snapshot (which embeds the
  covered WAL sequence number), opportunistic interval-policy fsync, and
  log compaction once the WAL outgrows ``compact_bytes``;
- :meth:`close` — graceful shutdown: final snapshot, then truncate the
  fully-covered log so the next boot replays nothing.

Compaction never loses data: the snapshot write captures the WAL byte
offset it covers (under the state lock, so it is exact), and compaction
drops only that prefix — records appended after the snapshot survive the
rename and remain the replay suffix.
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..observability import get_tracer
from ..server import metrics
from .recovery import RecoveryReport, recover_state
from .wal import WriteAheadLog

log = logging.getLogger("cpzk_tpu.durability")


class DurabilityManager:
    """Wire a :class:`WriteAheadLog` + snapshot pair to a ``ServerState``."""

    def __init__(self, state, settings, state_file: str, faults=None):
        if not state_file:
            raise ValueError("durability requires a state_file")
        self.state = state
        self.settings = settings
        self.state_file = state_file
        self.wal_path = settings.wal_path or state_file + ".wal"
        self.faults = faults
        self.wal: WriteAheadLog | None = None
        self.report: RecoveryReport | None = None
        self.covered_seq = 0
        self._covered_offset = 0
        self._last_snapshot_wall: float | None = None
        # replication coupling (SegmentShipper | None): compaction is
        # clamped to the shipped-and-acknowledged offset so a covering
        # snapshot can never drop records the standby has not received
        self.shipper = None

    def attach_shipper(self, shipper) -> None:
        """Couple a primary-side :class:`SegmentShipper` into the
        compaction path (see ``__init__``)."""
        self.shipper = shipper

    # -- lifecycle -----------------------------------------------------------

    async def recover(self) -> RecoveryReport:
        """Boot-time recovery, then open the WAL for append and attach it
        as the state's journal hook.  Call exactly once, before serving."""
        report = await recover_state(self.state, self.state_file, self.wal_path)
        self.report = report
        self.covered_seq = report.covered_seq
        # Conservative: the byte offset the last snapshot covers inside the
        # (possibly pre-existing) log is unknown until this process writes
        # a snapshot of its own — until then, compaction keeps everything.
        self._covered_offset = 0
        self.wal = WriteAheadLog(
            self.wal_path,
            fsync=self.settings.fsync,
            fsync_interval_ms=self.settings.fsync_interval_ms,
            start_seq=report.next_seq,
            faults=self.faults,
            segment_bytes=getattr(self.settings, "wal_segment_bytes", 0),
        )
        self.state.attach_journal(self.wal)
        return report

    async def checkpoint(self) -> bool:
        """One sweep's persistence work: snapshot when dirty, fsync an
        interval-policy log that is due, compact a log the snapshot now
        mostly covers.  Returns whether a snapshot was written."""
        wrote = await self.state.snapshot(self.state_file)
        if wrote:
            self.covered_seq = self.state.snapshot_covered_seq
            self._covered_offset = self.state.snapshot_covered_offset
            self._last_snapshot_wall = time.time()
        if self.wal is not None and self.wal.needs_sync():
            await asyncio.to_thread(self.wal.sync)
        compact_upto = self._covered_offset
        if self.shipper is not None:
            # never drop bytes the standby has not acknowledged
            compact_upto = min(
                compact_upto, self.shipper.safe_compact_offset()
            )
        if (
            self.wal is not None
            and compact_upto > 0
            and self.wal.size > self.settings.compact_bytes
        ):
            freed = await asyncio.to_thread(self.wal.compact, compact_upto)
            self._covered_offset -= freed
            if self.shipper is not None:
                self.shipper.note_compacted(freed)
            if freed:
                get_tracer().record_event(
                    "wal_compaction",
                    freed_bytes=freed,
                    covered_seq=self.covered_seq,
                    wal_bytes=self.wal.size,
                )
                log.info(
                    "WAL compaction: dropped %d covered bytes (<= seq %d), "
                    "%d bytes remain", freed, self.covered_seq, self.wal.size,
                )
        self._update_snapshot_age()
        return wrote

    async def close(self) -> None:
        """Graceful shutdown: final snapshot, truncate the fully-covered
        log, release the fd.  After this a reboot restores from the
        snapshot alone and replays nothing."""
        if self.wal is None:
            return
        wrote = await self.state.snapshot(self.state_file)
        if wrote:
            self.covered_seq = self.state.snapshot_covered_seq
            self._covered_offset = self.state.snapshot_covered_offset
            self._last_snapshot_wall = time.time()
        # Clean state means the last snapshot already covers every record
        # (every journaled mutation also dirties the snapshot flag), so
        # covered_seq == wal.seq here on both branches.
        if self.covered_seq == self.wal.seq and self.wal.size > 0:
            upto = self.wal.size
            if self.shipper is not None:
                upto = min(upto, self.shipper.safe_compact_offset())
            if upto > 0:
                freed = await asyncio.to_thread(self.wal.compact, upto)
                if self.shipper is not None:
                    self.shipper.note_compacted(freed)
            self._covered_offset = 0
        await asyncio.to_thread(self.wal.close)
        self._update_snapshot_age()

    # -- inspection ----------------------------------------------------------

    def _update_snapshot_age(self) -> None:
        if self._last_snapshot_wall is not None:
            metrics.gauge("state.snapshot.age_seconds").set(
                max(0.0, time.time() - self._last_snapshot_wall)
            )

    def status(self) -> dict:
        """The admin REPL ``/persist`` payload."""
        wal = self.wal
        return {
            "wal_path": self.wal_path,
            "wal_bytes": wal.size if wal is not None else 0,
            "wal_segments": wal.segment_count if wal is not None else 0,
            "wal_seq": wal.seq if wal is not None else 0,
            "covered_seq": self.covered_seq,
            "pending_appends": wal.pending if wal is not None else 0,
            "fsync_policy": self.settings.fsync,
            "last_fsync_age_s": (
                wal.last_fsync_age_s if wal is not None else float("inf")
            ),
            "snapshot_age_s": (
                time.time() - self._last_snapshot_wall
                if self._last_snapshot_wall is not None
                else None
            ),
        }
