"""DurabilityManager: the snapshot + WAL pair as one serving-stack unit.

Owns the whole persistence lifecycle the daemon wires up when
``[durability] enabled = true``:

- :meth:`recover` — boot: snapshot load (quarantine-safe), torn-tail
  truncation, WAL-suffix replay, then opens the log for append and
  attaches it to ``ServerState`` as the journal hook;
- :meth:`checkpoint` — each cleanup sweep: snapshot (which embeds the
  covered WAL sequence number), opportunistic interval-policy fsync, and
  log compaction once the WAL outgrows ``compact_bytes``;
- :meth:`close` — graceful shutdown: final snapshot, then truncate the
  fully-covered log so the next boot replays nothing.

Compaction never loses data: the snapshot write captures the WAL byte
offset it covers (under the state lock, so it is exact), and compaction
drops only that prefix — records appended after the snapshot survive the
rename and remain the replay suffix.
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..observability import get_tracer
from ..server import metrics
from .recovery import RecoveryReport, recover_state
from .wal import WriteAheadLog

log = logging.getLogger("cpzk_tpu.durability")


class DurabilityManager:
    """Wire a :class:`WriteAheadLog` + snapshot pair to a ``ServerState``."""

    def __init__(self, state, settings, state_file: str, faults=None):
        if not state_file:
            raise ValueError("durability requires a state_file")
        self.state = state
        self.settings = settings
        self.state_file = state_file
        self.wal_path = settings.wal_path or state_file + ".wal"
        self.faults = faults
        self.wal: WriteAheadLog | None = None
        self.report: RecoveryReport | None = None
        self.covered_seq = 0
        self._covered_offset = 0
        self._last_snapshot_wall: float | None = None

    # -- lifecycle -----------------------------------------------------------

    async def recover(self) -> RecoveryReport:
        """Boot-time recovery, then open the WAL for append and attach it
        as the state's journal hook.  Call exactly once, before serving."""
        report = await recover_state(self.state, self.state_file, self.wal_path)
        self.report = report
        self.covered_seq = report.covered_seq
        # Conservative: the byte offset the last snapshot covers inside the
        # (possibly pre-existing) log is unknown until this process writes
        # a snapshot of its own — until then, compaction keeps everything.
        self._covered_offset = 0
        self.wal = WriteAheadLog(
            self.wal_path,
            fsync=self.settings.fsync,
            fsync_interval_ms=self.settings.fsync_interval_ms,
            start_seq=report.next_seq,
            faults=self.faults,
        )
        self.state.attach_journal(self.wal)
        return report

    async def checkpoint(self) -> bool:
        """One sweep's persistence work: snapshot when dirty, fsync an
        interval-policy log that is due, compact a log the snapshot now
        mostly covers.  Returns whether a snapshot was written."""
        wrote = await self.state.snapshot(self.state_file)
        if wrote:
            self.covered_seq = self.state.snapshot_covered_seq
            self._covered_offset = self.state.snapshot_covered_offset
            self._last_snapshot_wall = time.time()
        if self.wal is not None and self.wal.needs_sync():
            await asyncio.to_thread(self.wal.sync)
        if (
            self.wal is not None
            and self._covered_offset > 0
            and self.wal.size > self.settings.compact_bytes
        ):
            freed = await asyncio.to_thread(self.wal.compact, self._covered_offset)
            self._covered_offset = 0
            if freed:
                get_tracer().record_event(
                    "wal_compaction",
                    freed_bytes=freed,
                    covered_seq=self.covered_seq,
                    wal_bytes=self.wal.size,
                )
                log.info(
                    "WAL compaction: dropped %d covered bytes (<= seq %d), "
                    "%d bytes remain", freed, self.covered_seq, self.wal.size,
                )
        self._update_snapshot_age()
        return wrote

    async def close(self) -> None:
        """Graceful shutdown: final snapshot, truncate the fully-covered
        log, release the fd.  After this a reboot restores from the
        snapshot alone and replays nothing."""
        if self.wal is None:
            return
        wrote = await self.state.snapshot(self.state_file)
        if wrote:
            self.covered_seq = self.state.snapshot_covered_seq
            self._covered_offset = self.state.snapshot_covered_offset
            self._last_snapshot_wall = time.time()
        # Clean state means the last snapshot already covers every record
        # (every journaled mutation also dirties the snapshot flag), so
        # covered_seq == wal.seq here on both branches.
        if self.covered_seq == self.wal.seq and self.wal.size > 0:
            await asyncio.to_thread(self.wal.compact, self.wal.size)
            self._covered_offset = 0
        await asyncio.to_thread(self.wal.close)
        self._update_snapshot_age()

    # -- inspection ----------------------------------------------------------

    def _update_snapshot_age(self) -> None:
        if self._last_snapshot_wall is not None:
            metrics.gauge("state.snapshot.age_seconds").set(
                max(0.0, time.time() - self._last_snapshot_wall)
            )

    def status(self) -> dict:
        """The admin REPL ``/persist`` payload."""
        wal = self.wal
        return {
            "wal_path": self.wal_path,
            "wal_bytes": wal.size if wal is not None else 0,
            "wal_seq": wal.seq if wal is not None else 0,
            "covered_seq": self.covered_seq,
            "pending_appends": wal.pending if wal is not None else 0,
            "fsync_policy": self.settings.fsync,
            "last_fsync_age_s": (
                wal.last_fsync_age_s if wal is not None else float("inf")
            ),
            "snapshot_age_s": (
                time.time() - self._last_snapshot_wall
                if self._last_snapshot_wall is not None
                else None
            ),
        }
