"""Boot-time crash recovery: snapshot + WAL-suffix replay, bounded loss.

The recovery invariant (the durability acceptance contract): after any
crash, a reboot yields exactly the acknowledged prefix —

- every mutation acknowledged before the crash is present (snapshot, or
  WAL record fsynced per the policy's loss window);
- no partially-written record is ever applied (``iter_frames`` stops at
  the first bad frame, and the torn tail is truncated on the spot);
- a corrupt snapshot or wholly unreadable WAL is **quarantined** to
  ``<path>.corrupt-<seq>`` (0600 preserved) with a loud ERROR, and the
  server boots from the remaining good state instead of crash-looping.

Replay goes through the same trust-boundary validators as
``ServerState.restore`` (``replay_journal_record``): a tampered log
cannot smuggle in what the live RPC would reject — invalid records are
skipped and counted, never applied and never fatal.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from dataclasses import dataclass

from ..errors import UnsupportedFormat
from ..observability import get_tracer
from ..server import metrics
from .wal import (
    NewerFormatError,
    check_record_format,
    iter_frames,
    wal_sealed_segments,
)

log = logging.getLogger("cpzk_tpu.durability")


def quarantine_file(path: str, seq: int) -> str:
    """Move an unreadable snapshot/WAL aside as ``<path>.corrupt-<seq>``
    (suffixed further if that name is taken), preserving 0600 — corrupt or
    not, the file may still hold live bearer tokens.  Returns the new
    path."""
    base = f"{path}.corrupt-{seq}"
    dst, i = base, 0
    while os.path.exists(dst):
        i += 1
        dst = f"{base}.{i}"
    os.replace(path, dst)
    try:
        os.chmod(dst, 0o600)
    except OSError:  # pragma: no cover - chmod on our own fresh rename
        pass
    return dst


@dataclass
class RecoveryReport:
    """What one boot-time recovery pass found and did."""

    snapshot_loaded: bool = False
    snapshot_quarantined: str | None = None
    wal_quarantined: str | None = None
    users: int = 0                 # loaded from the snapshot
    sessions: int = 0              # loaded from the snapshot
    covered_seq: int = 0           # WAL seq the snapshot covers
    replayed: int = 0              # WAL records applied past covered_seq
    skipped: int = 0               # WAL records rejected by the validators
    truncated_bytes: int = 0       # torn tail dropped from the WAL
    next_seq: int = 0              # where the reopened WAL resumes


async def recover_state(state, snapshot_path: str, wal_path: str) -> RecoveryReport:
    """Load the snapshot (quarantining a corrupt one), truncate the WAL's
    torn tail (quarantining a wholly unreadable log), and replay the valid
    suffix past the snapshot's covered sequence number into ``state``.

    ``state`` must be empty (a fresh ``ServerState``); serving must not
    have started — replay writes the maps single-threaded.
    """
    report = RecoveryReport()

    # 1. Read the WAL's valid prefix first: its last sequence number names
    #    the quarantine files, and a quarantined snapshot falls back to
    #    replaying the log from seq 0.  A segmented log is scanned in name
    #    order (sealed segments, then the active file) with the sequence
    #    numbers threaded across file boundaries — one logical prefix.
    records: list[dict] = []
    log_files = [(seg, False) for seg in wal_sealed_segments(wal_path)]
    if os.path.exists(wal_path):
        log_files.append((wal_path, True))
    prev_seq: int | None = None
    poisoned = False  # a corrupt SEALED file ends the trusted prefix
    for fpath, is_active in log_files:
        if poisoned:
            # history past a corrupt sealed segment is unreachable (replay
            # must never skip a gap): set it aside for the operator
            dst = quarantine_file(fpath, int(time.time()))
            log.error(
                "ERROR: WAL file %s follows a corrupt sealed segment; "
                "quarantined to %s", fpath, dst,
            )
            continue  # the reopened log O_CREATs a fresh active file

        def _read_log(p=fpath) -> bytes:
            with open(p, "rb") as f:
                return f.read()

        try:
            # worker thread: the log can be compact_bytes-sized, and boot
            # may run with the health listener already up
            raw = await asyncio.to_thread(_read_log)
        except OSError as e:
            dst = quarantine_file(fpath, int(time.time()))
            report.wal_quarantined = report.wal_quarantined or dst
            log.error(
                "ERROR: write-ahead log %s unreadable (%s); quarantined to %s",
                fpath, e, dst,
            )
            poisoned = not is_active
            continue
        if not raw:
            continue
        frecords, valid = iter_frames(raw, prev_seq=prev_seq)
        # format gate (before anything is replayed): a record stamped
        # newer than this build refuses the whole boot, loudly — the
        # file is fine, the binary is downgraded; quarantining would
        # throw away good data
        for rec in frecords:
            try:
                check_record_format(rec)
            except NewerFormatError as e:
                raise NewerFormatError(
                    f"write-ahead log {fpath}: {e}"
                ) from None
        if not frecords and valid == 0:
            # nonempty but yields no records: not a torn tail, the file
            # is garbage from byte 0 — quarantine rather than truncate
            # away what an operator may want to inspect
            dst = quarantine_file(fpath, int(time.time()))
            report.wal_quarantined = report.wal_quarantined or dst
            log.error(
                "ERROR: write-ahead log %s has no readable frames; "
                "quarantined to %s", fpath, dst,
            )
            poisoned = not is_active
            continue
        records.extend(frecords)
        if frecords:
            prev_seq = frecords[-1]["seq"]
        if valid < len(raw):
            if is_active:
                report.truncated_bytes = len(raw) - valid

                def _truncate() -> None:
                    fd = os.open(wal_path, os.O_WRONLY)
                    try:
                        os.ftruncate(fd, valid)
                        os.fsync(fd)
                    finally:
                        os.close(fd)

                await asyncio.to_thread(_truncate)
                log.warning(
                    "torn WAL tail: dropped %d trailing bytes of %s after "
                    "seq %d (crash mid-append; acknowledged records are "
                    "intact)",
                    report.truncated_bytes, wal_path, records[-1]["seq"],
                )
            else:
                # sealed segments are fsynced before their rename — a bad
                # interior is disk corruption: keep the valid prefix,
                # quarantine the file, refuse everything after the gap
                dst = quarantine_file(fpath, int(time.time()))
                report.wal_quarantined = report.wal_quarantined or dst
                log.error(
                    "ERROR: sealed WAL segment %s is corrupt past a valid "
                    "prefix; quarantined to %s (later log files will be "
                    "set aside — recover them manually if needed)",
                    fpath, dst,
                )
                poisoned = True
    last_seq = records[-1]["seq"] if records else 0

    # 2. Snapshot: corrupt files quarantine and boot, never crash-loop.
    if os.path.exists(snapshot_path):
        try:
            report.users, report.sessions = await state.restore(snapshot_path)
            report.covered_seq = state.restored_wal_seq
            report.snapshot_loaded = True
        except asyncio.CancelledError:
            raise
        except UnsupportedFormat as e:
            # NOT a quarantine case: the snapshot is from a newer build,
            # not corrupt — refuse to boot, naming both versions, so the
            # operator upgrades the binary instead of losing the file
            raise NewerFormatError(
                f"state snapshot {snapshot_path}: {e}"
            ) from e
        except Exception as e:
            report.snapshot_quarantined = quarantine_file(
                snapshot_path, last_seq or int(time.time())
            )
            log.error(
                "ERROR: state snapshot %s failed validation (%s); quarantined "
                "to %s and booting from the write-ahead log alone",
                snapshot_path, e, report.snapshot_quarantined,
            )

    # 3. Replay the suffix beyond the snapshot's covered sequence number.
    #    Challenge records bypass the covered-seq cut: challenges are
    #    deliberately NOT in the snapshot (300 s single-use nonces — see
    #    state.py), so their only durable home is the log.  Replaying the
    #    whole create/consume history is idempotent and cheap (expired
    #    creates drop, consumes of missing ids skip) and keeps in-flight
    #    logins alive across a crash that landed between a snapshot and
    #    the reboot.  Bounded by compaction: records older than the last
    #    covering compaction are gone, which the 300 s TTL outlives only
    #    under pathological sweep cadences (docs/operations.md).
    for rec in records:
        if rec["seq"] <= report.covered_seq and rec.get("type") not in (
            "create_challenge", "consume_challenge",
        ):
            continue
        msg = state.replay_journal_record(rec)
        if msg is None:
            report.replayed += 1
        else:
            report.skipped += 1
            log.warning(
                "WAL replay skipped seq %d (%s): %s",
                rec["seq"], rec.get("type"), msg,
            )

    report.next_seq = max(report.covered_seq, last_seq)
    if report.replayed:
        metrics.counter("state.recovery.replayed").inc(report.replayed)
    get_tracer().record_event(
        "recovery",
        snapshot_loaded=report.snapshot_loaded,
        snapshot_quarantined=report.snapshot_quarantined or "",
        wal_quarantined=report.wal_quarantined or "",
        covered_seq=report.covered_seq,
        replayed=report.replayed,
        skipped=report.skipped,
        truncated_bytes=report.truncated_bytes,
    )
    log.info(
        "recovery: snapshot users=%d sessions=%d covered_seq=%d; WAL "
        "replayed=%d skipped=%d truncated_bytes=%d next_seq=%d",
        report.users, report.sessions, report.covered_seq,
        report.replayed, report.skipped, report.truncated_bytes,
        report.next_seq,
    )
    return report
