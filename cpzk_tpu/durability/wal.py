"""Write-ahead log: length- and CRC32-framed JSON records on a raw fd.

The durability contract the snapshot-only persistence cannot give: an
acknowledged mutation survives a crash *between* snapshot sweeps.  Every
acknowledged change to persisted state (``register_user``,
``create_session``, ``revoke_session``, ``expire_sessions``) is appended
here before the RPC returns; boot-time recovery replays the suffix past
the last snapshot's covered sequence number (see :mod:`.recovery`).

Frame format (all integers big-endian)::

    +----------------+----------------+------------------------+
    | length  u32    | crc32   u32    | payload (JSON, length) |
    +----------------+----------------+------------------------+

The CRC covers the payload only; the payload is one JSON object with at
least ``{"seq": <monotonic int>, "type": <str>}``.  A reader accepts the
longest prefix of well-formed frames with strictly increasing sequence
numbers and stops at the first violation — a torn tail (the crash left a
partial frame) and mid-log corruption are therefore indistinguishable by
construction, and neither can ever make a partially-written record
visible to replay.

Fsync policy (``durability.fsync``):

- ``always``   — fsync before the mutation is acknowledged (loss window:
  none for acknowledged writes).
- ``interval`` — fsync at most every ``fsync_interval_ms``, piggybacked
  on appends and forced by the periodic sweep (loss window: about one
  interval of acknowledged writes).
- ``off``      — never fsync explicitly; the OS page cache decides
  (loss window: everything since the kernel's last writeback).

Appends go through ``os.write`` on an ``O_APPEND`` fd (no user-space
buffer), so ``size`` always reflects what a crashed process left in the
file.  The file is created 0600 and re-chmodded defensively: session
records hold live bearer tokens, the same protection requirement as the
snapshot.

Deterministic crash points (``pre_append`` / ``mid_frame`` /
``post_append_pre_fsync`` / ``pre_rename``) are consulted on a
:class:`~cpzk_tpu.resilience.faults.FaultPlan` passed as ``faults`` —
each raises :class:`CrashPoint` at exactly the file state a process
death at that instruction would leave, so the recovery tests assert
exact outcomes instead of sampling kill timing.
"""

from __future__ import annotations

import json
import os
import struct
import tempfile
import threading
import time
import zlib

from ..server import metrics

_HEADER = struct.Struct(">II")
HEADER_BYTES = _HEADER.size

#: Sanity cap on one frame's payload: a garbage length field must not make
#: the reader allocate gigabytes (largest real record is a register_user
#: at a few hundred bytes).
MAX_FRAME_PAYLOAD = 1 << 20

#: The deterministic crash sites a FaultPlan can schedule (see
#: ``FaultPlan.crash_on``); occurrence indexes count per-site visits.
WAL_CRASH_POINTS = (
    "pre_append",            # nothing written for this record
    "mid_frame",             # half the frame written: a torn tail on disk
    "post_append_pre_fsync",  # full frame written, never fsynced
    "pre_rename",            # compaction tmp written, rename never happened
)


class CrashPoint(RuntimeError):
    """Deterministic injected crash at a WAL write site — stands in for the
    process dying at exactly that instruction (the SIGKILL subprocess test
    does it for real)."""


def encode_record(rec: dict) -> bytes:
    """One framed record: compact, key-sorted JSON behind length + CRC32."""
    payload = json.dumps(rec, separators=(",", ":"), sort_keys=True).encode()
    if len(payload) > MAX_FRAME_PAYLOAD:
        raise ValueError(f"WAL record exceeds {MAX_FRAME_PAYLOAD} bytes")
    return _HEADER.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload


def iter_frames(
    buf: bytes, offset: int = 0, prev_seq: int | None = None
) -> tuple[list[dict], int]:
    """``(records, valid_bytes)``: the longest well-formed prefix of ``buf``.

    Stops at the first short header, oversized/zero length field, CRC
    mismatch, non-JSON payload, schema violation (missing ``seq``/``type``),
    or non-increasing sequence number.  ``valid_bytes`` is the byte offset
    the file should be truncated to; everything past it is a torn tail or
    corruption and is never surfaced as a record.

    ``offset``/``prev_seq`` resume a previous scan mid-file (the audit
    pipeline's cursor): parsing starts at ``offset`` and the first record's
    sequence number must exceed ``prev_seq`` — byte-identical results to
    one whole-buffer scan split at any frame boundary.
    """
    out: list[dict] = []
    off = offset
    n = len(buf)
    while n - off >= HEADER_BYTES:
        length, crc = _HEADER.unpack_from(buf, off)
        if length == 0 or length > MAX_FRAME_PAYLOAD:
            break
        end = off + HEADER_BYTES + length
        if end > n:
            break  # torn tail: the frame was cut mid-write
        payload = bytes(buf[off + HEADER_BYTES:end])
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            break
        try:
            rec = json.loads(payload)
        except ValueError:
            break
        if (
            not isinstance(rec, dict)
            or not isinstance(rec.get("seq"), int)
            or isinstance(rec.get("seq"), bool)
            or not isinstance(rec.get("type"), str)
        ):
            break
        if prev_seq is not None and rec["seq"] <= prev_seq:
            break
        prev_seq = rec["seq"]
        out.append(rec)
        off = end
    return out, off


def read_frames(path: str) -> tuple[list[dict], int, int]:
    """``(records, valid_bytes, file_bytes)`` for the log at ``path``."""
    with open(path, "rb") as f:
        raw = f.read()
    records, valid = iter_frames(raw)
    return records, valid, len(raw)


class WriteAheadLog:
    """Append-only framed-record log with a configurable fsync policy.

    ``append`` is synchronous and cheap (one ``os.write`` into the page
    cache) so :class:`~cpzk_tpu.server.state.ServerState` can call it
    under its state lock — WAL order then always matches in-memory
    application order.  The fsync (when the policy wants one) happens in
    :meth:`sync`, which callers run on a worker thread *after* releasing
    the lock but *before* acknowledging the mutation; fsync flushes every
    earlier write too, so per-record durability still holds under
    interleaving.

    A threading lock guards the fd: appends come from the event loop,
    ``sync`` and :meth:`compact` from worker threads.
    """

    def __init__(
        self,
        path: str,
        fsync: str = "always",
        fsync_interval_ms: float = 50.0,
        start_seq: int = 0,
        faults=None,
    ):
        if fsync not in ("always", "interval", "off"):
            raise ValueError(f"unknown WAL fsync policy: {fsync!r}")
        self.path = path
        self.policy = fsync
        self.interval_s = fsync_interval_ms / 1000.0
        self.seq = start_seq
        self._faults = faults
        self._lock = threading.Lock()
        self._fd: int | None = os.open(
            path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o600
        )
        os.chmod(path, 0o600)  # session records are bearer secrets
        self.size = os.fstat(self._fd).st_size
        self._pending = 0  # appends since the last fsync
        self._last_fsync = time.monotonic()

    # -- append / sync -------------------------------------------------------

    def _crash(self, point: str) -> bool:
        return self._faults is not None and self._faults.take_crash(point)

    def append(self, rtype: str, payload: dict) -> int:
        """Frame and write one record; returns its sequence number.  The
        record is in the OS page cache after this returns — call
        :meth:`sync` before acknowledging when the policy demands it."""
        with self._lock:
            if self._fd is None:
                raise OSError("write-ahead log is closed")
            seq = self.seq + 1
            rec = {"seq": seq, "type": rtype}
            rec.update(payload)
            frame = encode_record(rec)
            if self._crash("pre_append"):
                raise CrashPoint(f"pre_append at seq {seq}")
            if self._crash("mid_frame"):
                cut = max(1, len(frame) // 2)
                os.write(self._fd, frame[:cut])
                self.size += cut
                raise CrashPoint(f"mid_frame at seq {seq}")
            os.write(self._fd, frame)
            self.seq = seq
            self.size += len(frame)
            self._pending += 1
            metrics.counter("state.wal.appends").inc()
            metrics.counter("state.wal.bytes").inc(len(frame))
            if self._crash("post_append_pre_fsync"):
                raise CrashPoint(f"post_append_pre_fsync at seq {seq}")
            return seq

    def append_frames(self, frames: bytes, last_seq: int) -> None:
        """Append pre-framed records verbatim, adopting ``last_seq`` as the
        log head — the replication standby's write path: shipped segments
        keep the PRIMARY's sequence numbers (replay and a later promotion
        continue the same numbering), so they must not be re-framed
        through :meth:`append`.  The caller has already validated the
        frames (CRC + parse + contiguity); fsync policy applies as usual
        via :meth:`sync`."""
        with self._lock:
            if self._fd is None:
                raise OSError("write-ahead log is closed")
            if last_seq <= self.seq:
                raise ValueError(
                    f"append_frames would move seq backwards "
                    f"({last_seq} <= {self.seq})"
                )
            os.write(self._fd, frames)
            self.seq = last_seq
            self.size += len(frames)
            self._pending += 1
            metrics.counter("state.wal.appends").inc()
            metrics.counter("state.wal.bytes").inc(len(frames))

    def needs_sync(self) -> bool:
        """Whether :meth:`sync` would fsync right now under the policy —
        lets the async caller skip the worker-thread hop entirely."""
        if self._pending == 0 or self.policy == "off":
            return False
        if self.policy == "always":
            return True
        return time.monotonic() - self._last_fsync >= self.interval_s

    def sync(self, force: bool = False) -> bool:
        """Fsync pending appends per the policy (``force`` overrides it);
        returns whether an fsync happened."""
        with self._lock:
            if self._fd is None or self._pending == 0:
                return False
            if not force:
                if self.policy == "off":
                    return False
                if (
                    self.policy == "interval"
                    and time.monotonic() - self._last_fsync < self.interval_s
                ):
                    return False
            os.fsync(self._fd)
            self._pending = 0
            self._last_fsync = time.monotonic()
            metrics.counter("state.wal.fsyncs").inc()
            return True

    @property
    def last_fsync_age_s(self) -> float:
        """Seconds since the last fsync (or since open, if none yet)."""
        return max(0.0, time.monotonic() - self._last_fsync)

    @property
    def pending(self) -> int:
        return self._pending

    # -- compaction ----------------------------------------------------------

    def compact(self, upto_offset: int) -> int:
        """Drop the byte prefix a snapshot now covers: copy ``[upto_offset,
        EOF)`` to a 0600 tmp file, fsync it, and atomically rename it over
        the log.  Returns bytes freed.  Runs under the fd lock, so
        concurrent appends briefly queue; the copied tail is bounded by the
        compaction threshold, keeping the stall small.  A crash before the
        rename (``pre_rename`` crash point, or a real one) leaves the old
        log fully intact — compaction is all-or-nothing."""
        with self._lock:
            if self._fd is None:
                raise OSError("write-ahead log is closed")
            upto = max(0, min(upto_offset, self.size))
            if upto == 0:
                return 0
            with open(self.path, "rb") as f:
                f.seek(upto)
                tail = f.read()
            d = os.path.dirname(os.path.abspath(self.path)) or "."
            prefix = "." + os.path.basename(self.path) + ".compact."
            fd, tmp = tempfile.mkstemp(prefix=prefix, dir=d)  # 0600
            try:
                if tail:
                    os.write(fd, tail)
                os.fsync(fd)
                os.close(fd)
                if self._crash("pre_rename"):
                    raise CrashPoint("pre_rename during WAL compaction")
                os.replace(tmp, self.path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            os.close(self._fd)
            self._fd = os.open(
                self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o600
            )
            freed = self.size - len(tail)
            self.size = len(tail)
            self._pending = 0  # the tmp copy was fsynced before the rename
            return freed

    def truncate_to(self, valid_bytes: int) -> int:
        """Drop everything past ``valid_bytes`` (the torn tail a standby
        found at promotion time); returns bytes dropped.  The log's
        bookkeeping stays consistent — callers pass the valid-prefix
        boundary ``iter_frames`` reported."""
        with self._lock:
            if self._fd is None:
                raise OSError("write-ahead log is closed")
            valid = max(0, min(valid_bytes, self.size))
            dropped = self.size - valid
            if dropped:
                fd = os.open(self.path, os.O_WRONLY)
                try:
                    os.ftruncate(fd, valid)
                    os.fsync(fd)
                finally:
                    os.close(fd)
                self.size = valid
            return dropped

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Force-sync pending appends and release the fd (idempotent)."""
        self.sync(force=True)
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None
