"""Write-ahead log: length- and CRC32-framed JSON records on a raw fd.

The durability contract the snapshot-only persistence cannot give: an
acknowledged mutation survives a crash *between* snapshot sweeps.  Every
acknowledged change to persisted state (``register_user``,
``create_session``, ``revoke_session``, ``expire_sessions``) is appended
here before the RPC returns; boot-time recovery replays the suffix past
the last snapshot's covered sequence number (see :mod:`.recovery`).

Frame format (all integers big-endian)::

    +----------------+----------------+------------------------+
    | length  u32    | crc32   u32    | payload (JSON, length) |
    +----------------+----------------+------------------------+

The CRC covers the payload only; the payload is one JSON object with at
least ``{"seq": <monotonic int>, "type": <str>}``.  A reader accepts the
longest prefix of well-formed frames with strictly increasing sequence
numbers and stops at the first violation — a torn tail (the crash left a
partial frame) and mid-log corruption are therefore indistinguishable by
construction, and neither can ever make a partially-written record
visible to replay.

Fsync policy (``durability.fsync``):

- ``always``   — fsync before the mutation is acknowledged (loss window:
  none for acknowledged writes).
- ``interval`` — fsync at most every ``fsync_interval_ms``, piggybacked
  on appends and forced by the periodic sweep (loss window: about one
  interval of acknowledged writes).
- ``off``      — never fsync explicitly; the OS page cache decides
  (loss window: everything since the kernel's last writeback).

Appends go through ``os.write`` on an ``O_APPEND`` fd (no user-space
buffer), so ``size`` always reflects what a crashed process left in the
file.  The file is created 0600 and re-chmodded defensively: session
records hold live bearer tokens, the same protection requirement as the
snapshot.

Segmented mode (``wal_segment_bytes > 0``): the active file is sealed
into immutable ``<path>.<first_seq>-<last_seq>.seg`` files (zero-padded,
so lexicographic name order IS sequence order — the proof log's rotation
discipline) once it outgrows the threshold, off the event loop (the seal
runs inside :meth:`WriteAheadLog.sync` on the caller's worker thread).
Compaction then **unlinks** fully-covered sealed segments instead of
copying the surviving tail under the fd lock — the append stall stops
scaling with tail size (the million-user cliff of ISSUE 14).  All byte
offsets exposed by the class (``size``, ``read_from``, ``compact``,
``truncate_to``) are *logical*: positions in the concatenation of sealed
segments plus the active file, rebased by ``freed`` on compaction exactly
as the single-file offsets always were, so the snapshot watermark and the
replication shipper's acked-offset bookkeeping carry over unchanged.

Deterministic crash points (``pre_append`` / ``mid_frame`` /
``post_append_pre_fsync`` / ``pre_rename``, plus ``pre_seal`` /
``pre_unlink`` in segmented mode) are consulted on a
:class:`~cpzk_tpu.resilience.faults.FaultPlan` passed as ``faults`` —
each raises :class:`CrashPoint` at exactly the file state a process
death at that instruction would leave, so the recovery tests assert
exact outcomes instead of sampling kill timing.
"""

from __future__ import annotations

import json
import os
import re
import struct
import tempfile
import threading
import time
import zlib

from ..errors import UnsupportedFormat
from ..server import metrics

_HEADER = struct.Struct(">II")
HEADER_BYTES = _HEADER.size

#: Sanity cap on one frame's payload: a garbage length field must not make
#: the reader allocate gigabytes (largest real record is a register_user
#: at a few hundred bytes).
MAX_FRAME_PAYLOAD = 1 << 20

#: Format version stamped into every record this writer appends (the
#: ``"fmt"`` key; proof-log records carry the same stamp).  Recovery
#: refuses a record stamped NEWER than this — a downgraded binary must
#: never half-understand a newer format and silently misreplay — while
#: records with no stamp (pre-ISSUE-18 files) keep loading: the absence
#: of the key IS version 1.  Replay itself ignores unknown keys, so a
#: same-or-older stamp costs nothing.
WAL_FORMAT_VERSION = 1

#: The deterministic crash sites a FaultPlan can schedule (see
#: ``FaultPlan.crash_on``); occurrence indexes count per-site visits.
WAL_CRASH_POINTS = (
    "pre_append",            # nothing written for this record
    "mid_frame",             # half the frame written: a torn tail on disk
    "post_append_pre_fsync",  # full frame written, never fsynced
    "pre_rename",            # compaction tmp written, rename never happened
    "pre_seal",              # active file fsynced, seal rename never happened
    "pre_unlink",            # covered segment still on disk after compaction
)


class CrashPoint(RuntimeError):
    """Deterministic injected crash at a WAL write site — stands in for the
    process dying at exactly that instruction (the SIGKILL subprocess test
    does it for real)."""


def frame_payload(payload: bytes) -> bytes:
    """One framed blob: the shared ``length u32 | crc32 u32 | payload``
    header over arbitrary bytes.  THE construction helper for every
    plane speaking this discipline — the WAL and proof log (JSON records
    via :func:`encode_record`), and the sharded-ingest unix pipe
    (pickled request frames).  Hand-rolling the header elsewhere is a
    FRAME-001 finding: one copy of the contract, zero drift."""
    return _HEADER.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload


def unpack_frame_header(header: bytes) -> tuple[int, int]:
    """``(length, crc32)`` from one ``HEADER_BYTES``-byte frame header —
    the streaming read seam for consumers that cannot buffer the whole
    log (the ingest pipe reads frame-by-frame off a socket;
    :func:`iter_frames` is the whole-buffer scanner)."""
    return _HEADER.unpack(header)


def frame_crc_ok(payload: bytes, crc: int) -> bool:
    """Whether ``payload`` matches the header's CRC (masked compare,
    exactly as :func:`iter_frames` validates)."""
    return zlib.crc32(payload) & 0xFFFFFFFF == int(crc) & 0xFFFFFFFF


def encode_record(rec: dict) -> bytes:
    """One framed record: compact, key-sorted JSON behind length + CRC32."""
    payload = json.dumps(rec, separators=(",", ":"), sort_keys=True).encode()
    if len(payload) > MAX_FRAME_PAYLOAD:
        raise ValueError(f"WAL record exceeds {MAX_FRAME_PAYLOAD} bytes")
    return frame_payload(payload)


class NewerFormatError(UnsupportedFormat, ValueError):
    """A record (WAL or proof log) is stamped with a format version newer
    than this build writes — a downgraded binary looking at a newer
    file.  Recovery refuses LOUDLY (raises, never quarantines): the file
    is not corrupt, the binary is old, and silently replaying what it
    half-understands would be data loss with extra steps.  Subclasses
    :class:`~cpzk_tpu.errors.UnsupportedFormat` (the shared refusal
    taxonomy — snapshot version gates raise it too) and ``ValueError``
    (so pre-existing broad handlers keep their semantics)."""


def check_record_format(rec: dict) -> None:
    """Refuse a record stamped newer than ``WAL_FORMAT_VERSION`` (or with
    a junk stamp).  Unstamped records pass — pre-stamp files are format
    version 1 by definition."""
    fmt = rec.get("fmt")
    if fmt is None:
        return
    if not isinstance(fmt, int) or isinstance(fmt, bool) or fmt < 1:
        raise NewerFormatError(
            f"record seq {rec.get('seq')} carries an unintelligible "
            f"format stamp {fmt!r} (this build writes format "
            f"{WAL_FORMAT_VERSION})"
        )
    if fmt > WAL_FORMAT_VERSION:
        raise NewerFormatError(
            f"record seq {rec.get('seq')} is format version {fmt}, newer "
            f"than this build supports ({WAL_FORMAT_VERSION}) — run a "
            "binary at least as new as the one that wrote it"
        )


def iter_frames(
    buf: bytes, offset: int = 0, prev_seq: int | None = None
) -> tuple[list[dict], int]:
    """``(records, valid_bytes)``: the longest well-formed prefix of ``buf``.

    Stops at the first short header, oversized/zero length field, CRC
    mismatch, non-JSON payload, schema violation (missing ``seq``/``type``),
    or non-increasing sequence number.  ``valid_bytes`` is the byte offset
    the file should be truncated to; everything past it is a torn tail or
    corruption and is never surfaced as a record.

    ``offset``/``prev_seq`` resume a previous scan mid-file (the audit
    pipeline's cursor): parsing starts at ``offset`` and the first record's
    sequence number must exceed ``prev_seq`` — byte-identical results to
    one whole-buffer scan split at any frame boundary.
    """
    out: list[dict] = []
    off = offset
    n = len(buf)
    while n - off >= HEADER_BYTES:
        length, crc = _HEADER.unpack_from(buf, off)
        if length == 0 or length > MAX_FRAME_PAYLOAD:
            break
        end = off + HEADER_BYTES + length
        if end > n:
            break  # torn tail: the frame was cut mid-write
        payload = bytes(buf[off + HEADER_BYTES:end])
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            break
        try:
            rec = json.loads(payload)
        except ValueError:
            break
        if (
            not isinstance(rec, dict)
            or not isinstance(rec.get("seq"), int)
            or isinstance(rec.get("seq"), bool)
            or not isinstance(rec.get("type"), str)
        ):
            break
        if prev_seq is not None and rec["seq"] <= prev_seq:
            break
        prev_seq = rec["seq"]
        out.append(rec)
        off = end
    return out, off


def read_frames(path: str) -> tuple[list[dict], int, int]:
    """``(records, valid_bytes, file_bytes)`` for the log at ``path``."""
    with open(path, "rb") as f:
        raw = f.read()
    records, valid = iter_frames(raw)
    return records, valid, len(raw)


#: Sealed-segment name template: zero-padded first/last sequence numbers
#: so lexicographic order equals sequence order (the proof log's exact
#: rotation discipline — ``cpzk_tpu/audit/log.py``).
_SEG_WIDTH = 12
_SEG_RE = re.compile(r"\.(\d{12})-(\d{12})\.seg$")


def wal_segment_name(path: str, first_seq: int, last_seq: int) -> str:
    return (
        f"{path}.{first_seq:0{_SEG_WIDTH}d}-{last_seq:0{_SEG_WIDTH}d}.seg"
    )


def wal_segment_range(seg_path: str) -> tuple[int, int]:
    """``(first_seq, last_seq)`` encoded in a sealed-segment name."""
    m = _SEG_RE.search(seg_path)
    if m is None:
        raise ValueError(f"not a sealed WAL segment name: {seg_path!r}")
    return int(m.group(1)), int(m.group(2))


def wal_sealed_segments(path: str) -> list[str]:
    """Sealed-segment files rotated out of the log at ``path``, sequence
    order (their zero-padded names sort that way).  A directory scan, not
    in-memory state — survives restarts, exactly like the proof log's."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path)
    try:
        names = os.listdir(d)
    except OSError:
        return []
    out = [
        os.path.join(d, n)
        for n in names
        if n.startswith(base + ".") and _SEG_RE.search(n)
    ]
    out.sort()
    return out


def wal_files(path: str) -> list[str]:
    """Every file holding this log's records, read order: sealed segments
    (sequence order), then the active file when it exists — the set a
    boot-time recovery must scan."""
    out = wal_sealed_segments(path)
    if os.path.exists(path):
        out.append(path)
    return out


class WriteAheadLog:
    """Append-only framed-record log with a configurable fsync policy.

    ``append`` is synchronous and cheap (one ``os.write`` into the page
    cache) so :class:`~cpzk_tpu.server.state.ServerState` can call it
    under its state lock — WAL order then always matches in-memory
    application order.  The fsync (when the policy wants one) happens in
    :meth:`sync`, which callers run on a worker thread *after* releasing
    the lock but *before* acknowledging the mutation; fsync flushes every
    earlier write too, so per-record durability still holds under
    interleaving.

    A threading lock guards the fd: appends come from the event loop,
    ``sync`` and :meth:`compact` from worker threads.
    """

    def __init__(
        self,
        path: str,
        fsync: str = "always",
        fsync_interval_ms: float = 50.0,
        start_seq: int = 0,
        faults=None,
        segment_bytes: int = 0,
    ):
        if fsync not in ("always", "interval", "off"):
            raise ValueError(f"unknown WAL fsync policy: {fsync!r}")
        if segment_bytes < 0:
            raise ValueError("segment_bytes cannot be negative")
        self.path = path
        self.policy = fsync
        self.interval_s = fsync_interval_ms / 1000.0
        self.seq = start_seq
        self.segment_bytes = segment_bytes
        self._faults = faults
        self._lock = threading.Lock()
        # sealed segments already on disk (a restart, or a config change):
        # (path, byte length) in sequence order.  Loaded regardless of
        # segment_bytes so logical offsets stay correct after a downgrade.
        self._segments: list[tuple[str, int]] = [
            (seg, os.path.getsize(seg)) for seg in wal_sealed_segments(path)
        ]
        self._fd: int | None = os.open(
            path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o600
        )
        os.chmod(path, 0o600)  # session records are bearer secrets
        self._active_size = os.fstat(self._fd).st_size
        self.size = self._sealed_bytes() + self._active_size
        # first sequence number in the active file (names the seal): from
        # the file's own first frame when it has history, else the next
        # append's number
        self._active_first_seq = self.seq + 1
        if self._active_size and (self.segment_bytes or self._segments):
            # segmented mode needs the active file's own seq span (it
            # names the next seal); legacy mode keeps the caller's
            # start_seq untouched, exactly as before
            try:
                records, _, _ = read_frames(path)
                if records:
                    self._active_first_seq = int(records[0]["seq"])
                    self.seq = max(self.seq, int(records[-1]["seq"]))
            except OSError:  # pragma: no cover - racing external rotation
                pass
        for seg, _bytes in self._segments:
            try:
                self.seq = max(self.seq, wal_segment_range(seg)[1])
            except ValueError:  # pragma: no cover - name-filtered above
                pass
        self._rotate_due = False
        self._pending = 0  # appends since the last fsync
        self._last_fsync = time.monotonic()
        self._export_segment_gauge()

    def _sealed_bytes(self) -> int:
        return sum(b for _, b in self._segments)

    @property
    def segment_count(self) -> int:
        return len(self._segments)

    def _export_segment_gauge(self) -> None:
        metrics.gauge("state.wal.segments").set(float(len(self._segments)))

    # -- append / sync -------------------------------------------------------

    def _crash(self, point: str) -> bool:
        return self._faults is not None and self._faults.take_crash(point)

    def append(self, rtype: str, payload: dict) -> int:
        """Frame and write one record; returns its sequence number.  The
        record is in the OS page cache after this returns — call
        :meth:`sync` before acknowledging when the policy demands it."""
        with self._lock:
            if self._fd is None:
                raise OSError("write-ahead log is closed")
            seq = self.seq + 1
            rec = {"seq": seq, "type": rtype, "fmt": WAL_FORMAT_VERSION}
            rec.update(payload)
            frame = encode_record(rec)
            if self._crash("pre_append"):
                raise CrashPoint(f"pre_append at seq {seq}")
            if self._crash("mid_frame"):
                cut = max(1, len(frame) // 2)
                os.write(self._fd, frame[:cut])
                self.size += cut
                self._active_size += cut
                raise CrashPoint(f"mid_frame at seq {seq}")
            os.write(self._fd, frame)
            self.seq = seq
            self.size += len(frame)
            self._active_size += len(frame)
            self._pending += 1
            metrics.counter("state.wal.appends").inc()
            metrics.counter("state.wal.bytes").inc(len(frame))
            if self.segment_bytes and self._active_size >= self.segment_bytes:
                # sealed off the event loop: sync() (always run on a
                # worker thread by callers) performs the rotation
                self._rotate_due = True
            if self._crash("post_append_pre_fsync"):
                raise CrashPoint(f"post_append_pre_fsync at seq {seq}")
            return seq

    def append_frames(self, frames: bytes, last_seq: int) -> None:
        """Append pre-framed records verbatim, adopting ``last_seq`` as the
        log head — the replication standby's write path: shipped segments
        keep the PRIMARY's sequence numbers (replay and a later promotion
        continue the same numbering), so they must not be re-framed
        through :meth:`append`.  The caller has already validated the
        frames (CRC + parse + contiguity); fsync policy applies as usual
        via :meth:`sync`."""
        with self._lock:
            if self._fd is None:
                raise OSError("write-ahead log is closed")
            if last_seq <= self.seq:
                raise ValueError(
                    f"append_frames would move seq backwards "
                    f"({last_seq} <= {self.seq})"
                )
            os.write(self._fd, frames)
            self.seq = last_seq
            self.size += len(frames)
            self._active_size += len(frames)
            self._pending += 1
            metrics.counter("state.wal.appends").inc()
            metrics.counter("state.wal.bytes").inc(len(frames))
            if self.segment_bytes and self._active_size >= self.segment_bytes:
                self._rotate_due = True

    def needs_sync(self) -> bool:
        """Whether :meth:`sync` would do work right now — an fsync the
        policy wants, or a due segment seal — so the async caller can
        skip the worker-thread hop entirely otherwise."""
        if self._rotate_due:
            return True
        if self._pending == 0 or self.policy == "off":
            return False
        if self.policy == "always":
            return True
        return time.monotonic() - self._last_fsync >= self.interval_s

    def sync(self, force: bool = False) -> bool:
        """Fsync pending appends per the policy (``force`` overrides it);
        returns whether an fsync happened.  In segmented mode a due seal
        happens here too — callers always run :meth:`sync` on a worker
        thread, so the seal's fsync + rename never stall the event loop."""
        with self._lock:
            if self._fd is not None and self._rotate_due:
                self._seal_active_locked()
                return True
            if self._fd is None or self._pending == 0:
                return False
            if not force:
                if self.policy == "off":
                    return False
                if (
                    self.policy == "interval"
                    and time.monotonic() - self._last_fsync < self.interval_s
                ):
                    return False
            os.fsync(self._fd)
            self._pending = 0
            self._last_fsync = time.monotonic()
            metrics.counter("state.wal.fsyncs").inc()
            return True

    @property
    def last_fsync_age_s(self) -> float:
        """Seconds since the last fsync (or since open, if none yet)."""
        return max(0.0, time.monotonic() - self._last_fsync)

    @property
    def pending(self) -> int:
        return self._pending

    def _seal_active_locked(self) -> None:
        """Rotate the active file into an immutable sealed segment:
        fsync (a sealed segment is durable by definition), atomic rename
        to ``<path>.<first>-<last>.seg``, reopen a fresh active file.
        Caller holds ``_lock`` and runs on a worker thread."""
        assert self._fd is not None
        self._rotate_due = False
        if self._active_size == 0 or self.seq < self._active_first_seq:
            return  # nothing to seal (raced a compaction that truncated)
        os.fsync(self._fd)
        if self._crash("pre_seal"):
            # the process dies with the active file fsynced but the
            # rename not done: recovery sees the same records, unsealed
            raise CrashPoint(
                f"pre_seal of segments {self._active_first_seq}-{self.seq}"
            )
        os.close(self._fd)
        self._fd = None
        sealed = wal_segment_name(
            self.path, self._active_first_seq, self.seq
        )
        os.replace(self.path, sealed)
        self._segments.append((sealed, self._active_size))
        self._fd = os.open(
            self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o600
        )
        os.chmod(self.path, 0o600)
        self._active_size = 0
        self._active_first_seq = self.seq + 1
        self._pending = 0
        self._last_fsync = time.monotonic()
        metrics.counter("state.wal.rotations").inc()
        self._export_segment_gauge()

    def read_from(self, offset: int = 0) -> bytes:
        """Every log byte at or past the *logical* ``offset`` — the
        concatenation of sealed segments plus the active file.  The read
        seam the replication shipper tails and promotion replays through;
        single-file logs read exactly as before.  Runs under the fd lock
        (callers are worker threads); a torn concurrent append surfaces
        as a torn tail, which ``iter_frames`` already refuses to parse."""
        with self._lock:
            out = bytearray()
            pos = 0
            for seg, nbytes in self._segments:
                end = pos + nbytes
                if offset < end:
                    try:
                        with open(seg, "rb") as f:
                            f.seek(max(0, offset - pos))
                            out += f.read()
                    except FileNotFoundError:  # pragma: no cover - racing unlink
                        pass
                pos = end
            try:
                with open(self.path, "rb") as f:
                    f.seek(max(0, offset - pos))
                    out += f.read()
            except FileNotFoundError:  # pragma: no cover - closed + unlinked
                pass
            return bytes(out)

    # -- compaction ----------------------------------------------------------

    def compact(self, upto_offset: int) -> int:
        """Drop the byte prefix a snapshot now covers; returns bytes
        freed.  Offsets are logical (see the module docstring); callers
        rebase their own offsets by the return value exactly as before.

        **Segmented mode** (``segment_bytes > 0``): fully-covered sealed
        segments are simply **unlinked** — no copy, no stall proportional
        to the surviving tail (the ``pre_unlink`` crash point stands in
        for dying between unlinks: leftover covered segments replay
        idempotently at the next boot).  A covered prefix that ends
        inside the active file waits for that file's own seal, except
        when the WHOLE log is covered, where the active file is
        ftruncated to zero in place (O(1)).  **Single-file mode**
        (``segment_bytes == 0``, no sealed segments on disk): the
        historical copy-and-rename path, byte-for-byte, including the
        ``pre_rename`` all-or-nothing crash point."""
        with self._lock:
            if self._fd is None:
                raise OSError("write-ahead log is closed")
            upto = max(0, min(upto_offset, self.size))
            if upto == 0:
                return 0
            freed = 0
            # 1) unlink sealed segments the covered prefix fully spans
            while self._segments and self._segments[0][1] <= upto:
                seg, nbytes = self._segments[0]
                if self._crash("pre_unlink"):
                    raise CrashPoint(f"pre_unlink of {os.path.basename(seg)}")
                try:
                    os.unlink(seg)
                except FileNotFoundError:  # pragma: no cover - already gone
                    pass
                self._segments.pop(0)
                upto -= nbytes
                freed += nbytes
                self.size -= nbytes
            if self._segments:
                # the boundary lies inside a sealed segment: it stays
                # until a later snapshot covers it whole (no partial
                # rewrites of immutable files)
                self._export_segment_gauge()
                return freed
            # 2) the remaining covered prefix lies inside the active file
            if upto <= 0:
                self._export_segment_gauge()
                return freed
            if self.segment_bytes:
                if upto >= self._active_size:
                    # whole log covered: empty the active file in place
                    os.ftruncate(self._fd, 0)
                    freed += self._active_size
                    self.size -= self._active_size
                    self._active_size = 0
                    self._active_first_seq = self.seq + 1
                    self._pending = 0
                    self._rotate_due = False
                # else: wait for the seal — never copy under the fd lock
                self._export_segment_gauge()
                return freed
            # single-file mode: the historical copy-compaction
            with open(self.path, "rb") as f:
                f.seek(upto)
                tail = f.read()
            d = os.path.dirname(os.path.abspath(self.path)) or "."
            prefix = "." + os.path.basename(self.path) + ".compact."
            fd, tmp = tempfile.mkstemp(prefix=prefix, dir=d)  # 0600
            try:
                if tail:
                    os.write(fd, tail)
                os.fsync(fd)
                os.close(fd)
                if self._crash("pre_rename"):
                    raise CrashPoint("pre_rename during WAL compaction")
                os.replace(tmp, self.path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            os.close(self._fd)
            self._fd = os.open(
                self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o600
            )
            freed += self._active_size - len(tail)
            self.size -= self._active_size - len(tail)
            self._active_size = len(tail)
            self._pending = 0  # the tmp copy was fsynced before the rename
            return freed

    def truncate_to(self, valid_bytes: int) -> int:
        """Drop everything past the *logical* offset ``valid_bytes`` (the
        torn tail a standby found at promotion time); returns bytes
        dropped.  Callers pass the valid-prefix boundary ``iter_frames``
        reported over :meth:`read_from` output.  Sealed segments are
        fsynced before their rename, so the boundary normally lands in
        the active file; a boundary inside a sealed segment (disk
        corruption) truncates that segment in place, renames it to its
        corrected seq range, and drops everything after it."""
        with self._lock:
            if self._fd is None:
                raise OSError("write-ahead log is closed")
            valid = max(0, min(valid_bytes, self.size))
            dropped = self.size - valid
            if not dropped:
                return 0
            active_start = self.size - self._active_size
            if valid >= active_start:
                # the normal case: the torn tail is in the active file
                keep = valid - active_start
                fd = os.open(self.path, os.O_WRONLY)
                try:
                    os.ftruncate(fd, keep)
                    os.fsync(fd)
                finally:
                    os.close(fd)
                self._active_size = keep
                self.size = valid
                return dropped
            # corruption inside a sealed segment: drop the active file
            # and every later segment, cut the straddled one in place
            fd = os.open(self.path, os.O_WRONLY)
            try:
                os.ftruncate(fd, 0)
                os.fsync(fd)
            finally:
                os.close(fd)
            self._active_size = 0
            pos = 0
            keep_segments: list[tuple[str, int]] = []
            for seg, nbytes in self._segments:
                end = pos + nbytes
                if end <= valid:
                    keep_segments.append((seg, nbytes))
                elif pos < valid:
                    # straddled: truncate, rescan, rename to the real range
                    cut = valid - pos
                    sfd = os.open(seg, os.O_WRONLY)
                    try:
                        os.ftruncate(sfd, cut)
                        os.fsync(sfd)
                    finally:
                        os.close(sfd)
                    records, _, _ = read_frames(seg)
                    if records:
                        fixed = wal_segment_name(
                            self.path, int(records[0]["seq"]),
                            int(records[-1]["seq"]),
                        )
                        os.replace(seg, fixed)
                        keep_segments.append((fixed, cut))
                    else:
                        os.unlink(seg)
                else:
                    try:
                        os.unlink(seg)
                    except FileNotFoundError:  # pragma: no cover
                        pass
                pos = end
            self._segments = keep_segments
            self.size = self._sealed_bytes()
            self._active_first_seq = self.seq + 1
            self._export_segment_gauge()
            return dropped

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Force-sync pending appends and release the fd (idempotent)."""
        self.sync(force=True)
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None
