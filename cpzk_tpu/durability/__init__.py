"""Crash-consistent durability: write-ahead log + bounded-loss recovery.

Upgrades the opt-in snapshot persistence (``--state-file``) to the
contract every production serving stack provides — an acknowledged write
survives a crash, recovery is automatic and bounded, and the failure
modes are exercised by deterministic tests:

- :mod:`.wal` — :class:`WriteAheadLog`: length- and CRC32-framed JSON
  records (``register_user`` / ``create_session`` / ``revoke_session`` /
  ``expire_sessions``) with a configurable fsync policy and atomic-rename
  compaction, plus the deterministic crash points the fault harness
  schedules;
- :mod:`.recovery` — boot: snapshot load with corrupt-file quarantine,
  torn-tail truncation, and WAL-suffix replay through the same
  trust-boundary validators live RPCs pass;
- :mod:`.manager` — :class:`DurabilityManager`: the lifecycle object the
  daemon drives (recover → checkpoint-per-sweep → close-on-shutdown).

Configuration lives in the ``[durability]`` section of the server config
(``SERVER_DURABILITY_*`` env); the operator story is documented in
``docs/operations.md`` §"Durability & recovery".
"""

from __future__ import annotations

from .manager import DurabilityManager
from .recovery import RecoveryReport, quarantine_file, recover_state
from .wal import (
    MAX_FRAME_PAYLOAD,
    WAL_CRASH_POINTS,
    WAL_FORMAT_VERSION,
    CrashPoint,
    NewerFormatError,
    WriteAheadLog,
    check_record_format,
    encode_record,
    iter_frames,
    read_frames,
)

__all__ = [
    "CrashPoint",
    "DurabilityManager",
    "MAX_FRAME_PAYLOAD",
    "NewerFormatError",
    "RecoveryReport",
    "WAL_CRASH_POINTS",
    "WAL_FORMAT_VERSION",
    "WriteAheadLog",
    "check_record_format",
    "encode_record",
    "iter_frames",
    "quarantine_file",
    "read_frames",
    "recover_state",
]
