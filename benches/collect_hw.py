"""Collect hardware-sweep outputs (.hw/*.json) into a markdown table.

The sweep (.hardware_sweep.sh pattern: poll the accelerator tunnel,
run bench_kernels/bench.py tiers once it answers) drops one JSON-lines
file per tier; this prints a PROFILE.md-ready table plus the raw lines,
so a healed tunnel turns into a committed measurement section in one
step.  Usage: python benches/collect_hw.py [dir]   (default .hw)
"""

from __future__ import annotations

import json
import os
import sys


def main() -> None:
    d = sys.argv[1] if len(sys.argv) > 1 else ".hw"
    if not os.path.isdir(d):
        raise SystemExit(f"no sweep directory {d!r}")
    rows = []
    for name in sorted(os.listdir(d)):
        if not name.endswith(".json"):
            continue
        with open(os.path.join(d, name), encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                rows.append((name, rec))
    if not rows:
        print("(no sweep records yet)")
        return
    print("| source | metric | value | unit | extra |")
    print("|---|---|---|---|---|")
    for name, rec in rows:
        metric = rec.get("name") or rec.get("metric", "?")
        extra = {
            k: v
            for k, v in rec.items()
            if k not in ("name", "metric", "value", "unit")
        }
        print(
            f"| {name} | {metric} | {rec.get('value')} | "
            f"{rec.get('unit', '')} | {extra if extra else ''} |"
        )


if __name__ == "__main__":
    main()
