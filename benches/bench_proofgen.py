"""TPU batch proof-generation throughput (BASELINE config 3).

Times BatchProver.prove end-to-end (device comb kernels + host nonces,
challenge derivation, response closing) and the device commitment kernel
alone.  Prints JSON lines.

Usage: python benches/bench_proofgen.py [--n 4096] [--platform cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--runs", type=int, default=3)
    args = ap.parse_args()
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from cpzk_tpu import Parameters, SecureRng
    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.ops.prove import BatchProver

    rng = SecureRng()
    bp = BatchProver(Parameters.new())
    witnesses = [Ristretto255.random_scalar(rng) for _ in range(args.n)]
    statements = bp.statements(witnesses)  # warms the jit cache too

    # device commitment kernel only
    ks = [Ristretto255.random_scalar(rng).value for _ in range(args.n)]
    bp._fixed_base_bytes(ks)  # warm
    best = float("inf")
    for _ in range(args.runs):
        t0 = time.perf_counter()
        bp._fixed_base_bytes(ks)
        best = min(best, time.perf_counter() - t0)
    print(json.dumps({
        "name": "commitments_device", "n": args.n,
        "value": round(args.n / best, 1), "unit": "proofs/s",
    }))

    # end to end (statements precomputed, as in a serving deployment)
    best = float("inf")
    for _ in range(args.runs):
        t0 = time.perf_counter()
        bp.prove(witnesses, None, rng, statements=statements)
        best = min(best, time.perf_counter() - t0)
    print(json.dumps({
        "name": "batch_prove_e2e", "n": args.n,
        "value": round(args.n / best, 1), "unit": "proofs/s",
    }))


if __name__ == "__main__":
    main()
