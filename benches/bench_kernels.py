"""Device-kernel A/B harness (VERDICT r2 items 2-4).

Measures, on whatever backend is live (TPU under axon; CPU with
--platform cpu), one JSON line per configuration:

- field-mul throughput for each CPZK_MUL variant (schoolbook VPU
  outer-product vs matmul-fold MXU experiment; a Karatsuba level was
  evaluated and removed — int32 headroom, see PROFILE.md §2);
- point add/double throughput (XLA path vs Pallas kernels when enabled);
- Fiat-Shamir challenge derivation (threaded native C++ vs the device
  Keccak pipeline);
- the two batch-verify kernels (rowcombined / pippenger) at small N.

Each config runs in-process; variants toggle module globals, re-tracing
fresh jit graphs.  Timings are best-of-ITERS wall clock around
block_until_ready.

Usage: python benches/bench_kernels.py [--platform cpu] [--n 65536]
       [--iters 5] [--only mul|point|challenge|verify]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def best_of(fn, iters: int) -> float:
    import jax

    jax.block_until_ready(fn())  # compile + warm
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def emit(name: str, value: float, unit: str, **extra) -> None:
    print(json.dumps({"name": name, "value": round(value, 1), "unit": unit, **extra}), flush=True)


def bench_mul(n: int, iters: int) -> None:
    import secrets

    import jax

    from cpzk_tpu.ops import limbs

    xs = [secrets.randbelow(limbs.P) for _ in range(256)]
    ys = [secrets.randbelow(limbs.P) for _ in range(256)]
    import numpy as np

    reps = (n + 255) // 256
    a = jax.device_put(np.tile(limbs.ints_to_limbs(xs), (1, reps))[:, :n])
    b = jax.device_put(np.tile(limbs.ints_to_limbs(ys), (1, reps))[:, :n])

    for variant in ("schoolbook", "matmulfold"):
        old = limbs.MUL_VARIANT
        limbs.MUL_VARIANT = variant
        try:
            # chain 8 dependent muls so timing isn't dispatch-bound
            def chain(a, b):
                x = limbs.mul(a, b)
                for _ in range(7):
                    x = limbs.mul(x, b)
                return x

            fn = jax.jit(chain)
            dt = best_of(lambda: fn(a, b), iters)
            emit(f"field_mul_{variant}", 8 * n / dt / 1e6, "Mmul/s", n=n)
        except Exception as e:  # a variant failing to lower must not kill the run
            emit(f"field_mul_{variant}", 0.0, "Mmul/s", n=n, error=str(e)[:200])
        finally:
            limbs.MUL_VARIANT = old


def _random_points(n: int):
    import numpy as np

    from cpzk_tpu.core import edwards
    from cpzk_tpu.ops import curve

    base = [edwards.pt_scalar_mul(edwards.BASEPOINT, i + 2) for i in range(64)]
    reps = (n + 63) // 64
    cols = curve.points_to_device(base)
    import jax

    return tuple(jax.device_put(np.tile(np.asarray(c), (1, reps))[:, :n]) for c in cols)


def bench_point(n: int, iters: int) -> None:
    import jax

    from cpzk_tpu.ops import curve

    P = _random_points(n)

    def chain_add(p):
        x = curve.add(p, p)
        for _ in range(7):
            x = curve.add(x, p)
        return x

    def chain_dbl(p):
        x = curve.double(p)
        for _ in range(7):
            x = curve.double(x)
        return x

    from cpzk_tpu.ops import pallas_kernels

    for name, f in (("point_add", chain_add), ("point_double", chain_dbl)):
        try:
            fn = jax.jit(f)
            dt = best_of(lambda: fn(P), iters)
            emit(name, 8 * n / dt / 1e6, "Mop/s", n=n,
                 pallas=pallas_kernels.enabled())
        except Exception as e:  # a config failing to lower must not kill the run
            emit(name, 0.0, "Mop/s", n=n, pallas=pallas_kernels.enabled(),
                 error=str(e)[:200])


def bench_challenge(n: int, iters: int) -> None:
    """Fiat-Shamir challenge derivation: threaded native C++ (merlin.cpp)
    vs the device Keccak pipeline (ops/challenge.py) at n rows."""
    import os as _os

    import numpy as np

    from cpzk_tpu.core import _native

    cols = [
        np.frombuffer(_os.urandom(32 * n), dtype=np.uint8).reshape(n, 32).copy()
        for _ in range(7)
    ]
    blobs = [c.tobytes() for c in cols]

    if _native.load() is not None:
        def native_once():
            return _native.challenge_batch([None] * n, *blobs[1:])

        native_once()
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            native_once()
            best = min(best, time.perf_counter() - t0)
        emit("challenge_native_cpp", n / best / 1e3, "kchal/s", n=n)

    try:
        # inside the guard: this import pulls jax, and a jax-less host must
        # still emit the native number above
        from cpzk_tpu.ops.challenge import derive_challenges_device

        def device_once():
            out = derive_challenges_device(None, *cols[1:])
            return out

        device_once()  # compile + warm; output is host numpy (blocking)
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            device_once()
            best = min(best, time.perf_counter() - t0)
        emit("challenge_device", n / best / 1e3, "kchal/s", n=n)

        # fused variant: challenge bytes reduced to scalar limbs ON device
        # (what an all-device challenges->RLC pipeline consumes directly)
        import jax

        from cpzk_tpu.ops import sclimbs

        reduce_fn = jax.jit(sclimbs.reduce_wide)

        def fused_once():
            chal = derive_challenges_device(None, *cols[1:])
            return jax.block_until_ready(
                reduce_fn(sclimbs.bytes_wide_to_limbs(chal))
            )

        fused_once()
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            fused_once()
            best = min(best, time.perf_counter() - t0)
        emit("challenge_device_reduced", n / best / 1e3, "kchal/s", n=n)
    except Exception as e:
        emit("challenge_device", 0.0, "kchal/s", n=n, error=str(e)[:200])


def bench_verify(n: int, iters: int) -> None:
    """rowcombined + pippenger end-to-end device timings at modest N —
    the same kernels bench.py guards, but runnable inline for tuning."""
    os.environ.setdefault("CPZK_BENCH_ITERS", str(iters))
    os.environ["CPZK_BENCH_N"] = str(n)
    import importlib

    import bench as bench_mod

    importlib.reload(bench_mod)
    inp = bench_mod._Inputs()
    for kernel, fn in (
        ("rowcombined", bench_mod.bench_rowcombined),
        ("pippenger", bench_mod.bench_pippenger),
    ):
        try:
            rate = fn(inp)
            emit(f"verify_{kernel}", rate, "proofs/s", n=n,
                 vs_baseline=round(rate / bench_mod.BASELINE, 3))
        except Exception as e:
            emit(f"verify_{kernel}", 0.0, "proofs/s", n=n, error=str(e)[:200])


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--n", type=int, default=65536)
    ap.add_argument("--verify-n", type=int, default=4096)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--only", default=None,
                    choices=(None, "mul", "point", "verify", "challenge"))
    args = ap.parse_args()

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    import jax

    emit("backend", len(jax.devices()), "devices",
         kind=jax.devices()[0].platform)

    if args.only in (None, "mul"):
        bench_mul(args.n, args.iters)
    if args.only in (None, "point"):
        bench_point(args.n, args.iters)
    if args.only in (None, "challenge"):
        bench_challenge(args.n, args.iters)
    if args.only in (None, "verify"):
        bench_verify(args.verify_n, args.iters)


if __name__ == "__main__":
    main()
