"""Batch-verification benchmarks: the END-TO-END ``BatchVerifier`` path.

Mirror of the reference's criterion suite ``benches/batch_verification.rs``
(batch-vs-individual at n in {1,2,5,10,20,50,100} — ``:9-67``; with
transcript contexts — ``:69-113``; mixed validity — ``:115-150``; add()
cost — ``:152-172``), measured here end to end: challenge re-derivation,
random alpha draws, limb marshalling, and the backend pass are ALL inside
the timed region — this is the number a serving operator sees per batch,
complementing the device-kernel-only bench.py headline.

Backends: cpu (host oracle, default) and tpu (JAX data plane; pass --tpu,
add --platform cpu to force the JAX CPU backend for smoke runs).

Prints one JSON line per config: {"name", "n", "value", "unit"}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SIZES = (1, 2, 5, 10, 20, 50, 100)


def best_of(fn, runs: int = 3) -> tuple[float, float]:
    """(best seconds, spread) over ``runs`` calls; spread = max-min is the
    run's own noise bound, carried into PerfSnapshot entries so the
    regression gate widens itself on noisy machines."""
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return min(times), max(times) - min(times)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tpu", action="store_true", help="also bench the TPU backend")
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu) for --tpu smoke runs")
    ap.add_argument("--sizes", default=",".join(map(str, SIZES)))
    ap.add_argument("--runs", type=int, default=3,
                    help="timed repetitions per config (best-of)")
    ap.add_argument("--snapshot", default=None,
                    help="also write a cpzk-perf-snapshot JSON here (the "
                         "CI regression gate's input — see "
                         "cpzk_tpu.observability.regress)")
    args = ap.parse_args()
    sizes = [int(s) for s in args.sizes.split(",")]

    from cpzk_tpu import (
        BatchVerifier,
        Parameters,
        Prover,
        SecureRng,
        Transcript,
        Verifier,
        Witness,
    )
    from cpzk_tpu.core.ristretto import Ristretto255

    rng = SecureRng()
    params = Parameters.new()
    nmax = max(sizes)
    rows = []
    for i in range(nmax):
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        ctx = f"challenge-{i}".encode()
        t = Transcript()
        t.append_context(ctx)
        rows.append((prover.statement, prover.prove_with_transcript(rng, t), ctx))

    backends: list[tuple[str, object]] = [("cpu", None)]  # None -> CpuBackend default
    if args.tpu:
        if args.platform:
            import jax

            jax.config.update("jax_platforms", args.platform)
        from cpzk_tpu.ops.backend import TpuBackend

        backends.append(("tpu", TpuBackend()))

    results = []
    for n in sizes:
        # individual: n full verify_with_transcript passes
        def individual(n=n):
            for st, pr, ctx in rows[:n]:
                t = Transcript()
                t.append_context(ctx)
                Verifier(params, st).verify_with_transcript(pr, t)

        results.append(("individual", "host", n, *best_of(individual, args.runs)))

        for bname, backend in backends:
            def batched(n=n, backend=backend):
                bv = BatchVerifier(backend=backend)
                for st, pr, ctx in rows[:n]:
                    bv.add_with_context(params, st, pr, ctx)
                assert bv.verify(rng) == [None] * n

            if bname == "tpu":
                batched()  # warm the jit cache outside the timed region
            results.append(("batch_e2e", bname, n, *best_of(batched, args.runs)))

        # mixed validity: one mismatched row forces the fallback pass
        if n >= 2:
            def mixed(n=n):
                bv = BatchVerifier()
                for st, pr, ctx in rows[: n - 1]:
                    bv.add_with_context(params, st, pr, ctx)
                bv.add_with_context(params, rows[0][0], rows[1][1], rows[0][2])
                res = bv.verify(rng)
                assert res[-1] is not None

            results.append(
                ("batch_mixed_validity", "cpu", n, *best_of(mixed, args.runs))
            )

    # add() cost (validation on add), reference batch_verification.rs:152-172
    def add_cost():
        bv = BatchVerifier()
        for st, pr, ctx in rows[: min(100, nmax)]:
            bv.add_with_context(params, st, pr, ctx)

    results.append(
        ("batch_add", "host", min(100, nmax), *best_of(add_cost, args.runs))
    )

    for name, backend, n, secs, spread in results:
        print(
            json.dumps(
                {
                    "name": name,
                    "backend": backend,
                    "n": n,
                    "value": round(secs * 1e3, 3),
                    "unit": "ms/batch",
                    "spread_ms": round(spread * 1e3, 3),
                    "per_proof_us": round(secs / n * 1e6, 1),
                }
            )
        )

    if args.snapshot:
        from cpzk_tpu.observability.perf import PerfEntry, write_snapshot

        entries = [
            PerfEntry(
                name=name, backend=backend, n=n,
                value=round(secs * 1e3, 4), unit="ms/batch",
                spread=round(spread * 1e3, 4),
            )
            for name, backend, n, secs, spread in results
        ]
        write_snapshot(
            args.snapshot, entries,
            meta={"bench": "bench_batch", "runs": args.runs},
        )
        print(f"# perf snapshot written to {args.snapshot}", file=sys.stderr)


if __name__ == "__main__":
    main()
