"""Proof-lifecycle micro-benchmarks (host plane).

Mirror of the reference's criterion suite ``benches/proof_generation.rs``
(groups: generation, verification, serialization — ``proof_generation.rs:8-45``)
re-expressed for this framework's host path.  Prints one JSON line per
metric: {"name": ..., "value": ..., "unit": "us/op"}.

Usage: python benches/bench_proof.py [--iters N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, iters: int) -> float:
    """Best-of-runs microseconds per op."""
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e6


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    from cpzk_tpu import (
        Parameters,
        Proof,
        Prover,
        SecureRng,
        Transcript,
        Verifier,
        Witness,
    )
    from cpzk_tpu.core.ristretto import Ristretto255

    rng = SecureRng()
    params = Parameters.new()
    witness = Witness(Ristretto255.random_scalar(rng))
    prover = Prover(params, witness)
    proof = prover.prove_with_transcript(rng, Transcript())
    wire = proof.to_bytes()
    verifier = Verifier(params, prover.statement)

    out = []
    out.append(
        ("proof_generation", timeit(
            lambda: prover.prove_with_transcript(rng, Transcript()), args.iters))
    )
    out.append(
        ("proof_verification", timeit(
            lambda: verifier.verify_with_transcript(proof, Transcript()), args.iters))
    )
    out.append(("proof_serialization", timeit(lambda: proof.to_bytes(), args.iters)))
    out.append(
        ("proof_deserialization", timeit(lambda: Proof.from_bytes(wire), args.iters))
    )
    st = prover.statement
    out.append(
        ("statement_serialization", timeit(
            lambda: (
                Ristretto255.element_to_bytes(st.y1),
                Ristretto255.element_to_bytes(st.y2),
            ),
            args.iters,
        ))
    )

    for name, us in out:
        print(json.dumps({"name": name, "value": round(us, 1), "unit": "us/op"}))


if __name__ == "__main__":
    main()
