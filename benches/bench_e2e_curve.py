"""End-to-end serving throughput curve: gRPC -> batcher -> backend -> sessions.

VERDICT r3 item 3: the kernel benches time device compute alone; this
measures the FULL serving path at realistic batch totals — register,
challenge issuance, proof generation (all untimed setup), then timed
`VerifyProofBatch` RPCs (wire parse, challenge consumption, backend
verification, per-item session issuance), against the reference analog
`src/verifier/service.rs:407-617`.

Prints one JSON line per curve point:
    {"metric": "e2e_curve", "n": N, "grpc_pps": ...,
     "grpc_pipelined_pps": ..., "stream_pps": ..., "direct_pps": ...,
     "platform": ..., "backend": ..., "unit": "proofs/s"}

- grpc_pps  — proofs/s through the real asyncio gRPC loopback service
              (batched RPCs of <=1000 items, reference cap parity),
              one RPC in flight at a time.  BOTH backends route through
              the batcher -> dispatch-lane seam (the production serving
              architecture), so the snapshot carries flight-recorder
              stage percentiles on the CPU path too.
- grpc_pipelined_pps — same, but a wave's RPCs issued concurrently: the
              server verifies on a worker thread (GIL released), so one
              RPC's Python overlaps another's crypto — the many-client
              deployment shape.
- stream_pps — proofs/s through ONE VerifyProofStream bidi stream
              (verdict-only, no session issuance): entries feed the
              batcher continuously with no per-RPC boundary or 1000-item
              cap — the workload the streaming API exists for.  The
              acceptance bar is >= 0.95x direct_pps at n=64k.
- direct_pps — proofs/s through BatchVerifier.verify alone on the same
              backend (no RPC/session overhead); the serial gap is the
              serving layer's cost.

Backends: --backend cpu (native host core; the production CPU serving
config) or tpu (device data plane; meaningful on real TPU — on the XLA
CPU backend it is a correctness emulation ~1000x slower than silicon).
Env: CPZK_E2E_NS (comma list), CPZK_BENCH_PLATFORM (jax platform pin).

Usage: python benches/bench_e2e_curve.py [--ns 256,4096] [--backend cpu|tpu]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

USERS = 512            # corpus users registered once
CHALLENGES_PER_WAVE = 3  # per-user outstanding-challenge cap (state parity)
RPC_CAP = 1000         # MAX_BATCH parity (service.rs:428-432)
PIPELINE_WAYS = 4      # concurrent RPCs per wave in the pipelined pass


def build_corpus():
    from cpzk_tpu import Parameters, Prover, SecureRng, Witness
    from cpzk_tpu.core.ristretto import Ristretto255

    rng = SecureRng()
    params = Parameters.new()
    provers = [
        Prover(params, Witness(Ristretto255.random_scalar(rng)))
        for _ in range(USERS)
    ]
    return rng, params, provers


STREAM_CHUNK = 1024    # entries packed per stream message


def build_serving_plane(backend_name: str, lanes: int, quantum: int):
    """(backend, router, resolved_lanes): the serving compute plane of
    one curve point.  ``lanes != 1`` builds the multi-chip LaneRouter —
    per-device ``TpuBackend`` lanes on the tpu backend (emulate chips on
    CPU with ``XLA_FLAGS=--xla_force_host_platform_device_count=8``),
    per-host-core ``CpuBackend`` lanes on the cpu backend (the router
    machinery at native verify speeds — what the perf gate's lanes leg
    measures)."""
    if backend_name == "tpu":
        from cpzk_tpu.ops.backend import TpuBackend, prewarm_executables

        if lanes != 1:
            from cpzk_tpu.parallel import resolve_lane_devices
            from cpzk_tpu.server.router import LaneRouter

            devices = resolve_lane_devices(lanes)
            if devices is not None:
                # per-device AOT prewarm: every lane's first timed batch
                # books jit HITs, like a production [tpu] prewarm_quanta
                prewarm_executables([quantum], devices=devices)
                backends = [TpuBackend(device=d) for d in devices]
                router = LaneRouter(backends, devices=devices)
                return backends[0], router, len(devices)
        prewarm_executables([quantum])
        return TpuBackend(), None, 1
    from cpzk_tpu.protocol.batch import CpuBackend

    if lanes != 1:
        from cpzk_tpu.server.router import LaneRouter

        k = lanes if lanes > 0 else (os.cpu_count() or 1)
        if k > 1:
            return (
                CpuBackend(),
                LaneRouter([CpuBackend() for _ in range(k)]),
                k,
            )
    return CpuBackend(), None, 1


async def grpc_curve_point(
    n: int, provers, rng, backend_name: str, lanes: int = 1,
    wire: str = "native",
) -> tuple[float, float, float]:
    """(serial_pps, pipelined_pps, stream_pps): wall time of the timed
    verify RPCs for n proofs with one RPC in flight, then with each
    wave's RPCs issued concurrently (~PIPELINE_WAYS at a time), then
    pushed through one VerifyProofStream per wave (verdict-only)."""
    import grpc  # noqa: F401  (import check before server spin-up)

    from cpzk_tpu import Transcript
    from cpzk_tpu.client import AuthClient
    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.server import RateLimiter, ServerState
    from cpzk_tpu.server.service import serve

    from cpzk_tpu.server.batching import DynamicBatcher

    backend, router, _ = build_serving_plane(
        backend_name, lanes, min(n, RPC_CAP)
    )
    # BOTH backends serve through the batcher -> dispatch-lane seam (the
    # production serving architecture since the dedicated-lane PR); the
    # flight recorder therefore has stage percentiles for the snapshot
    # on the CPU path too, not only on device runs.  With lanes != 1 the
    # batcher places every settled batch through the LaneRouter instead.
    batcher = DynamicBatcher(backend, max_batch=RPC_CAP, window_ms=5.0,
                             pipeline_depth=2,  # serve() starts it
                             router=router)

    state = ServerState()
    # CPZK_BENCH_FLEET=1: enable fleet routing with a single-partition
    # map — the perf gate's proof that the N=1 ownership fast path taxes
    # the serving hot path by nothing measurable (the address is a
    # placeholder: a one-partition router never redirects)
    fleet = None
    if os.environ.get("CPZK_BENCH_FLEET"):
        from cpzk_tpu.fleet import FleetRouter, PartitionMap

        fleet = FleetRouter(PartitionMap.uniform(["127.0.0.1:0"]), 0)
    server, port = await serve(
        state, RateLimiter(10**9, 10**9), host="127.0.0.1", port=0,
        backend=backend, batcher=batcher, fleet=fleet, wire=wire,
    )
    # CPZK_BENCH_OPSPLANE=1: run the full HTTP introspection server +
    # SLO engine alongside the timed passes — the perf gate's proof that
    # the ops plane costs nothing measurable on the serving path
    ops_plane = None
    if os.environ.get("CPZK_BENCH_OPSPLANE"):
        from cpzk_tpu.observability.opsplane import OpsPlane, OpsSources
        from cpzk_tpu.observability.slo import SloEngine
        from cpzk_tpu.server.config import SloSettings

        ops_plane = OpsPlane(OpsSources(
            state=state, batcher=batcher, backend=backend,
            health=server.health, service=server.auth_service,
            slo=SloEngine(SloSettings()),
        ), port=0)
        await ops_plane.start()
    eb = Ristretto255.element_to_bytes
    timed = 0.0
    done = 0
    try:
        async with AuthClient(f"127.0.0.1:{port}") as client:
            resp = await client.register_batch(
                [f"u{i}" for i in range(len(provers))],
                [eb(pr.statement.y1) for pr in provers],
                [eb(pr.statement.y2) for pr in provers],
            )
            assert all(r.success for r in resp.results)
            async def make_wave(wave):
                ids, cids, proofs = [], [], []
                for k in range(wave):
                    u = k % USERS
                    ch = await client.create_challenge(f"u{u}")
                    cid = bytes(ch.challenge_id)
                    t = Transcript()
                    t.append_context(cid)
                    proof = provers[u].prove_with_transcript(rng, t)
                    ids.append(f"u{u}")
                    cids.append(cid)
                    proofs.append(proof.to_bytes())
                return ids, cids, proofs

            # untimed warmup RPC at the dominant batch shape (tpu backends
            # JIT-compile per padded shape; compile must not be timed)
            w0 = min(n, RPC_CAP)
            ids, cids, proofs = await make_wave(w0)
            resp = await client.verify_proof_batch(ids, cids, proofs)
            assert all(r.success for r in resp.results)
            for s in list(state._sessions):
                await state.revoke_session(s)

            while done < n:
                wave = min(n - done, USERS * CHALLENGES_PER_WAVE)
                ids, cids, proofs = await make_wave(wave)
                for lo in range(0, wave, RPC_CAP):
                    hi = min(lo + RPC_CAP, wave)
                    t0 = time.perf_counter()
                    resp = await client.verify_proof_batch(
                        ids[lo:hi], cids[lo:hi], proofs[lo:hi])
                    timed += time.perf_counter() - t0
                    assert all(r.success for r in resp.results), "verify failed"
                done += wave
                # free session capacity for the next wave (untimed): the
                # per-user session cap is 5, and each success mints one
                for s in list(state._sessions):
                    await state.revoke_session(s)

            # pipelined pass: each wave's RPCs in flight CONCURRENTLY, in
            # ~PIPELINE_WAYS chunks regardless of wave size (a single
            # RPC_CAP chunk would degenerate to the serial path).  The
            # server runs the crypto on a worker thread (GIL released), so
            # RPC k+1's Python overlaps RPC k's verify — the deployment
            # shape with many clients, and the fairer analog of the
            # reference's per-request tokio tasks (service.rs:321-405).
            done = 0
            timed_p = 0.0
            while done < n:
                wave = min(n - done, USERS * CHALLENGES_PER_WAVE)
                ids, cids, proofs = await make_wave(wave)
                step = min(RPC_CAP, max(1, -(-wave // PIPELINE_WAYS)))
                chunks = [(lo, min(lo + step, wave))
                          for lo in range(0, wave, step)]
                t0 = time.perf_counter()
                resps = await asyncio.gather(*[
                    client.verify_proof_batch(
                        ids[lo:hi], cids[lo:hi], proofs[lo:hi])
                    for lo, hi in chunks
                ])
                timed_p += time.perf_counter() - t0
                for resp in resps:
                    assert all(r.success for r in resp.results), "verify failed"
                done += wave
                for s in list(state._sessions):
                    await state.revoke_session(s)

            # streaming pass: every wave's proofs ride ONE bidi stream
            # (verdict-only — mint_sessions off, the bulk-verification
            # shape).  Entries flow into the batcher with no RPC
            # boundary, so the device sees the same deep batches the
            # direct path builds by hand.
            done = 0
            timed_s = 0.0
            while done < n:
                wave = min(n - done, USERS * CHALLENGES_PER_WAVE)
                ids, cids, proofs = await make_wave(wave)
                entries = list(zip(ids, cids, proofs))
                t0 = time.perf_counter()
                n_ok = 0
                # the chunk-level iterator is the bulk-driver surface:
                # per-verdict Python objects are pure client overhead at
                # device-batch rates
                async for chunk_v in client.verify_proof_stream_chunks(
                    entries, chunk=STREAM_CHUNK
                ):
                    n_ok += sum(chunk_v[1])
                timed_s += time.perf_counter() - t0
                assert n_ok == wave, f"stream verify failed: {n_ok}/{wave}"
                done += wave
    finally:
        if ops_plane is not None:
            await ops_plane.stop()
        if batcher is not None:
            await batcher.stop()
        await server.stop(None)
    return n / timed, n / timed_p, n / timed_s


def direct_curve_point(n: int, provers, rng, params, backend_name: str) -> float:
    """BatchVerifier.verify alone (reference batch.rs:171-183 analog)."""
    from cpzk_tpu import BatchVerifier, Transcript
    from cpzk_tpu.protocol.batch import BatchEntry

    if backend_name == "tpu":
        from cpzk_tpu.ops.backend import TpuBackend

        backend = TpuBackend()
    else:
        from cpzk_tpu.protocol.batch import CpuBackend

        backend = CpuBackend()

    proofs = [
        (pr.statement, pr.prove_with_transcript(rng, Transcript()))
        for pr in provers[:64]
    ]
    bv = BatchVerifier(backend=backend, max_size=max(n, 1000))
    for i in range(n):
        st, prf = proofs[i % 64]
        bv.entries.append(BatchEntry(params, st, prf, None))
    assert not any(r is not None for r in bv.verify(rng))  # untimed warmup:
    # on the tpu backend the first call at a new padded shape JIT-compiles;
    # the timed pass below measures throughput, not compilation
    t0 = time.perf_counter()
    results = bv.verify(rng)  # per-proof error-or-None; None == accepted
    dt = time.perf_counter() - t0
    assert not any(r is not None for r in results)
    return n / dt


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ns", default=os.environ.get("CPZK_E2E_NS", ""))
    ap.add_argument("--backend", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--lanes", type=int, default=1,
                    help="serve through N per-device dispatch lanes "
                         "behind the LaneRouter (-1 = one per local "
                         "device / host core; emulate devices on CPU "
                         "with XLA_FLAGS=--xla_force_host_platform_"
                         "device_count=8).  Entries carry the lane "
                         "count as a perf-gate config key, so a new "
                         "lane count seeds its own trajectory")
    ap.add_argument("--wire", default="native",
                    choices=["native", "python"],
                    help="transport wire path for the serving passes: "
                         "native = the C++ request parser straight off "
                         "the socket bytes (with protobuf fallback), "
                         "python = the protobuf runtime only (the "
                         "historical baseline).  Serving entries carry "
                         "the mode as a perf-gate config key (old "
                         "baselines load as wire=python; a new mode "
                         "seeds its own trajectory); the direct entries "
                         "never touch a wire and keep the python key")
    ap.add_argument("--snapshot", default=None,
                    help="also write a cpzk-perf-snapshot JSON here "
                         "(throughput per n + flight-recorder stage "
                         "percentiles when the batcher path ran)")
    args = ap.parse_args()

    plat = os.environ.get("CPZK_BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    if args.backend == "tpu":
        # share bench.py's persistent compile cache: the first serving
        # batch's device program must not re-pay a tunnel-window compile
        # the kernel sweep already performed
        try:
            import jax

            jax.config.update(
                "jax_compilation_cache_dir",
                os.path.join(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))), ".jax_bench_cache"))
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
        except Exception:
            pass  # older jax without the knob: cache is best-effort

    if args.ns:
        ns = [int(x) for x in args.ns.split(",")]
    else:
        # full curve by default; CPU runs should pass --ns to stay small
        ns = [256, 4096, 16384, 65536]

    import jax

    platform = jax.devices()[0].platform if args.backend == "tpu" else "host"

    rng, params, provers = build_corpus()
    snapshot_entries = []
    for n in ns:
        from cpzk_tpu.observability import get_flight_recorder
        from cpzk_tpu.observability.perf import PerfEntry, stage_percentiles

        recorder = get_flight_recorder()
        recorder.clear()  # stage percentiles attribute to this n only
        direct = direct_curve_point(n, provers, rng, params, args.backend)
        grpc_pps, grpc_pipelined, stream_pps = asyncio.run(
            grpc_curve_point(n, provers, rng, args.backend,
                             lanes=args.lanes, wire=args.wire))
        resolved_lanes = args.lanes
        if args.lanes == -1:
            # report the resolved count, not the sentinel
            if args.backend == "tpu":
                resolved_lanes = jax.local_device_count()
            else:
                resolved_lanes = os.cpu_count() or 1
        print(json.dumps({
            "metric": "e2e_curve",
            "n": n,
            "lanes": resolved_lanes,
            "wire": args.wire,
            "grpc_pps": round(grpc_pps, 1),
            "grpc_pipelined_pps": round(grpc_pipelined, 1),
            "stream_pps": round(stream_pps, 1),
            "stream_vs_direct": round(stream_pps / direct, 3),
            "direct_pps": round(direct, 1),
            "platform": platform,
            "backend": args.backend,
            "unit": "proofs/s",
        }), flush=True)
        stages = stage_percentiles(recorder.snapshot())
        for name, pps in (
            ("e2e_curve.grpc", grpc_pps),
            ("e2e_curve.grpc_pipelined", grpc_pipelined),
            ("e2e_curve.stream", stream_pps),
            ("e2e_curve.direct", direct),
        ):
            snapshot_entries.append(PerfEntry(
                name=name, backend=args.backend, n=n,
                value=round(pps, 2), unit="proofs/s",
                lanes=resolved_lanes,
                # direct never touches a wire: it keeps the python key
                # so it gates against the historical baseline on every
                # run regardless of --wire
                wire=args.wire if name != "e2e_curve.direct" else "python",
                stages_ms=stages if name.startswith("e2e_curve.grpc") else {},
            ))

    if args.snapshot:
        from cpzk_tpu.observability.perf import write_snapshot

        write_snapshot(
            args.snapshot, snapshot_entries,
            meta={"bench": "bench_e2e_curve", "platform": platform,
                  "dispatch": "lane"},
        )
        print(f"# perf snapshot written to {args.snapshot}", file=sys.stderr)


if __name__ == "__main__":
    main()
