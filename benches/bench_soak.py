"""Million-user state-plane soak: registration, mixed traffic, failover.

ROADMAP item 6 / ISSUE 14: nothing before this harness ever held 1M+
registered users, so snapshot pause, WAL compaction behavior, expiry
sweep cost, and steady-state RSS all had unmeasured constants.  This
driver registers ``--users`` users against a REAL daemon subprocess
(``python -m cpzk_tpu.server``) configured the million-user way
(raised capacity caps, durability + segmented WAL, ops plane), then
drives mixed login / verify-batch / stream traffic at a target QPS and
records into a ``BENCH_SOAK.json`` the perf-regression gate
(``python -m cpzk_tpu.observability.regress``) understands:

- per-RPC p50/p99 client latency (``ms``, lower is better) for the
  challenge+login pair, the batched verify, and the stream chunk;
- the daemon's longest synchronous snapshot cut
  (``state.snapshot.max_pause_ms`` — the streaming-snapshot acceptance
  number) and longest sweep (``state.sweep.max_ms``), scraped from the
  ops plane;
- steady-state RSS of the daemon (``bytes``) sampled from
  ``/proc/<pid>/status``;
- sealed WAL segment count at the end of the run;
- optionally (``--failover``) a replicated-pair leg: the primary is
  SIGKILLed mid-soak and the time until the auto-promoted standby
  serves a full login is recorded (``ms``).

Scaled-down smoke: ``--users 50000 --qps 300 --duration 20`` finishes
in about a minute on one core and is what CI's ``soak-smoke`` job gates
against the committed ``BENCH_SOAK_BASELINE.json``; the committed
``BENCH_SOAK.json`` is a full 1M-user CPU run.

``--storm {herd,brownout,split,crashloop,rolling,all}`` (ISSUE 16/18)
switches the driver into the failure-storm scenario suite:
thundering-herd reconnect after a primary SIGKILL, slow-chip lane
brownout under the live fleet controller, a controller-triggered
partition split at full write load, an ingest-shard crash-loop, and the
upgrade storm — a SIGTERM-driven rolling restart of a 2-partition
replicated fleet whose coordinated handovers must keep measured
write-unavailability strictly below the ``lease_ms`` blackout — each
asserting zero acked-write loss and bounded login burn, with no human
action anywhere.

Usage::

    python benches/bench_soak.py --users 1000000 --qps 1000 \
        --duration 60 --snapshot BENCH_SOAK.json
    python benches/bench_soak.py --storm all --storm-users 2000
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

POOL = 256           # distinct keypairs; users share statements round-robin
REG_BATCH = 1000     # register_batch chunk (MAX_BATCH service parity)
BATCH_N = 32         # proofs per verify-batch op
STREAM_N = 128       # proofs per stream-chunk op
CONCURRENCY = 16     # in-flight soak ops cap


def build_corpus():
    from cpzk_tpu import Parameters, Prover, SecureRng, Witness
    from cpzk_tpu.core.ristretto import Ristretto255

    rng = SecureRng()
    params = Parameters.new()
    provers = [
        Prover(params, Witness(Ristretto255.random_scalar(rng)))
        for _ in range(POOL)
    ]
    eb = Ristretto255.element_to_bytes
    y1s = [eb(p.statement.y1) for p in provers]
    y2s = [eb(p.statement.y2) for p in provers]
    return rng, provers, y1s, y2s


# -- daemon management --------------------------------------------------------


def daemon_env(
    state_dir: str,
    users: int,
    ops_port: int,
    role: str | None = None,
    peer: str | None = None,
    wal_segment_bytes: int = 4 * 1024 * 1024,
) -> dict:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SERVER_CONFIG_PATH": os.path.join(state_dir, "nonexistent.toml"),
        "SERVER_STATE_FILE": os.path.join(state_dir, "state.json"),
        # million-user shape: caps sized to the corpus, durability with a
        # segment-rotated WAL so compaction never copies the tail
        "SERVER_MAX_USERS": str(max(users * 2, 10_000)),
        "SERVER_MAX_SESSIONS": str(max(users * 2, 100_000)),
        "SERVER_MAX_CHALLENGES": str(max(users, 50_000)),
        "SERVER_DURABILITY_ENABLED": "1",
        "SERVER_DURABILITY_FSYNC": "interval",
        "SERVER_DURABILITY_FSYNC_INTERVAL_MS": "100",
        "SERVER_DURABILITY_WAL_SEGMENT_BYTES": str(wal_segment_bytes),
        "SERVER_DURABILITY_COMPACT_BYTES": str(8 * 1024 * 1024),
        "SERVER_OPSPLANE_ENABLED": "1",
        "SERVER_OPSPLANE_PORT": str(ops_port),
        "SERVER_RATE_LIMIT_REQUESTS_PER_MINUTE": "1000000000",
        "SERVER_RATE_LIMIT_BURST": "100000000",
        # sweeps + checkpoints on a soak-visible cadence
        "CPZK_CLEANUP_INTERVAL_S": os.environ.get("CPZK_CLEANUP_INTERVAL_S", "15"),
    })
    if role is not None:
        env.update({
            "SERVER_REPLICATION_ENABLED": "1",
            "SERVER_REPLICATION_ROLE": role,
            "SERVER_REPLICATION_MODE": "async",
            "SERVER_REPLICATION_LEASE_MS": "2000",
            "SERVER_REPLICATION_RENEW_INTERVAL_MS": "400",
        })
        if peer is not None:
            env["SERVER_REPLICATION_PEER"] = peer
    return env


def spawn_daemon(port: int, env: dict, log_path: str) -> subprocess.Popen:
    log_f = open(log_path, "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "cpzk_tpu.server", "--no-repl",
         "--host", "127.0.0.1", "--port", str(port)],
        env=env, stdout=log_f, stderr=log_f,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )


def wait_healthy(ops_port: int, timeout_s: float = 120.0) -> None:
    deadline = time.monotonic() + timeout_s
    url = f"http://127.0.0.1:{ops_port}/healthz"
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                if r.status == 200:
                    return
        except OSError:
            pass
        time.sleep(0.25)
    raise RuntimeError(f"daemon ops plane on :{ops_port} never became healthy")


def scrape_metrics(ops_port: int) -> dict[str, float]:
    """Flat {name_with_labels: value} off the ops plane's /metrics text."""
    out: dict[str, float] = {}
    with urllib.request.urlopen(
        f"http://127.0.0.1:{ops_port}/metrics", timeout=5
    ) as r:
        for line in r.read().decode().splitlines():
            if not line or line.startswith("#"):
                continue
            parts = line.rsplit(" ", 1)
            if len(parts) != 2:
                continue
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                continue
    return out


def rss_bytes(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


# -- phases -------------------------------------------------------------------


async def register_users(address: str, users: int, y1s, y2s) -> float:
    """Register ``users`` distinct ids (statements drawn from the keypair
    pool round-robin — state size is what the soak measures, not keygen
    throughput); returns registrations/s."""
    from cpzk_tpu.client import AuthClient

    t0 = time.monotonic()
    async with AuthClient(address) as client:
        done = 0
        while done < users:
            n = min(REG_BATCH, users - done)
            ids = [f"su{done + k}" for k in range(n)]
            resp = await client.register_batch(
                ids,
                [y1s[(done + k) % POOL] for k in range(n)],
                [y2s[(done + k) % POOL] for k in range(n)],
                timeout=120.0,
            )
            bad = [r.message for r in resp.results if not r.success]
            assert not bad, f"registration failed: {bad[:3]}"
            done += n
            if done % 100_000 < REG_BATCH:
                dt = time.monotonic() - t0
                print(f"# registered {done}/{users} ({done / dt:.0f}/s)",
                      file=sys.stderr, flush=True)
    return users / (time.monotonic() - t0)


def percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    values = sorted(values)
    idx = min(len(values) - 1, max(0, int(round(q / 100.0 * (len(values) - 1)))))
    return values[idx]


async def soak_traffic(
    address: str, users: int, qps: float, duration: float, rng, provers,
    lat: dict[str, list[float]], errors: list[str],
) -> int:
    """Mixed traffic at ~``qps`` proofs/s for ``duration`` seconds:
    single logins (challenge + VerifyProof, session minted), verify-proof
    batches, and stream chunks, users drawn round-robin over the whole
    registered corpus.  Returns proofs driven."""
    from cpzk_tpu import Transcript
    from cpzk_tpu.client import AuthClient

    sem = asyncio.Semaphore(CONCURRENCY)
    done_proofs = 0
    user_cursor = 0

    def next_users(n: int) -> list[tuple[str, int]]:
        nonlocal user_cursor
        out = [
            (f"su{(user_cursor + k) % users}", (user_cursor + k) % POOL)
            for k in range(n)
        ]
        user_cursor = (user_cursor + n) % users
        return out

    async with AuthClient(address) as client:

        async def challenge_and_prove(uid: str, pool_idx: int):
            t0 = time.monotonic()
            ch = await client.create_challenge(uid)
            lat["challenge"].append((time.monotonic() - t0) * 1000.0)
            cid = bytes(ch.challenge_id)
            t = Transcript()
            t.append_context(cid)
            proof = provers[pool_idx].prove_with_transcript(rng, t)
            return cid, proof.to_bytes()

        async def op_login():
            nonlocal done_proofs
            (uid, k), = next_users(1)
            try:
                cid, proof = await challenge_and_prove(uid, k)
                t0 = time.monotonic()
                resp = await client.verify_proof(uid, cid, proof)
                lat["login"].append((time.monotonic() - t0) * 1000.0)
                if not resp.success:
                    errors.append(f"login: {resp.message}")
                done_proofs += 1
            except Exception as e:  # noqa: BLE001 - recorded, run continues
                errors.append(f"login: {e!r}")

        async def op_batch():
            nonlocal done_proofs
            picked = next_users(BATCH_N)
            try:
                pairs = await asyncio.gather(*[
                    challenge_and_prove(uid, k) for uid, k in picked
                ])
                t0 = time.monotonic()
                resp = await client.verify_proof_batch(
                    [uid for uid, _ in picked],
                    [cid for cid, _ in pairs],
                    [proof for _, proof in pairs],
                )
                lat["verify_batch"].append((time.monotonic() - t0) * 1000.0)
                bad = [r.message for r in resp.results if not r.success]
                if bad:
                    errors.append(f"batch: {bad[:2]}")
                done_proofs += BATCH_N
            except Exception as e:  # noqa: BLE001
                errors.append(f"batch: {e!r}")

        async def op_stream():
            nonlocal done_proofs
            picked = next_users(STREAM_N)
            try:
                pairs = await asyncio.gather(*[
                    challenge_and_prove(uid, k) for uid, k in picked
                ])
                entries = [
                    (uid, cid, proof)
                    for (uid, _), (cid, proof) in zip(picked, pairs)
                ]
                t0 = time.monotonic()
                ok = 0
                async for chunk_v in client.verify_proof_stream_chunks(
                    entries, chunk=STREAM_N
                ):
                    ok += sum(chunk_v[1])
                lat["stream"].append((time.monotonic() - t0) * 1000.0)
                if ok != STREAM_N:
                    errors.append(f"stream: {ok}/{STREAM_N} ok")
                done_proofs += STREAM_N
            except Exception as e:  # noqa: BLE001
                errors.append(f"stream: {e!r}")

        # weighted schedule, paced by proofs-per-op against the target QPS
        schedule = [(op_login, 1)] * 6 + [(op_batch, BATCH_N)] + \
            [(op_login, 1)] * 6 + [(op_stream, STREAM_N)]
        tasks: set[asyncio.Task] = set()
        start = time.monotonic()
        next_at = start
        i = 0
        while time.monotonic() - start < duration:
            op, weight = schedule[i % len(schedule)]
            i += 1
            now = time.monotonic()
            if now < next_at:
                await asyncio.sleep(next_at - now)
            next_at = max(next_at + weight / qps, time.monotonic() - 1.0)
            await sem.acquire()

            async def run(op=op):
                try:
                    await op()
                finally:
                    sem.release()

            task = asyncio.ensure_future(run())
            tasks.add(task)
            task.add_done_callback(tasks.discard)
        if tasks:
            await asyncio.wait(tasks, timeout=60)
    return done_proofs


async def measure_failover(
    standby_addr: str, primary: subprocess.Popen, rng, provers,
) -> float:
    """SIGKILL the primary, then poll the standby with full logins until
    one succeeds; returns kill->first-served-login milliseconds."""
    from cpzk_tpu import Transcript
    from cpzk_tpu.client import AuthClient

    primary.send_signal(signal.SIGKILL)
    primary.wait(timeout=30)
    t_kill = time.monotonic()
    deadline = t_kill + 60.0
    uid, k = "su0", 0
    async with AuthClient(standby_addr) as client:
        while time.monotonic() < deadline:
            try:
                ch = await client.create_challenge(uid, timeout=2.0)
                cid = bytes(ch.challenge_id)
                t = Transcript()
                t.append_context(cid)
                proof = provers[k].prove_with_transcript(rng, t)
                resp = await client.verify_proof(
                    uid, cid, proof.to_bytes(), timeout=2.0
                )
                if resp.success:
                    return (time.monotonic() - t_kill) * 1000.0
            except Exception:  # noqa: BLE001 - standby not promoted yet
                await asyncio.sleep(0.05)
    raise RuntimeError("standby never served a login after primary SIGKILL")


# -- failure-storm scenario suite (ISSUE 16) ----------------------------------
#
# ``--storm {herd,brownout,split,crashloop,all}`` runs self-driving-fleet
# storms instead of the throughput soak.  Every leg asserts the same two
# robustness invariants end to end, with NO human action anywhere:
#
# - ZERO acked-write loss: anything acknowledged to a client exists
#   afterwards, on exactly one partition;
# - BOUNDED login burn: the outage window and the post-recovery error
#   ratio stay under explicit ceilings.
#
#   herd       thundering-herd reconnect: a replicated pair's primary is
#              SIGKILLed under a damped client herd; the auto-promoted
#              standby must absorb the synchronized reconnect wave
#              (single-flight map refresh, jittered re-dials) and serve
#              every previously registered user.
#   brownout   slow-chip brownout: FaultPlan latency + failures into one
#              router lane; the live controller drains the lane, every
#              batch still verifies via the healthy lane, and the lane is
#              re-admitted once its breaker re-closes.
#   split      controller-triggered live partition split under full write
#              load; every acknowledged registration lands on exactly one
#              side of the v2 map.
#   crashloop  ingest-shard crash-loop: one shard SIGKILLed through its
#              backoff schedule until the supervisor gives up (crashloop
#              marker), while the surviving shard keeps serving logins.
#
# Violations are collected per leg and make the exit code nonzero; each
# leg also prints a JSON report for eyeballing/trending.

HERD_WORKERS_PER_CLIENT = 8   # concurrent login loops sharing one client
RECOVERY_CEILING_S = 30.0     # herd: kill -> first served login
POST_BURN_CEILING = 0.02      # herd: error ratio after recovery + grace


def ops_json(ops_port: int, path: str) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{ops_port}{path}", timeout=5
    ) as r:
        return json.loads(r.read())


async def _full_login(client, uid: str, prover, rng,
                      timeout: float = 2.0) -> bool:
    from cpzk_tpu import Transcript

    ch = await client.create_challenge(uid, timeout=timeout)
    cid = bytes(ch.challenge_id)
    t = Transcript()
    t.append_context(cid)
    proof = prover.prove_with_transcript(rng, t)
    resp = await client.verify_proof(uid, cid, proof.to_bytes(),
                                     timeout=timeout)
    return bool(resp.success)


async def storm_herd(args) -> dict:
    """Thundering-herd reconnect after a primary SIGKILL."""
    from cpzk_tpu.client import AuthClient
    from cpzk_tpu.fleet import PartitionMap

    # every successful login mints a session and sessions are capped at
    # MAX_SESSIONS_PER_USER=5 (reference parity): keep the corpus large
    # relative to the paced herd's login volume so no user's quota runs
    # out mid-storm
    users = max(args.storm_users, 5000)
    state_dir = tempfile.mkdtemp(prefix="cpzk-storm-herd-")
    port, ops = args.port, args.ops_port
    sb_port, sb_ops = port + 1, ops + 1
    primary_addr = f"127.0.0.1:{port}"
    standby_addr = f"127.0.0.1:{sb_port}"
    procs: list[subprocess.Popen] = []
    violations: list[str] = []
    herd: list = []
    try:
        for name in ("primary", "standby"):
            os.makedirs(os.path.join(state_dir, name), exist_ok=True)
        standby = spawn_daemon(
            sb_port,
            daemon_env(os.path.join(state_dir, "standby"), users, sb_ops,
                       role="standby"),
            os.path.join(state_dir, "standby.log"),
        )
        procs.append(standby)
        wait_healthy(sb_ops)
        primary = spawn_daemon(
            port,
            daemon_env(os.path.join(state_dir, "primary"), users, ops,
                       role="primary", peer=standby_addr),
            os.path.join(state_dir, "primary.log"),
        )
        procs.append(primary)
        wait_healthy(ops)

        rng, provers, y1s, y2s = build_corpus()
        await register_users(primary_addr, users, y1s, y2s)
        # async replication: give the shipper a beat so everything acked
        # above is on the standby before the kill (the leg measures herd
        # behavior, not the async-mode replication-lag contract)
        await asyncio.sleep(2.0)

        # the herd: N clients x M login workers, all damped.  Clients
        # start routed at the primary; on failure a worker asks for a map
        # refresh (single-flight per client) whose fetch returns the
        # standby map — exactly the /partitionmap re-point a real control
        # plane would serve after promotion.
        def fresh_map():
            return PartitionMap.uniform([standby_addr], version=2)

        for _ in range(args.storm_clients):
            herd.append(AuthClient(
                primary_addr,
                partition_map=PartitionMap.uniform([primary_addr]),
                map_refresh=fresh_map,
                refresh_jitter_s=0.2,
                reconnect_damp_s=0.3,
            ))
        stop = asyncio.Event()
        ok_t: list[float] = []
        ok_standby_t: list[float] = []  # successes served under the v2 map
        err_t: list[float] = []

        async def worker(client, k0: int):
            k = k0
            while not stop.is_set():
                uid_n = k % users
                try:
                    good = await _full_login(
                        client, f"su{uid_n}", provers[uid_n % POOL], rng,
                    )
                    now = time.monotonic()
                    (ok_t if good else err_t).append(now)
                    if good and client.partition_map.version >= 2:
                        ok_standby_t.append(now)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 - the storm IS the errors
                    err_t.append(time.monotonic())
                    try:
                        await client._refresh_map()  # damped + coalesced
                    except Exception:  # noqa: BLE001
                        pass
                k += 7
                await asyncio.sleep(0.08)

        workers = [
            asyncio.ensure_future(worker(c, i * 1013 + j * 131))
            for i, c in enumerate(herd)
            for j in range(HERD_WORKERS_PER_CLIENT)
        ]
        await asyncio.sleep(2.0)            # warm the herd on the primary
        pre_ok = len(ok_t)
        primary.send_signal(signal.SIGKILL)
        primary.wait(timeout=30)
        t_kill = time.monotonic()
        print("# herd: primary SIGKILLed under "
              f"{len(workers)} login workers", file=sys.stderr, flush=True)

        # recovery = the first login served under the standby's (v2) map:
        # a primary ack racing the SIGKILL must not count as "recovered"
        recovery_s = None
        deadline = t_kill + 60.0
        while time.monotonic() < deadline:
            post = [t for t in ok_standby_t if t > t_kill]
            if post:
                recovery_s = post[0] - t_kill
                break
            await asyncio.sleep(0.05)
        await asyncio.sleep(args.storm_duration)
        stop.set()
        await asyncio.gather(*workers, return_exceptions=True)

        grace = t_kill + (recovery_s if recovery_s is not None else 60.0) + 1.0
        post_ok = len([t for t in ok_t if t > grace])
        post_err = len([t for t in err_t if t > grace])
        burn = post_err / max(1, post_ok + post_err)
        coalesced = sum(c.refresh_coalesced for c in herd)
        damped = sum(c.reconnects_damped for c in herd)
        fetches = sum(c.refresh_fetches for c in herd)

        if recovery_s is None:
            violations.append("standby never served a herd login within 60s")
        elif recovery_s > RECOVERY_CEILING_S:
            violations.append(
                f"recovery {recovery_s:.1f}s > {RECOVERY_CEILING_S}s ceiling"
            )
        if burn > POST_BURN_CEILING:
            violations.append(
                f"post-recovery burn {burn:.4f} > {POST_BURN_CEILING} "
                f"({post_err} errors / {post_ok + post_err} attempts)"
            )
        if coalesced == 0:
            violations.append("herd damping never engaged: no coalesced "
                              "map refreshes under a synchronized wave")

        # ZERO acked-write loss: every registration acked by the dead
        # primary must be servable on the promoted standby
        sample_n = min(200, users)
        stride = max(1, users // sample_n)
        lost = 0
        async with AuthClient(standby_addr) as checker:
            for j in range(sample_n):
                k = (j * stride) % users
                try:
                    if not await _full_login(
                        checker, f"su{k}", provers[k % POOL], rng,
                        timeout=5.0,
                    ):
                        lost += 1
                except Exception:  # noqa: BLE001
                    lost += 1
        if lost:
            violations.append(
                f"acked-write loss: {lost}/{sample_n} sampled registrations "
                "not servable on the promoted standby"
            )

        try:
            pages = ops_json(sb_ops, "/slo").get("pages_fired")
        except Exception:  # noqa: BLE001
            pages = None
        return {
            "leg": "herd",
            "users": users,
            "clients": len(herd),
            "workers": len(workers),
            "pre_kill_logins": pre_ok,
            "recovery_ms": (round(recovery_s * 1000.0, 1)
                            if recovery_s is not None else None),
            "post_recovery_ok": post_ok,
            "post_recovery_errors": post_err,
            "post_recovery_burn": round(burn, 5),
            "refresh_fetches": fetches,
            "refresh_coalesced": coalesced,
            "reconnects_damped": damped,
            "sampled_users_checked": sample_n,
            "sampled_users_lost": lost,
            "standby_pages_fired": pages,
            "violations": violations,
        }
    finally:
        for c in herd:
            try:
                await c.close()
            except Exception:  # noqa: BLE001
                pass
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in procs:
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
        if not args.keep_state:
            shutil.rmtree(state_dir, ignore_errors=True)


async def storm_brownout(args) -> dict:
    """Slow-chip brownout: the controller drains the faulted lane and
    re-admits it after the breaker re-closes; no batch is ever lost."""
    from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.fleet.controller import (
        ACTION_LANE_DRAIN, ACTION_LANE_READMIT, FleetController,
    )
    from cpzk_tpu.protocol.batch import BatchEntry, CpuBackend
    from cpzk_tpu.resilience.faults import FaultInjectionBackend, FaultPlan
    from cpzk_tpu.server.config import ControllerSettings
    from cpzk_tpu.server.router import LaneRouter

    rng = SecureRng()
    params = Parameters.new()
    provers = [
        Prover(params, Witness(Ristretto255.random_scalar(rng)))
        for _ in range(8)
    ]

    def make_batch(tag: int, n: int = 8) -> list:
        out = []
        for i in range(n):
            p = provers[(tag + i) % len(provers)]
            ctx = b"storm-brownout-%06d" % (tag * n + i)
            t = Transcript()
            t.append_context(ctx)
            out.append(BatchEntry(
                params, p.statement, p.prove_with_transcript(rng, t), ctx,
            ))
        return out

    violations: list[str] = []

    # dry-run preflight: a controller in dry_run watches a lane whose
    # breaker is forced open (every call on the faulted backend raises)
    # — it must emit the LANE_DRAIN decision WITHOUT actuating: same
    # decision stream, lane stays placed.  Proves the preview contract
    # at storm scale before the live phase below.
    dry_plan = FaultPlan(seed=17).fail_range(0, 256)
    dry_router = LaneRouter(
        [CpuBackend(), FaultInjectionBackend(CpuBackend(), dry_plan)],
        recovery_after_s=30.0,
    )
    dry_router.start()
    dry_controller = FleetController(
        ControllerSettings(
            enabled=True, dry_run=True, act_ticks=2, clear_ticks=2,
            lane_open_after_s=0.05, lane_cooldown_s=0.5,
        ),
        router=dry_router,
    )
    dry_decisions = []
    try:
        dry_deadline = time.monotonic() + 20.0
        while time.monotonic() < dry_deadline:
            try:
                await dry_router.submit(make_batch(0, 2), None)
            except Exception:  # noqa: BLE001 - the injected fault
                pass
            await asyncio.sleep(0.05)
            dry_decisions.extend(await dry_controller.tick())
            if any(d.action == ACTION_LANE_DRAIN for d in dry_decisions):
                break
    finally:
        dry_lanes = dry_router.lane_states()
        await dry_router.stop()
    if not any(d.action == ACTION_LANE_DRAIN for d in dry_decisions):
        violations.append(
            "dry-run controller never proposed LANE_DRAIN under a "
            "forced-open breaker")
    if any(d.fired for d in dry_decisions):
        violations.append("dry-run controller actuated a decision")
    if any(lane["drained"] for lane in dry_lanes):
        violations.append("dry-run phase left a lane drained")

    # lane 1 browns out: every batch +20ms, and calls 1..11 raise — the
    # breaker opens on the first failure, probe traffic keeps advancing
    # the plan, and the lane heals once the window passes
    plan = (FaultPlan(seed=16)
            .latency(0.02, every=2)
            .fail_range(1, 12))
    router = LaneRouter(
        [CpuBackend(), FaultInjectionBackend(CpuBackend(), plan)],
        recovery_after_s=0.5,
    )
    router.start()
    controller = FleetController(
        ControllerSettings(
            enabled=True, dry_run=False, act_ticks=2, clear_ticks=2,
            lane_open_after_s=0.3, lane_cooldown_s=0.5,
        ),
        router=router,
    )
    fired: list[str] = []
    submitted = retried = rejected = lost = 0
    batches = [make_batch(tag) for tag in range(6)]
    deadline = time.monotonic() + 60.0

    async def tick() -> None:
        for d in await controller.tick():
            if d.fired:
                fired.append(d.action)

    try:
        i = 0
        while time.monotonic() < deadline:
            entries = batches[i % len(batches)]
            i += 1
            ok = False
            while not ok and time.monotonic() < deadline:
                try:
                    results = await router.submit(entries, None)
                    # lane contract: per-entry result is None on accept,
                    # an error object on reject
                    if any(r is not None for r in results):
                        rejected += 1
                        break
                    ok = True
                except Exception:  # noqa: BLE001 - the injected fault
                    retried += 1
                    await asyncio.sleep(0.02)
                await tick()
            submitted += 1
            if not ok:
                lost += 1
            await tick()
            if (ACTION_LANE_DRAIN in fired
                    and ACTION_LANE_READMIT in fired):
                break
            await asyncio.sleep(0.01)
    finally:
        lanes = router.lane_states()
        decisions = controller.status()["decisions"][-8:]
        await router.stop()

    if ACTION_LANE_DRAIN not in fired:
        violations.append("controller never drained the browned-out lane")
    if ACTION_LANE_READMIT not in fired:
        violations.append("drained lane was never re-admitted after healing")
    if rejected:
        violations.append(f"{rejected} valid batches rejected")
    if lost:
        violations.append(f"{lost} batches never verified (work lost)")
    return {
        "leg": "brownout",
        "dry_run_decisions": len(dry_decisions),
        "dry_run_drain_proposed": any(
            d.action == ACTION_LANE_DRAIN for d in dry_decisions),
        "batches_verified": submitted - lost,
        "resubmissions": retried,
        "actions_fired": fired,
        "final_lanes": lanes,
        "last_decisions": decisions,
        "violations": violations,
    }


async def storm_split(args) -> dict:
    """Controller-triggered live split under full write load: every
    acknowledged registration exists on exactly one partition after."""
    from cpzk_tpu import Parameters, Prover, SecureRng, Witness
    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.durability.recovery import recover_state
    from cpzk_tpu.fleet import FleetRouter, PartitionMap
    from cpzk_tpu.fleet.controller import ACTION_SPLIT, FleetController
    from cpzk_tpu.server.config import ControllerSettings
    from cpzk_tpu.server.state import ServerState, UserData

    rng = SecureRng()
    params = Parameters.new()
    stmt = Prover(params, Witness(Ristretto255.random_scalar(rng))).statement
    users = args.storm_users
    state_dir = tempfile.mkdtemp(prefix="cpzk-storm-split-")
    map_path = os.path.join(state_dir, "map.json")
    violations: list[str] = []
    try:
        PartitionMap.uniform(["127.0.0.1:1"]).store(map_path)
        state = ServerState(max_users=max(users * 100, 1_000_000))
        seeded = [f"storm-{i:06d}" for i in range(users)]
        for uid in seeded:
            await state.register_user(UserData(uid, stmt, 1))
        fleet = FleetRouter(PartitionMap.load(map_path), 0,
                            map_path=map_path)
        controller = FleetController(
            ControllerSettings(
                enabled=True, dry_run=False, act_ticks=2,
                split_user_threshold=max(1, users // 2),
                split_target_address="127.0.0.1:2",
            ),
            state=state, fleet=fleet, segment_bytes=64 * 1024,
        )
        acked: list[str] = []
        redirected = 0
        stop = asyncio.Event()

        async def writer(wid: int):
            # the daemon's service layer checks ownership against the
            # live map BEFORE touching state; emulate that gate so "ack"
            # means what the daemon's ack means
            nonlocal redirected
            i = 0
            while not stop.is_set():
                uid = f"storm-w{wid}-{i:06d}"
                if fleet.map.partition_for(uid).index == fleet.self_index:
                    await state.register_user(UserData(uid, stmt, 1))
                    acked.append(uid)
                else:
                    redirected += 1
                i += 1
                await asyncio.sleep(0)

        writers = [asyncio.ensure_future(writer(w)) for w in range(4)]
        split_report = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            out = await controller.tick()
            hits = [d for d in out if d.fired and d.action == ACTION_SPLIT]
            if hits:
                split_report = hits[0].detail["report"]
                break
            await asyncio.sleep(0.02)
        await asyncio.sleep(0.1)        # post-flip traffic hits the gate
        stop.set()
        await asyncio.gather(*writers)

        if split_report is None:
            violations.append("controller never fired the live split")
            return {"leg": "split", "violations": violations}

        target = ServerState()
        await recover_state(
            target, split_report["target_state_file"],
            split_report["target_state_file"] + ".wal",
        )
        live = {u for sh in state._shards for u in sh._users}
        moved = {u for sh in target._shards for u in sh._users}
        overlap = live & moved
        union = live | moved
        if overlap:
            violations.append(f"{len(overlap)} users on BOTH partitions")
        lost = [u for u in seeded + acked if u not in union]
        if lost:
            violations.append(
                f"acked-write loss: {len(lost)} registrations on neither "
                f"partition (e.g. {lost[:3]})"
            )
        if fleet.map.version != 2:
            violations.append("split map v2 was not adopted in-process")
        if redirected == 0:
            violations.append("no post-flip redirects: the split did not "
                              "land mid-traffic")
        return {
            "leg": "split",
            "seeded_users": users,
            "acked_during_storm": len(acked),
            "redirected_after_flip": redirected,
            "moved_users": split_report["moved_users"],
            "moved_records": split_report["moved_records"],
            "map_version": fleet.map.version,
            "last_decisions": controller.status()["decisions"][-4:],
            "violations": violations,
        }
    finally:
        if not args.keep_state:
            shutil.rmtree(state_dir, ignore_errors=True)


async def storm_crashloop(args) -> dict:
    """Ingest-shard crash-loop: kill one shard through its backoff
    schedule until the supervisor gives up; serving must continue."""
    from cpzk_tpu.client import AuthClient

    users = min(args.storm_users, 1000)
    state_dir = tempfile.mkdtemp(prefix="cpzk-storm-crash-")
    port, ops = args.port + 4, args.ops_port + 4
    address = f"127.0.0.1:{port}"
    violations: list[str] = []
    env = daemon_env(state_dir, users, ops)
    env["SERVER_INGEST_SHARDS"] = "2"
    proc = spawn_daemon(port, env, os.path.join(state_dir, "daemon.log"))
    try:
        wait_healthy(ops)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            rows = (ops_json(ops, "/statusz").get("ingest") or {}) \
                .get("per_shard") or []
            if rows and all(r.get("connected") for r in rows):
                break
            await asyncio.sleep(0.2)
        rng, provers, y1s, y2s = build_corpus()
        await register_users(address, users, y1s, y2s)

        ok = errs = 0
        stop = asyncio.Event()
        # sessions are capped at MAX_SESSIONS_PER_USER=5 (reference
        # parity): the throttled traffic loop cycles the front of the
        # corpus and the post-storm check gets its own reserved tail, so
        # neither exhausts a user's session quota
        traffic_pool = max(1, users - 20)

        async def traffic():
            nonlocal ok, errs
            k = 0
            client = AuthClient(address)
            try:
                while not stop.is_set():
                    uid_n = k % traffic_pool
                    try:
                        good = await _full_login(
                            client, f"su{uid_n}", provers[uid_n % POOL], rng,
                        )
                        ok += 1 if good else 0
                        errs += 0 if good else 1
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001 - shard mid-death
                        errs += 1
                    k += 1
                    await asyncio.sleep(0.05)
            finally:
                await client.close()

        tr = asyncio.ensure_future(traffic())
        kills = 0
        seen_pids: set[int] = set()
        crashloop = False
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            st = ops_json(ops, "/statusz").get("ingest") or {}
            if st.get("crashloop_shards", 0) >= 1:
                crashloop = True
                break
            row = (st.get("per_shard") or [{}])[0]
            pid = row.get("pid")
            if pid and pid not in seen_pids:
                seen_pids.add(pid)
                try:
                    os.kill(pid, signal.SIGKILL)
                    kills += 1
                except ProcessLookupError:
                    pass
            await asyncio.sleep(0.2)
        if not crashloop:
            violations.append(
                f"crash-loop guard never tripped after {kills} SIGKILLs"
            )

        # serving must continue on the surviving shard, no human action
        post_fail = 0
        check_errors: list[str] = []
        async with AuthClient(address) as checker:
            for j in range(20):
                k = (traffic_pool + j) % users
                try:
                    if not await _full_login(
                        checker, f"su{k}", provers[k % POOL], rng,
                        timeout=5.0,
                    ):
                        post_fail += 1
                        check_errors.append("login not successful")
                except Exception as e:  # noqa: BLE001
                    post_fail += 1
                    check_errors.append(repr(e)[:200])
        if post_fail:
            violations.append(
                f"{post_fail}/20 logins failed after the crash-loop "
                "(the surviving shard stopped serving): "
                f"{check_errors[0]}"
            )
        stop.set()
        await tr
        scraped = scrape_metrics(ops)
        crash_ctr = scraped.get(
            "ingest_shard_crashloop_total",
            scraped.get("ingest_shard_crashloop", 0.0),
        )
        if crash_ctr < 1 and crashloop:
            violations.append("ingest.shard.crashloop counter never "
                              "incremented")
        burn = errs / max(1, ok + errs)
        return {
            "leg": "crashloop",
            "users": users,
            "shard_kills": kills,
            "crashloop_tripped": crashloop,
            "storm_logins_ok": ok,
            "storm_login_errors": errs,
            "storm_burn": round(burn, 5),
            "post_crashloop_login_failures": post_fail,
            "crashloop_counter": crash_ctr,
            "violations": violations,
        }
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
        if not args.keep_state:
            shutil.rmtree(state_dir, ignore_errors=True)


ROLLING_LEASE_MS = 2000.0       # daemon_env lease — the failover blackout
ROLLING_P99_CEILING_MS = 1500.0  # storm-wide successful-login p99 bound
ROLLING_PROBE_PERIOD_S = 0.02   # per-partition serial write probe cadence


async def storm_rolling(args) -> dict:
    """Upgrade storm (ISSUE 18): roll a 2-partition replicated fleet one
    partition at a time under mixed traffic.  Each roll is a SIGTERM to
    the partition's primary — the daemon runs the coordinated handover
    (``handover_on_term``) before draining — while a serial write probe
    per partition measures write-unavailability as the largest gap
    between consecutive acknowledged writes.  Invariants: zero
    acked-write loss (strided sample on the rolled fleet), zero
    post-convergence login errors, successful-login p99 bounded, and
    measured write-unavailability strictly below the ``lease_ms``
    blackout an unplanned failover would have cost."""
    from cpzk_tpu.client import AuthClient
    from cpzk_tpu.fleet import PartitionMap

    users = max(args.storm_users, 2000)
    state_dir = tempfile.mkdtemp(prefix="cpzk-storm-rolling-")
    base_port, base_ops = args.port, args.ops_port
    n_parts = 2
    prim = [f"127.0.0.1:{base_port + 2 * i}" for i in range(n_parts)]
    stby = [f"127.0.0.1:{base_port + 2 * i + 1}" for i in range(n_parts)]
    procs: dict[str, subprocess.Popen] = {}
    violations: list[str] = []
    clients: list = []
    try:
        # 2 partitions x replicated pair = 4 daemons (standbys first so
        # every primary's shipper finds its peer on boot)
        for i in range(n_parts):
            sdir = os.path.join(state_dir, f"p{i}-standby")
            os.makedirs(sdir, exist_ok=True)
            procs[f"p{i}-standby"] = spawn_daemon(
                base_port + 2 * i + 1,
                daemon_env(sdir, users, base_ops + 2 * i + 1,
                           role="standby"),
                os.path.join(state_dir, f"p{i}-standby.log"),
            )
        for i in range(n_parts):
            wait_healthy(base_ops + 2 * i + 1)
        for i in range(n_parts):
            pdir = os.path.join(state_dir, f"p{i}-primary")
            os.makedirs(pdir, exist_ok=True)
            procs[f"p{i}-primary"] = spawn_daemon(
                base_port + 2 * i,
                daemon_env(pdir, users, base_ops + 2 * i,
                           role="primary", peer=stby[i]),
                os.path.join(state_dir, f"p{i}-primary.log"),
            )
        for i in range(n_parts):
            wait_healthy(base_ops + 2 * i)

        # the authoritative v2 map: primaries + their warm standbys.
        # Rolls flip it (swap_standby); clients converge through the
        # UNAVAILABLE->standby dial first and the map refresh second.
        auth = {"map": PartitionMap.uniform(prim, standbys=stby)}

        def fresh_map():
            return PartitionMap.from_doc(auth["map"].to_doc())

        rng, provers, y1s, y2s = build_corpus()
        reg = AuthClient(partition_map=fresh_map())
        clients.append(reg)
        done = 0
        while done < users:
            n = min(REG_BATCH, users - done)
            ids = [f"su{done + k}" for k in range(n)]
            resp = await reg.register_batch(
                ids,
                [y1s[(done + k) % POOL] for k in range(n)],
                [y2s[(done + k) % POOL] for k in range(n)],
                timeout=120.0,
            )
            bad = [r.message for r in resp.results if not r.success]
            assert not bad, f"registration failed: {bad[:3]}"
            done += n
        # async replication: let the corpus tail ship before rolling
        await asyncio.sleep(2.0)

        stop = asyncio.Event()
        login_lat_ms: list[float] = []
        login_err_t: list[float] = []

        async def login_worker(k0: int):
            client = AuthClient(
                partition_map=fresh_map(), map_refresh=fresh_map,
                refresh_jitter_s=0.1, reconnect_damp_s=0.1,
            )
            clients.append(client)
            k = k0
            while not stop.is_set():
                uid_n = k % users
                t0 = time.monotonic()
                try:
                    good = await _full_login(
                        client, f"su{uid_n}", provers[uid_n % POOL], rng,
                        timeout=5.0,
                    )
                    if good:
                        login_lat_ms.append(
                            (time.monotonic() - t0) * 1000.0
                        )
                    else:
                        login_err_t.append(time.monotonic())
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 - the roll IS the churn
                    login_err_t.append(time.monotonic())
                k += 7
                await asyncio.sleep(0.08)

        # serial write probe per partition: uids chosen to route there
        # (ranges never move during a roll — only addresses swap), one
        # registration every ROLLING_PROBE_PERIOD_S, acks timestamped so
        # the largest inter-ack gap IS the write-unavailability window
        probe_acks: list[list[tuple[float, str, int]]] = [
            [] for _ in range(n_parts)
        ]

        async def probe_writer(part: int):
            client = AuthClient(
                partition_map=fresh_map(), map_refresh=fresh_map,
                refresh_jitter_s=0.1, reconnect_damp_s=0.1,
            )
            clients.append(client)
            k = 0
            pmap = auth["map"]
            while not stop.is_set():
                uid = f"probe{k}"
                k += 1
                if pmap.partition_for(uid).index != part:
                    continue
                pool_idx = k % POOL
                try:
                    resp = await client.register(
                        uid, y1s[pool_idx], y2s[pool_idx], timeout=3.0,
                    )
                    if resp.success:
                        probe_acks[part].append(
                            (time.monotonic(), uid, pool_idx)
                        )
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 - fenced/handing over
                    pass
                await asyncio.sleep(ROLLING_PROBE_PERIOD_S)

        workers = [
            asyncio.ensure_future(login_worker(j * 1013))
            for j in range(args.storm_clients)
        ] + [
            asyncio.ensure_future(probe_writer(i)) for i in range(n_parts)
        ]
        await asyncio.sleep(2.0)  # warm traffic on the pre-roll fleet

        # -- the roll: one partition at a time, health-gated ---------------
        rolls: list[dict] = []
        for i in range(n_parts):
            t_term = time.monotonic()
            procs[f"p{i}-primary"].send_signal(signal.SIGTERM)
            print(f"# rolling: SIGTERM partition {i} primary",
                  file=sys.stderr, flush=True)
            # the gate: the partition must serve writes again (probe ack
            # after the TERM) before the next partition rolls
            deadline = t_term + 60.0
            served_at = None
            while time.monotonic() < deadline:
                post = [t for t, _, _ in probe_acks[i] if t > t_term]
                if post:
                    served_at = post[0]
                    break
                await asyncio.sleep(0.02)
            if served_at is None:
                violations.append(
                    f"partition {i} never served a write within 60s of "
                    "its primary's SIGTERM — roll aborted"
                )
                break
            # old primary drains and exits; the map flips to the new
            # primary with the drained node parked as the standby slot
            try:
                await asyncio.to_thread(
                    procs[f"p{i}-primary"].wait, 60
                )
            except subprocess.TimeoutExpired:
                violations.append(
                    f"partition {i} old primary never exited after "
                    "handover + drain"
                )
            auth["map"] = auth["map"].swap_standby(i)
            rolls.append({
                "partition": i,
                "serve_gap_ms": round((served_at - t_term) * 1000.0, 1),
                "map_version": auth["map"].version,
            })
        t_converged = time.monotonic()

        # post-convergence window: the rolled fleet must serve cleanly
        await asyncio.sleep(max(args.storm_duration, 3.0))
        grace = t_converged + 1.0
        post_conv_errors = len([t for t in login_err_t if t > grace])
        stop.set()
        await asyncio.gather(*workers, return_exceptions=True)

        # write-unavailability per partition: largest gap between
        # consecutive acked probe writes across the whole storm
        write_unavail_ms = []
        for part in range(n_parts):
            acks = [t for t, _, _ in probe_acks[part]]
            gap = 0.0
            for a, b in zip(acks, acks[1:]):
                gap = max(gap, b - a)
            write_unavail_ms.append(round(gap * 1000.0, 1))
            if not acks:
                violations.append(f"partition {part} probe never acked")
        worst_unavail = max(write_unavail_ms) if write_unavail_ms else None

        if len(rolls) == n_parts:
            for part, unavail in enumerate(write_unavail_ms):
                if unavail >= ROLLING_LEASE_MS:
                    violations.append(
                        f"partition {part} write-unavailability "
                        f"{unavail:.0f}ms not below the {ROLLING_LEASE_MS:.0f}ms "
                        "lease blackout — the handover bought nothing"
                    )
        if post_conv_errors:
            violations.append(
                f"{post_conv_errors} login errors after the fleet "
                "converged on the rolled map"
            )
        p99 = percentile(login_lat_ms, 99)
        if p99 > ROLLING_P99_CEILING_MS:
            violations.append(
                f"login p99 {p99:.0f}ms > {ROLLING_P99_CEILING_MS:.0f}ms "
                "ceiling under the roll"
            )

        # ZERO acked-write loss on the rolled fleet: strided corpus
        # sample + every Nth acked probe write, through the final map
        lost = 0
        sample_n = min(200, users)
        stride = max(1, users // sample_n)
        checker = AuthClient(partition_map=fresh_map())
        clients.append(checker)
        for j in range(sample_n):
            k = (j * stride) % users
            try:
                if not await _full_login(
                    checker, f"su{k}", provers[k % POOL], rng, timeout=5.0,
                ):
                    lost += 1
            except Exception:  # noqa: BLE001
                lost += 1
        probe_lost = probe_checked = 0
        for part in range(n_parts):
            acks = probe_acks[part]
            for _, uid, pool_idx in acks[:: max(1, len(acks) // 50)]:
                probe_checked += 1
                try:
                    if not await _full_login(
                        checker, uid, provers[pool_idx], rng, timeout=5.0,
                    ):
                        probe_lost += 1
                except Exception:  # noqa: BLE001
                    probe_lost += 1
        if lost:
            violations.append(
                f"acked-write loss: {lost}/{sample_n} sampled "
                "registrations not servable on the rolled fleet"
            )
        if probe_lost:
            violations.append(
                f"acked-write loss: {probe_lost}/{probe_checked} "
                "mid-roll probe writes not servable on the rolled fleet"
            )

        standby_dials = sum(
            getattr(c, "standby_dials", 0) for c in clients
        )
        return {
            "leg": "rolling",
            "users": users,
            "partitions": n_parts,
            "rolls": rolls,
            "write_unavail_ms": write_unavail_ms,
            "worst_write_unavail_ms": worst_unavail,
            "lease_blackout_ms": ROLLING_LEASE_MS,
            "login_p99_ms": round(p99, 1),
            "logins_ok": len(login_lat_ms),
            "post_convergence_login_errors": post_conv_errors,
            "probe_acks": [len(a) for a in probe_acks],
            "standby_dials": standby_dials,
            "sampled_users_checked": sample_n,
            "sampled_users_lost": lost,
            "probe_writes_checked": probe_checked,
            "probe_writes_lost": probe_lost,
            "final_map_version": auth["map"].version,
            "violations": violations,
        }
    finally:
        for c in clients:
            try:
                await c.close()
            except Exception:  # noqa: BLE001
                pass
        for proc in procs.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in procs.values():
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
        if not args.keep_state:
            shutil.rmtree(state_dir, ignore_errors=True)


STORMS = {
    "herd": storm_herd,
    "brownout": storm_brownout,
    "split": storm_split,
    "crashloop": storm_crashloop,
    "rolling": storm_rolling,
}


async def run_storms(args) -> int:
    legs = list(STORMS) if args.storm == "all" else [args.storm]
    reports: dict[str, dict] = {}
    violations: list[str] = []
    for leg in legs:
        print(f"# storm: {leg}", file=sys.stderr, flush=True)
        report = await STORMS[leg](args)
        reports[leg] = report
        violations.extend(f"{leg}: {v}" for v in report.get("violations", []))
    print(json.dumps({
        "metric": "storm",
        "legs": reports,
        "violations": violations,
    }), flush=True)
    if args.snapshot and "rolling" in reports:
        # the rolling roll-vs-blackout numbers belong in BENCH_SOAK.json:
        # the measured planned-operations cost next to the lease blackout
        # an unplanned failover would have charged
        from cpzk_tpu.observability.perf import PerfEntry, write_snapshot

        r = reports["rolling"]
        entries = [
            PerfEntry("soak.rolling.write_unavail", "cpu", r["users"],
                      float(r["worst_write_unavail_ms"] or 0.0), "ms"),
            PerfEntry("soak.rolling.lease_blackout", "cpu", r["users"],
                      float(r["lease_blackout_ms"]), "ms"),
            PerfEntry("soak.rolling.login_p99", "cpu", r["users"],
                      float(r["login_p99_ms"]), "ms"),
        ]
        write_snapshot(args.snapshot, entries, meta={
            "bench": "bench_soak",
            "storm": args.storm,
            "users": r["users"],
            "platform": "host",
            "rolling": {
                "write_unavail_ms": r["write_unavail_ms"],
                "lease_blackout_ms": r["lease_blackout_ms"],
                "rolls": r["rolls"],
                "standby_dials": r["standby_dials"],
                "post_convergence_login_errors":
                    r["post_convergence_login_errors"],
            },
        })
        print(f"# perf snapshot written to {args.snapshot}",
              file=sys.stderr, flush=True)
    if violations:
        for v in violations:
            print(f"# VIOLATION {v}", file=sys.stderr, flush=True)
    return 1 if violations else 0


# -- main ---------------------------------------------------------------------


async def amain(args) -> int:
    from cpzk_tpu.observability.perf import PerfEntry, write_snapshot

    state_dir = args.state_dir or tempfile.mkdtemp(prefix="cpzk-soak-")
    os.makedirs(state_dir, exist_ok=True)
    primary_dir = os.path.join(state_dir, "primary")
    os.makedirs(primary_dir, exist_ok=True)
    address = f"127.0.0.1:{args.port}"

    procs: list[subprocess.Popen] = []
    standby = None
    try:
        if args.failover:
            standby_dir = os.path.join(state_dir, "standby")
            os.makedirs(standby_dir, exist_ok=True)
            standby_port, standby_ops = args.port + 1, args.ops_port + 1
            standby = spawn_daemon(
                standby_port,
                daemon_env(standby_dir, args.users, standby_ops,
                           role="standby"),
                os.path.join(state_dir, "standby.log"),
            )
            procs.append(standby)
            wait_healthy(standby_ops)
        primary = spawn_daemon(
            args.port,
            daemon_env(
                primary_dir, args.users, args.ops_port,
                role="primary" if args.failover else None,
                peer=f"127.0.0.1:{args.port + 1}" if args.failover else None,
            ),
            os.path.join(state_dir, "primary.log"),
        )
        procs.append(primary)
        wait_healthy(args.ops_port)

        print(f"# daemon up (pid {primary.pid}); building corpus",
              file=sys.stderr, flush=True)
        rng, provers, y1s, y2s = build_corpus()
        rss_before = rss_bytes(primary.pid)

        reg_rate = await register_users(address, args.users, y1s, y2s)
        rss_after_reg = rss_bytes(primary.pid)
        print(f"# registration: {reg_rate:.0f} users/s, RSS "
              f"{rss_after_reg / 1e6:.0f} MB", file=sys.stderr, flush=True)

        lat: dict[str, list[float]] = {
            "challenge": [], "login": [], "verify_batch": [], "stream": [],
        }
        errors: list[str] = []
        rss_samples: list[int] = []

        async def rss_sampler():
            while True:
                rss_samples.append(rss_bytes(primary.pid))
                await asyncio.sleep(2.0)

        sampler = asyncio.ensure_future(rss_sampler())
        proofs = await soak_traffic(
            address, args.users, args.qps, args.duration, rng, provers,
            lat, errors,
        )
        sampler.cancel()

        failover_ms = None
        if args.failover:
            assert standby is not None
            failover_ms = await measure_failover(
                f"127.0.0.1:{args.port + 1}", primary, rng, provers,
            )
            print(f"# failover: standby served a login {failover_ms:.0f} ms "
                  "after primary SIGKILL", file=sys.stderr, flush=True)

        # daemon-side numbers off the ops plane (primary may be dead after
        # the failover leg — scrape what the soak window recorded first)
        scraped: dict[str, float] = {}
        if not args.failover:
            scraped = scrape_metrics(args.ops_port)
        snap_pause = scraped.get("state_snapshot_max_pause_ms", 0.0)
        sweep_max = scraped.get("state_sweep_max_ms", 0.0)
        wal_segments = scraped.get("state_wal_segments", 0.0)

        steady = sorted(rss_samples[len(rss_samples) // 2:] or
                        [rss_after_reg])
        rss_steady = steady[len(steady) // 2]

        err_rate = len(errors) / max(1, proofs)
        report = {
            "metric": "soak",
            "users": args.users,
            "qps_target": args.qps,
            "duration_s": args.duration,
            "proofs_driven": proofs,
            "registration_users_per_s": round(reg_rate, 1),
            "rss_before_bytes": rss_before,
            "rss_after_registration_bytes": rss_after_reg,
            "rss_steady_bytes": int(rss_steady),
            "snapshot_max_pause_ms": snap_pause,
            "sweep_max_ms": sweep_max,
            "wal_segments": wal_segments,
            "latency_ms": {
                k: {"p50": round(percentile(v, 50), 3),
                    "p99": round(percentile(v, 99), 3),
                    "n": len(v)}
                for k, v in lat.items()
            },
            "failover_ms": failover_ms,
            "errors": len(errors),
            "error_samples": errors[:5],
        }
        print(json.dumps(report), flush=True)
        if errors:
            print(f"# {len(errors)} errors (rate {err_rate:.5f}); first: "
                  f"{errors[0]}", file=sys.stderr, flush=True)

        if args.snapshot:
            entries = [
                PerfEntry("soak.register", "cpu", args.users,
                          round(reg_rate, 1), "users/s"),
                PerfEntry("soak.rss_steady", "cpu", args.users,
                          float(int(rss_steady)), "bytes"),
            ]
            for kind in ("login", "verify_batch", "stream"):
                values = lat[kind]
                if not values:
                    continue
                entries.append(PerfEntry(
                    f"soak.{kind}.p50", "cpu", args.users,
                    round(percentile(values, 50), 3), "ms",
                    spread=round(percentile(values, 75)
                                 - percentile(values, 25), 3),
                ))
                entries.append(PerfEntry(
                    f"soak.{kind}.p99", "cpu", args.users,
                    round(percentile(values, 99), 3), "ms",
                ))
            if snap_pause > 0:
                entries.append(PerfEntry(
                    "soak.snapshot.max_pause", "cpu", args.users,
                    round(snap_pause, 3), "ms",
                ))
            if sweep_max > 0:
                entries.append(PerfEntry(
                    "soak.sweep.max", "cpu", args.users,
                    round(sweep_max, 3), "ms",
                ))
            if failover_ms is not None:
                entries.append(PerfEntry(
                    "soak.failover", "cpu", args.users,
                    round(failover_ms, 1), "ms",
                ))
            write_snapshot(args.snapshot, entries, meta={
                "bench": "bench_soak",
                "users": args.users,
                "qps": args.qps,
                "duration_s": args.duration,
                "platform": "host",
                "wal_segments": wal_segments,
                "proofs_driven": proofs,
                "errors": len(errors),
            })
            print(f"# perf snapshot written to {args.snapshot}",
                  file=sys.stderr, flush=True)
        return 1 if (errors and args.strict) else 0
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in procs:
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
        if args.state_dir is None and not args.keep_state:
            shutil.rmtree(state_dir, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="million-user state-plane soak against a live daemon"
    )
    ap.add_argument("--users", type=int, default=1_000_000)
    ap.add_argument("--qps", type=float, default=1000.0,
                    help="target mixed-traffic rate in proofs/s")
    ap.add_argument("--duration", type=float, default=60.0,
                    help="soak window seconds (after registration)")
    ap.add_argument("--port", type=int, default=50161)
    ap.add_argument("--ops-port", type=int, default=9161)
    ap.add_argument("--snapshot", default=None,
                    help="write a cpzk-perf-snapshot JSON here "
                         "(BENCH_SOAK.json)")
    ap.add_argument("--failover", action="store_true",
                    help="run a replicated pair and SIGKILL the primary "
                         "mid-soak, recording promotion-to-serving time")
    ap.add_argument("--state-dir", default=None,
                    help="daemon state directory (default: fresh tempdir, "
                         "removed afterwards)")
    ap.add_argument("--keep-state", action="store_true")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero when any soak op errored")
    ap.add_argument("--storm", default=None,
                    choices=["herd", "brownout", "split", "crashloop",
                             "rolling", "all"],
                    help="run the failure-storm scenario suite instead of "
                         "the throughput soak (nonzero exit on any "
                         "invariant violation)")
    ap.add_argument("--storm-users", type=int, default=2000,
                    help="registered corpus per storm leg")
    ap.add_argument("--storm-clients", type=int, default=8,
                    help="herd leg: damped clients "
                         f"(x{HERD_WORKERS_PER_CLIENT} login workers each)")
    ap.add_argument("--storm-duration", type=float, default=5.0,
                    help="herd leg: post-recovery soak window seconds")
    args = ap.parse_args()
    if args.storm:
        return asyncio.run(run_storms(args))
    return asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
