"""Million-user state-plane soak: registration, mixed traffic, failover.

ROADMAP item 6 / ISSUE 14: nothing before this harness ever held 1M+
registered users, so snapshot pause, WAL compaction behavior, expiry
sweep cost, and steady-state RSS all had unmeasured constants.  This
driver registers ``--users`` users against a REAL daemon subprocess
(``python -m cpzk_tpu.server``) configured the million-user way
(raised capacity caps, durability + segmented WAL, ops plane), then
drives mixed login / verify-batch / stream traffic at a target QPS and
records into a ``BENCH_SOAK.json`` the perf-regression gate
(``python -m cpzk_tpu.observability.regress``) understands:

- per-RPC p50/p99 client latency (``ms``, lower is better) for the
  challenge+login pair, the batched verify, and the stream chunk;
- the daemon's longest synchronous snapshot cut
  (``state.snapshot.max_pause_ms`` — the streaming-snapshot acceptance
  number) and longest sweep (``state.sweep.max_ms``), scraped from the
  ops plane;
- steady-state RSS of the daemon (``bytes``) sampled from
  ``/proc/<pid>/status``;
- sealed WAL segment count at the end of the run;
- optionally (``--failover``) a replicated-pair leg: the primary is
  SIGKILLed mid-soak and the time until the auto-promoted standby
  serves a full login is recorded (``ms``).

Scaled-down smoke: ``--users 50000 --qps 300 --duration 20`` finishes
in about a minute on one core and is what CI's ``soak-smoke`` job gates
against the committed ``BENCH_SOAK_BASELINE.json``; the committed
``BENCH_SOAK.json`` is a full 1M-user CPU run.

Usage::

    python benches/bench_soak.py --users 1000000 --qps 1000 \
        --duration 60 --snapshot BENCH_SOAK.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

POOL = 256           # distinct keypairs; users share statements round-robin
REG_BATCH = 1000     # register_batch chunk (MAX_BATCH service parity)
BATCH_N = 32         # proofs per verify-batch op
STREAM_N = 128       # proofs per stream-chunk op
CONCURRENCY = 16     # in-flight soak ops cap


def build_corpus():
    from cpzk_tpu import Parameters, Prover, SecureRng, Witness
    from cpzk_tpu.core.ristretto import Ristretto255

    rng = SecureRng()
    params = Parameters.new()
    provers = [
        Prover(params, Witness(Ristretto255.random_scalar(rng)))
        for _ in range(POOL)
    ]
    eb = Ristretto255.element_to_bytes
    y1s = [eb(p.statement.y1) for p in provers]
    y2s = [eb(p.statement.y2) for p in provers]
    return rng, provers, y1s, y2s


# -- daemon management --------------------------------------------------------


def daemon_env(
    state_dir: str,
    users: int,
    ops_port: int,
    role: str | None = None,
    peer: str | None = None,
    wal_segment_bytes: int = 4 * 1024 * 1024,
) -> dict:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "SERVER_CONFIG_PATH": os.path.join(state_dir, "nonexistent.toml"),
        "SERVER_STATE_FILE": os.path.join(state_dir, "state.json"),
        # million-user shape: caps sized to the corpus, durability with a
        # segment-rotated WAL so compaction never copies the tail
        "SERVER_MAX_USERS": str(max(users * 2, 10_000)),
        "SERVER_MAX_SESSIONS": str(max(users * 2, 100_000)),
        "SERVER_MAX_CHALLENGES": str(max(users, 50_000)),
        "SERVER_DURABILITY_ENABLED": "1",
        "SERVER_DURABILITY_FSYNC": "interval",
        "SERVER_DURABILITY_FSYNC_INTERVAL_MS": "100",
        "SERVER_DURABILITY_WAL_SEGMENT_BYTES": str(wal_segment_bytes),
        "SERVER_DURABILITY_COMPACT_BYTES": str(8 * 1024 * 1024),
        "SERVER_OPSPLANE_ENABLED": "1",
        "SERVER_OPSPLANE_PORT": str(ops_port),
        "SERVER_RATE_LIMIT_REQUESTS_PER_MINUTE": "1000000000",
        "SERVER_RATE_LIMIT_BURST": "100000000",
        # sweeps + checkpoints on a soak-visible cadence
        "CPZK_CLEANUP_INTERVAL_S": os.environ.get("CPZK_CLEANUP_INTERVAL_S", "15"),
    })
    if role is not None:
        env.update({
            "SERVER_REPLICATION_ENABLED": "1",
            "SERVER_REPLICATION_ROLE": role,
            "SERVER_REPLICATION_MODE": "async",
            "SERVER_REPLICATION_LEASE_MS": "2000",
            "SERVER_REPLICATION_RENEW_INTERVAL_MS": "400",
        })
        if peer is not None:
            env["SERVER_REPLICATION_PEER"] = peer
    return env


def spawn_daemon(port: int, env: dict, log_path: str) -> subprocess.Popen:
    log_f = open(log_path, "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "cpzk_tpu.server", "--no-repl",
         "--host", "127.0.0.1", "--port", str(port)],
        env=env, stdout=log_f, stderr=log_f,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )


def wait_healthy(ops_port: int, timeout_s: float = 120.0) -> None:
    deadline = time.monotonic() + timeout_s
    url = f"http://127.0.0.1:{ops_port}/healthz"
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                if r.status == 200:
                    return
        except OSError:
            pass
        time.sleep(0.25)
    raise RuntimeError(f"daemon ops plane on :{ops_port} never became healthy")


def scrape_metrics(ops_port: int) -> dict[str, float]:
    """Flat {name_with_labels: value} off the ops plane's /metrics text."""
    out: dict[str, float] = {}
    with urllib.request.urlopen(
        f"http://127.0.0.1:{ops_port}/metrics", timeout=5
    ) as r:
        for line in r.read().decode().splitlines():
            if not line or line.startswith("#"):
                continue
            parts = line.rsplit(" ", 1)
            if len(parts) != 2:
                continue
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                continue
    return out


def rss_bytes(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


# -- phases -------------------------------------------------------------------


async def register_users(address: str, users: int, y1s, y2s) -> float:
    """Register ``users`` distinct ids (statements drawn from the keypair
    pool round-robin — state size is what the soak measures, not keygen
    throughput); returns registrations/s."""
    from cpzk_tpu.client import AuthClient

    t0 = time.monotonic()
    async with AuthClient(address) as client:
        done = 0
        while done < users:
            n = min(REG_BATCH, users - done)
            ids = [f"su{done + k}" for k in range(n)]
            resp = await client.register_batch(
                ids,
                [y1s[(done + k) % POOL] for k in range(n)],
                [y2s[(done + k) % POOL] for k in range(n)],
                timeout=120.0,
            )
            bad = [r.message for r in resp.results if not r.success]
            assert not bad, f"registration failed: {bad[:3]}"
            done += n
            if done % 100_000 < REG_BATCH:
                dt = time.monotonic() - t0
                print(f"# registered {done}/{users} ({done / dt:.0f}/s)",
                      file=sys.stderr, flush=True)
    return users / (time.monotonic() - t0)


def percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    values = sorted(values)
    idx = min(len(values) - 1, max(0, int(round(q / 100.0 * (len(values) - 1)))))
    return values[idx]


async def soak_traffic(
    address: str, users: int, qps: float, duration: float, rng, provers,
    lat: dict[str, list[float]], errors: list[str],
) -> int:
    """Mixed traffic at ~``qps`` proofs/s for ``duration`` seconds:
    single logins (challenge + VerifyProof, session minted), verify-proof
    batches, and stream chunks, users drawn round-robin over the whole
    registered corpus.  Returns proofs driven."""
    from cpzk_tpu import Transcript
    from cpzk_tpu.client import AuthClient

    sem = asyncio.Semaphore(CONCURRENCY)
    done_proofs = 0
    user_cursor = 0

    def next_users(n: int) -> list[tuple[str, int]]:
        nonlocal user_cursor
        out = [
            (f"su{(user_cursor + k) % users}", (user_cursor + k) % POOL)
            for k in range(n)
        ]
        user_cursor = (user_cursor + n) % users
        return out

    async with AuthClient(address) as client:

        async def challenge_and_prove(uid: str, pool_idx: int):
            t0 = time.monotonic()
            ch = await client.create_challenge(uid)
            lat["challenge"].append((time.monotonic() - t0) * 1000.0)
            cid = bytes(ch.challenge_id)
            t = Transcript()
            t.append_context(cid)
            proof = provers[pool_idx].prove_with_transcript(rng, t)
            return cid, proof.to_bytes()

        async def op_login():
            nonlocal done_proofs
            (uid, k), = next_users(1)
            try:
                cid, proof = await challenge_and_prove(uid, k)
                t0 = time.monotonic()
                resp = await client.verify_proof(uid, cid, proof)
                lat["login"].append((time.monotonic() - t0) * 1000.0)
                if not resp.success:
                    errors.append(f"login: {resp.message}")
                done_proofs += 1
            except Exception as e:  # noqa: BLE001 - recorded, run continues
                errors.append(f"login: {e!r}")

        async def op_batch():
            nonlocal done_proofs
            picked = next_users(BATCH_N)
            try:
                pairs = await asyncio.gather(*[
                    challenge_and_prove(uid, k) for uid, k in picked
                ])
                t0 = time.monotonic()
                resp = await client.verify_proof_batch(
                    [uid for uid, _ in picked],
                    [cid for cid, _ in pairs],
                    [proof for _, proof in pairs],
                )
                lat["verify_batch"].append((time.monotonic() - t0) * 1000.0)
                bad = [r.message for r in resp.results if not r.success]
                if bad:
                    errors.append(f"batch: {bad[:2]}")
                done_proofs += BATCH_N
            except Exception as e:  # noqa: BLE001
                errors.append(f"batch: {e!r}")

        async def op_stream():
            nonlocal done_proofs
            picked = next_users(STREAM_N)
            try:
                pairs = await asyncio.gather(*[
                    challenge_and_prove(uid, k) for uid, k in picked
                ])
                entries = [
                    (uid, cid, proof)
                    for (uid, _), (cid, proof) in zip(picked, pairs)
                ]
                t0 = time.monotonic()
                ok = 0
                async for chunk_v in client.verify_proof_stream_chunks(
                    entries, chunk=STREAM_N
                ):
                    ok += sum(chunk_v[1])
                lat["stream"].append((time.monotonic() - t0) * 1000.0)
                if ok != STREAM_N:
                    errors.append(f"stream: {ok}/{STREAM_N} ok")
                done_proofs += STREAM_N
            except Exception as e:  # noqa: BLE001
                errors.append(f"stream: {e!r}")

        # weighted schedule, paced by proofs-per-op against the target QPS
        schedule = [(op_login, 1)] * 6 + [(op_batch, BATCH_N)] + \
            [(op_login, 1)] * 6 + [(op_stream, STREAM_N)]
        tasks: set[asyncio.Task] = set()
        start = time.monotonic()
        next_at = start
        i = 0
        while time.monotonic() - start < duration:
            op, weight = schedule[i % len(schedule)]
            i += 1
            now = time.monotonic()
            if now < next_at:
                await asyncio.sleep(next_at - now)
            next_at = max(next_at + weight / qps, time.monotonic() - 1.0)
            await sem.acquire()

            async def run(op=op):
                try:
                    await op()
                finally:
                    sem.release()

            task = asyncio.ensure_future(run())
            tasks.add(task)
            task.add_done_callback(tasks.discard)
        if tasks:
            await asyncio.wait(tasks, timeout=60)
    return done_proofs


async def measure_failover(
    standby_addr: str, primary: subprocess.Popen, rng, provers,
) -> float:
    """SIGKILL the primary, then poll the standby with full logins until
    one succeeds; returns kill->first-served-login milliseconds."""
    from cpzk_tpu import Transcript
    from cpzk_tpu.client import AuthClient

    primary.send_signal(signal.SIGKILL)
    primary.wait(timeout=30)
    t_kill = time.monotonic()
    deadline = t_kill + 60.0
    uid, k = "su0", 0
    async with AuthClient(standby_addr) as client:
        while time.monotonic() < deadline:
            try:
                ch = await client.create_challenge(uid, timeout=2.0)
                cid = bytes(ch.challenge_id)
                t = Transcript()
                t.append_context(cid)
                proof = provers[k].prove_with_transcript(rng, t)
                resp = await client.verify_proof(
                    uid, cid, proof.to_bytes(), timeout=2.0
                )
                if resp.success:
                    return (time.monotonic() - t_kill) * 1000.0
            except Exception:  # noqa: BLE001 - standby not promoted yet
                await asyncio.sleep(0.05)
    raise RuntimeError("standby never served a login after primary SIGKILL")


# -- main ---------------------------------------------------------------------


async def amain(args) -> int:
    from cpzk_tpu.observability.perf import PerfEntry, write_snapshot

    state_dir = args.state_dir or tempfile.mkdtemp(prefix="cpzk-soak-")
    os.makedirs(state_dir, exist_ok=True)
    primary_dir = os.path.join(state_dir, "primary")
    os.makedirs(primary_dir, exist_ok=True)
    address = f"127.0.0.1:{args.port}"

    procs: list[subprocess.Popen] = []
    standby = None
    try:
        if args.failover:
            standby_dir = os.path.join(state_dir, "standby")
            os.makedirs(standby_dir, exist_ok=True)
            standby_port, standby_ops = args.port + 1, args.ops_port + 1
            standby = spawn_daemon(
                standby_port,
                daemon_env(standby_dir, args.users, standby_ops,
                           role="standby"),
                os.path.join(state_dir, "standby.log"),
            )
            procs.append(standby)
            wait_healthy(standby_ops)
        primary = spawn_daemon(
            args.port,
            daemon_env(
                primary_dir, args.users, args.ops_port,
                role="primary" if args.failover else None,
                peer=f"127.0.0.1:{args.port + 1}" if args.failover else None,
            ),
            os.path.join(state_dir, "primary.log"),
        )
        procs.append(primary)
        wait_healthy(args.ops_port)

        print(f"# daemon up (pid {primary.pid}); building corpus",
              file=sys.stderr, flush=True)
        rng, provers, y1s, y2s = build_corpus()
        rss_before = rss_bytes(primary.pid)

        reg_rate = await register_users(address, args.users, y1s, y2s)
        rss_after_reg = rss_bytes(primary.pid)
        print(f"# registration: {reg_rate:.0f} users/s, RSS "
              f"{rss_after_reg / 1e6:.0f} MB", file=sys.stderr, flush=True)

        lat: dict[str, list[float]] = {
            "challenge": [], "login": [], "verify_batch": [], "stream": [],
        }
        errors: list[str] = []
        rss_samples: list[int] = []

        async def rss_sampler():
            while True:
                rss_samples.append(rss_bytes(primary.pid))
                await asyncio.sleep(2.0)

        sampler = asyncio.ensure_future(rss_sampler())
        proofs = await soak_traffic(
            address, args.users, args.qps, args.duration, rng, provers,
            lat, errors,
        )
        sampler.cancel()

        failover_ms = None
        if args.failover:
            assert standby is not None
            failover_ms = await measure_failover(
                f"127.0.0.1:{args.port + 1}", primary, rng, provers,
            )
            print(f"# failover: standby served a login {failover_ms:.0f} ms "
                  "after primary SIGKILL", file=sys.stderr, flush=True)

        # daemon-side numbers off the ops plane (primary may be dead after
        # the failover leg — scrape what the soak window recorded first)
        scraped: dict[str, float] = {}
        if not args.failover:
            scraped = scrape_metrics(args.ops_port)
        snap_pause = scraped.get("state_snapshot_max_pause_ms", 0.0)
        sweep_max = scraped.get("state_sweep_max_ms", 0.0)
        wal_segments = scraped.get("state_wal_segments", 0.0)

        steady = sorted(rss_samples[len(rss_samples) // 2:] or
                        [rss_after_reg])
        rss_steady = steady[len(steady) // 2]

        err_rate = len(errors) / max(1, proofs)
        report = {
            "metric": "soak",
            "users": args.users,
            "qps_target": args.qps,
            "duration_s": args.duration,
            "proofs_driven": proofs,
            "registration_users_per_s": round(reg_rate, 1),
            "rss_before_bytes": rss_before,
            "rss_after_registration_bytes": rss_after_reg,
            "rss_steady_bytes": int(rss_steady),
            "snapshot_max_pause_ms": snap_pause,
            "sweep_max_ms": sweep_max,
            "wal_segments": wal_segments,
            "latency_ms": {
                k: {"p50": round(percentile(v, 50), 3),
                    "p99": round(percentile(v, 99), 3),
                    "n": len(v)}
                for k, v in lat.items()
            },
            "failover_ms": failover_ms,
            "errors": len(errors),
            "error_samples": errors[:5],
        }
        print(json.dumps(report), flush=True)
        if errors:
            print(f"# {len(errors)} errors (rate {err_rate:.5f}); first: "
                  f"{errors[0]}", file=sys.stderr, flush=True)

        if args.snapshot:
            entries = [
                PerfEntry("soak.register", "cpu", args.users,
                          round(reg_rate, 1), "users/s"),
                PerfEntry("soak.rss_steady", "cpu", args.users,
                          float(int(rss_steady)), "bytes"),
            ]
            for kind in ("login", "verify_batch", "stream"):
                values = lat[kind]
                if not values:
                    continue
                entries.append(PerfEntry(
                    f"soak.{kind}.p50", "cpu", args.users,
                    round(percentile(values, 50), 3), "ms",
                    spread=round(percentile(values, 75)
                                 - percentile(values, 25), 3),
                ))
                entries.append(PerfEntry(
                    f"soak.{kind}.p99", "cpu", args.users,
                    round(percentile(values, 99), 3), "ms",
                ))
            if snap_pause > 0:
                entries.append(PerfEntry(
                    "soak.snapshot.max_pause", "cpu", args.users,
                    round(snap_pause, 3), "ms",
                ))
            if sweep_max > 0:
                entries.append(PerfEntry(
                    "soak.sweep.max", "cpu", args.users,
                    round(sweep_max, 3), "ms",
                ))
            if failover_ms is not None:
                entries.append(PerfEntry(
                    "soak.failover", "cpu", args.users,
                    round(failover_ms, 1), "ms",
                ))
            write_snapshot(args.snapshot, entries, meta={
                "bench": "bench_soak",
                "users": args.users,
                "qps": args.qps,
                "duration_s": args.duration,
                "platform": "host",
                "wal_segments": wal_segments,
                "proofs_driven": proofs,
                "errors": len(errors),
            })
            print(f"# perf snapshot written to {args.snapshot}",
                  file=sys.stderr, flush=True)
        return 1 if (errors and args.strict) else 0
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in procs:
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
        if args.state_dir is None and not args.keep_state:
            shutil.rmtree(state_dir, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="million-user state-plane soak against a live daemon"
    )
    ap.add_argument("--users", type=int, default=1_000_000)
    ap.add_argument("--qps", type=float, default=1000.0,
                    help="target mixed-traffic rate in proofs/s")
    ap.add_argument("--duration", type=float, default=60.0,
                    help="soak window seconds (after registration)")
    ap.add_argument("--port", type=int, default=50161)
    ap.add_argument("--ops-port", type=int, default=9161)
    ap.add_argument("--snapshot", default=None,
                    help="write a cpzk-perf-snapshot JSON here "
                         "(BENCH_SOAK.json)")
    ap.add_argument("--failover", action="store_true",
                    help="run a replicated pair and SIGKILL the primary "
                         "mid-soak, recording promotion-to-serving time")
    ap.add_argument("--state-dir", default=None,
                    help="daemon state directory (default: fresh tempdir, "
                         "removed afterwards)")
    ap.add_argument("--keep-state", action="store_true")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero when any soak op errored")
    args = ap.parse_args()
    return asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
