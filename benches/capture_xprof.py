"""Capture one JAX profiler (xprof) trace of a batch-verify kernel.

VERDICT r4 item 1's last sub-goal ("one xprof trace"): runs the chosen
kernel at N rows — compile untraced, then ITERS timed executions inside
``jax.profiler.trace`` — so the trace holds steady-state device steps,
not compilation.  Inspect with ``tensorboard --logdir <outdir>``.

Usage: python benches/capture_xprof.py [--n 4096] [--kernel rowcombined]
       [--outdir .hw/xprof] [--platform cpu]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--kernel", default="rowcombined",
                    choices=("rowcombined", "pippenger"))
    ap.add_argument("--outdir", default=".hw/xprof")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()

    os.environ["CPZK_BENCH_N"] = str(args.n)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    # same persistent compile cache bench.main() uses: a watcher retry
    # must not pay the (minutes-long on a tunnel) kernel compile twice
    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_bench_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except Exception:
        pass  # older jax without the knob: cache is best-effort

    import time

    import bench as bench_mod

    inp = bench_mod._Inputs()
    setup = {"rowcombined": bench_mod._rowcombined_setup,
             "pippenger": bench_mod._pippenger_setup}[args.kernel]
    # inputs, jit wrapper, compile and warmup all OUTSIDE the trace
    # window: the trace must hold only steady-state device executions
    fn, kargs = setup(inp)

    import jax

    ok = jax.block_until_ready(fn(*kargs))
    if not bool(ok):
        raise SystemExit("combined check rejected the warmup batch — "
                         "refusing to trace a broken run")

    best = float("inf")
    with jax.profiler.trace(args.outdir):
        with jax.profiler.TraceAnnotation(f"cpzk_{args.kernel}_{args.n}"):
            for _ in range(args.iters):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(*kargs))
                best = min(best, time.perf_counter() - t0)
    print(f"traced {args.kernel} at N={args.n}: {args.n / best:.1f} "
          f"proofs/s -> {args.outdir}")


if __name__ == "__main__":
    main()
