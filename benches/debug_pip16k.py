"""Device-side bisection for the Pippenger >=16k anomaly (PROFILE.md §7a).

On TPU the backend's combined check via `_combined_pippenger` rejected an
all-valid batch at N=16384 (m=65538 terms, model window c=13) and hung at
N=65536 (m=262146, c=15), while N=4096 (m=16386, c=11) passes with the
in-kernel assert.  Every CPU-reachable suspect is exonerated (the MSM
kernel matches the host oracle at every window c in {8,11,12,13,14,15}
on the XLA CPU backend, the digit recode round-trips, and the backend
combined check verifies True at N=16384 on CPU).  This script bisects the
DEVICE failure into its two stages, each reported independently:

  digits — device signed-digit recode (`sclimbs.to_signed_digits`, the
           exact `backend._signed_digits_jit` entry) vs the host recode
           (`msm.scalars_to_signed_digits`) on the same scalars;
  msm    — the Pippenger sort+scan kernel on HOST-computed digits vs a
           native-host expected point: points are g_i*G with known g_i,
           so expected = (sum a_i*g_i mod L)*G needs ONE scalar-mul.

Window-vs-size discrimination matrix (each line is one short device run;
`touch .hw/LOCK` first so the sweep watcher yields the tunnel):

  python benches/debug_pip16k.py --m 65538 --c 13 --stage digits
  python benches/debug_pip16k.py --m 65538 --c 13 --stage msm
  python benches/debug_pip16k.py --m 65538 --c 11 --stage msm   # size only
  python benches/debug_pip16k.py --m 16386 --c 13 --stage msm   # window only

Reference analog of the computation under test: the accumulation loop at
`src/verifier/batch.rs:271-312` this kernel replaces.
"""

from __future__ import annotations

import argparse
import json
import os
import secrets
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# cpzk_tpu.ops modules build jax arrays at import time, which initializes
# the backend — on a wedged axon tunnel that HANGS before --platform can
# apply.  Import lazily in _load(), called after the platform pin.
_native = he = hs = backend = curve = msm = sc = None


def _load() -> None:
    global _native, he, hs, backend, curve, msm, sc
    from cpzk_tpu.core import _native as _n
    from cpzk_tpu.core import edwards as _he
    from cpzk_tpu.core import scalars as _hs
    from cpzk_tpu.ops import backend as _b, curve as _c, msm as _m
    from cpzk_tpu.ops import sclimbs as _sc

    _native, he, hs, backend, curve, msm, sc = _n, _he, _hs, _b, _c, _m, _sc


def emit(**kw) -> None:
    print(json.dumps(kw), flush=True)


def stage_digits(m: int, c: int) -> bool:
    vals = [secrets.randbelow(hs.L) for _ in range(m)]
    t0 = time.monotonic()
    host = np.asarray(msm.scalars_to_signed_digits(vals, c))
    limbs = jnp.asarray(sc.ints_to_limbs(vals))
    dev = np.asarray(jax.device_get(backend._signed_digits_jit(c, limbs)))
    bad = np.argwhere(dev != host)
    rec = {
        "stage": "digits", "m": m, "c": c,
        "match": bool(bad.size == 0),
        "mismatch_cells": int(bad.shape[0]),
        "secs": round(time.monotonic() - t0, 1),
        "platform": jax.devices()[0].platform,
    }
    if bad.size:
        k, col = (int(v) for v in bad[0])
        rec["first_bad"] = {
            "window": k, "col": col, "scalar": hex(vals[col]),
            "host_digit": int(host[k, col]), "dev_digit": int(dev[k, col]),
        }
        # full digit columns for the first few bad scalars: enough to
        # replay the recode by hand offline
        cols = sorted({int(v[1]) for v in bad[:64]})[:4]
        rec["bad_cols"] = {
            str(col): {"scalar": hex(vals[col]),
                       "host": [int(x) for x in host[:, col]],
                       "dev": [int(x) for x in dev[:, col]]}
            for col in cols
        }
    emit(**rec)
    return bool(bad.size == 0)


def stage_msm(m: int, c: int) -> bool:
    g_wire = he.ristretto_encode(he.BASEPOINT)
    gs = [secrets.randbelow(hs.L) for _ in range(m)]
    avals = [secrets.randbelow(hs.L) for _ in range(m)]
    t0 = time.monotonic()
    wires = b"".join(
        _native.scalarmul(g_wire, hs.sc_to_bytes(g)) for g in gs
    )
    expected_wire = _native.scalarmul(
        g_wire, hs.sc_to_bytes(sum(a * g for a, g in zip(avals, gs)) % hs.L)
    )
    setup_secs = round(time.monotonic() - t0, 1)

    pts = curve.wires_to_device(wires, m)
    digits = jnp.asarray(msm.scalars_to_signed_digits(avals, c))
    t1 = time.monotonic()
    fn = jax.jit(msm.msm_kernel, static_argnums=2)
    out = fn(pts, digits, c)
    got = curve.points_from_device(jax.device_get(out))[0]
    device_secs = round(time.monotonic() - t1, 1)
    # determinism probe: same inputs through the cached executable —
    # separates a deterministic codegen bug from flaky memory corruption
    out2 = fn(pts, digits, c)
    got2 = curve.points_from_device(jax.device_get(out2))[0]

    got_aff = tuple(v % he.P for v in got)
    got2_aff = tuple(v % he.P for v in got2)
    exp_pt = he.ristretto_decode(expected_wire)
    ok = he.pt_eq(got_aff, exp_pt)
    emit(stage="msm", m=m, c=c, match=bool(ok), setup_secs=setup_secs,
         device_secs=device_secs, platform=jax.devices()[0].platform,
         deterministic=bool(he.pt_eq(got_aff, got2_aff)),
         got=he.ristretto_encode(got_aff).hex(),
         expected=expected_wire.hex())
    return bool(ok)


def _sample_cols(pt, cols):
    """Affine host points for the given lane columns of a device Point."""
    sub = tuple(np.asarray(jax.device_get(c))[:, cols] for c in pt)
    return [tuple(v % he.P for v in p) for p in curve.points_from_device(sub)]


def stage_addlanes(m: int) -> bool:
    """Elementwise R = P + Q over m lanes; host-verify 64 sampled lanes.

    The deepest isolation: rowcombined (no sort/scan) and the MSM
    (sort+scan) both fail past ~33k lanes, so the shared suspect is the
    lane-parallel extended-coordinate add itself under large lane counts.
    """
    g_wire = he.ristretto_encode(he.BASEPOINT)
    gp = [secrets.randbelow(hs.L) for _ in range(m)]
    gq = [secrets.randbelow(hs.L) for _ in range(m)]
    t0 = time.monotonic()
    wp = b"".join(_native.scalarmul(g_wire, hs.sc_to_bytes(g)) for g in gp)
    wq = b"".join(_native.scalarmul(g_wire, hs.sc_to_bytes(g)) for g in gq)
    setup_secs = round(time.monotonic() - t0, 1)
    P = curve.wires_to_device(wp, m)
    Q = curve.wires_to_device(wq, m)
    t1 = time.monotonic()
    R = jax.jit(curve.add)(P, Q)
    jax.block_until_ready(R)
    device_secs = round(time.monotonic() - t1, 1)
    cols = sorted({secrets.randbelow(m) for _ in range(64)})
    got = _sample_cols(R, cols)
    bad = []
    for col, gpt in zip(cols, got):
        exp_wire = _native.scalarmul(
            g_wire, hs.sc_to_bytes((gp[col] + gq[col]) % hs.L))
        if not he.pt_eq(gpt, he.ristretto_decode(exp_wire)):
            bad.append(col)
    emit(stage="addlanes", m=m, match=not bad, bad_lanes=bad[:8],
         sampled=len(cols), setup_secs=setup_secs,
         device_secs=device_secs, platform=jax.devices()[0].platform)
    return not bad


def stage_sum(m: int) -> bool:
    """tree_sum of m lanes of known points vs ONE native scalar-mul."""
    g_wire = he.ristretto_encode(he.BASEPOINT)
    gp = [secrets.randbelow(hs.L) for _ in range(m)]
    t0 = time.monotonic()
    wp = b"".join(_native.scalarmul(g_wire, hs.sc_to_bytes(g)) for g in gp)
    setup_secs = round(time.monotonic() - t0, 1)
    P = curve.wires_to_device(wp, m)
    t1 = time.monotonic()
    S = jax.jit(lambda p: curve.tree_sum(p, axis=-1))(P)
    arrs = [np.asarray(jax.device_get(c)) for c in S]
    arrs = [a[:, None] if a.ndim == 1 else a for a in arrs]
    got = curve.points_from_device(tuple(arrs))[0]
    device_secs = round(time.monotonic() - t1, 1)
    exp_wire = _native.scalarmul(g_wire, hs.sc_to_bytes(sum(gp) % hs.L))
    ok = he.pt_eq(tuple(v % he.P for v in got), he.ristretto_decode(exp_wire))
    emit(stage="sum", m=m, match=bool(ok), setup_secs=setup_secs,
         device_secs=device_secs, platform=jax.devices()[0].platform)
    return bool(ok)


def stage_threadlat() -> bool:
    """Main-thread vs worker-thread dispatch latency for the same cached
    executable (PROFILE.md §7c: the serving batcher verifies on a worker
    thread via asyncio.to_thread; the fast direct path runs on the main
    thread — a thread-dependent per-call penalty on the axon tunnel
    would explain the gRPC-on-device collapse).  Two sizes: tiny (pure
    dispatch) and ~5 MB (includes transfer)."""
    import concurrent.futures

    rec = {"stage": "threadlat", "platform": jax.devices()[0].platform}
    for label, shape in (("tiny", (1024,)), ("5mb", (1310720,))):
        f = jax.jit(lambda x: x + 1)
        x = jnp.zeros(shape, dtype=jnp.float32)
        jax.block_until_ready(f(x))

        def call():
            t0 = time.monotonic()
            jax.block_until_ready(f(x))
            return time.monotonic() - t0

        main = sorted(call() for _ in range(20))
        with concurrent.futures.ThreadPoolExecutor(1) as ex:
            worker = sorted(ex.submit(call).result() for _ in range(20))
        rec[f"{label}_main_med_ms"] = round(main[10] * 1e3, 2)
        rec[f"{label}_worker_med_ms"] = round(worker[10] * 1e3, 2)
    emit(**rec)
    return True


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--m", type=int, default=65538)
    ap.add_argument("--c", type=int, default=13)
    ap.add_argument("--stage",
                    choices=["digits", "msm", "addlanes", "sum", "threadlat",
                             "all"],
                    default="all")
    ap.add_argument("--platform", default=None,
                    help="force a jax backend (e.g. cpu); needed because "
                         "the axon sitecustomize pre-imports jax, so "
                         "JAX_PLATFORMS alone does not reach its config")
    args = ap.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    if args.stage != "threadlat":
        # threadlat uses bare jax only; skipping the ops-stack import
        # keeps the probe cheap and avoids import-time device touches
        _load()
    ok = True
    if args.stage in ("digits", "all"):
        ok &= stage_digits(args.m, args.c)
    if args.stage in ("msm", "all"):
        ok &= stage_msm(args.m, args.c)
    if args.stage in ("addlanes", "all"):
        ok &= stage_addlanes(args.m)
    if args.stage in ("threadlat", "all"):
        ok &= stage_threadlat()
    if args.stage in ("sum", "all"):
        # NOTE: hangs >25 min at m=65536 on TPU v5 lite (the large-lane
        # monolith pathology under investigation) — run last so the
        # other stages' verdicts land first
        ok &= stage_sum(args.m)
    raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
