"""Turn a hardware sweep (.hw/) into calibration DECISIONS.

VERDICT r3 item 2: "calibrate from measurement, then delete the losers."
The sweep (.hardware_sweep.sh) measures; this script reads its outputs
and prints the verdicts the flags are waiting on:

- CPZK_MSM_WINDOW   — best measured window vs `msm.pick_window`'s model;
- CPZK_PIPPENGER_MIN — rowcombined/pippenger crossover from the small-N
  bench points vs the 16k/64k points;
- CPZK_PALLAS        — graduate (make default) or drop, from the
  point-op A/B;
- CPZK_MUL           — same rule for the matmulfold experiment.

Usage: python benches/calibrate.py [dir]   (default .hw)
Prints a PROFILE.md-ready section; exits 1 when the sweep is too
incomplete to decide anything (so automation notices).
"""

from __future__ import annotations

import json
import os
import re
import sys


def _records(path: str):
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        continue
    except OSError:
        return


def _value(path: str, metric: str | None = None) -> float | None:
    for rec in _records(path):
        if metric is None or rec.get("metric") == metric or rec.get("name") == metric:
            v = rec.get("value")
            if isinstance(v, (int, float)) and v > 0:
                return float(v)
    return None


def main() -> None:
    d = sys.argv[1] if len(sys.argv) > 1 else ".hw"
    if not os.path.isdir(d):
        raise SystemExit(f"no sweep directory {d!r}")
    decided = 0
    print("## Hardware calibration (from the sweep in %s)\n" % d)

    # 1. window sweep
    wins: dict[int, float] = {}
    for name in os.listdir(d):
        m = re.fullmatch(r"win_(\d+)\.json", name)
        if m:
            v = _value(os.path.join(d, name))
            if v:
                wins[int(m.group(1))] = v
    if wins:
        best_w = max(wins, key=lambda w: wins[w])
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "msm", os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "cpzk_tpu", "ops", "msm.py"))
        model_w = None
        try:
            sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
            from cpzk_tpu.ops import msm

            model_w = msm.pick_window(4 * 16384 + 2)
        except Exception:
            pass
        print(f"- **CPZK_MSM_WINDOW**: measured best c={best_w} "
              f"({wins[best_w]:.0f} proofs/s at 16k; all: "
              f"{ {w: round(v) for w, v in sorted(wins.items())} }); "
              f"cost model picks c={model_w}.")
        if model_w is not None and model_w != best_w:
            print(f"  -> FIX `msm.pick_window` so the model lands on "
                  f"c={best_w} at m=4*16384+2, then delete the env knob "
                  "from the serving docs.")
        else:
            print("  -> model agrees; keep it, drop the knob from docs.")
        decided += 1
    else:
        print("- CPZK_MSM_WINDOW: no win_*.json points yet.")

    # 2. crossover
    small = {n: _value(os.path.join(d, f"cross_{n}.json")) for n in (1024, 4096)}
    big = {n: _value(os.path.join(d, f"bench_{n//1024}k.json")) for n in (16384, 65536)}
    have = {**{k: v for k, v in small.items() if v},
            **{k: v for k, v in big.items() if v}}
    if have:
        print(f"- **CPZK_PIPPENGER_MIN**: measured proofs/s by N: "
              f"{ {n: round(v) for n, v in sorted(have.items())} } "
              "(auto mode records the faster of rowcombined/pippenger; "
              "per-kernel rows are in the .err/.json files).")
        print("  -> set PIPPENGER_MIN_ROWS to the smallest N where the "
              "pippenger kernel wins its A/B, and delete the env knob.")
        decided += 1
    else:
        print("- CPZK_PIPPENGER_MIN: no crossover points yet.")

    # 3. pallas graduate-or-drop
    xla = _value(os.path.join(d, "point_xla.json"))
    pal = _value(os.path.join(d, "point_pallas.json"))
    if xla and pal:
        ratio = pal / xla
        verdict = "GRADUATE (make default)" if ratio >= 1.1 else (
            "DROP (delete ops/pallas_kernels.py + the flag)" if ratio <= 0.95
            else "keep behind the flag (within noise)")
        print(f"- **CPZK_PALLAS**: pallas/xla point-op ratio {ratio:.2f} "
              f"-> {verdict}.")
        decided += 1
    else:
        print("- CPZK_PALLAS: missing point_xla/point_pallas A/B.")

    # 4. mul A/B
    mul = _value(os.path.join(d, "mul.json"))
    if mul:
        print(f"- **CPZK_MUL**: mul A/B recorded ({mul:.0f}); apply the "
              "same graduate-or-drop rule from the per-config rows in "
              "mul.json.")
        decided += 1
    else:
        print("- CPZK_MUL: no mul A/B yet.")

    if decided == 0:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
