"""Device throughput scaling curve: combined-check proofs/sec over N.

Runs the repo-root ``bench.py`` (device-kernel timing) in one guarded
subprocess per (N, kernel) configuration — VERDICT r1 asked for a measured
scaling curve at N in {2k, 16k, 64k} as the credible path toward the
BASELINE.md north star.  Prints one JSON line per configuration.

Usage: python benches/bench_scaling.py [--sizes 2048,16384,65536]
       [--kernels rowcombined,pippenger] [--platform cpu] [--guard-secs S]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="2048,16384,65536")
    ap.add_argument("--kernels", default="rowcombined,pippenger")
    ap.add_argument("--platform", default=None)
    ap.add_argument("--guard-secs", type=int, default=1200)
    ap.add_argument("--iters", type=int, default=3)
    args = ap.parse_args()

    for n in (int(s) for s in args.sizes.split(",")):
        for kernel in args.kernels.split(","):
            env = dict(
                os.environ,
                CPZK_BENCH_N=str(n),
                CPZK_BENCH_KERNEL=kernel,
                CPZK_BENCH_ITERS=str(args.iters),
            )
            if args.platform:
                env["CPZK_BENCH_PLATFORM"] = args.platform
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.join(ROOT, "bench.py")],
                    env=env, capture_output=True, text=True,
                    timeout=args.guard_secs,
                )
            except subprocess.TimeoutExpired:
                print(json.dumps({"name": "combined_check", "kernel": kernel,
                                  "n": n, "error": "timeout"}))
                continue
            if proc.returncode != 0:
                print(json.dumps({"name": "combined_check", "kernel": kernel,
                                  "n": n, "error": proc.stderr[-300:]}))
                continue
            data = json.loads(proc.stdout.strip().splitlines()[-1])
            print(
                json.dumps(
                    {
                        "name": "combined_check",
                        "kernel": kernel,
                        "n": n,
                        "value": data["value"],
                        "unit": "proofs/s",
                        "vs_baseline": data["vs_baseline"],
                    }
                )
            )


if __name__ == "__main__":
    main()
