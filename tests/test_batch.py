"""Batch verifier tests (mirrors reference batch.rs:332-512 inline tests).

Key deviation under test: our combined RLC equation is *corrected*
(SURVEY.md §3.2), so the fast path actually succeeds for all-valid batches —
asserted here by checking verify_combined directly."""

import pytest

from cpzk_tpu import (
    BatchVerifier,
    InvalidParams,
    Parameters,
    Prover,
    Ristretto255,
    SecureRng,
    Statement,
    Transcript,
    Witness,
)
from cpzk_tpu.protocol.batch import MAX_BATCH_SIZE, CpuBackend


@pytest.fixture(scope="module")
def rng():
    return SecureRng()


@pytest.fixture(scope="module")
def params():
    return Parameters.new()


def make_entry(params, rng, context=None):
    x = Ristretto255.random_scalar(rng)
    prover = Prover(params, Witness(x))
    if context is None:
        proof = prover.prove(rng)
    else:
        t = Transcript()
        t.append_context(context)
        proof = prover.prove_with_transcript(rng, t)
    return prover.statement, proof


def test_empty_batch_rejected(rng):
    with pytest.raises(InvalidParams):
        BatchVerifier().verify(rng)


def test_single_proof_batch(params, rng):
    batch = BatchVerifier()
    st, proof = make_entry(params, rng)
    batch.add(params, st, proof)
    assert len(batch) == 1
    results = batch.verify(rng)
    assert results == [None]


def test_all_valid_batch(params, rng):
    batch = BatchVerifier()
    for _ in range(8):
        st, proof = make_entry(params, rng)
        batch.add(params, st, proof)
    results = batch.verify(rng)
    assert all(r is None for r in results)


def test_combined_fast_path_succeeds(params, rng):
    """The corrected RLC combined equation must accept an all-valid batch
    (the reference's buggy equation always fails here — SURVEY.md §3.2)."""
    batch = BatchVerifier(backend=CpuBackend())
    for _ in range(5):
        st, proof = make_entry(params, rng)
        batch.add(params, st, proof)
    rows = batch.prepare_rows(rng)
    beta = Ristretto255.random_scalar(rng)
    assert CpuBackend().verify_combined(rows, beta) is True


def test_mixed_validity_batch(params, rng):
    batch = BatchVerifier()
    st1, proof1 = make_entry(params, rng)
    batch.add(params, st1, proof1)
    # invalid: proof bound to a different context than verification expects
    st2, proof2 = make_entry(params, rng, context=b"other-context")
    batch.add(params, st2, proof2)  # verified without context -> must fail
    st3, proof3 = make_entry(params, rng)
    batch.add(params, st3, proof3)

    results = batch.verify(rng)
    assert results[0] is None
    assert isinstance(results[1], InvalidParams)
    assert results[2] is None


def test_batch_with_contexts(params, rng):
    batch = BatchVerifier()
    for i in range(3):
        ctx = f"challenge-{i}".encode()
        st, proof = make_entry(params, rng, context=ctx)
        batch.add_with_context(params, st, proof, ctx)
    assert all(r is None for r in batch.verify(rng))


def test_wrong_statement_in_batch(params, rng):
    batch = BatchVerifier()
    st1, proof1 = make_entry(params, rng)
    st2, _ = make_entry(params, rng)
    batch.add(params, st2, proof1)  # statement/proof mismatch
    results = batch.verify(rng)
    assert isinstance(results[0], InvalidParams)


def test_capacity_limit(params, rng):
    batch = BatchVerifier()
    batch.entries = [None] * MAX_BATCH_SIZE  # simulate full
    st, proof = make_entry(params, rng)
    with pytest.raises(InvalidParams):
        batch.add(params, st, proof)
    batch.entries = []
    assert batch.remaining_capacity() == MAX_BATCH_SIZE
