"""Deferred proof parsing: the serving-path fast parse postpones commitment
point decodes to the batch-verify stage (one decode per point across
ingress+verify).  These tests pin the invariant that deferral is
OBSERVATIONALLY IDENTICAL to eager parsing — same accept/reject set, same
error messages (reference ``gadgets.rs:364-489`` / ``service.rs:407-617``)
— across the gadget, dispatcher, and gRPC layers.
"""

import asyncio

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.client import AuthClient
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.errors import Error, InvalidProofEncoding
from cpzk_tpu.protocol.batch import BatchVerifier
from cpzk_tpu.protocol.gadgets import PROOF_WIRE_SIZE, Proof
from cpzk_tpu.server import RateLimiter, ServerState
from cpzk_tpu.server.service import serve

BAD_POINT_MSG = "Bytes do not represent a valid Ristretto point"


def _proof_corpus():
    """One valid wire plus every malformed family the parser rejects."""
    rng = SecureRng()
    params = Parameters.new()
    prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
    t = Transcript()
    t.append_context(b"ctx")
    wire = prover.prove_with_transcript(rng, t).to_bytes()
    assert len(wire) == PROOF_WIRE_SIZE
    l_bytes = (2**252 + 27742317777372353535851937790883648493).to_bytes(32, "little")
    return wire, [
        wire,
        wire[:50],                                # truncated
        b"",                                      # empty
        b"\x02" + wire[1:],                       # bad version
        wire[:5] + bytes(32) + wire[37:],         # identity r1
        wire[:41] + bytes(32) + wire[73:],        # identity r2
        wire[:5] + b"\xff" * 32 + wire[37:],      # invalid r1 point
        wire[:41] + b"\xff" * 32 + wire[73:],     # invalid r2 point
        wire[:77] + bytes(32),                    # zero scalar
        wire[:77] + l_bytes,                      # non-canonical scalar (= l)
        wire + b"\x00",                           # trailing byte
        wire[:1] + b"\x00\x00\x00\x21" + wire[5:],  # wrong length field
    ]


def _eager_result(item):
    try:
        Proof.from_bytes(item)
        return "OK"
    except Error as e:
        return f"{type(e).__name__}: {e}"


def test_from_bytes_batch_eager_differential():
    _, corpus = _proof_corpus()
    for got, item in zip(Proof.from_bytes_batch(corpus), corpus):
        want = _eager_result(item)
        if isinstance(got, Proof):
            assert want == "OK"
            assert not got.deferred
            assert got.to_bytes() == item
        else:
            assert f"{type(got).__name__}: {got}" == want


def test_from_bytes_batch_deferred_differential():
    """Deferred mode: only point-decode failures may surface later (as a
    deferred Proof); every other malformation errors identically here."""
    _, corpus = _proof_corpus()
    for got, item in zip(
        Proof.from_bytes_batch(corpus, defer_point_validation=True), corpus
    ):
        want = _eager_result(item)
        if isinstance(got, Proof):
            if want != "OK":  # postponed decode failure, settled at verify
                assert BAD_POINT_MSG in want
                assert got.deferred
        else:
            assert f"{type(got).__name__}: {got}" == want


def _entries_for(n):
    """n independent (params, statement, proof-wire, context) tuples."""
    rng = SecureRng()
    params = Parameters.new()
    out = []
    for i in range(n):
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        ctx = b"ctx-%d" % i
        t = Transcript()
        t.append_context(ctx)
        wire = prover.prove_with_transcript(rng, t).to_bytes()
        out.append((params, prover.statement, wire, ctx))
    return out


def test_batch_verifier_settles_deferred_rows():
    """Multi-row dispatch: valid deferred rows pass, an undecodable
    commitment wire maps to the exact parse error, a wrong-context row is
    a plain verification failure — all in one pass."""
    entries = _entries_for(4)
    wires = [w for _, _, w, _ in entries]
    wires[1] = wires[1][:5] + b"\xff" * 32 + wires[1][37:]  # bad r1 point
    parsed = Proof.from_bytes_batch(wires, defer_point_validation=True)
    assert all(isinstance(p, Proof) and p.deferred for p in parsed)

    bv = BatchVerifier()
    for (params, stmt, _, ctx), proof in zip(entries, parsed):
        use_ctx = b"wrong" if ctx == b"ctx-3" else ctx
        bv.add_with_context(params, stmt, proof, use_ctx)
    results = bv.verify(SecureRng())
    assert results[0] is None and results[2] is None
    assert isinstance(results[1], InvalidProofEncoding)
    assert str(results[1]) == BAD_POINT_MSG
    assert results[3] is not None and not isinstance(results[3], InvalidProofEncoding)


def test_batch_verifier_single_deferred_row():
    """n == 1 screens eagerly: a bad wire errors with parse parity, a good
    one verifies through the individual path."""
    (params, stmt, wire, ctx), = _entries_for(1)

    good, = Proof.from_bytes_batch([wire], defer_point_validation=True)
    bv = BatchVerifier()
    bv.add_with_context(params, stmt, good, ctx)
    assert bv.verify(SecureRng()) == [None]

    bad_wire = wire[:41] + b"\xff" * 32 + wire[73:]
    bad, = Proof.from_bytes_batch([bad_wire], defer_point_validation=True)
    if isinstance(bad, Proof):  # native frame path present -> deferred
        bv = BatchVerifier()
        bv.add_with_context(params, stmt, bad, ctx)
        res, = bv.verify(SecureRng())
        assert isinstance(res, InvalidProofEncoding) and str(res) == BAD_POINT_MSG


def test_grpc_batch_reports_exact_parse_error_for_bad_point():
    """End to end: the inline serving path defers parsing, yet a bad-point
    item still reports the eager parse message and consumes its challenge;
    valid siblings authenticate."""

    async def flow():
        state = ServerState()
        server, port = await serve(
            state, RateLimiter(10_000, 10_000), host="127.0.0.1", port=0
        )
        try:
            rng = SecureRng()
            async with AuthClient(f"127.0.0.1:{port}") as client:
                users = []
                for i in range(3):
                    prover = Prover(
                        Parameters.new(), Witness(Ristretto255.random_scalar(rng))
                    )
                    resp = await client.register(
                        f"dp{i}",
                        Ristretto255.element_to_bytes(prover.statement.y1),
                        Ristretto255.element_to_bytes(prover.statement.y2),
                    )
                    assert resp.success
                    users.append((f"dp{i}", prover))

                ids, cids, proofs = [], [], []
                for user_id, prover in users:
                    ch = await client.create_challenge(user_id)
                    cid = bytes(ch.challenge_id)
                    t = Transcript()
                    t.append_context(cid)
                    proofs.append(prover.prove_with_transcript(rng, t).to_bytes())
                    ids.append(user_id)
                    cids.append(cid)
                proofs[1] = proofs[1][:5] + b"\xff" * 32 + proofs[1][37:]

                resp = await client.verify_proof_batch(ids, cids, proofs)
                assert [r.success for r in resp.results] == [True, False, True]
                assert resp.results[1].message == f"Invalid proof: {BAD_POINT_MSG}"
                assert await state.challenge_count() == 0  # all consumed
        finally:
            await server.stop(None)

    asyncio.run(flow())


def test_grpc_batcher_path_reports_exact_parse_error_for_bad_point():
    """Same contract THROUGH the batcher -> dispatch lane: proofs defer
    parsing at the RPC layer, the lane's prep thread settles the decode
    (BatchVerifier screening / tri-state), and a bad-point item still
    reports the exact eager-parse message while siblings authenticate."""
    from cpzk_tpu.protocol.batch import CpuBackend
    from cpzk_tpu.server.batching import DynamicBatcher

    async def flow():
        state = ServerState()
        batcher = DynamicBatcher(CpuBackend(), max_batch=64, window_ms=5.0)
        server, port = await serve(
            state, RateLimiter(10_000, 10_000), host="127.0.0.1", port=0,
            batcher=batcher,
        )
        try:
            rng = SecureRng()
            async with AuthClient(f"127.0.0.1:{port}") as client:
                users = []
                for i in range(3):
                    prover = Prover(
                        Parameters.new(), Witness(Ristretto255.random_scalar(rng))
                    )
                    resp = await client.register(
                        f"dpl{i}",
                        Ristretto255.element_to_bytes(prover.statement.y1),
                        Ristretto255.element_to_bytes(prover.statement.y2),
                    )
                    assert resp.success
                    users.append((f"dpl{i}", prover))

                ids, cids, proofs = [], [], []
                for user_id, prover in users:
                    ch = await client.create_challenge(user_id)
                    cid = bytes(ch.challenge_id)
                    t = Transcript()
                    t.append_context(cid)
                    proofs.append(prover.prove_with_transcript(rng, t).to_bytes())
                    ids.append(user_id)
                    cids.append(cid)
                proofs[1] = proofs[1][:5] + b"\xff" * 32 + proofs[1][37:]

                resp = await client.verify_proof_batch(ids, cids, proofs)
                assert [r.success for r in resp.results] == [True, False, True]
                assert resp.results[1].message == f"Invalid proof: {BAD_POINT_MSG}"
                assert await state.challenge_count() == 0  # all consumed
        finally:
            await batcher.stop()
            await server.stop(None)

    asyncio.run(flow())
