"""VerifyProofStream tests: verdict correctness and ordering over real
gRPC, session minting, per-proof keyed admission with mid-stream
pushback (the hot-streamer chaos case), per-entry deadline shedding,
backend-raise confinement, disconnect-leak-freedom (reusing the
DispatchLane leak contract), chunk validation, and the client APIs."""

import asyncio

import grpc
import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.admission import AdmissionController
from cpzk_tpu.client import AuthClient
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.protocol.batch import CpuBackend, VerifierBackend
from cpzk_tpu.server import RateLimiter, ServerState
from cpzk_tpu.server.batching import DynamicBatcher
from cpzk_tpu.server.config import AdmissionSettings
from cpzk_tpu.server.service import MAX_STREAM_CHUNK, serve

EB = Ristretto255.element_to_bytes


def run(coro):
    return asyncio.run(coro)


class ExplodingBackend(VerifierBackend):
    """Raises for the first ``explode_times`` batches, then verifies."""

    prefers_combined = False

    def __init__(self, explode_times=0):
        self.calls = 0
        self.explode_times = explode_times
        self._inner = CpuBackend()

    def verify_combined(self, rows, beta):  # pragma: no cover - unused
        raise AssertionError("prefers_combined is False")

    def verify_each(self, rows):
        self.calls += 1
        if self.calls <= self.explode_times:
            raise RuntimeError("injected device loss")
        return self._inner.verify_each(rows)


class Harness:
    """One loopback server + registered provers + login-entry factory."""

    def __init__(self, users=8, **serve_kwargs):
        self.users = users
        self.serve_kwargs = serve_kwargs
        self.rng = SecureRng()
        self.params = Parameters.new()
        self.provers = [
            Prover(self.params, Witness(Ristretto255.random_scalar(self.rng)))
            for _ in range(users)
        ]
        self.state = ServerState()

    async def __aenter__(self):
        self.server, self.port = await serve(
            self.state, RateLimiter(10**9, 10**9), port=0,
            **self.serve_kwargs,
        )
        self.client = AuthClient(f"127.0.0.1:{self.port}")
        resp = await self.client.register_batch(
            [f"u{i}" for i in range(self.users)],
            [EB(p.statement.y1) for p in self.provers],
            [EB(p.statement.y2) for p in self.provers],
        )
        assert all(r.success for r in resp.results)
        return self

    async def __aexit__(self, *exc):
        await self.client.close()
        batcher = self.serve_kwargs.get("batcher")
        if batcher is not None:
            await batcher.stop()
        await self.server.stop(None)

    async def entries(self, n, corrupt=(), wrong_user=(), direct=False):
        """Login-ready (user, challenge_id, proof) tuples.  ``direct``
        mints challenges straight into server state — for tests whose
        admission config would shed the setup RPCs themselves."""
        out = []
        for k in range(n):
            u = k % self.users
            if direct:
                cid = self.state.tag_challenge_id(
                    f"u{u}", self.rng.fill_bytes(32))
                await self.state.create_challenge(f"u{u}", cid)
            else:
                ch = await self.client.create_challenge(f"u{u}")
                cid = bytes(ch.challenge_id)
            t = Transcript()
            t.append_context(cid)
            wire = self.provers[u].prove_with_transcript(self.rng, t).to_bytes()
            if k in corrupt:
                wire = wire[:-1] + bytes([wire[-1] ^ 1])
            uid = f"u{(u + 1) % self.users}" if k in wrong_user else f"u{u}"
            out.append((uid, cid, wire))
        return out


# --- verdict correctness -----------------------------------------------------


def test_stream_verdicts_ordered_and_correct():
    async def main():
        backend = CpuBackend()
        batcher = DynamicBatcher(backend, max_batch=16, window_ms=1.0)
        async with Harness(backend=backend, batcher=batcher) as h:
            entries = await h.entries(24, corrupt={3}, wrong_user={5})
            verdicts = [
                v async for v in h.client.verify_proof_stream(
                    entries, chunk=7)
            ]
            assert [v.id for v in verdicts] == list(range(24))
            for v in verdicts:
                if v.id == 3:
                    assert not v.ok and v.message == "Authentication failed"
                elif v.id == 5:
                    # wrong user for the challenge: consumed AND refused
                    assert not v.ok and v.message == "Authentication failed"
                else:
                    assert v.ok, (v.id, v.message)
                    assert v.session_token is None  # mint off by default
    run(main())


def test_stream_mints_sessions_on_request():
    async def main():
        backend = CpuBackend()
        batcher = DynamicBatcher(backend, max_batch=16, window_ms=1.0)
        async with Harness(backend=backend, batcher=batcher) as h:
            entries = await h.entries(6)
            verdicts = [
                v async for v in h.client.verify_proof_stream(
                    entries, mint_sessions=True)
            ]
            assert all(v.ok and v.session_token for v in verdicts)
            assert await h.state.session_count() == 6
            # the minted token is a real session
            user = await h.state.validate_session(
                verdicts[0].session_token)
            assert user == "u0"
    run(main())


def test_stream_inline_cpu_path_without_batcher():
    """No batcher wired (reference-parity inline config): the stream
    still answers through the shared dispatch seam."""
    async def main():
        async with Harness(backend=None, batcher=None) as h:
            entries = await h.entries(5, corrupt={2})
            oks = [
                v.ok async for v in h.client.verify_proof_stream(entries)
            ]
            assert oks == [True, True, False, True, True]
    run(main())


def test_stream_consumes_challenges_single_use():
    async def main():
        backend = CpuBackend()
        batcher = DynamicBatcher(backend, max_batch=16, window_ms=1.0)
        async with Harness(backend=backend, batcher=batcher) as h:
            entries = await h.entries(3)
            first = [
                v.ok async for v in h.client.verify_proof_stream(entries)
            ]
            assert first == [True] * 3
            # resend: every challenge is already consumed
            second = [
                v async for v in h.client.verify_proof_stream(entries)
            ]
            assert all(not v.ok for v in second)
            assert all(
                v.message == "Authentication failed" for v in second)
    run(main())


# --- chunk validation --------------------------------------------------------


def test_stream_malformed_chunks_answered_not_fatal():
    async def main():
        backend = CpuBackend()
        batcher = DynamicBatcher(backend, max_batch=16, window_ms=1.0)
        async with Harness(backend=backend, batcher=batcher) as h:
            pb2 = h.client.pb2
            call = h.client._stream_stub()
            # mismatched arrays
            await call.write(pb2.StreamVerifyRequest(
                ids=[0, 1], user_ids=["u0"], challenge_ids=[b"x"],
                proofs=[b"y"]))
            # oversized chunk
            n = MAX_STREAM_CHUNK + 1
            await call.write(pb2.StreamVerifyRequest(
                ids=list(range(n)), user_ids=["u0"] * n,
                challenge_ids=[b"x"] * n, proofs=[b"y"] * n))
            # then a real login: the stream is still alive
            (uid, cid, wire), = await h.entries(1)
            await call.write(pb2.StreamVerifyRequest(
                ids=[7], user_ids=[uid], challenge_ids=[cid],
                proofs=[wire]))
            await call.done_writing()
            resps = [r async for r in call]
            assert len(resps) == 3
            assert list(resps[0].success) == [False, False]
            assert "Mismatched array lengths" in resps[0].messages[0]
            assert not any(resps[1].success)
            assert "maximum" in resps[1].messages[0]
            assert list(resps[2].ids) == [7]
            assert list(resps[2].success) == [True]
    run(main())


# --- admission: per-proof charging + mid-stream pushback --------------------


def test_hot_streamer_shed_per_proof_with_pushback_stream_survives():
    """Chaos case: a hot streamer blows through its keyed bucket mid-
    stream.  Its over-budget entries get NOT-verdicts with a retry delay
    (and the stream's trailing metadata carries cpzk-retry-after-ms);
    the stream is NOT killed, and in-budget entries still verify."""
    async def main():
        backend = CpuBackend()
        batcher = DynamicBatcher(backend, max_batch=64, window_ms=1.0)
        settings = AdmissionSettings(
            per_client_rpm=60, per_client_burst=10, max_clients=16,
        )
        admission = AdmissionController(settings, batcher=batcher)
        async with Harness(
            backend=backend, batcher=batcher, admission=admission,
        ) as h:
            entries = await h.entries(16, direct=True)
            pb2 = h.client.pb2
            call = h.client._stream_stub(
                metadata=(("cpzk-client-id", "hot-streamer"),)
            )
            ids = list(range(16))
            await call.write(pb2.StreamVerifyRequest(
                ids=ids,
                user_ids=[e[0] for e in entries],
                challenge_ids=[e[1] for e in entries],
                proofs=[e[2] for e in entries],
            ))
            await call.done_writing()
            resps = [r async for r in call]
            flat_ok = [s for r in resps for s in r.success]
            flat_msg = [m for r in resps for m in r.messages]
            # burst of 10 admitted and verified; the rest shed per proof
            assert sum(flat_ok) == 10
            shed = [m for ok, m in zip(flat_ok, flat_msg) if not ok]
            assert all("rate limit" in m.lower() for m in shed)
            assert any(r.retry_after_ms > 0 for r in resps)
            code = await call.code()
            assert code == grpc.StatusCode.OK  # stream survived
            trailing = {
                str(k): v for k, v in (await call.trailing_metadata() or ())
            }
            assert float(trailing["cpzk-retry-after-ms"]) > 0

            # a well-behaved client (own bucket) is unaffected
            entries2 = await h.entries(4, direct=True)
            async with AuthClient(
                f"127.0.0.1:{h.port}", client_id="polite"
            ) as polite:
                oks = [
                    v.ok async for v in polite.verify_proof_stream(entries2)
                ]
            assert oks == [True] * 4
    run(main())


# --- per-entry deadline shedding ---------------------------------------------


def test_stream_entry_deadline_sheds_with_per_entry_not_verdicts():
    async def main():
        backend = CpuBackend()
        batcher = DynamicBatcher(backend, max_batch=16, window_ms=20.0)
        async with Harness(
            backend=backend, batcher=batcher,
            stream_entry_deadline_ms=0.01,  # expires before the window
        ) as h:
            entries = await h.entries(5)
            verdicts = [
                v async for v in h.client.verify_proof_stream(entries)
            ]
            assert len(verdicts) == 5
            assert all(not v.ok for v in verdicts)
            assert all(
                v.message == "Deadline expired before verification"
                for v in verdicts
            )
            # the challenges were still consumed (consume precedes
            # verification, deadline or not) — single-use holds
            assert await h.state.challenge_count() == 0
    run(main())


# --- failure isolation -------------------------------------------------------


def test_backend_raise_confined_to_its_chunk_stream_survives():
    async def main():
        backend = ExplodingBackend(explode_times=1)
        batcher = DynamicBatcher(backend, max_batch=4, window_ms=1.0)
        async with Harness(backend=backend, batcher=batcher) as h:
            entries = await h.entries(8)
            # two chunks of 4 -> two device batches (max_batch=4); the
            # first explodes, the second must still verify
            pb2 = h.client.pb2
            call = h.client._stream_stub()
            for lo in (0, 4):
                part = entries[lo:lo + 4]
                await call.write(pb2.StreamVerifyRequest(
                    ids=list(range(lo, lo + 4)),
                    user_ids=[e[0] for e in part],
                    challenge_ids=[e[1] for e in part],
                    proofs=[e[2] for e in part],
                ))
                # settle chunk 1 before sending chunk 2 so the batcher
                # cannot coalesce them into one batch
                if lo == 0:
                    first = await call.read()
                    assert not any(first.success)
                    assert all(
                        m == "Verification unavailable"
                        for m in first.messages
                    )
            await call.done_writing()
            second = await call.read()
            assert second is not grpc.aio.EOF
            assert all(second.success), second.messages
            assert await call.read() is grpc.aio.EOF
            assert await call.code() == grpc.StatusCode.OK
    run(main())


# --- disconnect leak-freedom -------------------------------------------------


def test_client_disconnect_mid_stream_leaks_no_futures():
    """Abandon a stream with chunks in flight: the server tears the
    handler down, the batcher's in-flight accounting returns to zero
    (DispatchLane leak contract), and the NEXT stream works."""
    async def main():
        backend = CpuBackend()
        batcher = DynamicBatcher(backend, max_batch=8, window_ms=1.0)
        async with Harness(backend=backend, batcher=batcher) as h:
            entries = await h.entries(16)
            got = 0
            async for v in h.client.verify_proof_stream(entries, chunk=4):
                got += 1
                break  # abandon mid-stream (generator finally cancels)
            assert got == 1
            # drain: every queued/claimed entry must resolve or be shed
            deadline = asyncio.get_running_loop().time() + 5.0
            while asyncio.get_running_loop().time() < deadline:
                depth, _ = batcher.load_snapshot()
                if depth == 0:
                    break
                await asyncio.sleep(0.02)
            depth, _ = batcher.load_snapshot()
            assert depth == 0, "abandoned stream left entries in flight"
            # the server still serves: a fresh stream verifies cleanly
            entries2 = await h.entries(3)
            oks = [
                v.ok async for v in h.client.verify_proof_stream(entries2)
            ]
            assert oks == [True] * 3
    run(main())


# --- client API equivalence --------------------------------------------------


def test_chunk_iterator_and_verdict_iterator_agree():
    async def main():
        backend = CpuBackend()
        batcher = DynamicBatcher(backend, max_batch=16, window_ms=1.0)
        async with Harness(backend=backend, batcher=batcher) as h:
            entries = await h.entries(10, corrupt={4})
            flat = [
                (v.id, v.ok) async for v in h.client.verify_proof_stream(
                    entries, chunk=3)
            ]
            entries2 = await h.entries(10, corrupt={4})
            chunked = []
            async for ids, succ, msgs, toks, push in (
                h.client.verify_proof_stream_chunks(entries2, chunk=3)
            ):
                chunked.extend(zip(ids, succ))
                assert len(ids) == len(succ) == len(msgs)
            assert flat == [(i, i != 4) for i in range(10)]
            assert chunked == flat
    run(main())


def test_batcher_settled_results_mix_verdicts_and_exceptions():
    """The settled contract under the stream: a deadline-expired entry
    comes back AS its exception while batch siblings carry verdicts —
    via both submit_many(settled=True) and the group-future enqueue."""
    import time as _time

    from cpzk_tpu import Parameters, SecureRng
    from cpzk_tpu.protocol.batch import BatchEntry
    from cpzk_tpu.server.batching import DeadlineExceeded

    rng = SecureRng()
    params = Parameters.new()

    def login_entries():
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        out = []
        for k in range(3):
            ctx = b"settled-%d" % k
            t = Transcript()
            t.append_context(ctx)
            proof = prover.prove_with_transcript(rng, t)
            out.append(BatchEntry(params, prover.statement, proof, ctx))
        out[1].deadline = _time.monotonic() - 1.0  # already expired
        return out

    async def main():
        batcher = DynamicBatcher(CpuBackend(), max_batch=8, window_ms=1.0)
        batcher.start()
        try:
            for submit in (
                lambda e: batcher.submit_many(e, settled=True),
                batcher.submit_group,
            ):
                results = await submit(login_entries())
                assert results[0] is None and results[2] is None
                assert isinstance(results[1], DeadlineExceeded)
        finally:
            await batcher.stop()

    run(main())


def test_stream_refused_on_unpromoted_standby():
    class FakeReplica:
        role = "standby"

    class FakeContext:
        def invocation_metadata(self):
            return ()

        def peer(self):
            return "ipv4:127.0.0.1:1"

        def time_remaining(self):
            return None

        async def abort(self, code, msg, **kw):
            raise RuntimeError(f"aborted:{code.name}:{msg}")

    from cpzk_tpu.server.service import AuthServiceImpl

    async def main():
        service = AuthServiceImpl(
            ServerState(), RateLimiter(10**9, 10**9),
            replica=FakeReplica(),
        )

        async def no_requests():
            return
            yield  # pragma: no cover

        agen = service.verify_proof_stream(no_requests(), FakeContext())
        with pytest.raises(RuntimeError, match="aborted:UNAVAILABLE"):
            await agen.__anext__()
    run(main())
