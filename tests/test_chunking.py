"""Lane-chunked dispatch differentials (the PROFILE.md §7a workaround).

On TPU v5 lite, monolithic device programs past ~33k lanes miscompile:
deterministic wrong MSM output at m>=40,962, an internal XLA error at
49,154, all-zero output buffers at 57,346 (benches/debug_pip16k.py),
and the per-row combined kernel fails its in-kernel check at 65,538
rows.  The backend therefore tiles large batches into ``LANE_CHUNK``-lane
programs and adds partial points (``ops/backend.py``).

These tests force MULTI-chunk execution with a tiny chunk size on the
CPU backend and require bit-identical accept/reject against the host
oracle — the same differential bar as tests/test_tpu_backend.py
(reference semantics: ``src/verifier/batch.rs:171-318``).
"""

import pytest

from cpzk_tpu import BatchVerifier, SecureRng, Statement, Witness
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.ops import backend as backend_mod
from cpzk_tpu.ops.backend import TpuBackend, _pad_lanes
from cpzk_tpu.protocol.batch import CpuBackend

from test_tpu_backend import make_entries


@pytest.fixture
def tiny_chunks(monkeypatch):
    monkeypatch.setattr(backend_mod, "LANE_CHUNK", 8)


def _run(backend, entries):
    bv = BatchVerifier(backend=backend)
    for p, st, pr in entries:
        bv.add(p, st, pr)
    return [e is None for e in bv.verify(SecureRng())]


def test_pad_lanes_schedule(tiny_chunks):
    assert _pad_lanes(5) == 8
    assert _pad_lanes(8) == 8
    assert _pad_lanes(9) == 16
    assert _pad_lanes(17) == 24
    assert _pad_lanes(24) == 24


def test_chunked_rowcombined_accepts_valid_batch(tiny_chunks):
    # n+1 = 21 lanes -> 3 chunks of 8 through combined_partial_kernel
    entries = make_entries(20)
    assert _run(TpuBackend(), entries) == [True] * 20


def test_chunked_rowcombined_mixed_matches_oracle(tiny_chunks):
    entries = make_entries(20)
    rng = SecureRng()
    params = entries[7][0]
    wrong = Statement.from_witness(params, Witness(Ristretto255.random_scalar(rng)))
    entries[7] = (params, wrong, entries[7][2])
    expect = _run(CpuBackend(), entries)
    # the combined check fails -> the chunked verify_each fallback decides
    assert _run(TpuBackend(), entries) == expect
    assert expect == [i != 7 for i in range(20)]


def test_chunked_pippenger_accepts_valid_batch(monkeypatch):
    # m = 4*pad_pow2(20)+2 = 130 terms -> 5 chunks of 32 through _msm_partial
    monkeypatch.setattr(backend_mod, "LANE_CHUNK", 32)
    entries = make_entries(20)
    assert _run(TpuBackend(pippenger_min=2), entries) == [True] * 20


def test_chunked_pippenger_mixed_matches_oracle(monkeypatch):
    monkeypatch.setattr(backend_mod, "LANE_CHUNK", 32)
    entries = make_entries(12)
    rng = SecureRng()
    params = entries[3][0]
    wrong = Statement.from_witness(params, Witness(Ristretto255.random_scalar(rng)))
    entries[3] = (params, wrong, entries[3][2])
    expect = _run(CpuBackend(), entries)
    assert _run(TpuBackend(pippenger_min=2), entries) == expect
    assert expect == [i != 3 for i in range(12)]


def test_chunked_pippenger_device_rlc(monkeypatch):
    monkeypatch.setattr(backend_mod, "LANE_CHUNK", 32)
    monkeypatch.setenv("CPZK_DEVICE_RLC", "1")
    entries = make_entries(10)
    assert _run(TpuBackend(pippenger_min=2), entries) == [True] * 10


def test_chunked_rowcombined_device_rlc(tiny_chunks, monkeypatch):
    """Device-RLC windows are built full-width (correction spliced at lane
    n, possibly inside a middle chunk) and then chunk-sliced — the layout
    must survive the tiling."""
    monkeypatch.setenv("CPZK_DEVICE_RLC", "1")
    entries = make_entries(20)  # correction lane lands at 20, chunk 3 of 3
    assert _run(TpuBackend(), entries) == [True] * 20
    entries = make_entries(11)  # correction lane 11 inside chunk 2 of 2
    assert _run(TpuBackend(), entries) == [True] * 11


def test_chunked_batch_prover(tiny_chunks):
    """BatchProver lane-tiles past LANE_CHUNK; the wire bytes must stay
    bit-identical to the host prover's statement computation and verify
    under the standard Verifier."""
    from cpzk_tpu import Parameters, SecureRng, Verifier, Statement, Proof, Transcript
    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.ops.prove import BatchProver

    rng = SecureRng()
    params = Parameters.new()
    bp = BatchProver(params)
    witnesses = [Ristretto255.random_scalar(rng) for _ in range(20)]
    ctxs = [b"chunk-ctx-%02d" % i for i in range(20)]
    statements, proof_wires = bp.prove(witnesses, ctxs, rng)
    for (y1b, y2b), wire, ctx, w in zip(statements, proof_wires, ctxs, witnesses):
        st = Statement(
            Ristretto255.element_from_bytes(y1b),
            Ristretto255.element_from_bytes(y2b),
        )
        expected = Statement.from_witness(params, Witness(w))
        assert (y1b, y2b) == (
            Ristretto255.element_to_bytes(expected.y1),
            Ristretto255.element_to_bytes(expected.y2),
        )
        t = Transcript()
        t.append_context(ctx)
        # raises on failure (verifier/mod.rs:120-139 parity)
        Verifier(params, st).verify_with_transcript(Proof.from_bytes(wire), t)


def test_mesh_chunked_prove(monkeypatch):
    """The sharded prover's over-cap slicing (n > d*LANE_CHUNK) must emit
    wire bytes bit-identical to the single-device prover."""
    from cpzk_tpu import Parameters, SecureRng
    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.ops.prove import BatchProver

    monkeypatch.setattr(backend_mod, "LANE_CHUNK", 4)
    rng = SecureRng()
    params = Parameters.new()
    sharded = BatchProver(params, mesh_devices=0)
    if sharded._sharded is None:
        pytest.skip("no multi-device mesh available")
    single = BatchProver(params)
    witnesses = [Ristretto255.random_scalar(rng) for _ in range(40)]
    # n=40 > step=8*4=32 -> the parts/concatenate branch runs
    assert sharded.statements(witnesses) == single.statements(witnesses)


def test_mesh_chunked_paths(monkeypatch):
    """Sharded mesh paths under the per-device lane cap: the sharded MSM
    (combined) and sharded verify_each both split into mesh-sized slices
    of d * LANE_CHUNK lanes and must stay bit-identical to the oracle."""
    monkeypatch.setattr(backend_mod, "LANE_CHUNK", 4)
    entries = make_entries(40)
    be = TpuBackend(mesh_devices=0)  # the 8-virtual-device CPU mesh
    if be._mesh is None:
        pytest.skip("no multi-device mesh available")
    # combined: m = 4*pad_pow2(40)+2 = 258 terms, step 8*4=32 -> 9 slices
    assert _run(be, entries) == [True] * 40

    rng = SecureRng()
    params = entries[11][0]
    wrong = Statement.from_witness(params, Witness(Ristretto255.random_scalar(rng)))
    entries[11] = (params, wrong, entries[11][2])
    # combined fails -> sharded verify_each (n=40, step 32 -> 2 slices)
    expect = _run(CpuBackend(), entries)
    assert _run(TpuBackend(mesh_devices=0), entries) == expect
    assert expect == [i != 11 for i in range(40)]
