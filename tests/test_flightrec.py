"""Flight-recorder subsystem tests: ring semantics (bounded, dump-stable,
thread-safe), the widened stage vocabulary through the real gRPC serving
path (stage sum ≈ wall within 10%), compile-vs-execute attribution via
the jit cache-key registry, dispatch-gap/occupancy metrics, the
``/flightrec`` + ``/profile`` REPL commands, the PerfSnapshot regression
comparator (identical passes, degraded flags), and the PR's satellite
fixes: chunk-aware Pippenger window sizing, mesh d-multiple padding, and
the LRU-bounded generator-pair cache.
"""

import asyncio
import json
import logging
import threading

import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.client import AuthClient
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.observability import get_flight_recorder
from cpzk_tpu.observability.flightrec import (
    RECORD_STAGES,
    SCHEMA,
    FlightRecord,
    FlightRecorder,
    format_flightrec,
)
from cpzk_tpu.observability.perf import (
    PerfEntry,
    compare_entries,
    load_snapshot,
    stage_percentiles,
    write_snapshot,
)
from cpzk_tpu.ops import backend as backend_mod
from cpzk_tpu.ops import msm
from cpzk_tpu.ops.backend import TpuBackend
from cpzk_tpu.protocol.batch import BatchVerifier, CpuBackend
from cpzk_tpu.server import RateLimiter, ServerState, metrics
from cpzk_tpu.server.__main__ import handle_command
from cpzk_tpu.server.batching import DynamicBatcher
from cpzk_tpu.server.service import serve


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _fresh_recorder():
    rec = get_flight_recorder()
    rec.clear()
    yield
    rec.clear()


def _make_proofs(n, rng, params):
    out = []
    for i in range(n):
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        ctx = b"fr-%04d" % i
        t = Transcript()
        t.append_context(ctx)
        out.append((prover.statement, prover.prove_with_transcript(rng, t), ctx))
    return out


# --- acceptance: stage sum ≈ wall on a CPU-backend gRPC e2e run -------------


def test_grpc_e2e_stage_sum_matches_wall():
    """The PR acceptance criterion: through the real gRPC serving path on
    the CPU backend, each flight record decomposes the dispatch into
    thread_hop/pad_and_pack/marshal/compile|execute/unpack spans whose
    sum is within 10% of the measured wall, and the dispatch-gap +
    occupancy metrics are populated."""
    rng = SecureRng()
    params = Parameters.new()

    async def main():
        state = ServerState()
        batcher = DynamicBatcher(CpuBackend(), max_batch=512, window_ms=5.0)
        server, port = await serve(
            state, RateLimiter(10**9, 10**9),
            host="127.0.0.1", port=0, batcher=batcher,
        )
        eb = Ristretto255.element_to_bytes
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                n = 256
                provers = [
                    Prover(params, Witness(Ristretto255.random_scalar(rng)))
                    for _ in range(n)
                ]
                resp = await client.register_batch(
                    [f"fr{i}" for i in range(n)],
                    [eb(p.statement.y1) for p in provers],
                    [eb(p.statement.y2) for p in provers],
                )
                assert all(r.success for r in resp.results)
                # two waves so the second dispatch has a measurable gap
                for _wave in range(2):
                    ids, cids, proofs = [], [], []
                    for i, p in enumerate(provers):
                        ch = await client.create_challenge(f"fr{i}")
                        cid = bytes(ch.challenge_id)
                        t = Transcript()
                        t.append_context(cid)
                        ids.append(f"fr{i}")
                        cids.append(cid)
                        proofs.append(
                            p.prove_with_transcript(rng, t).to_bytes()
                        )
                    resp = await client.verify_proof_batch(ids, cids, proofs)
                    assert all(r.success for r in resp.results)
                    for s in list(state._sessions):
                        await state.revoke_session(s)
        finally:
            await batcher.stop()
            await server.stop(None)

    run(main())

    records = get_flight_recorder().snapshot()
    assert len(records) >= 2
    big = [r for r in records if r.batch >= 64]
    assert big, [r.batch for r in records]
    for rec in big:
        assert rec.backend == "cpu"
        assert rec.wall_s > 0
        # the widened decomposition tiles the dispatch wall
        assert rec.stage_sum_s() == pytest.approx(rec.wall_s, rel=0.10), (
            rec.to_dict()
        )
        # CPU oracle: no marshal/compile attribution, pure execute
        assert rec.stages_s.get("execute", 0.0) > 0.0
        assert rec.stages_s.get("compile", 0.0) == 0.0
        assert rec.stages_s.get("thread_hop", 0.0) >= 0.0
        assert rec.occupancy == 1.0  # no device padding on the oracle
    # dispatch gap + occupancy + throughput populated
    gap_count, gap_sum = metrics.read_histogram("tpu.dispatch.gap")
    assert gap_count >= 2.0 and gap_sum >= 0.0
    assert metrics.read("tpu.device.busy_fraction", "g") > 0.0
    assert metrics.read("tpu.batch.occupancy", "g") == 1.0
    assert metrics.read("tpu.throughput.proofs_per_s", "g") >= 0.0
    assert metrics.read_histogram("tpu.batch.thread_hop")[0] >= 2.0


# --- compile vs execute attribution -----------------------------------------


def test_compile_then_cache_hit_attribution(monkeypatch):
    """First dispatch at a padded shape books a jit miss (compile
    attribution); a second batch at the same shape books hits and books
    its device time as execute."""
    monkeypatch.setattr(backend_mod, "_JIT_SEEN", set())
    rng = SecureRng()
    params = Parameters.new()
    proofs = _make_proofs(6, rng, params)

    async def submit_wave(batcher):
        from cpzk_tpu.protocol.batch import BatchEntry

        entries = [
            BatchEntry(params, st, pr, ctx) for st, pr, ctx in proofs
        ]
        res = await batcher.submit_many(entries)
        assert res == [None] * len(entries)

    async def main():
        batcher = DynamicBatcher(TpuBackend(), max_batch=16, window_ms=1.0)
        batcher.start()
        try:
            await submit_wave(batcher)
            await submit_wave(batcher)
        finally:
            await batcher.stop()

    run(main())
    records = get_flight_recorder().snapshot()
    assert len(records) == 2
    first, second = records
    assert first.jit_misses > 0
    assert first.compiled  # the first-sight shape keys are named
    assert first.stages_s.get("compile", 0.0) > 0.0
    assert first.stages_s.get("marshal", 0.0) > 0.0
    assert second.jit_misses == 0
    assert second.jit_hits > 0
    assert second.stages_s.get("compile", 0.0) == 0.0
    assert second.stages_s.get("execute", 0.0) > 0.0
    # device padding is visible: 6+1 correction rows pad to 8 lanes
    assert first.lanes == 8
    assert first.occupancy == pytest.approx(7 / 8)
    assert metrics.read("tpu.jit.cache", labels={"outcome": "miss"}) >= 1
    assert metrics.read("tpu.jit.cache", labels={"outcome": "hit"}) >= 1


def test_compile_storm_warning(caplog):
    rec = FlightRecorder(storm_threshold=3, storm_window_s=60.0)
    with caplog.at_level(
        logging.WARNING, logger="cpzk_tpu.observability.flightrec"
    ):
        for i in range(8):
            rec.note_compile_event(f"combined/{i}")
    storms = [r for r in caplog.records if "compile storm" in r.message]
    assert len(storms) == 1  # warned once per window, not once per compile


# --- ring semantics ----------------------------------------------------------


def test_ring_bounded_and_dump_stable(tmp_path):
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.record(FlightRecord(batch=i + 1, stages_s={"execute": 0.001}))
    records = rec.snapshot()
    assert len(records) == 4
    assert [r.batch for r in records] == [7, 8, 9, 10]
    assert [r.seq for r in records] == [7, 8, 9, 10]

    payload = json.loads(rec.to_json())
    assert payload["schema"] == SCHEMA
    assert len(payload["records"]) == 4
    for row in payload["records"]:
        assert set(row) >= {
            "seq", "batch", "lanes", "occupancy", "pad_waste", "backend",
            "stages_s", "wall_s", "dispatch_gap_s", "jit_hits", "jit_misses",
        }
    path = tmp_path / "flightrec.json"
    rec.dump(str(path))
    assert json.loads(path.read_text())["records"] == payload["records"]


def test_ring_thread_safe():
    rec = FlightRecorder(capacity=64)
    errors = []

    def writer(k):
        try:
            for i in range(200):
                rec.record(FlightRecord(batch=k * 1000 + i))
                rec.note_device_interval(float(i), float(i) + 0.5)
        except Exception as exc:  # pragma: no cover - the assertion target
            errors.append(exc)

    def reader():
        try:
            for _ in range(100):
                rec.snapshot()
                rec.to_json()
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=writer, args=(k,)) for k in range(4)]
    threads += [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(rec.snapshot()) == 64


def test_dispatch_gap_accounting():
    rec = FlightRecorder()
    assert rec.note_device_interval(10.0, 10.5) == 0.0  # first dispatch
    assert rec.note_device_interval(11.0, 11.2) == pytest.approx(0.5)
    # pipelined overlap: the device never went idle
    assert rec.note_device_interval(11.1, 11.4) == 0.0


def test_recorder_configure_capacity():
    rec = FlightRecorder(capacity=8)
    for i in range(8):
        rec.record(FlightRecord(batch=i))
    rec.configure(capacity=2)
    assert len(rec.snapshot()) == 2


# --- REPL commands -----------------------------------------------------------


def test_flightrec_command_empty_and_populated():
    async def main():
        state = ServerState()
        out_empty, _ = await handle_command("/flightrec", state)
        get_flight_recorder().record(
            FlightRecord(batch=12, lanes=16, occupancy=0.75,
                         stages_s={"execute": 0.002}, wall_s=0.002)
        )
        out, quit_ = await handle_command("/flightrec 5", state)
        out_bad, _ = await handle_command("/flightrec banana", state)
        return out_empty, out, quit_, out_bad

    out_empty, out, quit_, out_bad = run(main())
    assert "no recorded batches" in out_empty
    assert not quit_
    assert "n=12" in out and "occ=0.75" in out and "gap=" in out
    assert "usage: /flightrec" in out_bad


def test_profile_command_capture_and_guard(tmp_path):
    from cpzk_tpu.observability import flightrec as fr

    logdir = str(tmp_path / "xprof")

    async def main():
        state = ServerState()
        usage, _ = await handle_command("/profile", state)
        bad, _ = await handle_command("/profile banana", state)
        out, _ = await handle_command(f"/profile 0.05 {logdir}", state)
        return usage, bad, out

    usage, bad, out = run(main())
    assert "usage: /profile" in usage
    assert "usage: /profile" in bad
    assert logdir in out and "tensorboard" in out
    assert fr.profile_active() is None  # capture closed

    # concurrent-capture guard: second start is refused, not corrupting
    assert fr.start_profile(str(tmp_path / "a"))
    try:
        assert not fr.start_profile(str(tmp_path / "b"))
        assert fr.profile_active() == str(tmp_path / "a")
    finally:
        assert fr.stop_profile() == str(tmp_path / "a")
    assert fr.stop_profile() is None


# --- perf snapshot + regression gate ----------------------------------------


def _entry(name="batch_e2e", backend="cpu", n=50, value=10.0,
           unit="ms/batch", spread=0.0):
    return PerfEntry(name=name, backend=backend, n=n, value=value,
                     unit=unit, spread=spread)


def test_regress_identical_passes_and_degraded_flags():
    base = [_entry(value=10.0), _entry(name="other", value=5.0)]
    same = compare_entries(base, [_entry(value=10.0),
                                  _entry(name="other", value=5.0)])
    assert same["passed"] and same["compared"] == 2

    degraded = compare_entries(
        base,
        [_entry(value=20.0), _entry(name="other", value=5.0)],
    )
    assert not degraded["passed"]
    assert [d.key[0] for d in degraded["regressions"]] == ["batch_e2e"]


def test_regress_direction_per_unit():
    # throughput: DROP is a regression, rise is fine
    up = compare_entries([_entry(unit="proofs/s", value=100.0)],
                         [_entry(unit="proofs/s", value=300.0)])
    assert up["passed"]
    down = compare_entries([_entry(unit="proofs/s", value=100.0)],
                           [_entry(unit="proofs/s", value=50.0)])
    assert not down["passed"]
    # latency: the same 2x move flips polarity
    faster = compare_entries([_entry(value=100.0)], [_entry(value=50.0)])
    assert faster["passed"]


def test_regress_noise_widens_but_never_disables_gate():
    # 40% regression: over the base 35% gate...
    noisy_old = [_entry(value=10.0, spread=2.0)]  # 20% relative noise
    tight_old = [_entry(value=10.0, spread=0.0)]
    new = [_entry(value=14.0)]
    assert not compare_entries(tight_old, new, threshold=0.35)["passed"]
    # ...but within the noise-widened 55% gate
    assert compare_entries(noisy_old, new, threshold=0.35)["passed"]
    # the allowance caps at one extra threshold: a 3x regression still fails
    wild_old = [_entry(value=10.0, spread=100.0)]
    assert not compare_entries(
        wild_old, [_entry(value=30.0)], threshold=0.35
    )["passed"]


def test_regress_added_removed_configs_do_not_gate():
    report = compare_entries([_entry()], [_entry(name="brand-new")])
    assert report["passed"]
    assert report["compared"] == 0
    assert report["only_old"] and report["only_new"]


def test_regress_cli_exit_codes(tmp_path):
    from cpzk_tpu.observability.regress import main as regress_main

    old = tmp_path / "old.json"
    write_snapshot(str(old), [_entry(value=10.0)])
    new_same = tmp_path / "same.json"
    write_snapshot(str(new_same), [_entry(value=10.0)])
    new_bad = tmp_path / "bad.json"
    write_snapshot(str(new_bad), [_entry(value=99.0)])

    assert regress_main([str(old), str(new_same)]) == 0
    assert regress_main([str(old), str(new_bad)]) == 1
    assert regress_main([str(old), str(new_bad), "--json"]) == 1
    assert regress_main([str(tmp_path / "missing.json"), str(old)]) == 2
    assert regress_main([str(old), str(new_same), "--threshold", "99"]) == 2
    # schema tag is validated, not assumed
    junk = tmp_path / "junk.json"
    junk.write_text('{"schema": "something-else", "entries": []}')
    assert regress_main([str(junk), str(old)]) == 2
    assert load_snapshot(str(old))[0].value == 10.0


def test_stage_percentiles_from_records():
    records = [
        FlightRecord(stages_s={"execute": 0.001 * (i + 1), "marshal": 0.0005})
        for i in range(10)
    ]
    out = stage_percentiles(records)
    assert out["execute"]["p50"] == pytest.approx(5.0)
    assert out["execute"]["p90"] == pytest.approx(9.0)
    assert out["execute"]["p99"] == pytest.approx(10.0)
    assert out["marshal"]["p50"] == pytest.approx(0.5)
    assert stage_percentiles([]) == {}


# --- satellite: chunk-aware pick_window -------------------------------------


def test_pick_window_sized_from_chunk_not_total():
    """ADVICE.md / ROADMAP item 4: past LANE_CHUNK the MSM runs as
    <=16384-term tiles, so the window cost model must see the chunk
    length.  Pinned at the 4k/16k/64k term counts (LANE_CHUNK=16384):
    full-count sizing would pick c=13 at 64k — two windows too deep for
    the tiles that actually run."""
    chunk = 16384
    assert msm.pick_window(4098) == 10          # 4k terms: unchunked
    assert msm.pick_window(min(16386, chunk)) == 11   # 16k terms
    assert msm.pick_window(min(65538, chunk)) == 11   # 64k terms: chunked
    assert msm.pick_window(65538) == 13         # the old miscalibration


def test_backend_pippenger_windows_from_chunk(monkeypatch):
    """The backend actually sizes c from min(m, LANE_CHUNK): with a tiny
    chunk, _combined_pippenger must ask the cost model about the chunk
    length, and the chunked dispatch must stay correct."""
    monkeypatch.setattr(backend_mod, "LANE_CHUNK", 32)
    seen = []
    real_pick = msm.pick_window

    def spy(m):
        seen.append(m)
        return real_pick(m)

    monkeypatch.setattr(backend_mod.msm, "pick_window", spy)

    from test_tpu_backend import make_entries

    entries = make_entries(20)  # m = 4*pad_pow2(20)+2 = 130 > 32
    bv = BatchVerifier(backend=TpuBackend(pippenger_min=2))
    for p, st, pr in entries:
        bv.add(p, st, pr)
    assert bv.verify(SecureRng()) == [None] * 20
    assert seen and all(m == 32 for m in seen)


# --- satellite: mesh d-multiple padding -------------------------------------


def test_mesh_step_pads_to_d_multiple(monkeypatch):
    from cpzk_tpu.parallel import mesh as mesh_mod

    monkeypatch.setattr(backend_mod, "LANE_CHUNK", 8)
    monkeypatch.setattr(backend_mod, "LANE_QUANTUM", 2)
    d = 8
    step, n_to = mesh_mod._mesh_step(d, 72)  # one past a step boundary
    assert step == 64
    # old behavior padded to 2 full steps (128); now: 10 quantum-aligned
    # lanes per device -> 80 total, a d-multiple
    assert n_to == 80
    assert metrics.read("tpu.batch.occupancy", "g") == pytest.approx(72 / 80)
    # below one step: plain d-multiple, unchanged
    assert mesh_mod._mesh_step(d, 40) == (64, 40)
    assert mesh_mod._mesh_step(d, 41) == (64, 48)


def test_mesh_remainder_slice_matches_oracle(monkeypatch):
    """Over-cap mesh verify with a short (d-multiple) remainder slice
    stays bit-identical to the host oracle, and the occupancy gauge
    reflects the reclaimed lanes (80 padded lanes, not 128)."""
    import jax

    if jax.device_count() < 2:
        pytest.skip("no multi-device mesh available")
    monkeypatch.setattr(backend_mod, "LANE_CHUNK", 8)
    monkeypatch.setattr(backend_mod, "LANE_QUANTUM", 2)

    from test_tpu_backend import make_entries

    entries = make_entries(72)
    be = TpuBackend(mesh_devices=0)
    if be._mesh is None:
        pytest.skip("no multi-device mesh available")
    rng = SecureRng()
    from cpzk_tpu import Statement

    params = entries[11][0]
    wrong = Statement.from_witness(
        params, Witness(Ristretto255.random_scalar(rng))
    )
    entries[11] = (params, wrong, entries[11][2])

    def _run(backend):
        bv = BatchVerifier(backend=backend)
        for p, st, pr in entries:
            bv.add(p, st, pr)
        return [e is None for e in bv.verify(SecureRng())]

    expect = _run(CpuBackend())
    assert expect == [i != 11 for i in range(72)]
    assert _run(be) == expect  # combined fails -> sharded verify_each
    assert metrics.read("tpu.batch.occupancy", "g") == pytest.approx(72 / 80)


# --- satellite: LRU-bounded generator-pair cache ----------------------------


def test_gh_cache_lru_bounded():
    from cpzk_tpu.protocol.batch import BatchRow

    rng = SecureRng()
    params = Parameters.new()
    backend = TpuBackend(gh_cache_max=2)

    def row_with_generators():
        # any two distinct valid group elements work as a generator pair
        st = Prover(
            params, Witness(Ristretto255.random_scalar(rng))
        ).statement
        g, h = st.y1, st.y2
        return BatchRow(g=g, h=h, y1=g, y2=h, r1=g, r2=h,
                        s=Ristretto255.random_scalar(rng),
                        c=Ristretto255.random_scalar(rng),
                        alpha=Ristretto255.random_scalar(rng))

    rows = [row_with_generators() for _ in range(4)]
    for row in rows:
        backend._gh(row)
    assert len(backend._gh_cache) == 2
    assert metrics.read("tpu.gh_cache.size", "g") == 2.0
    assert metrics.read("tpu.gh_cache.evictions") >= 2.0
    # most-recently-used pairs survive; re-touching promotes
    backend._gh(rows[2])
    backend._gh(rows[0])  # re-marshal (was evicted), evicts rows[3]'s pair
    keys = list(backend._gh_cache)
    eb = Ristretto255.element_to_bytes
    assert keys[-1] == (eb(rows[0].g), eb(rows[0].h))
    assert len(backend._gh_cache) == 2


# --- recorder is a no-op outside instrumented paths -------------------------


def test_direct_batchverifier_unrecorded():
    """bench_batch's direct BatchVerifier path (stages=None) must not
    touch the recorder — the <=2% overhead criterion is structural."""
    rng = SecureRng()
    params = Parameters.new()
    proofs = _make_proofs(3, rng, params)
    bv = BatchVerifier()
    for st, pr, ctx in proofs:
        bv.add_with_context(params, st, pr, ctx)
    assert bv.verify(rng) == [None] * 3
    assert get_flight_recorder().snapshot() == []


# --- config knobs ------------------------------------------------------------


def test_flightrec_config_env_and_validation(monkeypatch):
    from cpzk_tpu.server import ServerConfig

    monkeypatch.setenv("SERVER_OBSERVABILITY_FLIGHT_RING", "16")
    monkeypatch.setenv("SERVER_OBS_COMPILE_STORM_THRESHOLD", "3")
    cfg = ServerConfig()
    cfg._merge_env()
    assert cfg.observability.flight_ring == 16
    assert cfg.observability.compile_storm_threshold == 3
    cfg.validate()

    cfg = ServerConfig()
    cfg.observability.flight_ring = 0
    with pytest.raises(ValueError):
        cfg.validate()
    cfg = ServerConfig()
    cfg.observability.compile_storm_threshold = 0
    with pytest.raises(ValueError):
        cfg.validate()


def test_configure_applies_flight_ring():
    from cpzk_tpu.observability import configure
    from cpzk_tpu.server.config import ObservabilitySettings

    rec = get_flight_recorder()
    try:
        configure(ObservabilitySettings(flight_ring=3))
        for i in range(6):
            rec.record(FlightRecord(batch=i))
        assert len(rec.snapshot()) == 3
        assert rec.storm_threshold == 8
    finally:
        configure(ObservabilitySettings())


def test_format_flightrec_limit():
    records = [
        FlightRecord(seq=i, batch=i, stages_s={}, wall_s=0.001)
        for i in range(1, 6)
    ]
    # the REPL consumes the same serialized payload shape the HTTP
    # /flightrec endpoint and the SIGUSR2 dump emit
    payload = {"records": [r.to_dict() for r in records]}
    out = format_flightrec(payload, limit=2)
    assert "#5" in out and "#4" in out and "#3" not in out
    for name in RECORD_STAGES:
        assert f"{name}=" in out
