"""cpzk-lint: self-hosted zero-findings gate + per-rule fixtures.

Three layers:

- **Self-hosting** — the analyzer runs over the whole ``cpzk_tpu`` tree
  and must report zero findings.  This is the structural enforcement of
  every invariant in docs/security.md "Mechanically enforced invariants":
  reverting any of this PR's real-violation fixes (the async-def file
  reads in ``state.restore`` / ``recovery.recover_state`` / the daemon's
  TLS load) or the PR-4 ``_abort_exhausted`` routing makes this test
  fail.
- **Fixtures** — each of the 12 rules has at least one true-positive and
  one clean fixture, so a rule that silently stops firing (or starts
  over-firing) is caught here rather than by the empty self-host run.
  The context rules (THREAD-001/PROC-001, plus ASYNC-001's nested-def
  upgrade) additionally pin the execution-context inference itself
  (spawn-site seeding, call-graph propagation, the sanctioned
  call_soon_threadsafe bridge).
- **Contract** — waiver handling (a reason is mandatory; a stale waiver
  is a WAIVER-002 finding; ``--audit-waivers`` lists liveness), JSON
  schema stability (v2: ``waivers`` audit list), the docs/rule-registry
  drift guard, and the secret-type redaction guard.

ISSUE 15's real-violation ledger (each reverts to a tier-1 failure):
FRAME-001 — ``server/ingest.py`` hand-rolled the WAL frame header, now
rides ``durability.wal.frame_payload`` (pinned below + self-host);
WAIVER-002 — six stale LOCK-001 waivers on the ``state.py`` mutation
funnels (they never suppressed anything: LOCK-001 treats
parameter-rooted mutations as the caller's obligation), deleted.
THREAD-001, FUNNEL-001, PROC-001: no live violations found — the
dispatch lane already posts via call_soon_threadsafe, every registry
mutation already routes through the funnels, and the ingest spawn
already ships plain data (each pinned by a targeted self-host test).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from cpzk_tpu.analysis import REGISTRY, all_rule_ids, analyze_paths, analyze_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "cpzk_tpu")

#: The rule pack the tentpole promises; WAIVER/PARSE are engine-emitted.
CORE_RULES = [
    "CT-001", "CT-002", "LEAK-001", "LOCK-001",
    "ASYNC-001", "ASYNC-002", "GRPC-001", "JAX-001",
    "THREAD-001", "FUNNEL-001", "PROC-001", "FRAME-001",
    "AWAIT-001", "ACK-001", "FENCE-001",
]


def rules_of(report) -> list[str]:
    return sorted({f.rule for f in report.findings})


# -- self-hosting -------------------------------------------------------------


class TestSelfHosted:
    def test_whole_tree_is_clean(self):
        """THE gate: zero findings over the real package.  A new violation
        anywhere in cpzk_tpu/ — or a reverted fix — fails tier-1."""
        report = analyze_paths([PKG])
        assert report.files > 50  # sanity: the walker saw the real tree
        assert [f.render() for f in report.findings] == []

    def test_real_waivers_carry_reasons(self):
        """The tree's own waivers are active, reasoned, and bounded:
        LOCK-001 on ServerState's documented single-threaded paths, plus
        the v3 atomicity waivers (unfenced consume/sweep/restore with
        their PR 16/18 rationale, and verify_proof_batch's per-entry
        fence mapping)."""
        report = analyze_paths([PKG])
        assert report.waived, "expected the documented waivers"
        assert {f.rule for f in report.waived} == {
            "LOCK-001", "AWAIT-001", "ACK-001", "FENCE-001",
        }
        assert all(
            f.path.endswith(("server/state.py", "server/service.py"))
            for f in report.waived
        )

    def test_cli_json_on_real_tree(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, "-m", "cpzk_tpu.analysis", PKG, "--json"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["findings"] == []
        assert doc["summary"]["findings"] == 0

    def test_cli_exit_two_on_missing_path(self):
        proc = subprocess.run(
            [sys.executable, "-m", "cpzk_tpu.analysis", "/no/such/dir"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 2  # a typo'd path must not gate green

    def test_cli_exit_one_on_findings(self, tmp_path):
        bad = tmp_path / "cpzk_tpu" / "server" / "bad.py"
        bad.parent.mkdir(parents=True)
        bad.write_text("import asyncio\nasyncio.create_task(f())\n")
        proc = subprocess.run(
            [sys.executable, "-m", "cpzk_tpu.analysis", str(bad)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 1
        assert "ASYNC-002" in proc.stdout


# -- CT-001 -------------------------------------------------------------------


class TestCT001:
    def test_true_positive_secret_bytes_equality(self):
        src = (
            "import hashlib\n"
            "def check(password: str, stored: bytes) -> bool:\n"
            "    okm = hashlib.sha256(password.encode()).digest()\n"
            "    return okm == stored\n"
        )
        report = analyze_source(src, path="cpzk_tpu/client/fx.py")
        assert "CT-001" in rules_of(report)

    def test_true_positive_kdf_output(self):
        src = (
            "from argon2.low_level import hash_secret_raw\n"
            "def check(data, stored):\n"
            "    okm = hash_secret_raw(secret=data, salt=b'x')\n"
            "    return stored != okm\n"
        )
        report = analyze_source(src, path="cpzk_tpu/client/fx.py")
        assert "CT-001" in rules_of(report)

    def test_clean_compare_digest(self):
        src = (
            "import hashlib, hmac\n"
            "def check(password: str, stored: bytes) -> bool:\n"
            "    okm = hashlib.sha256(password.encode()).digest()\n"
            "    return hmac.compare_digest(okm, stored)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/client/fx.py")
        assert "CT-001" not in rules_of(report)

    def test_clean_scalar_equality(self):
        """Scalar-to-Scalar == goes through the ct __eq__ — not a finding."""
        src = (
            "def check(witness: Witness, other: Witness) -> bool:\n"
            "    return witness.secret() == other.secret()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/protocol/fx.py")
        assert "CT-001" not in rules_of(report)

    def test_clean_public_equality(self):
        src = "def f(a: bytes, b: bytes):\n    return a == b\n"
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert rules_of(report) == []


# -- CT-002 -------------------------------------------------------------------


class TestCT002:
    TP = (
        "def f(witness: Witness):\n"
        "    x = witness.secret()\n"
        "    if x.value:\n"
        "        return 1\n"
        "    return 0\n"
    )

    def test_true_positive_in_core(self):
        report = analyze_source(self.TP, path="cpzk_tpu/core/fx.py")
        assert "CT-002" in rules_of(report)

    def test_true_positive_short_circuit(self):
        src = (
            "def f(nonce: Nonce, flag: bool):\n"
            "    return nonce.k().value and flag\n"
        )
        report = analyze_source(src, path="cpzk_tpu/protocol/fx.py")
        assert "CT-002" in rules_of(report)

    def test_out_of_scope_plane_is_clean(self):
        """Host planes branch on secrets' existence legitimately; CT-002
        is scoped to the protocol math."""
        report = analyze_source(self.TP, path="cpzk_tpu/server/fx.py")
        assert "CT-002" not in rules_of(report)

    def test_clean_public_branch(self):
        src = (
            "def f(witness: Witness, n: int):\n"
            "    if n > 0:\n"
            "        return witness.secret()\n"
            "    return None\n"
        )
        report = analyze_source(src, path="cpzk_tpu/core/fx.py")
        assert "CT-002" not in rules_of(report)


# -- LEAK-001 -----------------------------------------------------------------


class TestLEAK001:
    def test_true_positive_fstring_log(self):
        src = (
            "import logging\n"
            "log = logging.getLogger('x')\n"
            "def f(witness: Witness):\n"
            "    log.info(f'witness is {witness.secret().value}')\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "LEAK-001" in rules_of(report)

    def test_true_positive_exception_message(self):
        src = (
            "def f(password: str):\n"
            "    raise ValueError('bad password: ' + password)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/client/fx.py")
        assert "LEAK-001" in rules_of(report)

    def test_true_positive_record_event(self):
        src = (
            "def f(tracer, nonce: Nonce):\n"
            "    tracer.record_event('prove', k=nonce.k().value)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/protocol/fx.py")
        assert "LEAK-001" in rules_of(report)

    def test_true_positive_metric_label(self):
        src = (
            "def f(hist, password: str):\n"
            "    hist.labels(backend=password).observe(1.0)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "LEAK-001" in rules_of(report)

    def test_clean_public_logging(self):
        src = (
            "import logging\n"
            "log = logging.getLogger('x')\n"
            "def f(witness: Witness, user_id: str):\n"
            "    log.info('registered %s', user_id)\n"
            "    log.info(f'user {user_id} ok')\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "LEAK-001" not in rules_of(report)

    def test_clean_length_is_sanitized(self):
        src = (
            "import logging\n"
            "log = logging.getLogger('x')\n"
            "def f(password: str):\n"
            "    log.info('password length %d', len(password))\n"
        )
        report = analyze_source(src, path="cpzk_tpu/client/fx.py")
        assert "LEAK-001" not in rules_of(report)


# -- LOCK-001 -----------------------------------------------------------------


FIXTURE_STATE = """\
import asyncio

class ServerState:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._users = {}
        self._sessions = {}
        self._user_sessions = {}
        self.journal = None

    async def good(self, uid, data):
        async with self._lock:
            self._users[uid] = data
            self._journal_append("register_user", {})

    async def bad(self, uid, data):
        self._users[uid] = data

    async def bad_pop(self, token):
        self._sessions.pop(token, None)

    async def bad_alias(self, uid, token):
        per_user = self._user_sessions.setdefault(uid, [])
        per_user.append(token)

    async def bad_journal(self):
        self._journal_append("revoke_session", {})
"""


class TestLOCK001:
    def test_true_positives(self):
        report = analyze_source(FIXTURE_STATE, path="cpzk_tpu/server/state.py")
        lock_findings = [f for f in report.findings if f.rule == "LOCK-001"]
        flagged = "\n".join(f.message for f in lock_findings)
        # bad, bad_pop, bad_alias (both the .setdefault and the aliased
        # .append), bad_journal — and never the locked/`__init__` sites
        assert len(lock_findings) == 5
        assert "bad " in flagged or "rebinds" in flagged or "subscript" in flagged
        assert any("journal" in f.message for f in lock_findings)
        assert any(".append()" in f.message for f in lock_findings)

    def test_clean_under_lock_and_init(self):
        clean = FIXTURE_STATE.split("    async def bad")[0]
        report = analyze_source(clean, path="cpzk_tpu/server/state.py")
        assert "LOCK-001" not in rules_of(report)

    def test_other_classes_out_of_scope(self):
        src = (
            "class Batcher:\n"
            "    def f(self):\n"
            "        self._users['a'] = 1\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/batching.py")
        assert "LOCK-001" not in rules_of(report)


FIXTURE_SHARDED = """\
import asyncio

class ServerState:
    def __init__(self):
        self._shards = [None]

    def _shard_for_user(self, uid):
        return self._shards[0]

    async def good(self, uid, data):
        shard = self._shard_for_user(uid)
        async with shard.lock:
            shard._users[uid] = data
            per_user = shard._user_sessions.setdefault(uid, [])
            per_user.append("t")
            self._journal_append("register_user", {})

    async def good_sweep(self):
        for shard in self._shards:
            async with shard.lock:
                shard._sessions.pop("t", None)

    async def good_subscript_alias(self, idx, cid):
        shard = self._shards[idx]
        async with shard.lock:
            del shard._challenges[cid]

    async def bad(self, uid, data):
        shard = self._shard_for_user(uid)
        shard._users[uid] = data

    async def bad_wrong_shard_lock(self, uid, data):
        a = self._shard_for_user(uid)
        b = self._shard_for_user("other")
        async with a.lock:
            b._users[uid] = data

    async def bad_member_alias(self, uid, token):
        shard = self._shard_for_user(uid)
        per_user = shard._user_sessions.setdefault(uid, [])
        per_user.append(token)

    async def bad_journal_outside(self, uid):
        shard = self._shard_for_user(uid)
        self._journal_append("revoke_session", {})
"""


class TestLOCK001Sharded:
    """The sharded-lock contract (ISSUE 8): mutations through a shard
    alias need that SAME shard's lock; journal appends need any held
    state/shard lock.  The real sharded ``ServerState`` self-hosts at
    zero findings through these rules — no blanket waivers."""

    def test_true_positives(self):
        report = analyze_source(FIXTURE_SHARDED, path="cpzk_tpu/server/state.py")
        lock_findings = [f for f in report.findings if f.rule == "LOCK-001"]
        flagged = "\n".join(f.message for f in lock_findings)
        # bad, bad_wrong_shard_lock, bad_member_alias (setdefault + the
        # aliased append), bad_journal_outside — never the locked sites
        assert len(lock_findings) == 5
        assert "bad " in flagged or "subscript" in flagged
        # holding shard A's lock does not license mutating shard B
        assert "`with b.lock`" in flagged
        assert any("journal" in f.message for f in lock_findings)
        assert not any("good" in f.message for f in lock_findings)

    def test_clean_under_shard_locks(self):
        clean = FIXTURE_SHARDED.split("    async def bad")[0]
        report = analyze_source(clean, path="cpzk_tpu/server/state.py")
        assert "LOCK-001" not in rules_of(report)

    def test_real_sharded_state_self_hosts(self):
        """The actual ServerState — shard routing, bulk per-shard ops,
        journal funnel — passes with only its two documented waivers."""
        report = analyze_paths([os.path.join(PKG, "server", "state.py")])
        assert [f.render() for f in report.findings] == []
        assert report.waived  # replay/journal waivers are active, not dead


# -- ASYNC-001 ----------------------------------------------------------------


class TestASYNC001:
    def test_true_positive_sleep_and_open(self):
        src = (
            "import time\n"
            "async def handler():\n"
            "    time.sleep(0.1)\n"
            "    with open('/tmp/x') as f:\n"
            "        return f.read()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        msgs = [f.message for f in report.findings if f.rule == "ASYNC-001"]
        assert len(msgs) == 2
        assert any("time.sleep" in m for m in msgs)
        assert any("open()" in m for m in msgs)

    def test_true_positive_fsync_subprocess(self):
        src = (
            "import os, subprocess\n"
            "async def handler(fd):\n"
            "    os.fsync(fd)\n"
            "    subprocess.run(['ls'])\n"
        )
        report = analyze_source(src, path="cpzk_tpu/durability/fx.py")
        assert len([f for f in report.findings if f.rule == "ASYNC-001"]) == 2

    def test_clean_to_thread_and_nested_sync_def(self):
        src = (
            "import asyncio, os, time\n"
            "async def handler(path):\n"
            "    def write():\n"
            "        with open(path, 'w') as f:\n"
            "            f.write('x')\n"
            "            os.fsync(f.fileno())\n"
            "    await asyncio.to_thread(write)\n"
            "    await asyncio.to_thread(time.sleep, 0.1)\n"
            "    await asyncio.sleep(0.1)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "ASYNC-001" not in rules_of(report)

    def test_nested_sync_def_called_inline_is_flagged(self):
        """The context-inference upgrade (ISSUE 15): a nested helper the
        async body calls inline runs ON the loop — the indirection no
        longer hides the blocking call."""
        src = (
            "import time\n"
            "async def handler():\n"
            "    def helper():\n"
            "        time.sleep(1)\n"
            "    helper()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        msgs = [f.message for f in report.findings if f.rule == "ASYNC-001"]
        assert len(msgs) == 1
        assert "helper" in msgs[0] and "handler" in msgs[0]

    def test_nested_def_both_inline_and_to_thread_is_exempt(self):
        """Shipped to a thread at least once -> the helper may block."""
        src = (
            "import asyncio, time\n"
            "async def handler():\n"
            "    def helper():\n"
            "        time.sleep(1)\n"
            "    helper()\n"
            "    await asyncio.to_thread(helper)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "ASYNC-001" not in rules_of(report)

    def test_out_of_scope_plane_is_clean(self):
        src = "import time\nasync def f():\n    time.sleep(1)\n"
        report = analyze_source(src, path="cpzk_tpu/ops/fx.py")
        assert "ASYNC-001" not in rules_of(report)

    def test_sync_functions_are_clean(self):
        src = "def f(path):\n    return open(path).read()\n"
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "ASYNC-001" not in rules_of(report)


# -- ASYNC-002 ----------------------------------------------------------------


class TestASYNC002:
    def test_true_positive_discarded(self):
        src = (
            "import asyncio\n"
            "async def f():\n"
            "    asyncio.create_task(work())\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "ASYNC-002" in rules_of(report)

    def test_true_positive_underscore(self):
        src = (
            "import asyncio\n"
            "async def f():\n"
            "    _ = asyncio.ensure_future(work())\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "ASYNC-002" in rules_of(report)

    def test_clean_retained(self):
        src = (
            "import asyncio\n"
            "async def f(self):\n"
            "    self._task = asyncio.create_task(work())\n"
            "    t = asyncio.get_running_loop().create_task(work())\n"
            "    self._tasks.add(t)\n"
            "    await asyncio.create_task(work())\n"
            "    await self._task\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "ASYNC-002" not in rules_of(report)


# -- GRPC-001 -----------------------------------------------------------------


class TestGRPC001:
    def test_true_positive_direct_abort(self):
        """The PR-4 pushback invariant: reverting a handler to a direct
        RESOURCE_EXHAUSTED abort is flagged."""
        src = (
            "import grpc\n"
            "class AuthServiceImpl:\n"
            "    async def create_challenge(self, request, context):\n"
            "        await context.abort(\n"
            "            grpc.StatusCode.RESOURCE_EXHAUSTED, 'overloaded')\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/service.py")
        assert "GRPC-001" in rules_of(report)

    def test_clean_funnel_and_other_codes(self):
        src = (
            "import grpc\n"
            "class AuthServiceImpl:\n"
            "    async def _abort_exhausted(self, context, msg, retry_after_s):\n"
            "        await context.abort(\n"
            "            grpc.StatusCode.RESOURCE_EXHAUSTED, msg,\n"
            "            trailing_metadata=(('cpzk-retry-after-ms', '50'),))\n"
            "    async def handler(self, request, context):\n"
            "        await self._abort_exhausted(context, 'overloaded', 0.05)\n"
            "        await context.abort(grpc.StatusCode.INVALID_ARGUMENT, 'bad')\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/service.py")
        assert "GRPC-001" not in rules_of(report)


# -- JAX-001 ------------------------------------------------------------------


class TestJAX001:
    def test_true_positive_impure_body(self):
        src = (
            "import jax, time\n"
            "@jax.jit\n"
            "def kernel(x):\n"
            "    return x * time.time()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/ops/fx.py")
        assert "JAX-001" in rules_of(report)

    def test_true_positive_python_rng(self):
        src = (
            "import jax, random\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnums=(0,))\n"
            "def kernel(n, x):\n"
            "    return x + random.random()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/ops/fx.py")
        assert "JAX-001" in rules_of(report)

    def test_true_positive_bad_static_argnames(self):
        src = (
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnames=('missing',))\n"
            "def kernel(n, x):\n"
            "    return x * n\n"
        )
        report = analyze_source(src, path="cpzk_tpu/ops/fx.py")
        assert "JAX-001" in rules_of(report)

    def test_true_positive_bad_static_argnums(self):
        src = (
            "import jax\n"
            "def kernel(x):\n"
            "    return x\n"
            "jitted = jax.jit(kernel, static_argnums=(3,))\n"
        )
        report = analyze_source(src, path="cpzk_tpu/ops/fx.py")
        assert "JAX-001" in rules_of(report)

    def test_clean_pure_kernel(self):
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnames=('n',))\n"
            "def kernel(n, x):\n"
            "    key = jax.random.PRNGKey(0)\n"
            "    return jnp.sum(x) * n + jax.random.uniform(key)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/ops/fx.py")
        assert "JAX-001" not in rules_of(report)

    def test_clean_host_function_uses_clock(self):
        src = "import time\ndef host():\n    return time.time()\n"
        report = analyze_source(src, path="cpzk_tpu/ops/fx.py")
        assert "JAX-001" not in rules_of(report)


# -- execution-context inference ----------------------------------------------


class TestContextInference:
    """The interprocedural layer the context rules read: spawn-site
    seeding + caller->callee propagation (tentpole of ISSUE 15)."""

    @staticmethod
    def contexts_of(src: str) -> dict:
        from cpzk_tpu.analysis.engine import parse_module

        mod = parse_module(src, "cpzk_tpu/server/fx.py")
        return {
            info.qualname: set(info.contexts)
            for info in mod.contexts.values()
        }

    def test_thread_target_and_propagation(self):
        ctx = self.contexts_of(
            "import threading\n"
            "class Lane:\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop).start()\n"
            "    def _loop(self):\n"
            "        self._post()\n"
            "    def _post(self):\n"
            "        pass\n"
        )
        assert "thread" in ctx["Lane._loop"]
        assert "thread" in ctx["Lane._post"]  # propagated through the call
        assert "thread" not in ctx["Lane.start"]

    def test_to_thread_and_run_in_executor_targets(self):
        ctx = self.contexts_of(
            "import asyncio\n"
            "async def handler(loop):\n"
            "    def work():\n"
            "        pass\n"
            "    def work2():\n"
            "        pass\n"
            "    await asyncio.to_thread(work)\n"
            "    await loop.run_in_executor(None, work2)\n"
        )
        assert "thread" in ctx["handler.work"]
        assert "thread" in ctx["handler.work2"]

    def test_spawn_target_is_process_context(self):
        ctx = self.contexts_of(
            "import multiprocessing\n"
            "def child():\n"
            "    helper()\n"
            "def helper():\n"
            "    pass\n"
            "def spawn():\n"
            "    ctx = multiprocessing.get_context('spawn')\n"
            "    ctx.Process(target=child).start()\n"
        )
        assert "process" in ctx["child"]
        assert "process" in ctx["helper"]  # propagated

    def test_callback_runs_on_the_loop(self):
        """A callable registered through call_soon_threadsafe is seeded
        event-loop — the sanctioned bridge's callback is loop context."""
        ctx = self.contexts_of(
            "import threading\n"
            "def worker(loop, fut):\n"
            "    def deliver():\n"
            "        fut.set_result(1)\n"
            "    loop.call_soon_threadsafe(deliver)\n"
            "threading.Thread(target=worker).start()\n"
        )
        assert "thread" in ctx["worker"]
        assert ctx["worker.deliver"] == {"event-loop"}

    def test_async_defs_absorb_no_thread_context(self):
        """Calling an async def from a thread builds a coroutine; THREAD
        must not flow into it."""
        ctx = self.contexts_of(
            "import threading\n"
            "async def coro():\n"
            "    pass\n"
            "def worker():\n"
            "    coro()\n"
            "threading.Thread(target=worker).start()\n"
        )
        assert ctx["coro"] == {"event-loop"}

    def test_nested_def_resolution_is_lexical(self):
        ctx = self.contexts_of(
            "import threading\n"
            "def outer():\n"
            "    def run():\n"
            "        pass\n"
            "    threading.Thread(target=run).start()\n"
        )
        assert "thread" in ctx["outer.run"]


# -- THREAD-001 ---------------------------------------------------------------


class TestTHREAD001:
    def test_true_positive_thread_settles_future(self):
        src = (
            "import threading\n"
            "class Lane:\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._loop).start()\n"
            "    def _loop(self):\n"
            "        self._post()\n"
            "    def _post(self):\n"
            "        self.fut.set_result(1)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        msgs = [f.message for f in report.findings if f.rule == "THREAD-001"]
        assert len(msgs) == 1
        assert "set_result" in msgs[0] and "call_soon_threadsafe" in msgs[0]

    def test_true_positive_to_thread_schedules_task(self):
        src = (
            "import asyncio\n"
            "async def handler(self):\n"
            "    def work():\n"
            "        asyncio.ensure_future(self.job())\n"
            "    await asyncio.to_thread(work)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "THREAD-001" in rules_of(report)

    def test_clean_call_soon_threadsafe_bridge(self):
        """The dispatch lane's exact posting pattern: the bridge call is
        sanctioned and the callback is event-loop context."""
        src = (
            "import threading\n"
            "class Lane:\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._post).start()\n"
            "    def _post(self):\n"
            "        def _resolve():\n"
            "            self.fut.set_result(1)\n"
            "        self.loop.call_soon_threadsafe(_resolve)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "THREAD-001" not in rules_of(report)

    def test_clean_thread_owned_loop(self):
        """The start_in_thread bootstrap: a loop the thread itself
        created is driven with call_soon legitimately."""
        src = (
            "import asyncio, threading\n"
            "def start_in_thread(self):\n"
            "    def run():\n"
            "        loop = asyncio.new_event_loop()\n"
            "        loop.call_soon(self.start)\n"
            "        loop.run_forever()\n"
            "    threading.Thread(target=run).start()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "THREAD-001" not in rules_of(report)

    def test_clean_event_loop_context_untouched(self):
        src = (
            "async def handler(self):\n"
            "    self.fut.set_result(1)\n"
            "def plain(self):\n"
            "    self.fut.set_result(1)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "THREAD-001" not in rules_of(report)


# -- FUNNEL-001 ---------------------------------------------------------------


class TestFUNNEL001:
    def test_true_positive_direct_shard_write(self):
        src = (
            "class ServerState:\n"
            "    async def bad(self, uid, data):\n"
            "        shard = self._shard_for_user(uid)\n"
            "        async with shard.lock:\n"
            "            shard._sessions[uid] = data\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/state.py")
        msgs = [f.message for f in report.findings if f.rule == "FUNNEL-001"]
        assert len(msgs) == 1
        assert "_session_insert" in msgs[0]

    def test_true_positive_registry_alias_pop(self):
        """The sweep's ternary alias shape must not hide a mutation."""
        src = (
            "class ServerState:\n"
            "    async def bad(self, key, is_sessions):\n"
            "        for shard in self._shards:\n"
            "            registry = (\n"
            "                shard._sessions if is_sessions\n"
            "                else shard._challenges\n"
            "            )\n"
            "            registry.pop(key, None)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/state.py")
        assert "FUNNEL-001" in rules_of(report)

    def test_true_positive_del_through_self(self):
        src = (
            "class ServerState:\n"
            "    async def bad(self, uid):\n"
            "        del self._users[uid]\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/state.py")
        assert "FUNNEL-001" in rules_of(report)

    def test_clean_funnels_and_reads(self):
        src = (
            "class ServerState:\n"
            "    def __init__(self):\n"
            "        self._users = {}\n"
            "    def _session_insert(self, shard, data):\n"
            "        shard._sessions[data.token] = data\n"
            "    def _session_remove(self, shard, token):\n"
            "        return shard._sessions.pop(token, None)\n"
            "    async def good(self, uid, data):\n"
            "        shard = self._shard_for_user(uid)\n"
            "        async with shard.lock:\n"
            "            self._session_insert(shard, data)\n"
            "            return shard._sessions.get(uid)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/state.py")
        assert "FUNNEL-001" not in rules_of(report)

    def test_other_classes_out_of_scope(self):
        src = (
            "class Cache:\n"
            "    def put(self, k, v):\n"
            "        self._sessions[k] = v\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/batching.py")
        assert "FUNNEL-001" not in rules_of(report)

    def test_real_state_self_hosts(self):
        """The live ServerState routes every registry mutation through
        the six funnels — zero FUNNEL-001 findings, no waivers needed."""
        report = analyze_paths(
            [os.path.join(PKG, "server", "state.py")], rules=["FUNNEL-001"]
        )
        assert [f.render() for f in report.findings] == []


# -- PROC-001 -----------------------------------------------------------------


class TestPROC001:
    def test_true_positive_bound_method_target(self):
        src = (
            "import multiprocessing\n"
            "class Sup:\n"
            "    def spawn(self):\n"
            "        ctx = multiprocessing.get_context('spawn')\n"
            "        ctx.Process(target=self._run).start()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        msgs = [f.message for f in report.findings if f.rule == "PROC-001"]
        assert len(msgs) == 1 and "bound" in msgs[0]

    def test_true_positive_nested_def_target(self):
        src = (
            "import multiprocessing\n"
            "def spawn():\n"
            "    def child():\n"
            "        pass\n"
            "    multiprocessing.Process(target=child).start()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "PROC-001" in rules_of(report)

    def test_true_positive_lambda_target(self):
        src = (
            "import multiprocessing\n"
            "multiprocessing.Process(target=lambda: None).start()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "PROC-001" in rules_of(report)

    def test_true_positive_unsafe_args(self):
        src = (
            "import multiprocessing, threading\n"
            "def child(x, y):\n"
            "    pass\n"
            "class Sup:\n"
            "    def spawn(self):\n"
            "        lock = threading.Lock()\n"
            "        ctx = multiprocessing.get_context('spawn')\n"
            "        ctx.Process(target=child, args=(lock, self)).start()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        msgs = [f.message for f in report.findings if f.rule == "PROC-001"]
        assert len(msgs) == 2
        assert any("lock" in m for m in msgs)
        assert any("`self`" in m for m in msgs)

    def test_clean_module_level_target_plain_args(self):
        """The real ingest spawn shape: module-level target, primitives,
        attribute reads (self.host is a value, not the instance)."""
        src = (
            "import multiprocessing\n"
            "def run_shard(i, path, opts):\n"
            "    pass\n"
            "class Sup:\n"
            "    def spawn(self, index):\n"
            "        ctx = multiprocessing.get_context('spawn')\n"
            "        ctx.Process(\n"
            "            target=run_shard,\n"
            "            args=(index, self.uds_path, {'host': self.host}),\n"
            "        ).start()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "PROC-001" not in rules_of(report)

    def test_real_ingest_self_hosts(self):
        report = analyze_paths(
            [os.path.join(PKG, "server", "ingest.py")], rules=["PROC-001"]
        )
        assert [f.render() for f in report.findings] == []


# -- FRAME-001 ----------------------------------------------------------------


class TestFRAME001:
    TP = (
        "import struct, zlib\n"
        "_H = struct.Struct('>II')\n"
        "def frame(p: bytes) -> bytes:\n"
        "    crc = zlib.crc32(p) & 0xFFFFFFFF\n"
        "    return _H.pack(len(p), crc) + p\n"
    )

    def test_true_positive_hand_rolled_frame(self):
        report = analyze_source(self.TP, path="cpzk_tpu/server/fx.py")
        msgs = [f.message for f in report.findings if f.rule == "FRAME-001"]
        # the private header declaration AND the pack-with-crc are each
        # findings (reverting the ingest refactor re-fails on both)
        assert len(msgs) == 2
        assert any("frame_payload" in m for m in msgs)
        assert any("'>II'" in m for m in msgs)

    def test_wal_itself_is_the_canonical_home(self):
        report = analyze_source(self.TP, path="cpzk_tpu/durability/wal.py")
        assert "FRAME-001" not in rules_of(report)

    def test_clean_non_framing_crc(self):
        """Whole-object CRCs (segment checksums, shard hashes) that never
        enter a packed header are out of scope."""
        src = (
            "import zlib\n"
            "def shard_index(uid: str, n: int) -> int:\n"
            "    return zlib.crc32(uid.encode()) % n\n"
            "def seg_crc(frames: bytes) -> int:\n"
            "    return zlib.crc32(frames) & 0xFFFFFFFF\n"
        )
        report = analyze_source(src, path="cpzk_tpu/replication/fx.py")
        assert "FRAME-001" not in rules_of(report)

    def test_real_ingest_uses_shared_helpers(self):
        """The FRAME-001 fix of this PR: reverting server/ingest.py to
        its hand-rolled _HEADER re-fails here (and in the self-host)."""
        report = analyze_paths(
            [os.path.join(PKG, "server", "ingest.py")], rules=["FRAME-001"]
        )
        assert [f.render() for f in report.findings] == []

    def test_shared_helpers_are_byte_identical(self):
        """pack_frame rides wal.frame_payload — one framing contract."""
        from cpzk_tpu.durability.wal import frame_payload, iter_frames
        from cpzk_tpu.server.ingest import pack_frame

        payload = b'{"seq":1,"type":"x"}'
        assert pack_frame(payload) == frame_payload(payload)
        rec, valid = iter_frames(frame_payload(payload))
        assert rec == [{"seq": 1, "type": "x"}]
        assert valid == len(frame_payload(payload))


# -- WAIVER-002 ---------------------------------------------------------------


class TestWAIVER002:
    def test_stale_waiver_is_a_finding(self):
        src = "x = 1  # cpzk-lint: disable=CT-001 -- nothing fires here\n"
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert [f.rule for f in report.findings] == ["WAIVER-002"]
        assert "stale" in report.findings[0].message

    def test_live_waiver_is_not_stale(self):
        src = (
            "import asyncio\n"
            "asyncio.create_task(f())  "
            "# cpzk-lint: disable=ASYNC-002 -- fixture: live\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert report.findings == []

    def test_unknown_rule_id_is_stale(self):
        src = "x = 1  # cpzk-lint: disable=NO-SUCH-RULE -- typo'd id\n"
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert [f.rule for f in report.findings] == ["WAIVER-002"]

    def test_mixed_waiver_reports_only_the_stale_id(self):
        src = (
            "import asyncio\n"
            "asyncio.create_task(f())  "
            "# cpzk-lint: disable=ASYNC-002,CT-001 -- one live, one stale\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert [f.rule for f in report.findings] == ["WAIVER-002"]
        assert "CT-001" in report.findings[0].message
        assert "ASYNC-002" not in report.findings[0].message

    def test_rules_filter_cannot_judge_staleness(self):
        """A --rules run that skipped the waived rule must not call its
        waiver stale (the rule never got a chance to fire)."""
        from cpzk_tpu.analysis.engine import _analyze

        src = "x = 1  # cpzk-lint: disable=CT-001 -- fixture\n"
        report = _analyze(
            [(src, "cpzk_tpu/server/fx.py")], ["ASYNC-002", "WAIVER-002"]
        )
        assert report.findings == []

    def test_waiver_002_cannot_be_waived(self):
        """Emitted by the engine after waiver matching — a disable
        comment cannot suppress its own staleness."""
        src = (
            "x = 1  "
            "# cpzk-lint: disable=CT-001,WAIVER-002 -- try to self-excuse\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "WAIVER-002" in [f.rule for f in report.findings]

    def test_docstring_mention_is_not_a_waiver(self):
        """The tokenize-based comment scan: waiver syntax quoted inside a
        string/docstring (the docs do) must not register at all."""
        src = (
            '"""Write `# cpzk-lint: disable=CT-001 -- why` inline."""\n'
            "MSG = 'use # cpzk-lint: disable=LOCK-001 -- reason'\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert report.findings == []
        assert report.waivers == []

    def test_real_tree_has_no_stale_waivers(self):
        report = analyze_paths([PKG])
        stale = [w.render() for w in report.waivers if w.stale]
        assert stale == []
        assert all(w.reason for w in report.waivers)

    def test_audit_waivers_cli(self):
        proc = subprocess.run(
            [sys.executable, "-m", "cpzk_tpu.analysis", PKG,
             "--audit-waivers"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "waivers (0 stale)" in proc.stdout
        assert "state.py" in proc.stdout  # the documented LOCK-001 trio
        assert "active (" in proc.stdout

    def test_audit_waivers_cli_exits_one_on_stale(self, tmp_path):
        bad = tmp_path / "cpzk_tpu" / "server" / "fx.py"
        bad.parent.mkdir(parents=True)
        bad.write_text("x = 1  # cpzk-lint: disable=CT-001 -- stale\n")
        proc = subprocess.run(
            [sys.executable, "-m", "cpzk_tpu.analysis", str(bad),
             "--audit-waivers"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 1
        assert "STALE" in proc.stdout


# -- waivers ------------------------------------------------------------------


class TestWaivers:
    BAD_LINE = "import asyncio\nasyncio.create_task(f())"

    def test_waiver_with_reason_suppresses(self):
        src = (
            "import asyncio\n"
            "asyncio.create_task(f())  "
            "# cpzk-lint: disable=ASYNC-002 -- fixture: lifetime managed by caller\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert report.findings == []
        assert [f.rule for f in report.waived] == ["ASYNC-002"]

    def test_waiver_without_reason_is_a_finding(self):
        src = (
            "import asyncio\n"
            "asyncio.create_task(f())  # cpzk-lint: disable=ASYNC-002\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        # the original finding IS suppressed, but the bare waiver is its
        # own (unwaivable) finding — suppressions always carry a why
        assert [f.rule for f in report.findings] == ["WAIVER-001"]

    def test_waiver_wrong_rule_does_not_suppress(self):
        src = (
            "import asyncio\n"
            "asyncio.create_task(f())  # cpzk-lint: disable=CT-001 -- wrong id\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "ASYNC-002" in rules_of(report)

    def test_function_scope_waiver_on_def_line(self):
        src = (
            "import asyncio\n"
            "# cpzk-lint: disable=ASYNC-002 -- fixture: fire-and-forget by design\n"
            "async def f():\n"
            "    asyncio.create_task(a())\n"
            "    asyncio.create_task(b())\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert report.findings == []
        assert len(report.waived) == 2

    def test_comment_only_waiver_covers_next_line(self):
        src = (
            "import asyncio\n"
            "# cpzk-lint: disable=ASYNC-002 -- fixture: covered next line\n"
            "asyncio.create_task(f())\n"
            "asyncio.create_task(g())\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert len(report.findings) == 1  # only the uncovered second spawn
        assert len(report.waived) == 1


# -- AWAIT-001 (guard staleness across awaits — the PR 16 bug shape) ----------


class TestAWAIT001:
    PRE_FIX = (
        # the exact pre-fix VerifyProof shape: ownership checked at
        # entry, handler parks in the batcher, mints on a stale verdict
        "async def verify_proof(self, request):\n"
        "    if not self.fleet.owns(request.user_id):\n"
        "        return self._redirect_abort(request)\n"
        "    ok = await self.batcher.submit(request)\n"
        "    return await self.state.create_session(request.user_id, ok)\n"
    )

    def test_true_positive_pre_fix_verify_proof_shape(self):
        report = analyze_source(self.PRE_FIX, path="cpzk_tpu/server/fx.py")
        assert rules_of(report) == ["AWAIT-001"]
        assert "await" in report.findings[0].message

    def test_post_fix_wrong_partition_handler_is_clean(self):
        # the shipped fix: the mutation re-fences inside its shard lock
        # and the call site catches WrongPartition -> redirect
        src = (
            "from cpzk_tpu import errors\n"
            "async def verify_proof(self, request):\n"
            "    if not self.fleet.owns(request.user_id):\n"
            "        return self._redirect_abort(request)\n"
            "    ok = await self.batcher.submit(request)\n"
            "    try:\n"
            "        return await self.state.create_session(\n"
            "            request.user_id, ok)\n"
            "    except errors.WrongPartition:\n"
            "        return self._redirect_abort(request)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert report.findings == []

    def test_guard_reread_after_await_is_clean(self):
        src = (
            "async def verify_proof(self, request):\n"
            "    if not self.fleet.owns(request.user_id):\n"
            "        return self._redirect_abort(request)\n"
            "    ok = await self.batcher.submit(request)\n"
            "    if not self.fleet.owns(request.user_id):\n"
            "        return self._redirect_abort(request)\n"
            "    return await self.state.create_session(request.user_id, ok)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert report.findings == []

    def test_no_await_between_guard_and_mutation_is_clean(self):
        # the register_batch shape: guard re-read synchronously in the
        # same iteration, nothing suspends in between
        src = (
            "async def register(self, request):\n"
            "    if not self.fleet.owns(request.user_id):\n"
            "        return self._redirect_abort(request)\n"
            "    return await self.state.register_user(request.user_id)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert report.findings == []

    def test_waiver_suppresses_and_stale_waiver_fires(self):
        waived = self.PRE_FIX.replace(
            "    ok = await self.batcher.submit(request)\n",
            "    ok = await self.batcher.submit(request)\n"
            "    # cpzk-lint: disable=AWAIT-001 -- fixture: callee re-fences\n",
        )
        report = analyze_source(waived, path="cpzk_tpu/server/fx.py")
        assert report.findings == []
        assert [f.rule for f in report.waived] == ["AWAIT-001"]
        stale = (
            "# cpzk-lint: disable=AWAIT-001 -- fixture: nothing fires here\n"
            "async def quiet(self):\n"
            "    return 1\n"
        )
        report = analyze_source(stale, path="cpzk_tpu/server/fx.py")
        assert [f.rule for f in report.findings] == ["WAIVER-002"]


# -- ACK-001 (journal append must dominate the ack) ---------------------------


class TestACK001:
    def test_true_positive_ack_before_durable(self):
        src = (
            "class ServerState:\n"
            "    async def register_user(self, user_id, record):\n"
            "        shard = self._shard(user_id)\n"
            "        async with shard.lock:\n"
            "            self._fence(user_id)\n"
            "            self._user_insert(user_id, record)\n"
            "        return True\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert rules_of(report) == ["ACK-001"]

    def test_journal_then_sync_before_ack_is_clean(self):
        # the real funnel discipline: append under the shard lock, fsync
        # after it is released, ack last
        src = (
            "class ServerState:\n"
            "    async def register_user(self, user_id, record):\n"
            "        shard = self._shard(user_id)\n"
            "        async with shard.lock:\n"
            "            self._fence(user_id)\n"
            "            self._user_insert(user_id, record)\n"
            "            rec = self._journal_append(record)\n"
            "        await self._journal_sync()\n"
            "        return rec\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert report.findings == []

    def test_fall_off_the_end_counts_as_an_ack(self):
        # returning None to an awaiting RPC acknowledges it just as much
        src = (
            "class ServerState:\n"
            "    async def revoke_session(self, user_id, sid):\n"
            "        shard = self._shard(user_id)\n"
            "        async with shard.lock:\n"
            "            self._fence(user_id)\n"
            "            self._session_remove(user_id, sid)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert rules_of(report) == ["ACK-001"]

    def test_set_result_is_an_ack(self):
        src = (
            "class ServerState:\n"
            "    async def register_user(self, user_id, fut, record):\n"
            "        shard = self._shard(user_id)\n"
            "        async with shard.lock:\n"
            "            self._fence(user_id)\n"
            "            self._user_insert(user_id, record)\n"
            "            fut.set_result(True)\n"
            "        await self._journal_sync()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "ACK-001" in rules_of(report)


# -- FENCE-001 (user-keyed mutations re-check ownership under the lock) -------


class TestFENCE001:
    def test_true_positive_unfenced_funnel_in_lock(self):
        src = (
            "class ServerState:\n"
            "    async def register_user(self, user_id, record):\n"
            "        shard = self._shard(user_id)\n"
            "        async with shard.lock:\n"
            "            self._user_insert(user_id, record)\n"
            "            rec = self._journal_append(record)\n"
            "        await self._journal_sync()\n"
            "        return rec\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert rules_of(report) == ["FENCE-001"]

    def test_true_positive_funnel_outside_any_lock(self):
        src = (
            "class ServerState:\n"
            "    async def register_user(self, user_id, record):\n"
            "        self._user_insert(user_id, record)\n"
            "        rec = self._journal_append(record)\n"
            "        await self._journal_sync()\n"
            "        return rec\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "FENCE-001" in rules_of(report)
        assert "lock" in report.findings[0].message

    def test_fence_inside_same_lock_is_clean(self):
        src = (
            "class ServerState:\n"
            "    async def register_user(self, user_id, record):\n"
            "        shard = self._shard(user_id)\n"
            "        async with shard.lock:\n"
            "            self._fence(user_id)\n"
            "            self._user_insert(user_id, record)\n"
            "            rec = self._journal_append(record)\n"
            "        await self._journal_sync()\n"
            "        return rec\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert report.findings == []

    def test_fence_alias_is_tracked(self):
        # the create_sessions shape: the bound method is hoisted once
        # and called per entry inside the lock
        src = (
            "class ServerState:\n"
            "    async def create_sessions(self, entries):\n"
            "        fence = self.owner_fence\n"
            "        shard = self._shard(0)\n"
            "        async with shard.lock:\n"
            "            for user_id, rec in entries:\n"
            "                fence(user_id)\n"
            "                self._session_insert(user_id, rec)\n"
            "                self._journal_append(rec)\n"
            "        await self._journal_sync()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert report.findings == []

    def test_other_classes_are_out_of_scope(self):
        # the fence contract is ServerState's; a test double reusing the
        # funnel names must not fire
        src = (
            "class FakeStore:\n"
            "    async def register_user(self, user_id, record):\n"
            "        self._user_insert(user_id, record)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "FENCE-001" not in rules_of(report)

    def test_waiver_suppresses(self):
        src = (
            "class ServerState:\n"
            "    # cpzk-lint: disable=FENCE-001,ACK-001 -- fixture: boot path\n"
            "    async def register_user(self, user_id, record):\n"
            "        self._user_insert(user_id, record)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert report.findings == []
        assert {f.rule for f in report.waived} == {"FENCE-001", "ACK-001"}


# -- report contract ----------------------------------------------------------


class TestReportContract:
    def test_json_schema_stable(self):
        """Drift guard: the CI artifact's consumers pin these keys.
        Version 2 added the ``waivers`` audit list (WAIVER-002)."""
        doc = analyze_source("x = 1\n").to_dict()
        assert sorted(doc) == [
            "files", "findings", "rule_ids", "schema_version", "summary",
            "tool", "waived", "waivers",
        ]
        assert doc["schema_version"] == 2
        assert doc["tool"] == "cpzk-lint"
        assert sorted(doc["summary"]) == ["findings", "waived"]
        waivers = analyze_source(
            "import asyncio\n"
            "asyncio.create_task(f())  "
            "# cpzk-lint: disable=ASYNC-002 -- fixture: schema pin\n",
            path="cpzk_tpu/server/fx.py",
        ).to_dict()["waivers"]
        assert sorted(waivers[0]) == [
            "line", "path", "reason", "rules", "stale", "waived",
        ]
        bad = analyze_source(
            "import asyncio\nasyncio.create_task(f())\n",
            path="cpzk_tpu/server/fx.py",
        ).to_dict()
        assert sorted(bad["findings"][0]) == [
            "col", "line", "message", "path", "rule",
        ]

    def test_registry_has_the_promised_rule_pack(self):
        for rule_id in CORE_RULES + ["WAIVER-001", "WAIVER-002", "PARSE-001"]:
            assert rule_id in REGISTRY, rule_id
        assert all_rule_ids() == sorted(REGISTRY)

    def test_rules_documented_in_security_md(self):
        """Docs drift guard: every registered rule id appears in
        docs/security.md's enforced-invariants section, and no documented
        CT/LEAK/LOCK/ASYNC/GRPC/JAX id is missing from the registry."""
        with open(os.path.join(REPO, "docs", "security.md")) as f:
            doc = f.read()
        for rule_id in all_rule_ids():
            assert rule_id in doc, f"{rule_id} missing from docs/security.md"

    def test_parse_error_is_a_finding_not_a_crash(self):
        report = analyze_source("def f(:\n")
        assert [f.rule for f in report.findings] == ["PARSE-001"]

    def test_rule_filter(self):
        src = (
            "import asyncio, time\n"
            "async def f():\n"
            "    time.sleep(1)\n"
            "    asyncio.create_task(g())\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert set(rules_of(report)) == {"ASYNC-001", "ASYNC-002"}
        from cpzk_tpu.analysis.engine import _analyze

        only = _analyze([(src, "cpzk_tpu/server/fx.py")], ["ASYNC-001"])
        assert rules_of(only) == ["ASYNC-001"]


# -- output formats (--format text|json|sarif) --------------------------------


class TestOutputFormats:
    BAD = "import asyncio\nasyncio.create_task(f())\n"

    def _run(self, *argv, cwd=REPO):
        return subprocess.run(
            [sys.executable, "-m", "cpzk_tpu.analysis", *argv],
            capture_output=True, text=True, cwd=cwd,
        )

    def _bad_file(self, tmp_path):
        bad = tmp_path / "cpzk_tpu" / "server" / "bad.py"
        bad.parent.mkdir(parents=True)
        bad.write_text(self.BAD)
        return bad

    def test_sarif_document_shape(self):
        doc = analyze_source(
            self.BAD, path="cpzk_tpu/server/fx.py"
        ).to_sarif()
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "cpzk-lint"
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert set(all_rule_ids()) <= rule_ids
        results = run["results"]
        assert results, "expected the ASYNC-002 finding as a result"
        res = results[0]
        assert res["ruleId"] == "ASYNC-002"
        assert res["level"] == "error"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "cpzk_tpu/server/fx.py"
        assert loc["region"]["startLine"] == 2
        assert loc["region"]["startColumn"] >= 1  # SARIF is 1-based

    def test_sarif_waived_findings_are_suppressed_results(self):
        src = (
            "import asyncio\n"
            "asyncio.create_task(f())  "
            "# cpzk-lint: disable=ASYNC-002 -- fixture: sarif suppression\n"
        )
        doc = analyze_source(src, path="cpzk_tpu/server/fx.py").to_sarif()
        results = doc["runs"][0]["results"]
        assert len(results) == 1
        assert results[0]["suppressions"] == [{"kind": "inSource"}]

    def test_cli_format_sarif_parses_and_exit_codes_unchanged(
        self, tmp_path
    ):
        bad = self._bad_file(tmp_path)
        proc = self._run(str(bad), "--format", "sarif")
        assert proc.returncode == 1  # findings still gate, whatever format
        doc = json.loads(proc.stdout)
        assert doc["version"] == "2.1.0"
        clean = self._run(PKG, "--format", "sarif")
        assert clean.returncode == 0, clean.stdout + clean.stderr
        results = json.loads(clean.stdout)["runs"][0]["results"]
        # the tree's reasoned waivers ride along as suppressed results;
        # nothing may be live
        assert [r for r in results if not r.get("suppressions")] == []
        assert all(
            r["suppressions"] == [{"kind": "inSource"}] for r in results
        )

    def test_cli_json_flag_is_an_alias_for_format_json(self, tmp_path):
        bad = self._bad_file(tmp_path)
        via_alias = self._run(str(bad), "--json")
        via_format = self._run(str(bad), "--format", "json")
        assert via_alias.returncode == via_format.returncode == 1
        assert json.loads(via_alias.stdout) == json.loads(via_format.stdout)

    def test_cli_default_output_is_unchanged_human_text(self, tmp_path):
        bad = self._bad_file(tmp_path)
        proc = self._run(str(bad))
        assert proc.returncode == 1
        assert "ASYNC-002" in proc.stdout
        with pytest.raises(json.JSONDecodeError):
            json.loads(proc.stdout)


# -- redaction guard (secret-type reprs) --------------------------------------


class TestRedactionGuard:
    @pytest.fixture()
    def secret_scalar(self):
        from cpzk_tpu.core.ristretto import Scalar

        return Scalar(0x1F2E3D4C5B6A79880102030405060708090A0B0C0D0E0F1011121314151617)

    def _assert_redacted(self, obj, scalar):
        from cpzk_tpu.core.scalars import sc_to_bytes

        encodings = {
            f"{scalar.value:x}",
            f"{scalar.value:064x}",
            str(scalar.value),
            sc_to_bytes(scalar.value).hex(),
        }
        for text in (repr(obj), str(obj), f"{obj}"):
            low = text.lower()
            for enc in encodings:
                assert enc.lower() not in low, (
                    f"secret encoding leaked through {type(obj).__name__} repr"
                )
            assert "redacted" in low

    def test_witness_repr_redacts(self, secret_scalar):
        from cpzk_tpu.protocol.gadgets import Witness

        self._assert_redacted(Witness(secret_scalar), secret_scalar)

    def test_nonce_repr_redacts(self, secret_scalar):
        from cpzk_tpu.protocol.prover import Nonce

        self._assert_redacted(Nonce(secret_scalar), secret_scalar)

    def test_response_repr_redacts(self, secret_scalar):
        from cpzk_tpu.protocol.gadgets import Response

        self._assert_redacted(Response(secret_scalar), secret_scalar)
