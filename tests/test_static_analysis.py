"""cpzk-lint: self-hosted zero-findings gate + per-rule fixtures.

Three layers:

- **Self-hosting** — the analyzer runs over the whole ``cpzk_tpu`` tree
  and must report zero findings.  This is the structural enforcement of
  every invariant in docs/security.md "Mechanically enforced invariants":
  reverting any of this PR's real-violation fixes (the async-def file
  reads in ``state.restore`` / ``recovery.recover_state`` / the daemon's
  TLS load) or the PR-4 ``_abort_exhausted`` routing makes this test
  fail.
- **Fixtures** — each of the 8 rules has at least one true-positive and
  one clean fixture, so a rule that silently stops firing (or starts
  over-firing) is caught here rather than by the empty self-host run.
- **Contract** — waiver handling (a reason is mandatory), JSON schema
  stability, the docs/rule-registry drift guard, and the secret-type
  redaction guard.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from cpzk_tpu.analysis import REGISTRY, all_rule_ids, analyze_paths, analyze_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "cpzk_tpu")

#: The rule pack the tentpole promises; WAIVER/PARSE are engine-emitted.
CORE_RULES = [
    "CT-001", "CT-002", "LEAK-001", "LOCK-001",
    "ASYNC-001", "ASYNC-002", "GRPC-001", "JAX-001",
]


def rules_of(report) -> list[str]:
    return sorted({f.rule for f in report.findings})


# -- self-hosting -------------------------------------------------------------


class TestSelfHosted:
    def test_whole_tree_is_clean(self):
        """THE gate: zero findings over the real package.  A new violation
        anywhere in cpzk_tpu/ — or a reverted fix — fails tier-1."""
        report = analyze_paths([PKG])
        assert report.files > 50  # sanity: the walker saw the real tree
        assert [f.render() for f in report.findings] == []

    def test_real_waivers_carry_reasons(self):
        """The tree's own waivers (ServerState's documented
        single-threaded paths) are active, reasoned, and bounded."""
        report = analyze_paths([PKG])
        assert report.waived, "expected the documented LOCK-001 waivers"
        assert {f.rule for f in report.waived} == {"LOCK-001"}
        assert all("state.py" in f.path for f in report.waived)

    def test_cli_json_on_real_tree(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, "-m", "cpzk_tpu.analysis", PKG, "--json"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["findings"] == []
        assert doc["summary"]["findings"] == 0

    def test_cli_exit_two_on_missing_path(self):
        proc = subprocess.run(
            [sys.executable, "-m", "cpzk_tpu.analysis", "/no/such/dir"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 2  # a typo'd path must not gate green

    def test_cli_exit_one_on_findings(self, tmp_path):
        bad = tmp_path / "cpzk_tpu" / "server" / "bad.py"
        bad.parent.mkdir(parents=True)
        bad.write_text("import asyncio\nasyncio.create_task(f())\n")
        proc = subprocess.run(
            [sys.executable, "-m", "cpzk_tpu.analysis", str(bad)],
            capture_output=True, text=True, cwd=REPO,
        )
        assert proc.returncode == 1
        assert "ASYNC-002" in proc.stdout


# -- CT-001 -------------------------------------------------------------------


class TestCT001:
    def test_true_positive_secret_bytes_equality(self):
        src = (
            "import hashlib\n"
            "def check(password: str, stored: bytes) -> bool:\n"
            "    okm = hashlib.sha256(password.encode()).digest()\n"
            "    return okm == stored\n"
        )
        report = analyze_source(src, path="cpzk_tpu/client/fx.py")
        assert "CT-001" in rules_of(report)

    def test_true_positive_kdf_output(self):
        src = (
            "from argon2.low_level import hash_secret_raw\n"
            "def check(data, stored):\n"
            "    okm = hash_secret_raw(secret=data, salt=b'x')\n"
            "    return stored != okm\n"
        )
        report = analyze_source(src, path="cpzk_tpu/client/fx.py")
        assert "CT-001" in rules_of(report)

    def test_clean_compare_digest(self):
        src = (
            "import hashlib, hmac\n"
            "def check(password: str, stored: bytes) -> bool:\n"
            "    okm = hashlib.sha256(password.encode()).digest()\n"
            "    return hmac.compare_digest(okm, stored)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/client/fx.py")
        assert "CT-001" not in rules_of(report)

    def test_clean_scalar_equality(self):
        """Scalar-to-Scalar == goes through the ct __eq__ — not a finding."""
        src = (
            "def check(witness: Witness, other: Witness) -> bool:\n"
            "    return witness.secret() == other.secret()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/protocol/fx.py")
        assert "CT-001" not in rules_of(report)

    def test_clean_public_equality(self):
        src = "def f(a: bytes, b: bytes):\n    return a == b\n"
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert rules_of(report) == []


# -- CT-002 -------------------------------------------------------------------


class TestCT002:
    TP = (
        "def f(witness: Witness):\n"
        "    x = witness.secret()\n"
        "    if x.value:\n"
        "        return 1\n"
        "    return 0\n"
    )

    def test_true_positive_in_core(self):
        report = analyze_source(self.TP, path="cpzk_tpu/core/fx.py")
        assert "CT-002" in rules_of(report)

    def test_true_positive_short_circuit(self):
        src = (
            "def f(nonce: Nonce, flag: bool):\n"
            "    return nonce.k().value and flag\n"
        )
        report = analyze_source(src, path="cpzk_tpu/protocol/fx.py")
        assert "CT-002" in rules_of(report)

    def test_out_of_scope_plane_is_clean(self):
        """Host planes branch on secrets' existence legitimately; CT-002
        is scoped to the protocol math."""
        report = analyze_source(self.TP, path="cpzk_tpu/server/fx.py")
        assert "CT-002" not in rules_of(report)

    def test_clean_public_branch(self):
        src = (
            "def f(witness: Witness, n: int):\n"
            "    if n > 0:\n"
            "        return witness.secret()\n"
            "    return None\n"
        )
        report = analyze_source(src, path="cpzk_tpu/core/fx.py")
        assert "CT-002" not in rules_of(report)


# -- LEAK-001 -----------------------------------------------------------------


class TestLEAK001:
    def test_true_positive_fstring_log(self):
        src = (
            "import logging\n"
            "log = logging.getLogger('x')\n"
            "def f(witness: Witness):\n"
            "    log.info(f'witness is {witness.secret().value}')\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "LEAK-001" in rules_of(report)

    def test_true_positive_exception_message(self):
        src = (
            "def f(password: str):\n"
            "    raise ValueError('bad password: ' + password)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/client/fx.py")
        assert "LEAK-001" in rules_of(report)

    def test_true_positive_record_event(self):
        src = (
            "def f(tracer, nonce: Nonce):\n"
            "    tracer.record_event('prove', k=nonce.k().value)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/protocol/fx.py")
        assert "LEAK-001" in rules_of(report)

    def test_true_positive_metric_label(self):
        src = (
            "def f(hist, password: str):\n"
            "    hist.labels(backend=password).observe(1.0)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "LEAK-001" in rules_of(report)

    def test_clean_public_logging(self):
        src = (
            "import logging\n"
            "log = logging.getLogger('x')\n"
            "def f(witness: Witness, user_id: str):\n"
            "    log.info('registered %s', user_id)\n"
            "    log.info(f'user {user_id} ok')\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "LEAK-001" not in rules_of(report)

    def test_clean_length_is_sanitized(self):
        src = (
            "import logging\n"
            "log = logging.getLogger('x')\n"
            "def f(password: str):\n"
            "    log.info('password length %d', len(password))\n"
        )
        report = analyze_source(src, path="cpzk_tpu/client/fx.py")
        assert "LEAK-001" not in rules_of(report)


# -- LOCK-001 -----------------------------------------------------------------


FIXTURE_STATE = """\
import asyncio

class ServerState:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._users = {}
        self._sessions = {}
        self._user_sessions = {}
        self.journal = None

    async def good(self, uid, data):
        async with self._lock:
            self._users[uid] = data
            self._journal_append("register_user", {})

    async def bad(self, uid, data):
        self._users[uid] = data

    async def bad_pop(self, token):
        self._sessions.pop(token, None)

    async def bad_alias(self, uid, token):
        per_user = self._user_sessions.setdefault(uid, [])
        per_user.append(token)

    async def bad_journal(self):
        self._journal_append("revoke_session", {})
"""


class TestLOCK001:
    def test_true_positives(self):
        report = analyze_source(FIXTURE_STATE, path="cpzk_tpu/server/state.py")
        lock_findings = [f for f in report.findings if f.rule == "LOCK-001"]
        flagged = "\n".join(f.message for f in lock_findings)
        # bad, bad_pop, bad_alias (both the .setdefault and the aliased
        # .append), bad_journal — and never the locked/`__init__` sites
        assert len(lock_findings) == 5
        assert "bad " in flagged or "rebinds" in flagged or "subscript" in flagged
        assert any("journal" in f.message for f in lock_findings)
        assert any(".append()" in f.message for f in lock_findings)

    def test_clean_under_lock_and_init(self):
        clean = FIXTURE_STATE.split("    async def bad")[0]
        report = analyze_source(clean, path="cpzk_tpu/server/state.py")
        assert "LOCK-001" not in rules_of(report)

    def test_other_classes_out_of_scope(self):
        src = (
            "class Batcher:\n"
            "    def f(self):\n"
            "        self._users['a'] = 1\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/batching.py")
        assert "LOCK-001" not in rules_of(report)


FIXTURE_SHARDED = """\
import asyncio

class ServerState:
    def __init__(self):
        self._shards = [None]

    def _shard_for_user(self, uid):
        return self._shards[0]

    async def good(self, uid, data):
        shard = self._shard_for_user(uid)
        async with shard.lock:
            shard._users[uid] = data
            per_user = shard._user_sessions.setdefault(uid, [])
            per_user.append("t")
            self._journal_append("register_user", {})

    async def good_sweep(self):
        for shard in self._shards:
            async with shard.lock:
                shard._sessions.pop("t", None)

    async def good_subscript_alias(self, idx, cid):
        shard = self._shards[idx]
        async with shard.lock:
            del shard._challenges[cid]

    async def bad(self, uid, data):
        shard = self._shard_for_user(uid)
        shard._users[uid] = data

    async def bad_wrong_shard_lock(self, uid, data):
        a = self._shard_for_user(uid)
        b = self._shard_for_user("other")
        async with a.lock:
            b._users[uid] = data

    async def bad_member_alias(self, uid, token):
        shard = self._shard_for_user(uid)
        per_user = shard._user_sessions.setdefault(uid, [])
        per_user.append(token)

    async def bad_journal_outside(self, uid):
        shard = self._shard_for_user(uid)
        self._journal_append("revoke_session", {})
"""


class TestLOCK001Sharded:
    """The sharded-lock contract (ISSUE 8): mutations through a shard
    alias need that SAME shard's lock; journal appends need any held
    state/shard lock.  The real sharded ``ServerState`` self-hosts at
    zero findings through these rules — no blanket waivers."""

    def test_true_positives(self):
        report = analyze_source(FIXTURE_SHARDED, path="cpzk_tpu/server/state.py")
        lock_findings = [f for f in report.findings if f.rule == "LOCK-001"]
        flagged = "\n".join(f.message for f in lock_findings)
        # bad, bad_wrong_shard_lock, bad_member_alias (setdefault + the
        # aliased append), bad_journal_outside — never the locked sites
        assert len(lock_findings) == 5
        assert "bad " in flagged or "subscript" in flagged
        # holding shard A's lock does not license mutating shard B
        assert "`with b.lock`" in flagged
        assert any("journal" in f.message for f in lock_findings)
        assert not any("good" in f.message for f in lock_findings)

    def test_clean_under_shard_locks(self):
        clean = FIXTURE_SHARDED.split("    async def bad")[0]
        report = analyze_source(clean, path="cpzk_tpu/server/state.py")
        assert "LOCK-001" not in rules_of(report)

    def test_real_sharded_state_self_hosts(self):
        """The actual ServerState — shard routing, bulk per-shard ops,
        journal funnel — passes with only its two documented waivers."""
        report = analyze_paths([os.path.join(PKG, "server", "state.py")])
        assert [f.render() for f in report.findings] == []
        assert report.waived  # replay/journal waivers are active, not dead


# -- ASYNC-001 ----------------------------------------------------------------


class TestASYNC001:
    def test_true_positive_sleep_and_open(self):
        src = (
            "import time\n"
            "async def handler():\n"
            "    time.sleep(0.1)\n"
            "    with open('/tmp/x') as f:\n"
            "        return f.read()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        msgs = [f.message for f in report.findings if f.rule == "ASYNC-001"]
        assert len(msgs) == 2
        assert any("time.sleep" in m for m in msgs)
        assert any("open()" in m for m in msgs)

    def test_true_positive_fsync_subprocess(self):
        src = (
            "import os, subprocess\n"
            "async def handler(fd):\n"
            "    os.fsync(fd)\n"
            "    subprocess.run(['ls'])\n"
        )
        report = analyze_source(src, path="cpzk_tpu/durability/fx.py")
        assert len([f for f in report.findings if f.rule == "ASYNC-001"]) == 2

    def test_clean_to_thread_and_nested_sync_def(self):
        src = (
            "import asyncio, os, time\n"
            "async def handler(path):\n"
            "    def write():\n"
            "        with open(path, 'w') as f:\n"
            "            f.write('x')\n"
            "            os.fsync(f.fileno())\n"
            "    await asyncio.to_thread(write)\n"
            "    await asyncio.to_thread(time.sleep, 0.1)\n"
            "    await asyncio.sleep(0.1)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "ASYNC-001" not in rules_of(report)

    def test_out_of_scope_plane_is_clean(self):
        src = "import time\nasync def f():\n    time.sleep(1)\n"
        report = analyze_source(src, path="cpzk_tpu/ops/fx.py")
        assert "ASYNC-001" not in rules_of(report)

    def test_sync_functions_are_clean(self):
        src = "def f(path):\n    return open(path).read()\n"
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "ASYNC-001" not in rules_of(report)


# -- ASYNC-002 ----------------------------------------------------------------


class TestASYNC002:
    def test_true_positive_discarded(self):
        src = (
            "import asyncio\n"
            "async def f():\n"
            "    asyncio.create_task(work())\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "ASYNC-002" in rules_of(report)

    def test_true_positive_underscore(self):
        src = (
            "import asyncio\n"
            "async def f():\n"
            "    _ = asyncio.ensure_future(work())\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "ASYNC-002" in rules_of(report)

    def test_clean_retained(self):
        src = (
            "import asyncio\n"
            "async def f(self):\n"
            "    self._task = asyncio.create_task(work())\n"
            "    t = asyncio.get_running_loop().create_task(work())\n"
            "    self._tasks.add(t)\n"
            "    await asyncio.create_task(work())\n"
            "    await self._task\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "ASYNC-002" not in rules_of(report)


# -- GRPC-001 -----------------------------------------------------------------


class TestGRPC001:
    def test_true_positive_direct_abort(self):
        """The PR-4 pushback invariant: reverting a handler to a direct
        RESOURCE_EXHAUSTED abort is flagged."""
        src = (
            "import grpc\n"
            "class AuthServiceImpl:\n"
            "    async def create_challenge(self, request, context):\n"
            "        await context.abort(\n"
            "            grpc.StatusCode.RESOURCE_EXHAUSTED, 'overloaded')\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/service.py")
        assert "GRPC-001" in rules_of(report)

    def test_clean_funnel_and_other_codes(self):
        src = (
            "import grpc\n"
            "class AuthServiceImpl:\n"
            "    async def _abort_exhausted(self, context, msg, retry_after_s):\n"
            "        await context.abort(\n"
            "            grpc.StatusCode.RESOURCE_EXHAUSTED, msg,\n"
            "            trailing_metadata=(('cpzk-retry-after-ms', '50'),))\n"
            "    async def handler(self, request, context):\n"
            "        await self._abort_exhausted(context, 'overloaded', 0.05)\n"
            "        await context.abort(grpc.StatusCode.INVALID_ARGUMENT, 'bad')\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/service.py")
        assert "GRPC-001" not in rules_of(report)


# -- JAX-001 ------------------------------------------------------------------


class TestJAX001:
    def test_true_positive_impure_body(self):
        src = (
            "import jax, time\n"
            "@jax.jit\n"
            "def kernel(x):\n"
            "    return x * time.time()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/ops/fx.py")
        assert "JAX-001" in rules_of(report)

    def test_true_positive_python_rng(self):
        src = (
            "import jax, random\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnums=(0,))\n"
            "def kernel(n, x):\n"
            "    return x + random.random()\n"
        )
        report = analyze_source(src, path="cpzk_tpu/ops/fx.py")
        assert "JAX-001" in rules_of(report)

    def test_true_positive_bad_static_argnames(self):
        src = (
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnames=('missing',))\n"
            "def kernel(n, x):\n"
            "    return x * n\n"
        )
        report = analyze_source(src, path="cpzk_tpu/ops/fx.py")
        assert "JAX-001" in rules_of(report)

    def test_true_positive_bad_static_argnums(self):
        src = (
            "import jax\n"
            "def kernel(x):\n"
            "    return x\n"
            "jitted = jax.jit(kernel, static_argnums=(3,))\n"
        )
        report = analyze_source(src, path="cpzk_tpu/ops/fx.py")
        assert "JAX-001" in rules_of(report)

    def test_clean_pure_kernel(self):
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnames=('n',))\n"
            "def kernel(n, x):\n"
            "    key = jax.random.PRNGKey(0)\n"
            "    return jnp.sum(x) * n + jax.random.uniform(key)\n"
        )
        report = analyze_source(src, path="cpzk_tpu/ops/fx.py")
        assert "JAX-001" not in rules_of(report)

    def test_clean_host_function_uses_clock(self):
        src = "import time\ndef host():\n    return time.time()\n"
        report = analyze_source(src, path="cpzk_tpu/ops/fx.py")
        assert "JAX-001" not in rules_of(report)


# -- waivers ------------------------------------------------------------------


class TestWaivers:
    BAD_LINE = "import asyncio\nasyncio.create_task(f())"

    def test_waiver_with_reason_suppresses(self):
        src = (
            "import asyncio\n"
            "asyncio.create_task(f())  "
            "# cpzk-lint: disable=ASYNC-002 -- fixture: lifetime managed by caller\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert report.findings == []
        assert [f.rule for f in report.waived] == ["ASYNC-002"]

    def test_waiver_without_reason_is_a_finding(self):
        src = (
            "import asyncio\n"
            "asyncio.create_task(f())  # cpzk-lint: disable=ASYNC-002\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        # the original finding IS suppressed, but the bare waiver is its
        # own (unwaivable) finding — suppressions always carry a why
        assert [f.rule for f in report.findings] == ["WAIVER-001"]

    def test_waiver_wrong_rule_does_not_suppress(self):
        src = (
            "import asyncio\n"
            "asyncio.create_task(f())  # cpzk-lint: disable=CT-001 -- wrong id\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert "ASYNC-002" in rules_of(report)

    def test_function_scope_waiver_on_def_line(self):
        src = (
            "import asyncio\n"
            "# cpzk-lint: disable=ASYNC-002 -- fixture: fire-and-forget by design\n"
            "async def f():\n"
            "    asyncio.create_task(a())\n"
            "    asyncio.create_task(b())\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert report.findings == []
        assert len(report.waived) == 2

    def test_comment_only_waiver_covers_next_line(self):
        src = (
            "import asyncio\n"
            "# cpzk-lint: disable=ASYNC-002 -- fixture: covered next line\n"
            "asyncio.create_task(f())\n"
            "asyncio.create_task(g())\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert len(report.findings) == 1  # only the uncovered second spawn
        assert len(report.waived) == 1


# -- report contract ----------------------------------------------------------


class TestReportContract:
    def test_json_schema_stable(self):
        """Drift guard: the CI artifact's consumers pin these keys."""
        doc = analyze_source("x = 1\n").to_dict()
        assert sorted(doc) == [
            "files", "findings", "rule_ids", "schema_version", "summary",
            "tool", "waived",
        ]
        assert doc["schema_version"] == 1
        assert doc["tool"] == "cpzk-lint"
        assert sorted(doc["summary"]) == ["findings", "waived"]
        bad = analyze_source(
            "import asyncio\nasyncio.create_task(f())\n",
            path="cpzk_tpu/server/fx.py",
        ).to_dict()
        assert sorted(bad["findings"][0]) == [
            "col", "line", "message", "path", "rule",
        ]

    def test_registry_has_the_promised_rule_pack(self):
        for rule_id in CORE_RULES + ["WAIVER-001", "PARSE-001"]:
            assert rule_id in REGISTRY, rule_id
        assert all_rule_ids() == sorted(REGISTRY)

    def test_rules_documented_in_security_md(self):
        """Docs drift guard: every registered rule id appears in
        docs/security.md's enforced-invariants section, and no documented
        CT/LEAK/LOCK/ASYNC/GRPC/JAX id is missing from the registry."""
        with open(os.path.join(REPO, "docs", "security.md")) as f:
            doc = f.read()
        for rule_id in all_rule_ids():
            assert rule_id in doc, f"{rule_id} missing from docs/security.md"

    def test_parse_error_is_a_finding_not_a_crash(self):
        report = analyze_source("def f(:\n")
        assert [f.rule for f in report.findings] == ["PARSE-001"]

    def test_rule_filter(self):
        src = (
            "import asyncio, time\n"
            "async def f():\n"
            "    time.sleep(1)\n"
            "    asyncio.create_task(g())\n"
        )
        report = analyze_source(src, path="cpzk_tpu/server/fx.py")
        assert set(rules_of(report)) == {"ASYNC-001", "ASYNC-002"}
        from cpzk_tpu.analysis.engine import _analyze

        only = _analyze([(src, "cpzk_tpu/server/fx.py")], ["ASYNC-001"])
        assert rules_of(only) == ["ASYNC-001"]


# -- redaction guard (secret-type reprs) --------------------------------------


class TestRedactionGuard:
    @pytest.fixture()
    def secret_scalar(self):
        from cpzk_tpu.core.ristretto import Scalar

        return Scalar(0x1F2E3D4C5B6A79880102030405060708090A0B0C0D0E0F1011121314151617)

    def _assert_redacted(self, obj, scalar):
        from cpzk_tpu.core.scalars import sc_to_bytes

        encodings = {
            f"{scalar.value:x}",
            f"{scalar.value:064x}",
            str(scalar.value),
            sc_to_bytes(scalar.value).hex(),
        }
        for text in (repr(obj), str(obj), f"{obj}"):
            low = text.lower()
            for enc in encodings:
                assert enc.lower() not in low, (
                    f"secret encoding leaked through {type(obj).__name__} repr"
                )
            assert "redacted" in low

    def test_witness_repr_redacts(self, secret_scalar):
        from cpzk_tpu.protocol.gadgets import Witness

        self._assert_redacted(Witness(secret_scalar), secret_scalar)

    def test_nonce_repr_redacts(self, secret_scalar):
        from cpzk_tpu.protocol.prover import Nonce

        self._assert_redacted(Nonce(secret_scalar), secret_scalar)

    def test_response_repr_redacts(self, secret_scalar):
        from cpzk_tpu.protocol.gadgets import Response

        self._assert_redacted(Response(secret_scalar), secret_scalar)
