"""Tests for the explicit-state model checker (cpzk_tpu.analysis.model).

Three legs:

- the three protocol models run clean **to exhaustion** (the frontier
  drains within the bounds — "invariants hold" means checked in every
  reachable state, not a sampled subset), fast enough for tier-1;
- **validation by mutation**: re-introducing the PR 16 bug (drop the
  write-time owner fence) and the PR 18 bug (serve challenge mints on a
  fenced primary) must each produce a readable step-by-step
  counterexample and a nonzero CLI exit;
- the **crash-point drift guard**: every point in the three FaultPlan
  registries (REPLICATION / FLEET / HANDOVER) must be (a) scheduled by
  some test in tests/ and (b) explored as a ``crash:<point>``
  transition by its protocol model.  Adding a crash point to a registry
  without exercising it fails here, by name.
"""

from __future__ import annotations

import ast
from pathlib import Path

import pytest

from cpzk_tpu.analysis.model import (
    MODELS,
    FailoverModel,
    HandoverModel,
    SplitModel,
    check,
    main,
    render_trace,
)
from cpzk_tpu.resilience.faults import (
    ALL_CRASH_POINTS,
    FLEET_CRASH_POINTS,
    HANDOVER_CRASH_POINTS,
    REPLICATION_CRASH_POINTS,
    WAL_CRASH_POINTS,
)

TESTS_DIR = Path(__file__).resolve().parent

#: registry-name indirection the drift guard understands: a parametrize
#: over one of these names schedules every point in the tuple.
_REGISTRY_NAMES = {
    "WAL_CRASH_POINTS": WAL_CRASH_POINTS,
    "REPLICATION_CRASH_POINTS": REPLICATION_CRASH_POINTS,
    "FLEET_CRASH_POINTS": FLEET_CRASH_POINTS,
    "SPLIT_CRASH_POINTS": FLEET_CRASH_POINTS,  # fleet.split re-export
    "HANDOVER_CRASH_POINTS": HANDOVER_CRASH_POINTS,
    "ALL_CRASH_POINTS": ALL_CRASH_POINTS,
}


@pytest.fixture(scope="module")
def clean_results():
    return {name: check(cls()) for name, cls in MODELS.items()}


class TestCleanModels:
    def test_all_models_exhaustive_and_clean(self, clean_results):
        for name, result in clean_results.items():
            assert result.violation is None, (
                f"model {name} found a violation in the UNMUTATED "
                f"protocol:\n{render_trace(result)}"
            )
            assert result.complete, (
                f"model {name} hit the exploration bounds before "
                "exhausting its state space — the clean verdict would "
                "only cover a prefix of the reachable states"
            )

    def test_state_spaces_stay_ci_sized(self, clean_results):
        # the CI model-smoke leg budgets 60s for all three models plus
        # both mutations; keep each space small enough that a 100x
        # regression would still fit
        for name, result in clean_results.items():
            assert result.states < 50_000, (
                f"model {name} exploded to {result.states} states"
            )

    def test_models_nontrivial(self, clean_results):
        # a model that collapses to a handful of states is not checking
        # interleavings; each protocol has concurrency worth exploring
        for name, result in clean_results.items():
            assert result.states > 20, (
                f"model {name} explored only {result.states} states — "
                "the interleaving structure degenerated"
            )

    def test_clean_render_mentions_exhaustion(self, clean_results):
        text = render_trace(clean_results["split"])
        assert "no counterexample" in text
        assert "invariants hold" in text


class TestMutationValidation:
    """The checker must catch the two bugs the robustness PRs fixed —
    otherwise a clean verdict means nothing."""

    def test_split_drop_write_fence_reproduces_pr16(self):
        result = check(SplitModel(mutation="drop_write_fence"))
        v = result.violation
        assert v is not None, (
            "dropping the write-time owner fence must lose an acked "
            "write to the split — the checker missed the PR 16 bug"
        )
        assert v.invariant in ("acked-on-owner", "no-acked-write-loss")
        labels = [label for label, _ in v.trace]
        # the canonical interleaving: ownership checked, handler parked
        # in the batcher await, the split cuts underneath it, the
        # unfenced mint acks onto the source's stale copy
        assert "split:cut" in labels
        assert "handler:mint_unfenced" in labels
        assert labels.index("split:cut") < labels.index(
            "handler:mint_unfenced"
        )

    def test_handover_serve_fenced_challenges_reproduces_pr18(self):
        result = check(HandoverModel(mutation="serve_fenced_challenges"))
        v = result.violation
        assert v is not None, (
            "a fenced primary minting challenges locally must strand a "
            "login — the checker missed the PR 18 bug"
        )
        assert v.invariant == "no-stranded-login"
        labels = [label for label, _ in v.trace]
        assert "handover:fence" in labels
        assert "client:mint_on_fenced" in labels

    def test_counterexample_trace_is_readable(self):
        result = check(SplitModel(mutation="drop_write_fence"))
        text = render_trace(result)
        assert "counterexample" in text
        assert "mutation: drop_write_fence" in text
        assert "step 0: initial" in text
        assert "step 1:" in text
        assert "violated: " in text
        # every step after the initial shows only the state delta
        assert "-> " in text

    def test_counterexample_is_shortest(self):
        # BFS order: no strict prefix of the returned trace violates
        result = check(SplitModel(mutation="drop_write_fence"))
        model = result.model
        invs = model.invariants()
        for _, frozen in result.violation.trace[:-1]:
            state = dict(frozen)
            assert all(pred(state) for _, pred in invs)

    def test_unknown_mutation_is_rejected(self):
        with pytest.raises(ValueError, match="no mutation"):
            SplitModel(mutation="drop_the_other_thing")
        with pytest.raises(ValueError, match="no mutation"):
            FailoverModel(mutation="drop_write_fence")


class TestCli:
    def test_clean_run_exits_zero(self, capsys):
        assert main(["--quiet"]) == 0

    def test_violation_exits_nonzero(self, capsys):
        rc = main(["--model", "split", "--mutate", "drop_write_fence"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "counterexample" in out

    def test_expect_violation_inverts(self, capsys):
        assert main([
            "--model", "split", "--mutate", "drop_write_fence",
            "--expect-violation",
        ]) == 0
        assert main([
            "--model", "handover", "--mutate", "serve_fenced_challenges",
            "--expect-violation",
        ]) == 0
        # a clean model under --expect-violation is a FAILURE: the
        # mutation-validation leg must never silently pass
        assert main(["--model", "failover", "--expect-violation"]) == 1

    def test_mutate_requires_single_model(self, capsys):
        assert main(["--mutate", "drop_write_fence"]) == 2

    def test_unknown_mutation_exits_usage(self, capsys):
        assert main(["--model", "split", "--mutate", "nope"]) == 2

    def test_list_inventories_models(self, capsys):
        assert main(["--list"]) == 0
        out = capsys.readouterr().out
        for name in MODELS:
            assert name in out
        assert "drop_write_fence" in out
        assert "serve_fenced_challenges" in out


# -- the crash-point drift guard ---------------------------------------------


def _scheduled_crash_points() -> set[str]:
    """Every crash point some test in tests/ schedules: literal
    ``crash_on("<point>")`` args, string literals inside
    ``pytest.mark.parametrize`` argvalue lists (including tuple-valued
    rows), and registry-name indirection (``parametrize("point",
    SPLIT_CRASH_POINTS)``)."""
    known = set(ALL_CRASH_POINTS)
    scheduled: set[str] = set()

    def strings_in(node: ast.AST):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                yield sub.value

    for path in sorted(TESTS_DIR.glob("test_*.py")):
        tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else None
            )
            if name == "crash_on" and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    scheduled.add(arg.value)
            elif name == "parametrize" and len(node.args) >= 2:
                argvalues = node.args[1]
                if isinstance(argvalues, ast.Name):
                    scheduled.update(
                        _REGISTRY_NAMES.get(argvalues.id, ())
                    )
                else:
                    scheduled.update(
                        v for v in strings_in(argvalues) if v in known
                    )
    return scheduled


class TestCrashPointDriftGuard:
    """A crash point that exists in a FaultPlan registry but is never
    exercised is a hole in the chaos matrix AND in the model — this
    guard fails with the point's name so the drift is obvious."""

    REGISTRIES = [
        ("REPLICATION_CRASH_POINTS", REPLICATION_CRASH_POINTS, "failover"),
        ("FLEET_CRASH_POINTS", FLEET_CRASH_POINTS, "split"),
        ("HANDOVER_CRASH_POINTS", HANDOVER_CRASH_POINTS, "handover"),
    ]

    def test_every_registry_point_is_scheduled_by_a_test(self):
        scheduled = _scheduled_crash_points()
        missing = [
            f"{reg_name}:{point}"
            for reg_name, registry, _ in self.REGISTRIES
            for point in registry
            if point not in scheduled
        ]
        assert not missing, (
            "crash points registered in cpzk_tpu.resilience.faults but "
            f"never scheduled by any test in tests/: {missing} — add a "
            "crash_on()/parametrize leg exercising each, or remove the "
            "registry entry"
        )

    def test_every_registry_point_is_explored_by_its_model(
        self, clean_results
    ):
        missing = []
        for reg_name, registry, model_name in self.REGISTRIES:
            labels = clean_results[model_name].labels
            for point in registry:
                if f"crash:{point}" not in labels:
                    missing.append(f"{reg_name}:{point} (model {model_name})")
        assert not missing, (
            "crash points never explored as a crash:<point> transition "
            f"by their protocol model: {missing} — teach "
            "cpzk_tpu/analysis/model.py the failure, or remove the "
            "registry entry"
        )

    def test_models_declare_their_registries_verbatim(self):
        # the model's crash_points attribute IS the registry object —
        # adding a point to the registry automatically widens what the
        # two checks above demand
        assert FailoverModel.crash_points == REPLICATION_CRASH_POINTS
        assert SplitModel.crash_points == FLEET_CRASH_POINTS
        assert HandoverModel.crash_points == HANDOVER_CRASH_POINTS

    def test_guard_actually_detects_drift(self):
        # sanity: the scanner sees the literal/indirect schedules that
        # exist today; an empty scan would make the guard vacuous
        scheduled = _scheduled_crash_points()
        assert "pre_handover_ack" in scheduled     # literal crash_on
        assert "pre_flip" in scheduled             # SPLIT_CRASH_POINTS name
        assert "mid_segment" in scheduled          # tuple-valued parametrize
