"""Protocol-layer tests: gadgets, codec, prover/verifier.

Mirrors the reference inline tests (gadgets.rs:492-653, prover/mod.rs:154-197,
verifier/mod.rs:174-230)."""

import pytest

from cpzk_tpu import (
    Commitment,
    InvalidParams,
    Parameters,
    Proof,
    Prover,
    Response,
    Ristretto255,
    Scalar,
    SecureRng,
    Statement,
    Transcript,
    Verifier,
    Witness,
)
from cpzk_tpu.protocol.gadgets import PROTOCOL_VERSION


@pytest.fixture(scope="module")
def rng():
    return SecureRng()


@pytest.fixture(scope="module")
def params():
    return Parameters.new()


def make_proof(params, rng):
    x = Ristretto255.random_scalar(rng)
    prover = Prover(params, Witness(x))
    return prover, prover.prove(rng)


def test_parameters_default(params):
    assert params.generator_g == Ristretto255.generator_g()
    assert params.generator_h == Ristretto255.generator_h()


def test_parameters_rejects_identity_and_equal():
    ident = Ristretto255.identity()
    g = Ristretto255.generator_g()
    with pytest.raises(InvalidParams):
        Parameters.with_generators(ident, g)
    with pytest.raises(InvalidParams):
        Parameters.with_generators(g, ident)
    with pytest.raises(InvalidParams):
        Parameters.with_generators(g, g)


def test_statement_from_witness(params, rng):
    x = Ristretto255.random_scalar(rng)
    st = Statement.from_witness(params, Witness(x))
    assert st.y1 == Ristretto255.scalar_mul(params.generator_g, x)
    assert st.y2 == Ristretto255.scalar_mul(params.generator_h, x)
    st.validate()


def test_proof_wire_format_109_bytes(params, rng):
    _, proof = make_proof(params, rng)
    data = proof.to_bytes()
    assert len(data) == 109  # CHANGELOG.md:113 parity
    assert data[0] == PROTOCOL_VERSION
    assert int.from_bytes(data[1:5], "big") == 32
    parsed = Proof.from_bytes(data)
    assert parsed.commitment == proof.commitment
    assert parsed.response.s == proof.response.s


@pytest.mark.parametrize(
    "mutate",
    [
        lambda b: b"",  # empty
        lambda b: b[:4],  # tiny
        lambda b: bytes([99]) + b[1:],  # wrong version
        lambda b: b[:1] + (0).to_bytes(4, "big") + b[5:],  # zero-length field
        lambda b: b[:1] + (0xFFFFFFFF).to_bytes(4, "big") + b[5:],  # excessive length
        lambda b: b + b"\xff",  # trailing byte
        lambda b: b[:-1],  # truncated
    ],
)
def test_proof_from_bytes_rejects(params, rng, mutate):
    _, proof = make_proof(params, rng)
    with pytest.raises(InvalidParams):
        Proof.from_bytes(mutate(proof.to_bytes()))


def test_proof_rejects_identity_commitment(params, rng):
    _, proof = make_proof(params, rng)
    bad = Proof(Commitment(Ristretto255.identity(), proof.commitment.r2), proof.response)
    with pytest.raises(InvalidParams):
        Proof.from_bytes(bad.to_bytes())


def test_proof_rejects_zero_response(params, rng):
    _, proof = make_proof(params, rng)
    bad = Proof(proof.commitment, Response(Scalar(0)))
    with pytest.raises(InvalidParams):
        Proof.from_bytes(bad.to_bytes())


def test_prove_verify_roundtrip(params, rng):
    prover, proof = make_proof(params, rng)
    Verifier(params, prover.statement).verify(proof)


def test_verify_rejects_wrong_statement(params, rng):
    prover, proof = make_proof(params, rng)
    other = Statement.from_witness(params, Witness(Ristretto255.random_scalar(rng)))
    with pytest.raises(InvalidParams):
        Verifier(params, other).verify(proof)


def test_interactive_protocol(params, rng):
    x = Ristretto255.random_scalar(rng)
    prover = Prover(params, Witness(x))
    commitment, nonce = prover.commit(rng)
    challenge = Ristretto255.random_scalar(rng)
    response = prover.respond(nonce, challenge)
    proof = Proof(commitment, response)
    Verifier(params, prover.statement).verify_response(challenge, proof)
    # wrong challenge fails
    with pytest.raises(InvalidParams):
        Verifier(params, prover.statement).verify_response(
            Ristretto255.random_scalar(rng), proof
        )


def test_proof_context_binding(params, rng):
    """Context replay rejection (security_tests.rs:5-39)."""
    x = Ristretto255.random_scalar(rng)
    prover = Prover(params, Witness(x))
    t = Transcript()
    t.append_context(b"challenge-id-1")
    proof = prover.prove_with_transcript(rng, t)

    ok = Transcript()
    ok.append_context(b"challenge-id-1")
    Verifier(params, prover.statement).verify_with_transcript(proof, ok)

    replay = Transcript()
    replay.append_context(b"challenge-id-2")
    with pytest.raises(InvalidParams):
        Verifier(params, prover.statement).verify_with_transcript(proof, replay)


def test_proofs_are_randomized(params, rng):
    """Proof uniqueness (security_tests.rs:165-209)."""
    x = Ristretto255.random_scalar(rng)
    prover = Prover(params, Witness(x))
    p1 = prover.prove(rng)
    p2 = prover.prove(rng)
    assert p1.to_bytes() != p2.to_bytes()
    v = Verifier(params, prover.statement)
    v.verify(p1)
    v.verify(p2)


def test_corrupted_proof_bytes_reject(params, rng):
    """Bit-flip corruption (security_tests.rs:41-105): every single-bit flip
    either fails to parse or fails verification."""
    prover, proof = make_proof(params, rng)
    verifier = Verifier(params, prover.statement)
    data = bytearray(proof.to_bytes())
    # flip one bit in r1, one in r2, one in s
    for pos in (10, 46, 108):
        corrupted = bytearray(data)
        corrupted[pos] ^= 0x40
        try:
            parsed = Proof.from_bytes(bytes(corrupted))
        except Exception:
            continue
        with pytest.raises(InvalidParams):
            verifier.verify(parsed)
