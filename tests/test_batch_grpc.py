"""Batch RPC integration tests — reference ``tests/batch_verification_tests.rs``
twins (multi-valid, mixed validity, malformed batches, batch registration,
large batch)."""

import asyncio

import pytest

import grpc

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.client import AuthClient
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.server import RateLimiter, ServerState
from cpzk_tpu.server.service import serve


def run(coro):
    return asyncio.run(coro)


async def start():
    state = ServerState()
    server, port = await serve(state, RateLimiter(10_000, 10_000), host="127.0.0.1", port=0)
    return state, server, port


async def register_users(client, n, prefix="user"):
    rng = SecureRng()
    users = []
    for i in range(n):
        user_id = f"{prefix}{i}"
        prover = Prover(Parameters.new(), Witness(Ristretto255.random_scalar(rng)))
        resp = await client.register(
            user_id,
            Ristretto255.element_to_bytes(prover.statement.y1),
            Ristretto255.element_to_bytes(prover.statement.y2),
        )
        assert resp.success
        users.append((user_id, prover))
    return users


async def challenge_and_prove(client, users, wrong_context_for=()):
    rng = SecureRng()
    ids, cids, proofs = [], [], []
    for idx, (user_id, prover) in enumerate(users):
        ch = await client.create_challenge(user_id)
        cid = bytes(ch.challenge_id)
        t = Transcript()
        if idx in wrong_context_for:
            t.append_context(b"wrong-context")
        else:
            t.append_context(cid)
        proofs.append(prover.prove_with_transcript(rng, t).to_bytes())
        ids.append(user_id)
        cids.append(cid)
    return ids, cids, proofs


def test_batch_verify_all_valid():
    async def flow():
        _, server, port = await start()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                users = await register_users(client, 5, "bv")
                ids, cids, proofs = await challenge_and_prove(client, users)
                resp = await client.verify_proof_batch(ids, cids, proofs)
                assert len(resp.results) == 5
                for r in resp.results:
                    assert r.success and r.session_token
        finally:
            await server.stop(None)

    run(flow())


def test_batch_verify_mixed_validity():
    async def flow():
        _, server, port = await start()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                users = await register_users(client, 6, "mx")
                ids, cids, proofs = await challenge_and_prove(
                    client, users, wrong_context_for={1, 4}
                )
                resp = await client.verify_proof_batch(ids, cids, proofs)
                outcomes = [r.success for r in resp.results]
                assert outcomes == [True, False, True, True, False, True]
                assert resp.results[1].message == "Authentication failed"
        finally:
            await server.stop(None)

    run(flow())


def test_batch_rejects_malformed():
    async def flow():
        _, server, port = await start()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await client.verify_proof_batch([], [], [])
                assert "Empty batch" in exc.value.details()

                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await client.verify_proof_batch(["a"], [], [])
                assert "Mismatched array lengths" in exc.value.details()

                big = ["u"] * 1001
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await client.verify_proof_batch(big, [b"c"] * 1001, [b"p"] * 1001)
                assert "maximum limit of 1000" in exc.value.details()
        finally:
            await server.stop(None)

    run(flow())


def test_batch_single_proof():
    async def flow():
        _, server, port = await start()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                users = await register_users(client, 1, "solo")
                ids, cids, proofs = await challenge_and_prove(client, users)
                resp = await client.verify_proof_batch(ids, cids, proofs)
                assert len(resp.results) == 1 and resp.results[0].success
        finally:
            await server.stop(None)

    run(flow())


def test_batch_registration_with_duplicates():
    async def flow():
        _, server, port = await start()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                rng = SecureRng()
                provers = [
                    Prover(Parameters.new(), Witness(Ristretto255.random_scalar(rng)))
                    for _ in range(3)
                ]
                ids = ["br0", "br1", "br0"]  # duplicate in one batch
                y1s = [Ristretto255.element_to_bytes(p.statement.y1) for p in provers]
                y2s = [Ristretto255.element_to_bytes(p.statement.y2) for p in provers]
                resp = await client.register_batch(ids, y1s, y2s)
                assert [r.success for r in resp.results] == [True, True, False]
                assert "already registered" in resp.results[2].message

                # bad element bytes -> per-item failure, batch still succeeds
                resp = await client.register_batch(
                    ["br2", "br3"], [b"\x00" * 32, y1s[0]], [y2s[0], b"garbage" + b"\x00" * 25]
                )
                assert [r.success for r in resp.results] == [False, False]
        finally:
            await server.stop(None)

    run(flow())


def test_batch_challenge_consumed_even_on_failure():
    """Challenges are consumed atomically BEFORE verification
    (service.rs:478; docs/protocol.md:174-176)."""

    async def flow():
        state, server, port = await start()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                users = await register_users(client, 2, "cc")
                ids, cids, proofs = await challenge_and_prove(
                    client, users, wrong_context_for={0}
                )
                resp = await client.verify_proof_batch(ids, cids, proofs)
                assert [r.success for r in resp.results] == [False, True]
                assert await state.challenge_count() == 0  # both consumed
        finally:
            await server.stop(None)

    run(flow())


def test_batch_duplicate_challenge_id_first_wins():
    """Two batch items sharing one challenge id: the first consumes it,
    the second fails — single-use semantics inside one RPC (the bulk
    consume path must behave exactly as sequential consumes did)."""

    async def flow():
        state, server, port = await start()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                users = await register_users(client, 1, prefix="dup")
                ids, cids, proofs = await challenge_and_prove(client, users)
                # same user, same challenge, same proof submitted twice
                resp = await client.verify_proof_batch(
                    ids * 2, cids * 2, proofs * 2)
                assert [r.success for r in resp.results] == [True, False]
                assert "Authentication failed" in resp.results[1].message
                assert await state.challenge_count() == 0
        finally:
            await server.stop(None)

    run(flow())


def test_batch_session_cap_enforced_mid_batch():
    """A user at the per-user session cap gets per-item session errors
    while other items in the same batch still succeed (bulk create_sessions
    enforces caps in order, like sequential mints did)."""
    from cpzk_tpu.server.state import MAX_SESSIONS_PER_USER

    async def flow():
        state, server, port = await start()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                users = await register_users(client, 2, prefix="cap")
                # fill user cap0's session cap via repeated logins
                for _ in range(MAX_SESSIONS_PER_USER):
                    ids, cids, proofs = await challenge_and_prove(client, users[:1])
                    resp = await client.verify_proof_batch(ids, cids, proofs)
                    assert resp.results[0].success
                # now a batch with both users: cap0 verifies but cannot mint
                ids, cids, proofs = await challenge_and_prove(client, users)
                resp = await client.verify_proof_batch(ids, cids, proofs)
                assert not resp.results[0].success
                assert "session" in resp.results[0].message.lower()
                assert resp.results[1].success and resp.results[1].session_token
        finally:
            await server.stop(None)

    run(flow())


def test_large_batch_100_users():
    async def flow():
        _, server, port = await start()
        try:
            async with AuthClient(f"127.0.0.1:{port}") as client:
                rng = SecureRng()
                provers = [
                    Prover(Parameters.new(), Witness(Ristretto255.random_scalar(rng)))
                    for _ in range(100)
                ]
                ids = [f"big{i}" for i in range(100)]
                resp = await client.register_batch(
                    ids,
                    [Ristretto255.element_to_bytes(p.statement.y1) for p in provers],
                    [Ristretto255.element_to_bytes(p.statement.y2) for p in provers],
                )
                assert all(r.success for r in resp.results)

                users = list(zip(ids, provers))
                bids, cids, proofs = await challenge_and_prove(client, users)
                resp = await client.verify_proof_batch(bids, cids, proofs)
                assert len(resp.results) == 100
                assert all(r.success for r in resp.results)
                tokens = {r.session_token for r in resp.results}
                assert len(tokens) == 100  # unique sessions
        finally:
            await server.stop(None)

    run(flow())
