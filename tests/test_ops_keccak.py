"""Device Keccak-f[1600] vs the host oracle: bit-exact over random batched
states (the host permutation is itself validated against hashlib SHA3)."""

import secrets

import numpy as np

import jax

from cpzk_tpu.core import keccak as host
from cpzk_tpu.ops import keccak as dev


def test_device_permutation_matches_host():
    n = 17
    lanes = np.array(
        [[secrets.randbelow(1 << 64) for _ in range(25)] for _ in range(n)],
        dtype=np.uint64,
    )
    out = jax.jit(dev.keccak_f1600)(dev.lanes_to_state(lanes))
    got = dev.state_to_lanes(out)
    for i in range(n):
        exp = host.keccak_f1600([int(v) for v in lanes[i]])
        assert [int(v) for v in got[i]] == exp, f"row {i}"


def test_device_permutation_zero_and_ones():
    pats = [np.zeros((1, 25), dtype=np.uint64),
            np.full((1, 25), 0xFFFFFFFFFFFFFFFF, dtype=np.uint64)]
    for lanes in pats:
        out = dev.state_to_lanes(jax.jit(dev.keccak_f1600)(dev.lanes_to_state(lanes)))
        exp = host.keccak_f1600([int(v) for v in lanes[0]])
        assert [int(v) for v in out[0]] == exp


def test_device_permutation_iterated():
    """Three chained permutations stay in lockstep with the host (catches
    any int32 sign-extension drift across applications)."""
    lanes = np.array([[i * 0x9E3779B97F4A7C15 % (1 << 64) for i in range(25)]],
                     dtype=np.uint64)
    st = dev.lanes_to_state(lanes)
    exp = [int(v) for v in lanes[0]]
    fn = jax.jit(dev.keccak_f1600)
    for _ in range(3):
        st = fn(st)
        exp = host.keccak_f1600(exp)
    assert [int(v) for v in dev.state_to_lanes(st)[0]] == exp


def test_device_challenge_derivation_matches_host():
    """derive_challenges_device is byte-identical to the per-row Merlin
    transcript (and therefore to the native C++ path) for rows with and
    without contexts."""
    import os

    from cpzk_tpu.core.transcript import MerlinTranscript, PROTOCOL_DST, PROTOCOL_LABEL, CHALLENGE_DST
    from cpzk_tpu.ops.challenge import derive_challenges_device

    n = 9
    cols = {
        name: np.frombuffer(os.urandom(32 * n), dtype=np.uint8).reshape(n, 32).copy()
        for name in ("ctx", "g", "h", "y1", "y2", "r1", "r2")
    }

    def host_row(i, with_ctx):
        t = MerlinTranscript(PROTOCOL_LABEL)
        t.append_message(b"protocol", PROTOCOL_DST)
        if with_ctx:
            t.append_message(b"context", cols["ctx"][i].tobytes())
        t.append_message(b"generator-g", cols["g"][i].tobytes())
        t.append_message(b"generator-h", cols["h"][i].tobytes())
        t.append_message(b"y1", cols["y1"][i].tobytes())
        t.append_message(b"y2", cols["y2"][i].tobytes())
        t.append_message(b"r1", cols["r1"][i].tobytes())
        t.append_message(b"r2", cols["r2"][i].tobytes())
        return t.challenge_bytes(CHALLENGE_DST, 64)

    for with_ctx in (True, False):
        got = derive_challenges_device(
            cols["ctx"] if with_ctx else None,
            cols["g"], cols["h"], cols["y1"], cols["y2"], cols["r1"], cols["r2"],
        )
        for i in range(n):
            assert got[i].tobytes() == host_row(i, with_ctx), (with_ctx, i)


def test_device_challenge_odd_context_length():
    """Context lengths that straddle the 166-byte STROBE rate boundary
    still agree with the host (permutation mid-message)."""
    import os

    from cpzk_tpu.core.transcript import MerlinTranscript, PROTOCOL_DST, PROTOCOL_LABEL, CHALLENGE_DST
    from cpzk_tpu.ops.challenge import derive_challenges_device

    n, clen = 3, 147  # pushes the first message across the rate boundary
    ctx = np.frombuffer(os.urandom(clen * n), dtype=np.uint8).reshape(n, clen).copy()
    pts = {
        name: np.frombuffer(os.urandom(32 * n), dtype=np.uint8).reshape(n, 32).copy()
        for name in ("g", "h", "y1", "y2", "r1", "r2")
    }
    got = derive_challenges_device(ctx, pts["g"], pts["h"], pts["y1"],
                                   pts["y2"], pts["r1"], pts["r2"])
    for i in range(n):
        t = MerlinTranscript(PROTOCOL_LABEL)
        t.append_message(b"protocol", PROTOCOL_DST)
        t.append_message(b"context", ctx[i].tobytes())
        for name, label in (("g", b"generator-g"), ("h", b"generator-h"),
                            ("y1", b"y1"), ("y2", b"y2"), ("r1", b"r1"), ("r2", b"r2")):
            t.append_message(label, pts[name][i].tobytes())
        assert got[i].tobytes() == t.challenge_bytes(CHALLENGE_DST, 64), i


def test_device_challenges_match_host_batch_api():
    """The device Keccak pipeline produces the same Scalars as the host
    ``derive_challenges_batch`` (uniform and empty context shapes).  The
    serving wiring for this path (CPZK_DEVICE_CHALLENGES) was removed
    after round-5 calibration measured it 18-37x slower than the native
    pool at every tier; the kernel stays correct and covered here for
    silicon where the trade flips."""
    import secrets

    import numpy as np

    from cpzk_tpu.core.scalars import sc_from_bytes_mod_order_wide
    from cpzk_tpu.core.transcript import derive_challenges_batch
    from cpzk_tpu.ops.challenge import derive_challenges_device

    n = 6
    mk = lambda: [secrets.token_bytes(32) for _ in range(n)]
    cols = [mk() for _ in range(6)]

    def as_cols(xs):
        blob = b"".join(xs)
        if not blob:
            return np.zeros((len(xs), 0), dtype=np.uint8)
        return np.frombuffer(blob, dtype=np.uint8).reshape(len(xs), -1)

    for contexts in ([None] * n, [b"X" * 32] * n, [b""] * n):
        expected = derive_challenges_batch(contexts, *cols)
        ctx = None if contexts[0] is None else as_cols(contexts)
        chal = derive_challenges_device(ctx, *(as_cols(c) for c in cols))
        got = [sc_from_bytes_mod_order_wide(chal[i].tobytes()) for i in range(n)]
        assert got == [s.value for s in expected]
