"""Checkpoint/resume (SURVEY.md §5): opt-in state snapshots.

In-memory remains the default (reference parity — state.rs holds only
maps and a restart loses everything); --state-file adds versioned-JSON
persistence of users + live sessions.  Challenges are deliberately NOT
persisted (300-second single-use nonces; resurrection across restarts
would widen their replay window)."""

import asyncio
import json
import os

import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Witness
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.errors import Error
from cpzk_tpu.server.state import ServerState, SessionData, UserData


def run(coro):
    return asyncio.run(coro)


def make_statement(rng, params):
    return Prover(params, Witness(Ristretto255.random_scalar(rng))).statement


def test_snapshot_restore_roundtrip(tmp_path):
    rng, params = SecureRng(), Parameters.new()
    path = str(tmp_path / "state.json")

    async def main():
        st = ServerState()
        stmts = {}
        for i in range(3):
            stmts[f"u{i}"] = make_statement(rng, params)
            await st.register_user(UserData(f"u{i}", stmts[f"u{i}"], 1234 + i))
        await st.create_session("tok-a", "u0")
        await st.create_session("tok-b", "u1")
        await st.create_challenge("u0", b"c" * 32)  # must NOT persist
        await st.snapshot(path)

        st2 = ServerState()
        nu, ns = await st2.restore(path)
        assert (nu, ns) == (3, 2)
        for i in range(3):
            u = await st2.get_user(f"u{i}")
            assert u is not None and u.statement == stmts[f"u{i}"]
            assert u.registered_at == 1234 + i
        assert await st2.validate_session("tok-a") == "u0"
        assert await st2.challenge_count() == 0
        # restored per-user session indexes enforce the cap
        for i in range(4):
            await st2.create_session(f"x{i}", "u0")
        with pytest.raises(Error, match="maximum session limit"):
            await st2.create_session("x5", "u0")

    run(main())
    assert os.stat(path).st_mode & 0o777 == 0o600


def test_restore_rejects_bad_input(tmp_path):
    rng, params = Parameters, None  # unused
    path = str(tmp_path / "state.json")

    async def main():
        # wrong version
        with open(path, "w") as f:
            json.dump({"version": 99, "users": {}, "sessions": []}, f)
        with pytest.raises(Error, match="version"):
            await ServerState().restore(path)

        # tampered statement bytes fail canonical decode
        with open(path, "w") as f:
            json.dump(
                {
                    "version": 1,
                    "users": {"evil": {"y1": "ff" * 32, "y2": "ff" * 32,
                                        "registered_at": 1}},
                    "sessions": [],
                },
                f,
            )
        with pytest.raises(Error):
            await ServerState().restore(path)

        # restore into a non-empty state refuses
        st = ServerState()
        r = SecureRng()
        p = Parameters.new()
        await st.register_user(UserData("u", make_statement(r, p), 1))
        with open(path, "w") as f:
            json.dump({"version": 1, "users": {}, "sessions": []}, f)
        with pytest.raises(Error, match="empty state"):
            await st.restore(path)

    run(main())


def test_restore_applies_registration_invariants(tmp_path):
    """A tampered snapshot cannot smuggle in what the register RPC rejects
    (service.rs:37-56,:93-97): identity statement elements, invalid user
    ids, duplicate session tokens."""
    path = str(tmp_path / "state.json")
    rng, params = SecureRng(), Parameters.new()
    eb = Ristretto255.element_to_bytes
    stmt = make_statement(rng, params)
    good_user = {"y1": eb(stmt.y1).hex(), "y2": eb(stmt.y2).hex(),
                 "registered_at": 1}

    def write(doc):
        with open(path, "w") as f:
            json.dump(doc, f)

    async def main():
        # every rejection runs against ONE instance: a failed restore must
        # be all-or-nothing, leaving the state empty and retryable
        st = ServerState()

        # identity y1 (32 zero bytes decodes canonically but must reject)
        write({"version": 1, "sessions": [],
               "users": {"u": {"y1": "00" * 32, "y2": good_user["y2"],
                               "registered_at": 1}}})
        with pytest.raises(Error, match="identity"):
            await st.restore(path)

        # user-id rules: empty, overlong, bad charset
        for uid in ["", "x" * 257, "bad user!"]:
            write({"version": 1,
                   "users": {"ok-user": dict(good_user), uid: dict(good_user)},
                   "sessions": []})
            with pytest.raises(Error, match="User ID"):
                await st.restore(path)

        # duplicate session tokens must not silently overwrite
        sess = {"token": "tok", "user_id": "u", "created_at": 10**10,
                "expires_at": 10**10 + 60}
        write({"version": 1, "users": {"u": dict(good_user)},
               "sessions": [dict(sess), dict(sess)]})
        with pytest.raises(Error, match="duplicate session"):
            await st.restore(path)
        assert await st.user_count() == 0  # nothing leaked from rejected docs

        # control: the untampered document restores fine on the same object
        write({"version": 1, "users": {"u": dict(good_user)},
               "sessions": [dict(sess)]})
        nu, ns = await st.restore(path)
        assert (nu, ns) == (1, 1)

    run(main())


def test_concurrent_snapshots_leave_no_debris(tmp_path):
    """Overlapping snapshot writers (cleanup sweep vs shutdown) use unique
    tmp names: the survivor is valid JSON and no tmp files leak."""
    path = str(tmp_path / "state.json")

    async def main():
        st = ServerState()
        rng, params = SecureRng(), Parameters.new()
        await st.register_user(UserData("u0", make_statement(rng, params), 1))
        writes = []
        for i in range(4):
            await st.create_session(f"tok-{i}", "u0")  # re-dirty between writes
            writes.append(st.snapshot(path))
        assert any(await asyncio.gather(*writes))

    run(main())
    assert json.load(open(path))["version"] == 1
    assert os.listdir(tmp_path.as_posix()) == ["state.json"]


def test_restore_drops_expired_sessions(tmp_path):
    path = str(tmp_path / "state.json")

    async def main():
        st = ServerState()
        rng, params = SecureRng(), Parameters.new()
        await st.register_user(UserData("u0", make_statement(rng, params), 1))
        await st.create_session("live", "u0")
        # inject an expired session directly, then snapshot
        st._sessions["dead"] = SessionData(
            token="dead", user_id="u0", created_at=1, expires_at=2
        )
        st._user_sessions.setdefault("u0", []).append("dead")
        await st.snapshot(path)

        st2 = ServerState()
        _, ns = await st2.restore(path)
        assert ns == 1
        assert await st2.validate_session("live") == "u0"
        with pytest.raises(Error):
            await st2.validate_session("dead")

    run(main())


def test_grpc_restart_with_snapshot(tmp_path):
    """Register on one server instance, snapshot, restore into a fresh
    instance, and log in WITHOUT re-registering — the checkpoint/resume
    end-to-end story."""
    from cpzk_tpu.client import AuthClient
    from cpzk_tpu.client.__main__ import do_login, do_register
    from cpzk_tpu.server import RateLimiter
    from cpzk_tpu.server.service import serve

    path = str(tmp_path / "state.json")

    async def main():
        state1 = ServerState()
        server1, port1 = await serve(state1, RateLimiter(1000, 1000), port=0)
        async with AuthClient(f"127.0.0.1:{port1}") as c:
            assert "Registered" in await do_register(c, "carol", "pw-carol")
        await state1.snapshot(path)
        await server1.stop(None)

        state2 = ServerState()
        await state2.restore(path)
        server2, port2 = await serve(state2, RateLimiter(1000, 1000), port=0)
        async with AuthClient(f"127.0.0.1:{port2}") as c:
            assert "Login OK" in await do_login(c, "carol", "pw-carol")
            bad = await do_login(c, "carol", "wrong")
            assert "Login OK" not in bad
        await server2.stop(None)

    run(main())


def test_snapshot_skips_when_clean(tmp_path):
    """Idle servers don't rewrite the snapshot every sweep."""
    rng, params = SecureRng(), Parameters.new()
    path = str(tmp_path / "state.json")

    async def main():
        st = ServerState()
        await st.register_user(UserData("u", make_statement(rng, params), 1))
        assert await st.snapshot(path) is True
        assert await st.snapshot(path) is False  # nothing changed
        await st.create_session("t", "u")
        assert await st.snapshot(path) is True

    run(main())


def test_state_file_config_layering(tmp_path, monkeypatch):
    """state_file resolves through the same precedence chain as every
    other knob (TOML < env < CLI)."""
    from cpzk_tpu.server.config import ServerConfig

    monkeypatch.chdir(tmp_path)  # no stray .env/config pickup
    assert ServerConfig.from_env().state_file == ""
    monkeypatch.setenv("SERVER_STATE_FILE", "/tmp/a.json")
    assert ServerConfig.from_env().state_file == "/tmp/a.json"


def test_snapshot_oserror_mid_write_preserves_previous(tmp_path):
    """Fault-injected OSError mid-``write()`` (resilience subsystem,
    ``SnapshotFaults``): the injected failure lands after the JSON bytes
    hit the tmp file but before the rename — the previous snapshot must
    stay intact, tmp debris must be cleaned up, and the dirty flag must
    re-arm so the next sweep retries."""
    from cpzk_tpu.resilience.faults import FaultPlan, SnapshotFaults

    rng, params = SecureRng(), Parameters.new()
    path = str(tmp_path / "state.json")

    async def main():
        st = ServerState()
        await st.register_user(UserData("u0", make_statement(rng, params), 1))
        assert await st.snapshot(path) is True  # good baseline snapshot

        await st.create_session("tok", "u0")  # re-dirty
        with SnapshotFaults(FaultPlan().snapshot_errors(1)):
            with pytest.raises(OSError):
                await st.snapshot(path)

        # previous snapshot intact: restores the pre-crash document
        st2 = ServerState()
        nu, ns = await st2.restore(path)
        assert (nu, ns) == (1, 0)  # the session never made it to disk

        # the crashed write left no tmp debris holding bearer tokens
        assert sorted(os.listdir(tmp_path.as_posix())) == ["state.json"]

        # dirty flag re-armed: the next (un-faulted) snapshot catches up
        assert await st.snapshot(path) is True
        st3 = ServerState()
        nu, ns = await st3.restore(path)
        assert (nu, ns) == (1, 1)
        assert await st3.validate_session("tok") == "u0"

    run(main())


def test_snapshot_repeated_io_errors_then_recovery(tmp_path):
    """A run of injected write failures (flaky disk) never corrupts the
    on-disk document; the first clean write lands the full state."""
    from cpzk_tpu.resilience.faults import FaultPlan, SnapshotFaults

    rng, params = SecureRng(), Parameters.new()
    path = str(tmp_path / "state.json")

    async def main():
        st = ServerState()
        await st.register_user(UserData("u0", make_statement(rng, params), 1))
        assert await st.snapshot(path) is True
        plan = FaultPlan().snapshot_errors(3)
        with SnapshotFaults(plan):
            for i in range(3):
                await st.create_session(f"tok-{i}", "u0")
                with pytest.raises(OSError):
                    await st.snapshot(path)
                assert json.load(open(path))["sessions"] == []  # untouched
            # 4th write: fault budget exhausted, passes through
            assert await st.snapshot(path) is True
        st2 = ServerState()
        nu, ns = await st2.restore(path)
        assert (nu, ns) == (1, 3)

    run(main())


def test_restore_partial_write_leaves_state_empty_and_retryable(tmp_path):
    """A torn half-document (what a crash WITHOUT the atomic-rename
    protocol would leave) fails loudly and all-or-nothing: nothing loads,
    and the same ServerState instance still restores a good file."""
    rng, params = SecureRng(), Parameters.new()
    path = str(tmp_path / "state.json")

    async def main():
        st = ServerState()
        await st.register_user(UserData("u0", make_statement(rng, params), 1))
        await st.create_session("tok", "u0")
        await st.snapshot(path)
        good = open(path, "rb").read()

        fresh = ServerState()
        for cut in (1, len(good) // 2, len(good) - 2):
            with open(path, "wb") as f:
                f.write(good[:cut])  # torn write
            with pytest.raises((Error, ValueError, KeyError, TypeError)):
                await fresh.restore(path)
            assert await fresh.user_count() == 0  # nothing leaked

        with open(path, "wb") as f:
            f.write(good)
        nu, ns = await fresh.restore(path)
        assert (nu, ns) == (1, 1)

    run(main())


def test_restore_survives_mutated_snapshots(tmp_path):
    """Random structural mutations of a valid snapshot must either load
    cleanly or raise Error/ValueError-family exceptions — never crash the
    process or accept garbage silently (the file is a trust boundary)."""
    import random

    rng, params = SecureRng(), Parameters.new()
    path = str(tmp_path / "state.json")

    async def build():
        st = ServerState()
        for i in range(2):
            await st.register_user(UserData(f"u{i}", make_statement(rng, params), i))
        await st.create_session("tok", "u0")
        await st.snapshot(path)

    run(build())
    good = open(path).read()

    r = random.Random(1234)
    mutations = 0
    for _ in range(120):
        doc = bytearray(good.encode())
        for _ in range(r.randint(1, 6)):
            op = r.random()
            i = r.randrange(len(doc))
            if op < 0.4:
                doc[i] = r.randrange(256)          # byte flip
            elif op < 0.7:
                del doc[i]                          # deletion
            else:
                doc.insert(i, r.randrange(32, 127))  # insertion
        with open(path, "wb") as f:
            f.write(doc)

        async def attempt():
            st = ServerState()
            try:
                await st.restore(path)
            except Exception as e:
                # JSON / schema / crypto rejections are the contract;
                # anything else (segfault-class, assertion) would escape
                from cpzk_tpu.errors import Error

                assert isinstance(
                    e, (Error, ValueError, KeyError, TypeError, UnicodeDecodeError)
                ), type(e)
                return False
            return True

        run(attempt())
        mutations += 1
    assert mutations == 120
