"""Real 2-process ``jax.distributed`` job on the CPU backend (VERDICT r2
item 6): two subprocesses form a coordinator-backed job, build the global
batch mesh, and run one sharded verify over it — covering the main path of
:mod:`cpzk_tpu.parallel.multihost` (``jax.distributed.initialize``, global
device view, cross-process ``shard_map``) that the single-process no-op
test cannot reach.

Each process contributes 2 virtual CPU devices (XLA_FLAGS), so the global
mesh is 4 devices across 2 OS processes — the same topology class as two
TPU hosts on DCN, minus the physical ICI.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys
import jax

jax.config.update("jax_platforms", "cpu")  # before any device use

from cpzk_tpu.parallel import multihost

multihost.initialize()  # CPZK_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID env

EXPECT_PC = int(os.environ["CPZK_TEST_EXPECT_PROCS"])
EXPECT_LOCAL = int(os.environ["CPZK_TEST_EXPECT_LOCAL"])

pi, pc = multihost.process_info()
assert pc == EXPECT_PC, f"expected {EXPECT_PC} processes, got {pc}"
assert jax.device_count() == EXPECT_PC * EXPECT_LOCAL, jax.device_count()
assert len(jax.local_devices()) == EXPECT_LOCAL

mesh = multihost.global_batch_mesh()
assert mesh.devices.size == EXPECT_PC * EXPECT_LOCAL

# Deterministic corpus: every process must build identical host data (SPMD
# over identical replicated inputs).  A counter-stream "rng" replaces the
# OS entropy source.
import hashlib


class StubRng:
    def __init__(self, seed: bytes):
        self.seed, self.n = seed, 0

    def fill_bytes(self, k: int) -> bytes:
        out = b""
        while len(out) < k:
            out += hashlib.sha256(self.seed + self.n.to_bytes(8, "little")).digest()
            self.n += 1
        return out[:k]


from cpzk_tpu import Parameters, Prover, Transcript, Witness
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.protocol.batch import BatchRow, BatchVerifier
from cpzk_tpu.ops.backend import TpuBackend

rng = StubRng(b"multihost-test")
params = Parameters.new()
rows = []
for i in range(6):
    pr = Prover(params, Witness(Ristretto255.random_scalar(rng)))
    proof = pr.prove_with_transcript(rng, Transcript())
    rows.append((pr.statement, proof))

backend = TpuBackend(mesh_devices=0)  # global mesh: all devices
assert backend._mesh is not None
assert backend._mesh.devices.size == EXPECT_PC * EXPECT_LOCAL

# all-valid batch: the combined RLC single-check path must accept it
# across the cross-process mesh (TpuBackend.prefers_combined)
bv = BatchVerifier(backend=backend)
for st, p in rows:
    bv.add(params, st, p)
assert bv.verify(rng) == [None] * 6

# mismatched row -> combined check fails -> per-row fallback isolates it
bv = BatchVerifier(backend=backend)
for st, p in rows:
    bv.add(params, st, p)
bv.add(params, rows[0][0], rows[1][1])  # mismatched row -> index 6 fails
res = bv.verify(rng)
flags = [r is None for r in res]
assert flags == [True] * 6 + [False], flags

print(f"MULTIHOST_OK process={pi}/{pc} devices={jax.device_count()}")
"""


def test_single_process_global_mesh_serves_backend_and_prover():
    """Default-suite multihost coverage (VERDICT r4 item 5): the same
    entrypoints a pod deployment uses — ``multihost.initialize`` (no-op
    single-process), ``global_batch_mesh`` — feed a TpuBackend verify and
    a BatchProver statement pass over the full 8-virtual-device mesh, so
    the multihost module is exercised beyond import without the slow
    2-process gate."""
    from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
    from cpzk_tpu.core.ristretto import Ristretto255
    from cpzk_tpu.ops.backend import TpuBackend
    from cpzk_tpu.ops.prove import BatchProver
    from cpzk_tpu.parallel import multihost
    from cpzk_tpu.protocol.batch import BatchVerifier

    multihost.initialize()  # unconfigured: must be a no-op, not a latch
    pi, pc = multihost.process_info()
    assert (pi, pc) == (0, 1)
    mesh = multihost.global_batch_mesh()
    import jax

    assert mesh.devices.size == jax.device_count() >= 1

    rng = SecureRng()
    params = Parameters.new()
    bv = BatchVerifier(backend=TpuBackend(mesh_devices=0))
    witnesses = [Ristretto255.random_scalar(rng) for _ in range(3)]
    for w in witnesses:
        prover = Prover(params, Witness(w))
        t = Transcript()
        t.append_context(b"mh")
        proof = prover.prove_with_transcript(rng, t)
        bv.add_with_context(params, prover.statement, proof, b"mh")
    assert bv.verify(rng) == [None] * 3

    # prover side over the same global mesh: device statements must match
    # the host-plane derivation bit-exactly
    bp = BatchProver(params, mesh_devices=0)
    for (y1b, y2b), w in zip(bp.statements(witnesses), witnesses):
        g, h = params.generator_g, params.generator_h
        assert y1b == Ristretto255.element_to_bytes(Ristretto255.scalar_mul(g, w))
        assert y2b == Ristretto255.element_to_bytes(Ristretto255.scalar_mul(h, w))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.skipif(
    not os.environ.get("CPZK_SLOW_TESTS"),
    reason="set CPZK_SLOW_TESTS=1 (CI slow tier) — spawns a coordinator-"
    "backed multi-process job, ~2 min each",
)
@pytest.mark.parametrize(
    "n_procs,local_devices",
    [
        (2, 2),  # two hosts x two chips: the v5e-slice topology class
        (4, 1),  # four hosts x one chip: max process fan-out on DCN
    ],
)
def test_multi_process_distributed_sharded_verify(n_procs, local_devices):
    port = _free_port()
    env_base = dict(os.environ)
    env_base.pop("JAX_PLATFORMS", None)
    # the axon sitecustomize registers the TPU PJRT plugin at interpreter
    # startup, which initializes the XLA backend before
    # jax.distributed.initialize can run; disarm it for the CPU workers
    env_base.pop("PALLAS_AXON_POOL_IPS", None)
    env_base["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={local_devices}"
    )
    env_base["CPZK_COORDINATOR"] = f"127.0.0.1:{port}"
    env_base["CPZK_NUM_PROCESSES"] = str(n_procs)
    env_base["CPZK_TEST_EXPECT_PROCS"] = str(n_procs)
    env_base["CPZK_TEST_EXPECT_LOCAL"] = str(local_devices)
    env_base["CPZK_NO_NATIVE_BUILD"] = "1"  # no concurrent make churn

    procs = []
    for pid in range(n_procs):
        env = dict(env_base, CPZK_PROCESS_ID=str(pid))
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", WORKER],
                env=env,
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("distributed workers timed out")

    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
        assert "MULTIHOST_OK" in out, out
