"""Config layering: defaults < TOML < .env < SERVER_* env < CLI flags —
the reconciliation the reference never does (SURVEY.md §3.3 split-brain:
figment config is validated but clap args win unconditionally).
"""

import os

import pytest

from cpzk_tpu.server.__main__ import parse_args, resolve_config
from cpzk_tpu.server.config import ServerConfig


@pytest.fixture()
def clean_env(tmp_path, monkeypatch):
    for key in list(os.environ):
        if key.startswith("SERVER_"):
            monkeypatch.delenv(key)
    monkeypatch.chdir(tmp_path)  # isolate .env discovery
    monkeypatch.setenv("SERVER_CONFIG_PATH", str(tmp_path / "server.toml"))
    return tmp_path


def test_defaults(clean_env):
    cfg = resolve_config(parse_args([]))
    assert (cfg.host, cfg.port) == ("127.0.0.1", 50051)
    assert cfg.rate_limit.requests_per_minute == 100
    assert cfg.metrics.enabled is False
    assert cfg.tpu.backend == "cpu"


def test_toml_layer_survives_argparse(clean_env):
    (clean_env / "server.toml").write_text(
        'host = "0.0.0.0"\nport = 60000\n'
        "[rate_limit]\nrequests_per_minute = 500\n"
        "[metrics]\nenabled = true\n"
        '[tpu]\nbackend = "tpu"\nbatch_max = 128\n'
    )
    cfg = resolve_config(parse_args([]))
    assert (cfg.host, cfg.port) == ("0.0.0.0", 60000)
    assert cfg.rate_limit.requests_per_minute == 500
    assert cfg.metrics.enabled is True
    assert (cfg.tpu.backend, cfg.tpu.batch_max) == ("tpu", 128)


def test_env_overrides_toml(clean_env, monkeypatch):
    (clean_env / "server.toml").write_text('port = 60000\n')
    monkeypatch.setenv("SERVER_PORT", "61000")
    monkeypatch.setenv("SERVER_RATE_LIMIT_REQUESTS_PER_MINUTE", "42")
    monkeypatch.setenv("SERVER_TPU_BATCH_WINDOW_MS", "9.5")
    cfg = resolve_config(parse_args([]))
    assert cfg.port == 61000
    assert cfg.rate_limit.requests_per_minute == 42
    assert cfg.tpu.batch_window_ms == 9.5


def test_dotenv_under_env(clean_env, monkeypatch):
    (clean_env / ".env").write_text(
        "SERVER_PORT=59000\nSERVER_METRICS_ENABLED=true\n"
    )
    monkeypatch.setenv("SERVER_PORT", "58000")  # real env beats .env
    cfg = resolve_config(parse_args([]))
    assert cfg.port == 58000
    assert cfg.metrics.enabled is True


def test_cli_is_top_layer(clean_env, monkeypatch):
    (clean_env / "server.toml").write_text('port = 60000\nhost = "0.0.0.0"\n')
    monkeypatch.setenv("SERVER_PORT", "61000")
    cfg = resolve_config(
        parse_args(["--port", "62000", "--rate-limit", "7", "--backend", "tpu"])
    )
    assert cfg.port == 62000          # CLI beats env beats TOML
    assert cfg.host == "0.0.0.0"      # unset flags leave lower layers intact
    assert cfg.rate_limit.requests_per_minute == 7
    assert cfg.tpu.backend == "tpu"


def test_validation_still_runs(clean_env):
    with pytest.raises(ValueError):
        resolve_config(parse_args(["--rate-limit", "0"]))


def test_unknown_backend_rejected(clean_env):
    cfg = ServerConfig()
    cfg.tpu.backend = "gpu"
    with pytest.raises(ValueError):
        cfg.validate()


def test_empty_primary_env_beats_alias(clean_env, monkeypatch):
    """An explicitly-set empty primary name must not fall through to its
    short alias (ADVICE r2)."""
    monkeypatch.setenv("SERVER_METRICS_ENABLED", "")
    monkeypatch.setenv("SERVER_METRICS", "true")
    cfg = ServerConfig.from_env()
    # "" parses as not-enabled; the SERVER_METRICS alias must NOT override
    assert cfg.metrics.enabled is False

    monkeypatch.setenv("SERVER_RATE_LIMIT_BURST", "7")
    monkeypatch.setenv("SERVER_RATE_BURST", "99")
    cfg = ServerConfig.from_env()
    assert cfg.rate_limit.burst == 7


def test_resilience_knob_layering(clean_env, monkeypatch):
    """The resilience knobs (breaker recovery, probe size, deadline shed,
    client retry budget) resolve through the same precedence chain as
    every other setting: TOML < env < CLI."""
    (clean_env / "server.toml").write_text(
        "[tpu]\nrecovery_after_s = 9.5\nprobe_batch_max = 16\n"
        "shed_expired = false\n"
        "[retry]\nmax_attempts = 7\nbudget = 2.5\n"
    )
    monkeypatch.setenv("SERVER_TPU_RECOVERY_AFTER_S", "4.0")
    monkeypatch.setenv("SERVER_TPU_SHED_EXPIRED", "true")
    monkeypatch.setenv("SERVER_RETRY_BUDGET", "3.5")
    monkeypatch.setenv("SERVER_RETRY_INITIAL_BACKOFF_MS", "25")
    cfg = resolve_config(parse_args([]))
    assert cfg.tpu.recovery_after_s == 4.0      # env beats TOML
    assert cfg.tpu.probe_batch_max == 16        # TOML beats default
    assert cfg.tpu.shed_expired is True         # env beats TOML
    assert cfg.retry.max_attempts == 7          # TOML beats default
    assert cfg.retry.budget == 3.5              # env beats TOML
    assert cfg.retry.initial_backoff_ms == 25.0

    policy = cfg.retry.build_policy()
    assert policy is not None
    assert policy.max_attempts == 7
    assert policy.initial_backoff_s == 0.025
    assert policy.budget is not None and policy.budget.tokens == 3.5


def test_resilience_knob_validation(clean_env):
    cfg = ServerConfig()
    cfg.tpu.recovery_after_s = -2.0
    with pytest.raises(ValueError):
        cfg.validate()
    cfg.tpu.recovery_after_s = -1.0  # sentinel: never self-heal
    cfg.validate()

    cfg = ServerConfig()
    cfg.tpu.probe_batch_max = 0
    with pytest.raises(ValueError):
        cfg.validate()

    cfg = ServerConfig()
    cfg.retry.multiplier = 0.5
    with pytest.raises(ValueError):
        cfg.validate()

    cfg = ServerConfig()
    cfg.retry.budget = 0.0
    cfg.validate()  # valid: retries disabled
    assert cfg.retry.build_policy() is None


def test_empty_int_env_keeps_default(clean_env, monkeypatch):
    """Deployment templates render optional vars as "": that must keep the
    default (and suppress the alias), not crash int("") at startup."""
    default_burst = ServerConfig.from_env().rate_limit.burst
    monkeypatch.setenv("SERVER_RATE_LIMIT_BURST", "")
    monkeypatch.setenv("SERVER_RATE_BURST", "99")
    cfg = ServerConfig.from_env()
    assert cfg.rate_limit.burst == default_burst
