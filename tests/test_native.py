"""Native C++ crypto core vs pure-Python twin: byte-identical transcripts.

If the toolchain is unavailable the native core is skipped gracefully — the
Python fallback remains the reference behavior."""

import secrets

import pytest

from cpzk_tpu.core import _native
from cpzk_tpu.core.strobe import Strobe128
from cpzk_tpu.core.transcript import (
    CHALLENGE_DST,
    PROTOCOL_DST,
    PROTOCOL_LABEL,
    MerlinTranscript,
    Transcript,
    derive_challenges_batch,
)

native_available = _native.load() is not None
needs_native = pytest.mark.skipif(not native_available, reason="native core not built")


@needs_native
def test_native_merlin_matches_python():
    for _ in range(5):
        label = secrets.token_bytes(secrets.randbelow(40) + 1)
        py = MerlinTranscript(PROTOCOL_LABEL)
        nat = _native.NativeMerlin(PROTOCOL_LABEL)
        msgs = [
            (b"protocol", PROTOCOL_DST),
            (b"context", label),
            (b"big", secrets.token_bytes(700)),  # > strobe rate, forces runs of F
            (b"empty", b""),
        ]
        for lab, msg in msgs:
            py.append_message(lab, msg)
            nat.append_message(lab, msg)
        assert py.challenge_bytes(CHALLENGE_DST, 64) == nat.challenge_bytes(CHALLENGE_DST, 64)
        # post-challenge state still aligned
        py.append_message(b"more", b"x")
        nat.append_message(b"more", b"x")
        assert py.challenge_bytes(b"c2", 32) == nat.challenge_bytes(b"c2", 32)


@needs_native
def test_native_challenge_batch_matches_python():
    n = 17
    # mix of absent (None), empty (b"" -> still appended), and sized contexts
    contexts = [None if i % 3 == 0 else secrets.token_bytes(i - 1) for i in range(n)]
    assert b"" in contexts
    cols = [[secrets.token_bytes(32) for _ in range(n)] for _ in range(6)]
    native = derive_challenges_batch(contexts, *cols)

    # forced-Python comparison path
    py = []
    for i in range(n):
        t = Transcript.__new__(Transcript)
        t._t = MerlinTranscript(PROTOCOL_LABEL)
        t._t.append_message(b"protocol", PROTOCOL_DST)
        if contexts[i] is not None:
            t.append_context(contexts[i])
        t.append_parameters(cols[0][i], cols[1][i])
        t.append_statement(cols[2][i], cols[3][i])
        t.append_commitment(cols[4][i], cols[5][i])
        py.append(t.challenge_scalar())
    assert [s.value for s in native] == [s.value for s in py]


def test_strobe_rate_boundary():
    """Python Strobe handles absorb/squeeze across the 166-byte rate."""
    s1 = Strobe128(b"proto")
    s2 = Strobe128(b"proto")
    s1.ad(b"a" * 400, False)
    s2.ad(b"a" * 400, False)
    assert s1.prf(200, False) == s2.prf(200, False)


# --- C++ ristretto255 verification core (native/ristretto.cpp) -------------


def _skip_without_ristretto():
    from cpzk_tpu.core import _native

    lib = _native.load()
    if lib is None or not hasattr(lib, "cpzk_verify_rows"):
        import pytest

        pytest.skip("native ristretto core unavailable")


def test_native_point_roundtrip_differential():
    _skip_without_ristretto()
    import secrets

    from cpzk_tpu.core import _native, edwards as he, scalars as hs

    for _ in range(24):
        wire = he.ristretto_encode(
            he.pt_scalar_mul(he.BASEPOINT, secrets.randbelow(hs.L))
        )
        assert _native.point_roundtrip(wire) == wire
    # canonical-decode rejections: odd s, non-canonical, garbage
    assert _native.point_roundtrip((3).to_bytes(32, "little")) == b""
    assert _native.point_roundtrip(((he.P + 1) % 2**256).to_bytes(32, "little")) == b""
    assert _native.point_roundtrip(b"\xff" * 32) == b""
    # valid control
    assert _native.point_roundtrip(he.ristretto_encode(he.BASEPOINT)) != b""


def test_native_group_ops_differential():
    _skip_without_ristretto()
    import secrets

    from cpzk_tpu.core import _native, edwards as he, scalars as hs

    for _ in range(10):
        k, m = secrets.randbelow(hs.L), secrets.randbelow(hs.L)
        P = he.pt_scalar_mul(he.BASEPOINT, k)
        Q = he.pt_scalar_mul(he.BASEPOINT, m)
        wp, wq = he.ristretto_encode(P), he.ristretto_encode(Q)
        assert _native.scalarmul(wp, m.to_bytes(32, "little")) == he.ristretto_encode(
            he.pt_scalar_mul(P, m)
        )
        assert _native.point_add(wp, wq) == he.ristretto_encode(he.pt_add(P, Q))
    # edge scalars
    P = he.pt_scalar_mul(he.BASEPOINT, 7)
    wp = he.ristretto_encode(P)
    assert _native.scalarmul(wp, (0).to_bytes(32, "little")) == he.ristretto_encode(
        he.IDENTITY
    )
    assert _native.scalarmul(wp, (1).to_bytes(32, "little")) == wp


def test_native_verify_rows_differential():
    _skip_without_ristretto()
    from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
    from cpzk_tpu.core import _native
    from cpzk_tpu.core.ristretto import Ristretto255

    rng = SecureRng()
    params = Parameters.new()
    eb = Ristretto255.element_to_bytes
    rows = []
    for _ in range(6):
        pr = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        proof = pr.prove_with_transcript(rng, Transcript())
        t = Transcript()
        t.append_parameters(eb(params.generator_g), eb(params.generator_h))
        t.append_statement(eb(pr.statement.y1), eb(pr.statement.y2))
        t.append_commitment(eb(proof.commitment.r1), eb(proof.commitment.r2))
        rows.append((pr.statement, proof, t.challenge_scalar()))

    cols = [
        b"".join(eb(st.y1) for st, _, _ in rows),
        b"".join(eb(st.y2) for st, _, _ in rows),
        b"".join(eb(p.commitment.r1) for _, p, _ in rows),
        b"".join(eb(p.commitment.r2) for _, p, _ in rows),
        b"".join(Ristretto255.scalar_to_bytes(p.response.s) for _, p, _ in rows),
        b"".join(Ristretto255.scalar_to_bytes(c) for _, _, c in rows),
    ]
    g, h = eb(params.generator_g), eb(params.generator_h)
    assert _native.verify_rows(g, h, *cols) == [1] * 6

    # corrupted challenge -> that row only fails
    bad = cols[5][:32] + bytes(32) + cols[5][64:]
    assert _native.verify_rows(g, h, *cols[:5], bad) == [1, 0] + [1] * 4

    # swapped statements -> both swapped rows fail
    y1_sw = cols[0][32:64] + cols[0][:32] + cols[0][64:]
    res = _native.verify_rows(g, h, y1_sw, *cols[1:])
    assert res[0] == 0 and res[1] == 0 and res[2:] == [1] * 4

    # invalid STATEMENT encoding in a row -> plain failure (0), no crash
    y1_bad = b"\xff" * 32 + cols[0][32:]
    res = _native.verify_rows(g, h, y1_bad, *cols[1:])
    assert res[0] == 0 and res[1:] == [1] * 5

    # invalid COMMITMENT encoding -> tri-state 2 (deferred-parse contract:
    # the serving layer maps it back to the exact parse error)
    r1_bad = b"\xff" * 32 + cols[2][32:]
    res = _native.verify_rows(g, h, cols[0], cols[1], r1_bad, *cols[3:])
    assert res[0] == 2 and res[1:] == [1] * 5


def test_native_point_validate_differential():
    _skip_without_ristretto()
    from cpzk_tpu.core import _native, edwards as he, scalars as hs

    for _ in range(24):
        wire = he.ristretto_encode(
            he.pt_scalar_mul(he.BASEPOINT, secrets.randbelow(hs.L))
        )
        assert _native.point_validate(wire) is True
    # decode-only must reject exactly what the roundtrip rejects
    assert _native.point_validate((3).to_bytes(32, "little")) is False
    assert _native.point_validate(((he.P + 1) % 2**256).to_bytes(32, "little")) is False
    assert _native.point_validate(b"\xff" * 32) is False
    assert _native.point_validate(bytes(32)) is True  # identity is valid wire


def test_native_sc_mul_beta_differential():
    """The merged-verify weight math (beta * s mod l) against Python ints,
    including boundary betas/scalars that stress the Barrett-style folds."""
    _skip_without_ristretto()
    from cpzk_tpu.core import _native, scalars as hs

    cases = []
    for _ in range(200):
        cases.append((secrets.randbits(128), secrets.randbelow(hs.L)))
    cases += [
        (0, 5),
        (1, hs.L - 1),
        (2**128 - 1, hs.L - 1),
        (2**128 - 1, 2**252),
        (2**127, hs.L - 1),
        (1, 0),
    ]
    for beta, s in cases:
        out = _native.sc_mul_beta(
            beta.to_bytes(16, "little"), s.to_bytes(32, "little")
        )
        assert out is not None
        assert int.from_bytes(out, "little") == (beta * s) % hs.L, (beta, s)
    # out-of-domain scalars (>= 2^253) are rejected, not silently wrong
    with pytest.raises(ValueError, match="domain"):
        _native.sc_mul_beta((1).to_bytes(16, "little"),
                            (2**253).to_bytes(32, "little"))


def test_verify_rows_single_equation_failures():
    """Rows where exactly ONE of the two Chaum-Pedersen equations fails —
    the case the beta-merged fast path must never falsely accept (it
    falls back to the exact per-equation check on a merged miss)."""
    _skip_without_ristretto()
    from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
    from cpzk_tpu.core import _native
    from cpzk_tpu.core.ristretto import Ristretto255

    rng = SecureRng()
    params = Parameters.new()
    eb = Ristretto255.element_to_bytes
    pr = Prover(params, Witness(Ristretto255.random_scalar(rng)))
    proof = pr.prove_with_transcript(rng, Transcript())
    t = Transcript()
    t.append_parameters(eb(params.generator_g), eb(params.generator_h))
    t.append_statement(eb(pr.statement.y1), eb(pr.statement.y2))
    t.append_commitment(eb(proof.commitment.r1), eb(proof.commitment.r2))
    c = t.challenge_scalar()

    g, h = eb(params.generator_g), eb(params.generator_h)
    y1, y2 = eb(pr.statement.y1), eb(pr.statement.y2)
    r1, r2 = eb(proof.commitment.r1), eb(proof.commitment.r2)
    s = Ristretto255.scalar_to_bytes(proof.response.s)
    cb = Ristretto255.scalar_to_bytes(c)
    junk = eb(Ristretto255.scalar_mul(params.generator_g,
                                      Ristretto255.random_scalar(rng)))

    assert _native.verify_rows(g, h, y1, y2, r1, r2, s, cb) == [True]
    # eq1 holds, eq2 broken (r2 replaced by a random valid point)
    assert _native.verify_rows(g, h, y1, y2, r1, junk, s, cb) == [False]
    # eq2 holds, eq1 broken
    assert _native.verify_rows(g, h, y1, y2, junk, r2, s, cb) == [False]


def test_verify_rows_custom_generator_pairs():
    """Non-default generator pairs rebuild the cached verify context;
    alternating pairs (churn) must stay correct on every call."""
    _skip_without_ristretto()
    from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
    from cpzk_tpu.core import _native
    from cpzk_tpu.core.ristretto import Ristretto255

    rng = SecureRng()
    eb = Ristretto255.element_to_bytes

    def make(params):
        pr = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        proof = pr.prove_with_transcript(rng, Transcript())
        t = Transcript()
        t.append_parameters(eb(params.generator_g), eb(params.generator_h))
        t.append_statement(eb(pr.statement.y1), eb(pr.statement.y2))
        t.append_commitment(eb(proof.commitment.r1), eb(proof.commitment.r2))
        c = t.challenge_scalar()
        return (
            eb(params.generator_g), eb(params.generator_h),
            eb(pr.statement.y1), eb(pr.statement.y2),
            eb(proof.commitment.r1), eb(proof.commitment.r2),
            Ristretto255.scalar_to_bytes(proof.response.s),
            Ristretto255.scalar_to_bytes(c),
        )

    k = Ristretto255.random_scalar(rng)
    base = Parameters.new()
    custom = Parameters.with_generators(
        Ristretto255.scalar_mul(base.generator_g, k),
        base.generator_h,
    )
    a, b = make(base), make(custom)
    for row in (a, b, a, b):  # alternate to force context churn
        assert _native.verify_rows(*row) == [True]


def test_cpu_backend_uses_native_rows():
    """BatchVerifier on the CpuBackend and the pure-Python oracle agree
    through the native fast path (mixed valid/invalid)."""
    from cpzk_tpu import BatchVerifier, Parameters, Prover, SecureRng, Transcript, Witness
    from cpzk_tpu.core.ristretto import Ristretto255

    rng = SecureRng()
    params = Parameters.new()
    proofs = []
    for _ in range(5):
        pr = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        proofs.append((pr.statement, pr.prove_with_transcript(rng, Transcript())))

    bv = BatchVerifier()
    for st, p in proofs:
        bv.add(params, st, p)
    bv.add(params, proofs[0][0], proofs[1][1])  # mismatched row
    res = bv.verify(rng)
    assert [r is None for r in res] == [True] * 5 + [False]


def test_double_basemul_matches_python_oracle():
    """The constant-time fixed-base comb (cpzk_double_basemul) is bit-exact
    vs the pure-Python ladder for random and edge-case scalars, for both
    the standard generator pair and a custom pair."""
    from cpzk_tpu.core import _native, edwards, scalars
    from cpzk_tpu.core.ristretto import Ristretto255, Scalar
    from cpzk_tpu.core.rng import SecureRng

    lib = _native._ristretto_lib()
    if lib is None or not hasattr(lib, "cpzk_double_basemul"):
        pytest.skip("native core unavailable")

    rng = SecureRng()
    g, h = Ristretto255.generator_g(), Ristretto255.generator_h()
    cases = [Ristretto255.random_scalar(rng).value for _ in range(8)]
    cases += [0, 1, 15, 16, 17, 255, scalars.L - 1, 2**252 + 27742]
    for v in cases:
        r1, r2 = Ristretto255.double_base_mul(g, h, Scalar(v))
        assert r1.wire() == edwards.ristretto_encode(
            edwards.pt_scalar_mul(g.point, v % scalars.L)
        )
        assert r2.wire() == edwards.ristretto_encode(
            edwards.pt_scalar_mul(h.point, v % scalars.L)
        )

    # custom generator pair: tables rebuild for the new pair (and back)
    x = Ristretto255.random_scalar(rng)
    g2, h2 = Ristretto255.double_base_mul(g, h, x)  # some other pair
    s = Ristretto255.random_scalar(rng)
    a1, a2 = Ristretto255.double_base_mul(g2, h2, s)
    assert a1.wire() == edwards.ristretto_encode(
        edwards.pt_scalar_mul(g2.point, s.value)
    )
    assert a2.wire() == edwards.ristretto_encode(
        edwards.pt_scalar_mul(h2.point, s.value)
    )
    b1, b2 = Ristretto255.double_base_mul(g, h, s)
    assert b1.wire() == edwards.ristretto_encode(
        edwards.pt_scalar_mul(g.point, s.value)
    )
    assert b2.wire() == edwards.ristretto_encode(
        edwards.pt_scalar_mul(h.point, s.value)
    )


def test_verify_rows_rejects_ragged_scalar_column():
    """len(ss) not a multiple of 32 raises instead of silently truncating
    (ADVICE r2)."""
    from cpzk_tpu.core import _native

    if _native._ristretto_lib() is None:
        pytest.skip("native core unavailable")
    with pytest.raises(ValueError, match="multiple of 32"):
        _native.verify_rows(b"\x00" * 32, b"\x00" * 32, b"", b"", b"", b"", b"\x01" * 33, b"")


def test_double_basemul_concurrent_generator_churn():
    """Two threads alternating generator pairs must always get correct
    points — the C side serializes table rebuilds with a rwlock (ctypes
    releases the GIL, so the GIL alone is no protection)."""
    import threading

    from cpzk_tpu.core import edwards, scalars
    from cpzk_tpu.core.ristretto import Ristretto255, Scalar
    from cpzk_tpu.core.rng import SecureRng

    rng = SecureRng()
    g, h = Ristretto255.generator_g(), Ristretto255.generator_h()
    x = Ristretto255.random_scalar(rng)
    g2, h2 = Ristretto255.double_base_mul(g, h, x)
    pairs = [(g, h), (g2, h2)]
    scalars_ = [Ristretto255.random_scalar(rng) for _ in range(8)]
    failures: list[str] = []

    def worker(which: int) -> None:
        for i in range(20):
            gg, hh = pairs[(which + i) % 2]
            s = scalars_[i % len(scalars_)]
            r1, r2 = Ristretto255.double_base_mul(gg, hh, s)
            e1 = edwards.ristretto_encode(edwards.pt_scalar_mul(gg.point, s.value))
            e2 = edwards.ristretto_encode(edwards.pt_scalar_mul(hh.point, s.value))
            if r1.wire() != e1 or r2.wire() != e2:
                failures.append(f"thread {which} iter {i}")

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not failures, failures
