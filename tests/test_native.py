"""Native C++ crypto core vs pure-Python twin: byte-identical transcripts.

If the toolchain is unavailable the native core is skipped gracefully — the
Python fallback remains the reference behavior."""

import secrets

import pytest

from cpzk_tpu.core import _native
from cpzk_tpu.core.strobe import Strobe128
from cpzk_tpu.core.transcript import (
    CHALLENGE_DST,
    PROTOCOL_DST,
    PROTOCOL_LABEL,
    MerlinTranscript,
    Transcript,
    derive_challenges_batch,
)

native_available = _native.load() is not None
needs_native = pytest.mark.skipif(not native_available, reason="native core not built")


@needs_native
def test_native_merlin_matches_python():
    for _ in range(5):
        label = secrets.token_bytes(secrets.randbelow(40) + 1)
        py = MerlinTranscript(PROTOCOL_LABEL)
        nat = _native.NativeMerlin(PROTOCOL_LABEL)
        msgs = [
            (b"protocol", PROTOCOL_DST),
            (b"context", label),
            (b"big", secrets.token_bytes(700)),  # > strobe rate, forces runs of F
            (b"empty", b""),
        ]
        for lab, msg in msgs:
            py.append_message(lab, msg)
            nat.append_message(lab, msg)
        assert py.challenge_bytes(CHALLENGE_DST, 64) == nat.challenge_bytes(CHALLENGE_DST, 64)
        # post-challenge state still aligned
        py.append_message(b"more", b"x")
        nat.append_message(b"more", b"x")
        assert py.challenge_bytes(b"c2", 32) == nat.challenge_bytes(b"c2", 32)


@needs_native
def test_native_challenge_batch_matches_python():
    n = 17
    # mix of absent (None), empty (b"" -> still appended), and sized contexts
    contexts = [None if i % 3 == 0 else secrets.token_bytes(i - 1) for i in range(n)]
    assert b"" in contexts
    cols = [[secrets.token_bytes(32) for _ in range(n)] for _ in range(6)]
    native = derive_challenges_batch(contexts, *cols)

    # forced-Python comparison path
    py = []
    for i in range(n):
        t = Transcript.__new__(Transcript)
        t._t = MerlinTranscript(PROTOCOL_LABEL)
        t._t.append_message(b"protocol", PROTOCOL_DST)
        if contexts[i] is not None:
            t.append_context(contexts[i])
        t.append_parameters(cols[0][i], cols[1][i])
        t.append_statement(cols[2][i], cols[3][i])
        t.append_commitment(cols[4][i], cols[5][i])
        py.append(t.challenge_scalar())
    assert [s.value for s in native] == [s.value for s in py]


def test_strobe_rate_boundary():
    """Python Strobe handles absorb/squeeze across the 166-byte rate."""
    s1 = Strobe128(b"proto")
    s2 = Strobe128(b"proto")
    s1.ad(b"a" * 400, False)
    s2.ad(b"a" * 400, False)
    assert s1.prf(200, False) == s2.prf(200, False)
