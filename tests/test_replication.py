"""Replicated server state (ISSUE 8): sharded locks, WAL segment
shipping, lease-based promotion.

Covers the pieces the chaos acceptance scenario (``test_chaos.py``)
composes: shard routing + the contention contract, segment
sealing/validation, the standby applier's idempotency/fencing/gap
semantics, epoch persistence, promotion, the sync-mode acknowledgement
barrier, compaction clamping, and the ``[replication]`` config surface
(drift guard, env precedence, validation — including the
lease-must-exceed-renew rejection).
"""

import asyncio
import dataclasses
import os
import pathlib
import re
import zlib

import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Witness
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.durability import DurabilityManager
from cpzk_tpu.durability.wal import encode_record, read_frames
from cpzk_tpu.replication import (
    SegmentApplier,
    SegmentShipper,
    StandbyReplica,
    load_epoch,
    seal_segment,
    split_records,
    store_epoch,
    validate_segment,
)
from cpzk_tpu.resilience.faults import CrashPoint, FaultPlan
from cpzk_tpu.server.config import (
    DurabilitySettings,
    RateLimiter,
    ReplicationSettings,
    ServerConfig,
)
from cpzk_tpu.server.state import ServerState, UserData

ROOT = pathlib.Path(__file__).resolve().parent.parent

rng = SecureRng()
params = Parameters.new()


def run(coro):
    return asyncio.run(coro)


def make_statement():
    return Prover(params, Witness(Ristretto255.random_scalar(rng))).statement


def uid_on_shard(state: ServerState, shard: int, avoid: set | None = None) -> str:
    """A user id hashing to ``shard`` under ``state``'s shard count."""
    avoid = avoid or set()
    i = 0
    while True:
        uid = f"user-{i}"
        if uid not in avoid and state._shard_index(uid) == shard:
            return uid
        i += 1


def make_records(n, start_seq=1, rtype="register_user"):
    stmts = [make_statement() for _ in range(n)]
    eb = Ristretto255.element_to_bytes
    return [
        {
            "seq": start_seq + i, "type": rtype, "user_id": f"user-{i}",
            "y1": eb(stmts[i].y1).hex(), "y2": eb(stmts[i].y2).hex(),
            "registered_at": 1,
        }
        for i in range(n)
    ]


async def make_pair(tmp_path, lease_ms=400.0, renew_ms=40.0, mode="sync",
                    segment_bytes=65536, standby_faults=None,
                    primary_faults=None, auto_promote=True,
                    wal_segment_bytes=0):
    """(primary side, standby side) wired over a real gRPC link."""
    from cpzk_tpu.server.service import serve

    sstate = ServerState()
    smgr = DurabilityManager(
        sstate,
        DurabilitySettings(enabled=True, wal_segment_bytes=wal_segment_bytes),
        str(tmp_path / "standby.json"), faults=standby_faults,
    )
    await smgr.recover()
    ssettings = ReplicationSettings(
        enabled=True, role="standby", lease_ms=lease_ms,
        renew_interval_ms=renew_ms, mode=mode, auto_promote=auto_promote,
    )
    replica = StandbyReplica(sstate, smgr, ssettings, faults=standby_faults)
    sserver, sport = await serve(
        sstate, RateLimiter(100_000, 100_000), port=0, replica=replica
    )
    replica.start()

    pstate = ServerState()
    pmgr = DurabilityManager(
        pstate,
        DurabilitySettings(enabled=True, wal_segment_bytes=wal_segment_bytes),
        str(tmp_path / "primary.json"), faults=primary_faults,
    )
    await pmgr.recover()
    psettings = ReplicationSettings(
        enabled=True, role="primary", peer=f"127.0.0.1:{sport}",
        lease_ms=lease_ms, renew_interval_ms=renew_ms, mode=mode,
        segment_bytes=segment_bytes,
    )
    shipper = SegmentShipper(pstate, pmgr, psettings, faults=primary_faults)
    pmgr.attach_shipper(shipper)
    if mode == "sync":
        pstate.attach_replication_barrier(shipper.wait_replicated)
    shipper.start()
    return (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, sport)


async def wait_for(predicate, timeout=5.0, interval=0.02):
    deadline = asyncio.get_running_loop().time() + timeout
    while not predicate():
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError("condition not reached in time")
        await asyncio.sleep(interval)


# --- sharded state ----------------------------------------------------------


class TestShardedState:
    def test_shard_count_bounds(self):
        with pytest.raises(ValueError):
            ServerState(shards=0)
        with pytest.raises(ValueError):
            ServerState(shards=257)
        assert ServerState(shards=1).num_shards == 1

    def test_stable_hash_and_tags(self):
        st = ServerState(shards=8)
        uid = "alice"
        idx = st._shard_index(uid)
        assert idx == zlib.crc32(b"alice") % 8  # stable across processes
        cid = st.tag_challenge_id(uid, b"\xff" * 32)
        assert cid[0] == idx and cid[1:] == b"\xff" * 31 and len(cid) == 32
        tok = st.tag_session_token(uid, "f" * 64)
        assert tok == f"{idx:02x}" + "f" * 62

    def test_tagged_routing_and_untagged_fallback(self):
        async def main():
            st = ServerState(shards=8)
            await st.register_user(UserData("alice", make_statement(), 1))
            # tagged challenge: routed by the tag byte
            cid = st.tag_challenge_id("alice", os.urandom(32))
            await st.create_challenge("alice", cid)
            assert st._locate_challenge(cid) == st._shard_index("alice")
            got = await st.consume_challenge(cid)
            assert got.user_id == "alice"
            # untagged (legacy/test) ids fall back to the scan and still work
            raw = b"c" * 32
            await st.create_challenge("alice", raw)
            assert (await st.consume_challenge(raw)).user_id == "alice"
            # tagged session token routes; untagged falls back
            tok = st.tag_session_token("alice", "a" * 64)
            await st.create_session(tok, "alice")
            assert await st.validate_session(tok) == "alice"
            await st.create_session("tok", "alice")
            assert await st.validate_session("tok") == "alice"
            await st.revoke_session("tok")
            with pytest.raises(Exception, match="Invalid session token"):
                await st.validate_session("tok")

        run(main())

    def test_distinct_users_do_not_serialize(self):
        """THE contention pin (ISSUE 8 acceptance): holding one shard's
        lock blocks same-shard users but not users on other shards — the
        per-RPC global serialization is gone."""

        async def main():
            st = ServerState(shards=4)
            a = uid_on_shard(st, 0)
            same = uid_on_shard(st, 0, avoid={a})
            other = uid_on_shard(st, 1)
            async with st._shard_for_user(a).lock:
                # a different user's registration proceeds under the held lock
                await asyncio.wait_for(
                    st.register_user(UserData(other, make_statement(), 1)),
                    timeout=2.0,
                )
                # a SAME-shard registration must block on the held lock
                with pytest.raises(asyncio.TimeoutError):
                    await asyncio.wait_for(
                        st.register_user(UserData(same, make_statement(), 1)),
                        timeout=0.1,
                    )
            # released: the same-shard user registers fine now
            await st.register_user(UserData(same, make_statement(), 1))
            assert await st.user_count() == 2  # other + same (a never registered)

        run(main())

    def test_views_merge_shards(self):
        async def main():
            st = ServerState(shards=4)
            uids = [uid_on_shard(st, i) for i in range(4)]
            for u in uids:
                await st.register_user(UserData(u, make_statement(), 1))
                await st.create_session(
                    st.tag_session_token(u, os.urandom(32).hex()), u
                )
            assert sorted(st._users) == sorted(uids)
            assert len(st._sessions) == 4
            for tok, sess in st._sessions.items():
                assert st._sessions[tok] is sess
                assert tok in st._sessions
            assert "nope" not in st._sessions

        run(main())


# --- segments ---------------------------------------------------------------


class TestSegments:
    def test_seal_split_validate_roundtrip(self):
        records = make_records(5)
        segs = split_records(records, epoch=1, first_index=0, segment_bytes=400)
        assert len(segs) == 3  # 2 + 2 sealed at ~400B, 1-record remainder
        assert [s.index for s in segs] == list(range(len(segs)))
        assert segs[0].sealed and not segs[-1].sealed  # tail-follow
        seen = []
        for seg in segs:
            got, err = validate_segment(seg)
            assert err is None
            seen.extend(r["seq"] for r in got)
        assert seen == [r["seq"] for r in records]

    def test_validation_rejects_torn_and_tampered(self):
        seg = seal_segment(1, 0, make_records(3))
        ok, err = validate_segment(seg)
        assert err is None and len(ok) == 3
        torn = dataclasses.replace(seg, frames=seg.frames[: len(seg.frames) // 2])
        assert validate_segment(torn)[1] is not None
        flipped = bytearray(seg.frames)
        flipped[12] ^= 0x40
        assert "CRC" in validate_segment(
            dataclasses.replace(seg, frames=bytes(flipped))
        )[1]
        assert "first_seq" in validate_segment(
            dataclasses.replace(seg, first_seq=99)
        )[1]
        assert "last_seq" in validate_segment(
            dataclasses.replace(seg, last_seq=99)
        )[1]
        assert validate_segment(dataclasses.replace(seg, frames=b""))[1]

    def test_applier_semantics(self):
        """Duplicate = idempotent accept; gap = reject; stale epoch =
        fenced; higher epoch = adopted; invalid records skip, not crash."""
        state = ServerState()
        applier = SegmentApplier(state, epoch=2)
        records = make_records(4)
        seg01 = seal_segment(2, 0, records[:2])
        seg23 = seal_segment(2, 1, records[2:])
        accepted, _, new = applier.prepare(seg01)
        assert accepted and len(new) == 2
        applier.commit(new)
        assert applier.applied_seq == 2
        assert run(state.user_count()) == 2
        # duplicate: accepted, nothing new
        accepted, msg, new = applier.prepare(seg01)
        assert accepted and not new and "duplicate" in msg
        # gap: seq 5.. while applied is 2
        gap = seal_segment(2, 5, make_records(1, start_seq=5))
        accepted, msg, _ = applier.prepare(gap)
        assert not accepted and "gap" in msg
        # stale epoch: fenced, no state change
        stale = seal_segment(1, 9, records[2:])
        accepted, msg, _ = applier.prepare(stale)
        assert not accepted and "fenced" in msg and applier.fenced == 1
        # higher epoch: adopted
        future = seal_segment(3, 1, records[2:])
        accepted, _, new = applier.prepare(future)
        assert accepted and applier.epoch == 3
        applier.commit(new)
        assert run(state.user_count()) == 4
        # overlap (partially applied): only the new suffix applies
        overlap = seal_segment(3, 2, make_records(3, start_seq=3))
        accepted, _, new = applier.prepare(overlap)
        assert accepted and [r["seq"] for r in new] == [5]
        applier.commit(new)  # duplicate user id: skipped by the validators
        assert applier.records_skipped == 1 and applier.applied_seq == 5
        # a record the RPC would reject is skipped, never fatal
        bad = seal_segment(3, 3, [
            {"seq": 6, "type": "register_user", "user_id": "bad user!",
             "y1": "00", "y2": "00", "registered_at": 1},
        ])
        accepted, _, new = applier.prepare(bad)
        assert accepted
        applier.commit(new)
        assert applier.records_skipped == 2
        assert applier.applied_seq == 6

    def test_epoch_file_roundtrip(self, tmp_path):
        path = str(tmp_path / "node.epoch")
        assert load_epoch(path) == 1  # absent -> first epoch
        store_epoch(path, 7)
        assert load_epoch(path) == 7
        (tmp_path / "node.epoch").write_text("garbage")
        assert load_epoch(path) == 1


# --- shipping + promotion over a real gRPC link ------------------------------


class TestShipAndPromote:
    def test_sync_barrier_and_warm_standby(self, tmp_path):
        async def main():
            (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, _) = (
                await make_pair(tmp_path)
            )
            try:
                for i in range(5):
                    await pstate.register_user(
                        UserData(f"u{i}", make_statement(), 1)
                    )
                # sync mode: the ack barrier means the standby applied it
                # BEFORE register_user returned — no polling needed
                assert shipper.acked_seq == pmgr.wal.seq == replica.applied_seq
                assert await sstate.user_count() == 5
                assert replica.applier.records_applied == 5
                assert shipper.segments_shipped >= 1
                # the standby's own WAL holds the primary's frames verbatim
                srecords, valid, total = read_frames(smgr.wal.path)
                assert valid == total
                assert [r["seq"] for r in srecords] == [1, 2, 3, 4, 5]
                assert replica.status()["role"] == "standby"
                assert shipper.status()["lag_records"] == 0
            finally:
                await shipper.kill()
                await replica.stop()
                await sserver.stop(None)

        run(main())

    def test_promotion_on_lease_expiry_and_epoch_fencing(self, tmp_path):
        async def main():
            (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, sport) = (
                await make_pair(tmp_path, lease_ms=300, renew_ms=30)
            )
            try:
                await pstate.register_user(UserData("alice", make_statement(), 1))
                assert replica.applied_seq == 1
                assert sserver.health.standby is True
                # SIGKILL stand-in: the shipper dies, renewals stop
                await shipper.kill()
                await wait_for(lambda: replica.role == "primary")
                assert replica.epoch == 2
                assert sserver.health.standby is False  # readiness flipped
                assert load_epoch(replica.epoch_path) == 2
                # deposed primary comes back and ships: fenced, no effect
                psettings = ReplicationSettings(
                    enabled=True, role="primary", peer=f"127.0.0.1:{sport}",
                    lease_ms=300, renew_interval_ms=30,
                )
                deposed = SegmentShipper(pstate, pmgr, psettings)
                assert deposed.epoch == 1
                # the revived deposed primary runs async mode (a fresh
                # process would rebuild its barrier from config)
                pstate.attach_replication_barrier(None)
                await pstate.register_user(UserData("evil", make_statement(), 1))
                deposed.start()
                await wait_for(lambda: deposed.fenced)
                assert await sstate.get_user("evil") is None
                assert replica.applier.fenced >= 1
                await deposed.kill()
                # promoting again is a no-op
                report = await replica.promote(reason="operator")
                assert not report["promoted"]
            finally:
                await shipper.kill()
                await replica.stop()
                await sserver.stop(None)

        run(main())

    def test_pre_promote_crash_point_is_retryable(self, tmp_path):
        async def main():
            plan = FaultPlan().crash_on("pre_promote", occurrence=0)
            (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, _) = (
                await make_pair(tmp_path, standby_faults=plan,
                                auto_promote=False)
            )
            try:
                await pstate.register_user(UserData("alice", make_statement(), 1))
                await shipper.kill()
                with pytest.raises(CrashPoint):
                    await replica.promote(reason="operator")
                assert replica.role == "standby"  # nothing half-promoted
                assert load_epoch(replica.epoch_path) == 1
                report = await replica.promote(reason="operator")  # retry
                assert report["promoted"] and replica.epoch == 2
                assert await sstate.get_user("alice") is not None
            finally:
                await replica.stop()
                await sserver.stop(None)

        run(main())

    def test_sync_mode_refuses_to_ack_without_standby(self, tmp_path):
        """Zero-loss means failing the write, not lying: with the standby
        gone and the shipper dead, a sync-mode mutation raises instead of
        acknowledging."""
        from cpzk_tpu.replication import ReplicationTimeout

        async def main():
            (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, _) = (
                await make_pair(tmp_path)
            )
            shipper.settings.sync_timeout_ms = 200.0
            await pstate.register_user(UserData("ok", make_statement(), 1))
            await replica.stop()
            await sserver.stop(None)  # standby gone
            with pytest.raises(ReplicationTimeout):
                await pstate.register_user(UserData("lost", make_statement(), 1))
            await shipper.kill()

        run(main())

    def test_restarted_primary_catches_up_against_warm_standby(self, tmp_path):
        """A primary restart re-reads WAL history the standby already
        holds: the re-shipped segment is an idempotent duplicate, the
        acked offset catches up to the whole log (clearing the compaction
        floor), and fresh writes flow normally."""

        async def main():
            (pside, sside) = await make_pair(tmp_path)
            pstate, pmgr, shipper = pside
            sstate, smgr, replica, sserver, sport = sside
            try:
                for i in range(3):
                    await pstate.register_user(
                        UserData(f"u{i}", make_statement(), 1)
                    )
                applied_before = replica.applier.records_applied
                # "restart": a fresh shipper with zero local bookkeeping
                await shipper.kill()
                psettings = ReplicationSettings(
                    enabled=True, role="primary", peer=f"127.0.0.1:{sport}",
                    lease_ms=400, renew_interval_ms=40, mode="sync",
                )
                shipper2 = SegmentShipper(pstate, pmgr, psettings)
                pmgr.attach_shipper(shipper2)
                pstate.attach_replication_barrier(shipper2.wait_replicated)
                shipper2.start()
                await wait_for(
                    lambda: shipper2.acked_offset == pmgr.wal.size
                )
                # duplicates were not re-applied on the standby
                assert replica.applier.records_applied == applied_before
                assert replica.applied_seq == 3
                # and fresh writes replicate normally through the new shipper
                await pstate.register_user(UserData("u3", make_statement(), 1))
                assert replica.applied_seq == 4
                assert await sstate.get_user("u3") is not None
                await shipper2.kill()
            finally:
                await shipper.kill()
                await replica.stop()
                await sserver.stop(None)

        run(main())

    def test_compaction_clamped_to_standby_ack(self, tmp_path):
        """A covering snapshot must not let compaction drop bytes the
        standby has not acknowledged."""

        async def main():
            state = ServerState()
            mgr = DurabilityManager(
                state,
                DurabilitySettings(enabled=True, compact_bytes=0),
                str(tmp_path / "p.json"),
            )
            await mgr.recover()

            class StalledShipper:
                def __init__(self):
                    self.rebased = 0

                def safe_compact_offset(self):
                    return 0  # standby has acknowledged nothing

                def note_compacted(self, freed):
                    self.rebased += freed

            stalled = StalledShipper()
            mgr.attach_shipper(stalled)
            for i in range(4):
                await state.register_user(UserData(f"u{i}", make_statement(), 1))
            size = mgr.wal.size
            await mgr.checkpoint()  # snapshot covers all — but acked=0
            assert mgr.wal.size == size  # nothing compacted
            assert stalled.rebased == 0

            class CaughtUpShipper(StalledShipper):
                def safe_compact_offset(self):
                    return 10**9

            caught = CaughtUpShipper()
            mgr.attach_shipper(caught)
            state._persist_dirty = True
            await mgr.checkpoint()
            assert mgr.wal.size == 0  # covered AND acked: compacts
            assert caught.rebased == size

        run(main())


# --- config surface ----------------------------------------------------------


class TestReplicationConfig:
    def test_layering_env_precedence_and_validation(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)  # no stray .env/config pickup
        cfg = ServerConfig.from_env()
        assert cfg.replication.enabled is False
        assert cfg.replication.role == "primary"
        assert cfg.replication.mode == "async"

        (tmp_path / "server.toml").write_text(
            "state_file = 's.json'\n"
            "[durability]\nenabled = true\n"
            '[replication]\nenabled = true\nrole = "standby"\n'
            "lease_ms = 2000.0\nshards = 32\n"
        )
        monkeypatch.setenv("SERVER_CONFIG_PATH", str(tmp_path / "server.toml"))
        cfg = ServerConfig.from_env()
        assert cfg.replication.enabled is True
        assert cfg.replication.role == "standby"
        assert cfg.replication.lease_ms == 2000.0
        assert cfg.replication.shards == 32
        cfg.validate()
        # env overrides TOML
        monkeypatch.setenv("SERVER_REPLICATION_ROLE", "PRIMARY")
        monkeypatch.setenv("SERVER_REPLICATION_PEER", "10.0.0.2:50051")
        monkeypatch.setenv("SERVER_REPLICATION_MODE", "SYNC")
        monkeypatch.setenv("SERVER_REPLICATION_RENEW_INTERVAL_MS", "250")
        monkeypatch.setenv("SERVER_REPLICATION_AUTO_PROMOTE", "false")
        monkeypatch.setenv("SERVER_REPLICATION_SEGMENT_BYTES", "1024")
        monkeypatch.setenv("SERVER_REPLICATION_SYNC_TIMEOUT_MS", "750")
        monkeypatch.setenv("SERVER_REPLICATION_EPOCH_FILE", "/tmp/e")
        monkeypatch.setenv("SERVER_REPLICATION_SHARDS", "64")
        cfg = ServerConfig.from_env()
        assert cfg.replication.role == "primary"
        assert cfg.replication.peer == "10.0.0.2:50051"
        assert cfg.replication.mode == "sync"
        assert cfg.replication.renew_interval_ms == 250.0
        assert cfg.replication.auto_promote is False
        assert cfg.replication.segment_bytes == 1024
        assert cfg.replication.sync_timeout_ms == 750.0
        assert cfg.replication.epoch_file == "/tmp/e"
        assert cfg.replication.shards == 64
        cfg.validate()

    @pytest.mark.parametrize("mutate,match", [
        (lambda c: setattr(c.replication, "role", "observer"), "role"),
        (lambda c: setattr(c.replication, "mode", "eventual"), "mode"),
        (lambda c: setattr(c.replication, "renew_interval_ms", 0.0),
         "renew_interval_ms"),
        # THE footgun: a lease the renewal cadence cannot keep alive
        (lambda c: setattr(c.replication, "lease_ms", 500.0) or
         setattr(c.replication, "renew_interval_ms", 500.0), "lease_ms"),
        (lambda c: setattr(c.replication, "lease_ms", 100.0) or
         setattr(c.replication, "renew_interval_ms", 500.0), "lease_ms"),
        (lambda c: setattr(c.replication, "segment_bytes", 0), "segment_bytes"),
        (lambda c: setattr(c.replication, "sync_timeout_ms", 0.0),
         "sync_timeout_ms"),
        (lambda c: setattr(c.replication, "shards", 0), "shards"),
        (lambda c: setattr(c.replication, "shards", 257), "shards"),
    ])
    def test_validation_rejects(self, mutate, match):
        cfg = ServerConfig()
        mutate(cfg)
        with pytest.raises(ValueError, match=match):
            cfg.validate()

    def test_enabled_requires_durability_and_peer(self):
        cfg = ServerConfig()
        cfg.replication.enabled = True
        with pytest.raises(ValueError, match="requires durability"):
            cfg.validate()
        cfg.state_file = "s.json"
        cfg.durability.enabled = True
        with pytest.raises(ValueError, match="peer"):
            cfg.validate()
        cfg.replication.peer = "10.0.0.2:50051"
        cfg.validate()
        # a standby needs no peer
        cfg.replication.peer = ""
        cfg.replication.role = "standby"
        cfg.validate()

    def test_replication_config_keys_documented(self):
        """CI drift guard: every [replication] knob ships in the TOML
        example, the .env example, and the operations-doc knob inventory."""
        keys = [f.name for f in dataclasses.fields(ReplicationSettings)]
        assert keys  # the guard itself must not silently go vacuous

        toml_text = (ROOT / "config" / "server.toml.example").read_text()
        m = re.search(r"^\[replication\]$", toml_text, re.M)
        assert m, "[replication] section missing from config/server.toml.example"
        section = toml_text[m.end():].split("\n[", 1)[0]
        env_text = (ROOT / ".env.example").read_text()
        docs = (ROOT / "docs" / "operations.md").read_text()
        for key in keys:
            assert re.search(rf"^{key}\s*=", section, re.M), (
                f"[replication] key {key!r} missing from "
                "config/server.toml.example"
            )
            assert f"SERVER_REPLICATION_{key.upper()}" in env_text, (
                f"SERVER_REPLICATION_{key.upper()} missing from .env.example"
            )
            assert f"`replication.{key}`" in docs, (
                f"`replication.{key}` missing from the docs/operations.md "
                "knob inventory"
            )

    def test_repl_commands(self, tmp_path):
        from cpzk_tpu.server.__main__ import handle_command

        async def main():
            state = ServerState()
            out, _ = await handle_command("/replication", state)
            assert "replication disabled" in out
            out, _ = await handle_command("/promote", state)
            assert "nothing to promote" in out

            (pside, sside) = await make_pair(tmp_path, auto_promote=False)
            pstate, pmgr, shipper = pside
            sstate, smgr, replica, sserver, _ = sside
            try:
                await pstate.register_user(
                    UserData("alice", make_statement(), 1)
                )
                out, _ = await handle_command(
                    "/replication", pstate, None, pmgr, None, shipper
                )
                assert "role=primary" in out and "mode=sync" in out
                assert "acked_seq=1" in out and "fenced=False" in out
                out, _ = await handle_command(
                    "/replication", sstate, None, smgr, None, replica
                )
                assert "role=standby" in out and "applied_seq=1" in out
                await shipper.kill()
                out, _ = await handle_command(
                    "/promote", sstate, None, smgr, None, replica
                )
                assert "PROMOTED" in out and "epoch=2" in out
                out, _ = await handle_command(
                    "/promote", sstate, None, smgr, None, replica
                )
                assert "not promoted" in out
            finally:
                await replica.stop()
                await sserver.stop(None)

        run(main())


# --- the shipped frames are byte-exact --------------------------------------


def test_shipped_frames_are_canonical():
    """Re-encoding a parsed record reproduces the exact bytes the primary
    framed (compact key-sorted JSON) — what lets the standby's WAL carry
    identical frames and replay them through ordinary recovery."""
    from cpzk_tpu.durability.wal import iter_frames

    records = make_records(3)
    frames = b"".join(encode_record(r) for r in records)
    parsed, valid = iter_frames(frames)
    assert valid == len(frames)
    again = b"".join(encode_record(r) for r in parsed)
    assert again == frames


# --- segmented WAL under replication (ISSUE 14) ------------------------------


class TestSegmentedWalReplication:
    def test_shipping_promotion_and_clamp_across_segment_boundaries(
        self, tmp_path
    ):
        """A rotating primary WAL ships transparently: the shipper's
        logical-offset tail spans sealed segments, the standby (itself
        rotating) applies every record, the compaction clamp still never
        drops unshipped bytes, and the promoted standby serves the full
        history — the PR 8 contract, unchanged by rotation."""

        async def main():
            (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, _p) = (
                await make_pair(
                    tmp_path, mode="sync", wal_segment_bytes=600,
                    segment_bytes=700, auto_promote=False,
                )
            )
            try:
                stmts = {}
                for i in range(30):
                    stmts[i] = make_statement()
                    await pstate.register_user(
                        UserData(f"user-{i}", stmts[i], 1)
                    )
                # sync mode: every ack waited for standby apply
                assert shipper.acked_seq == pmgr.wal.seq
                # rotation actually happened on both sides
                await asyncio.to_thread(pmgr.wal.sync, True)
                await asyncio.to_thread(smgr.wal.sync, True)
                assert pmgr.wal.segment_count > 0
                assert await sstate.user_count() == 30

                # compaction: a covering snapshot may unlink only what is
                # BOTH covered and shipped; everything acked here, so the
                # checkpoint unlinks the sealed prefix with no copy
                pmgr.settings.compact_bytes = 0  # compact on this snapshot
                size_before = pmgr.wal.size
                await pmgr.checkpoint()
                assert pmgr.wal.size < size_before
                assert shipper.safe_compact_offset() <= pmgr.wal.size

                # more writes after compaction keep shipping
                await pstate.register_user(
                    UserData("user-99", make_statement(), 1)
                )
                await wait_for(lambda: replica.applied_seq == pmgr.wal.seq)

                # promotion over a rotated standby WAL
                await shipper.kill()
                report = await replica.promote(reason="test")
                assert report["promoted"]
                assert await sstate.user_count() == 31
                for i in (0, 7, 29):
                    u = await sstate.get_user(f"user-{i}")
                    assert u is not None and u.statement == stmts[i]
                # the promoted node keeps journaling into the same log
                await sstate.register_user(
                    UserData("post-promote", make_statement(), 1)
                )
            finally:
                await shipper.kill()
                await replica.stop()
                await sserver.stop(None)
                pmgr.wal.close()
                smgr.wal.close()

        run(main())

    def test_standby_reboot_recovers_rotated_wal(self, tmp_path):
        """A standby that crashed with sealed segments on disk recovers
        through ordinary durability recovery (the segment scan) and
        resumes from the right applied_seq."""

        async def main():
            (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, _p) = (
                await make_pair(
                    tmp_path, mode="sync", wal_segment_bytes=500,
                    auto_promote=False,
                )
            )
            try:
                for i in range(20):
                    await pstate.register_user(
                        UserData(f"user-{i}", make_statement(), 1)
                    )
                applied = replica.applied_seq
                assert applied == pmgr.wal.seq
            finally:
                await shipper.kill()
                await replica.stop()
                await sserver.stop(None)
                pmgr.wal.close()
                smgr.wal.close()

            # standby "reboot": fresh state recovered from its own files
            sstate2 = ServerState()
            smgr2 = DurabilityManager(
                sstate2,
                DurabilitySettings(enabled=True, wal_segment_bytes=500),
                str(tmp_path / "standby.json"),
            )
            report = await smgr2.recover()
            assert report.next_seq == applied
            assert await sstate2.user_count() == 20
            smgr2.wal.close()

        run(main())


# --- coordinated handover (ISSUE 18) -----------------------------------------


class TestCoordinatedHandover:
    def test_handover_end_to_end(self, tmp_path):
        """The tentpole path: fence → ship tail → promote at epoch+1 →
        deposed-redirecting.  Zero acked-write loss, writes fenced with
        the standard redirect shape, reads still open, and the promoted
        standby serves new writes."""
        from cpzk_tpu.errors import WrongPartition
        from cpzk_tpu.replication import HandoverError

        async def main():
            (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, sport) = (
                await make_pair(tmp_path, auto_promote=False)
            )
            try:
                stmts = {}
                for i in range(8):
                    stmts[i] = make_statement()
                    await pstate.register_user(
                        UserData(f"user-{i}", stmts[i], 1)
                    )
                report = await shipper.run_handover(reason="test")
                assert report["ok"] and report["epoch"] == 2
                assert report["fence_seq"] == pmgr.wal.seq
                assert report["applied_seq"] >= report["fence_seq"]
                # new primary: promoted, serving, zero loss
                assert replica.role == "primary" and replica.epoch == 2
                assert await sstate.user_count() == 8
                for i in (0, 3, 7):
                    u = await sstate.get_user(f"user-{i}")
                    assert u is not None and u.statement == stmts[i]
                await sstate.register_user(
                    UserData("post-handover", make_statement(), 1)
                )
                # old primary: deposed-redirecting — fenced writes carry
                # the standby address, reads stay open
                assert shipper.fenced
                assert shipper.redirect_address == f"127.0.0.1:{sport}"
                st = shipper.handover_status()
                assert st["stage"] == "deposed"
                assert st["completed"] == 1 and st["aborted"] == 0
                assert st["last_duration_s"] is not None
                with pytest.raises(WrongPartition, match="handover"):
                    await pstate.register_user(
                        UserData("too-late", make_statement(), 1)
                    )
                assert (await pstate.get_user("user-0")) is not None
                # a second handover is structurally refused
                with pytest.raises(HandoverError, match="fenced"):
                    await shipper.run_handover()
            finally:
                await shipper.kill()
                await replica.stop()
                await sserver.stop(None)
                pmgr.wal.close()
                smgr.wal.close()

        run(main())

    def test_stale_standby_aborts_and_primary_keeps_serving(self, tmp_path):
        """A standby that cannot reach the fence watermark aborts the
        handover inside the deadline; the fence is rolled back and the
        primary keeps acknowledging writes — the loud fallback the
        SIGTERM path relies on."""
        from cpzk_tpu.replication import HandoverError

        async def main():
            (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, _p) = (
                await make_pair(tmp_path, mode="async", auto_promote=False)
            )
            try:
                await pstate.register_user(
                    UserData("before", make_statement(), 1)
                )
                await wait_for(lambda: shipper.acked_seq == pmgr.wal.seq)
                # standby goes away; async mode keeps acking locally
                await replica.stop()
                await sserver.stop(None)
                await pstate.register_user(
                    UserData("unshipped", make_statement(), 1)
                )
                with pytest.raises(HandoverError, match="stale standby"):
                    await shipper.run_handover(timeout_ms=400.0)
                st = shipper.handover_status()
                assert st["stage"] == "aborted"
                assert st["aborted"] == 1 and st["completed"] == 0
                assert not shipper.fenced
                assert shipper.redirect_address is None
                # the fence was rolled back: the primary still serves
                await pstate.register_user(
                    UserData("after-abort", make_statement(), 1)
                )
                assert await pstate.user_count() == 3
            finally:
                await shipper.kill()
                pmgr.wal.close()
                smgr.wal.close()

        run(main())

    @pytest.mark.parametrize("point", [
        "pre_handover_fence",
        "post_handover_fence",
        "pre_handover_promote",
        "post_handover_promote",
    ])
    def test_primary_crash_at_every_stage_degrades_to_lease_failover(
        self, tmp_path, point
    ):
        """SIGKILL stand-in at each primary-side handover stage: before
        promotion the pair is left exactly as it was (fence rolled back,
        primary serving) and a real death degrades to ordinary lease
        failover; after promotion the old primary stays deposed — no
        forked history either way, zero acked-write loss."""
        from cpzk_tpu.errors import WrongPartition

        async def main():
            plan = FaultPlan().crash_on(point, occurrence=0)
            (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, _p) = (
                await make_pair(tmp_path, primary_faults=plan)
            )
            try:
                for i in range(5):
                    await pstate.register_user(
                        UserData(f"user-{i}", make_statement(), 1)
                    )
                with pytest.raises(CrashPoint):
                    await shipper.run_handover(reason="crash-test")
                assert shipper.handovers_aborted == 1
                # every acked write reached the standby regardless
                assert await sstate.user_count() == 5
                if point == "post_handover_promote":
                    # the standby IS primary; the crashed node must stay
                    # deposed — anything less re-forks history
                    assert replica.role == "primary" and replica.epoch == 2
                    assert shipper.fenced
                    assert shipper.handover_status()["stage"] == "deposed"
                    with pytest.raises(WrongPartition):
                        await pstate.register_user(
                            UserData("forked", make_statement(), 1)
                        )
                    await sstate.register_user(
                        UserData("new-primary", make_statement(), 1)
                    )
                else:
                    # nothing irreversible happened: fence rolled back,
                    # primary serving, standby still a standby
                    assert replica.role == "standby"
                    assert not shipper.fenced
                    assert shipper.redirect_address is None
                    assert shipper.handover_status()["stage"] == "aborted"
                    await pstate.register_user(
                        UserData("still-primary", make_statement(), 1)
                    )
                    await wait_for(
                        lambda: replica.applied_seq == pmgr.wal.seq
                    )
                    # ...and a real process death now degrades to the
                    # ordinary lease failover (auto_promote)
                    await shipper.kill()
                    await wait_for(
                        lambda: replica.role == "primary", timeout=10.0
                    )
                    assert replica.epoch == 2
                    assert await sstate.user_count() == 6
            finally:
                await shipper.kill()
                await replica.stop()
                await sserver.stop(None)
                pmgr.wal.close()
                smgr.wal.close()

        run(main())

    def test_standby_crash_at_pre_handover_ack_then_retry_succeeds(
        self, tmp_path
    ):
        """The standby-side crash point fires before any state change:
        the primary's handover aborts cleanly (fence rolled back, pair
        unchanged), and a straight retry completes the handover."""
        import grpc

        async def main():
            plan = FaultPlan().crash_on("pre_handover_ack", occurrence=0)
            (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, _p) = (
                await make_pair(
                    tmp_path, standby_faults=plan, auto_promote=False
                )
            )
            try:
                for i in range(3):
                    await pstate.register_user(
                        UserData(f"user-{i}", make_statement(), 1)
                    )
                with pytest.raises(grpc.RpcError):
                    await shipper.run_handover(reason="crash-test")
                # pair unchanged: primary serving, standby a standby
                assert replica.role == "standby"
                assert not shipper.fenced
                assert shipper.handover_status()["stage"] == "aborted"
                assert shipper.handovers_aborted == 1
                await pstate.register_user(
                    UserData("between", make_statement(), 1)
                )
                # the crash occurrence is consumed; retry goes through
                report = await shipper.run_handover(reason="retry")
                assert report["ok"] and report["epoch"] == 2
                assert replica.role == "primary"
                assert await sstate.user_count() == 4
            finally:
                await shipper.kill()
                await replica.stop()
                await sserver.stop(None)
                pmgr.wal.close()
                smgr.wal.close()

        run(main())

    def test_wire_initiate_and_rolling_restart_cli(self, tmp_path):
        """serve(replica=shipper) exposes Handover next to auth traffic
        on the primary, and the fleet rolling-restart CLI drives it end
        to end: health-gate → initiate → poll promotion → flip the map
        (swap_standby) — the stored map ends v2 with the roles swapped."""
        import json
        from types import SimpleNamespace

        from cpzk_tpu.fleet.partition_map import PartitionMap
        from cpzk_tpu.fleet.__main__ import _roll_fleet
        from cpzk_tpu.server.service import serve

        async def main():
            (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, sport) = (
                await make_pair(tmp_path, auto_promote=False)
            )
            pserver, pport = await serve(
                pstate, RateLimiter(100_000, 100_000), port=0,
                replica=shipper,
            )
            try:
                for i in range(4):
                    await pstate.register_user(
                        UserData(f"user-{i}", make_statement(), 1)
                    )
                mpath = tmp_path / "fleet.json"
                PartitionMap.uniform(
                    [f"127.0.0.1:{pport}"],
                    standbys=[f"127.0.0.1:{sport}"],
                ).store(str(mpath))
                rc = await _roll_fleet(
                    SimpleNamespace(map=str(mpath), timeout=15.0)
                )
                assert rc == 0
                assert replica.role == "primary" and replica.epoch == 2
                assert shipper.fenced
                flipped = PartitionMap.load(str(mpath))
                assert flipped.partitions[0].address == f"127.0.0.1:{sport}"
                assert flipped.partitions[0].standby == f"127.0.0.1:{pport}"
                assert flipped.version == 2
                doc = json.loads(mpath.read_text())
                assert doc["schema"] == "cpzk-partition-map/2"
                assert await sstate.user_count() == 4
            finally:
                await shipper.kill()
                await replica.stop()
                await pserver.stop(None)
                await sserver.stop(None)
                pmgr.wal.close()
                smgr.wal.close()

        run(main())

    def test_deposed_primary_redirects_challenge_flow(self, tmp_path):
        """A fenced/deposed primary redirects the whole challenge flow —
        CreateChallenge AND the VerifyProof-side consume — before
        touching state.  The consume must not stay open the way it does
        across a live split: a challenge minted after the fence
        watermark replicates nowhere, and one minted at the promoted
        standby must survive a stale client that still dials the old
        primary, so the redirect has to go out pre-consume and the
        retry at the standby finds the challenge intact there."""
        import grpc

        from cpzk_tpu import Transcript
        from cpzk_tpu.client import AuthClient
        from cpzk_tpu.server.service import serve

        async def main():
            (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, sport) = (
                await make_pair(tmp_path)
            )
            pserver, pport = await serve(
                pstate, RateLimiter(100_000, 100_000), port=0,
                replica=shipper,
            )
            stale = standby_cli = None
            try:
                prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
                await pstate.register_user(
                    UserData("ho-user", prover.statement, 1)
                )
                await shipper.run_handover()

                # stale mapless client at the OLD primary: create redirects
                # with the standby in the owner trailer
                stale = AuthClient(f"127.0.0.1:{pport}")
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await stale.create_challenge("ho-user")
                assert exc.value.code() == grpc.StatusCode.FAILED_PRECONDITION
                assert "handover in progress" in exc.value.details()
                tmd = {k: v for k, v in exc.value.trailing_metadata() or ()}
                assert tmd["cpzk-partition-owner"] == f"127.0.0.1:{sport}"

                # a live challenge at the promoted standby, misdialed to
                # the deposed primary with a VALID proof: redirected, not
                # consumed anywhere...
                standby_cli = AuthClient(f"127.0.0.1:{sport}")
                ch = await standby_cli.create_challenge("ho-user")
                cid = bytes(ch.challenge_id)
                t = Transcript()
                t.append_context(cid)
                proof = prover.prove_with_transcript(rng, t)
                with pytest.raises(grpc.aio.AioRpcError) as exc:
                    await stale.verify_proof("ho-user", cid, proof.to_bytes())
                assert exc.value.code() == grpc.StatusCode.FAILED_PRECONDITION
                assert "handover in progress" in exc.value.details()

                # ...so the SAME proof retried at the standby completes
                resp = await standby_cli.verify_proof(
                    "ho-user", cid, proof.to_bytes()
                )
                assert resp.success
            finally:
                if stale is not None:
                    await stale.close()
                if standby_cli is not None:
                    await standby_cli.close()
                await shipper.kill()
                await replica.stop()
                await pserver.stop(None)
                await sserver.stop(None)
                pmgr.wal.close()
                smgr.wal.close()

        run(main())

    def test_handover_repl_command(self, tmp_path):
        """`/handover` runs the coordinated handover from the REPL and
        refuses cleanly on a node that is not a replication primary."""
        from cpzk_tpu.server.__main__ import handle_command

        async def main():
            out, _ = await handle_command("/handover", ServerState())
            assert "nothing to hand over" in out

            (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, _p) = (
                await make_pair(tmp_path, auto_promote=False)
            )
            try:
                await pstate.register_user(
                    UserData("alice", make_statement(), 1)
                )
                out, _ = await handle_command(
                    "/handover", sstate, None, smgr, None, replica
                )
                assert "nothing to hand over" in out
                out, _ = await handle_command(
                    "/handover", pstate, None, pmgr, None, shipper
                )
                assert "HANDOVER complete" in out and "epoch=2" in out
                assert replica.role == "primary"
                # a second attempt surfaces the abort, not a traceback
                out, _ = await handle_command(
                    "/handover", pstate, None, pmgr, None, shipper
                )
                assert "ABORTED" in out
            finally:
                await shipper.kill()
                await replica.stop()
                await sserver.stop(None)
                pmgr.wal.close()
                smgr.wal.close()

        run(main())

    def test_statusz_handover_block(self, tmp_path):
        """/statusz carries the handover block on a primary and None on
        nodes without one (satellite 3)."""
        from cpzk_tpu.observability.opsplane import OpsSources

        async def main():
            (pstate, pmgr, shipper), (sstate, smgr, replica, sserver, _p) = (
                await make_pair(tmp_path, auto_promote=False)
            )
            try:
                src = OpsSources(state=pstate, replication=shipper)
                doc = src.statusz()
                assert doc["handover"]["stage"] == "idle"
                assert doc["handover"]["attempts"] == 0
                await shipper.run_handover(reason="test")
                doc = src.statusz()
                assert doc["handover"]["stage"] == "deposed"
                assert doc["handover"]["completed"] == 1
                # a standby (no handover_status seam) renders null
                assert OpsSources(state=sstate, replication=replica
                                  ).statusz()["handover"] is None
            finally:
                await shipper.kill()
                await replica.stop()
                await sserver.stop(None)
                pmgr.wal.close()
                smgr.wal.close()

        run(main())
