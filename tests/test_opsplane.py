"""Fleet ops plane tests: HTTP introspection endpoints, exposition
parity on both metric backings, /statusz e2e against a live serving
stack with replication + audit enabled, the SLO burn-rate engine under
a synthetic error storm, the shared REPL/HTTP/SIGUSR2 serializers, and
the [opsplane]/[slo] config surface."""

import asyncio
import json
import logging
import os
import pathlib
import re
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import dataclasses
import pytest

from cpzk_tpu import Parameters, Prover, SecureRng, Transcript, Witness
from cpzk_tpu.admission import AdmissionController
from cpzk_tpu.audit import ProofLogWriter
from cpzk_tpu.client import AuthClient
from cpzk_tpu.core.ristretto import Ristretto255
from cpzk_tpu.durability import DurabilityManager
from cpzk_tpu.observability import get_tracer
from cpzk_tpu.observability.flightrec import FlightRecord, get_flight_recorder
from cpzk_tpu.observability.opsplane import ENDPOINTS, OpsPlane, OpsSources
from cpzk_tpu.observability.slo import RPC_CLASSES, SloEngine
from cpzk_tpu.protocol.batch import CpuBackend
from cpzk_tpu.replication import SegmentShipper, StandbyReplica
from cpzk_tpu.server import RateLimiter, ServerState, metrics
from cpzk_tpu.server.batching import DynamicBatcher
from cpzk_tpu.server.config import (
    AdmissionSettings,
    DurabilitySettings,
    OpsplaneSettings,
    ReplicationSettings,
    ServerConfig,
    SloSettings,
)
from cpzk_tpu.server.service import serve
from cpzk_tpu.server.state import _LOCK_WAIT_STRIDE, StateShard

ROOT = pathlib.Path(__file__).resolve().parent.parent
EB = Ristretto255.element_to_bytes

rng = SecureRng()
params = Parameters.new()


def run(coro):
    return asyncio.run(coro)


def http_get(port: int, path: str, timeout: float = 10.0):
    """(status, content_type, body bytes) — raises on transport errors,
    returns the error status for HTTP-level failures."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout
        ) as resp:
            return resp.status, resp.headers.get("Content-Type"), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type"), e.read()


async def aget(port: int, path: str):
    return await asyncio.to_thread(http_get, port, path)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# --- exposition parity -------------------------------------------------------


def test_exposition_contains_every_registered_family():
    """Every (kind, name) in the facade registry renders into the
    exposition text on the prometheus backing (the in-process one)."""
    metrics.counter("opsx.count").inc(3)
    metrics.gauge("opsx.depth").set(7)
    metrics.histogram("opsx.dur").observe(0.5)
    metrics.counter("opsx.labeled", labelnames=("rpc",)).labels(rpc="A").inc()
    text = metrics.render_exposition()
    for _kind, name in metrics.registered():
        assert metrics._sanitize(name) in text, name
    assert text.rstrip().endswith("# EOF")
    # TYPE lines name the kinds
    assert "# TYPE opsx_count counter" in text
    assert "# TYPE opsx_depth gauge" in text
    assert "# TYPE opsx_dur histogram" in text
    assert re.search(r'opsx_labeled(?:_total)?\{rpc="A"\} 1\.0', text)


_NOOP_PARITY_SCRIPT = """
import importlib.abc, sys

class _Block(importlib.abc.MetaPathFinder):
    def find_spec(self, fullname, path, target=None):
        if fullname.split(".")[0] == "prometheus_client":
            raise ImportError("blocked")
        return None

sys.meta_path.insert(0, _Block())

from cpzk_tpu.server import metrics

assert metrics.HAVE_PROMETHEUS is False
# the same family kinds the prometheus-backed test creates
metrics.counter("opsx.count").inc(3)
metrics.gauge("opsx.depth").set(7)
metrics.histogram("opsx.dur").observe(0.5)
metrics.counter("opsx.labeled", labelnames=("rpc",)).labels(rpc="A").inc()
text = metrics.render_exposition()
for _kind, name in metrics.registered():
    assert metrics._sanitize(name) in text, name
assert "opsx_count_total 3.0" in text
assert "opsx_depth 7.0" in text
assert "opsx_dur_count 1.0" in text and "opsx_dur_sum 0.5" in text
assert 'opsx_labeled_total{rpc="A"} 1.0' in text
assert text.rstrip().endswith("# EOF")

# ...and over real HTTP through the ops plane
import asyncio, urllib.request
from cpzk_tpu.observability.opsplane import OpsPlane, OpsSources

async def main():
    plane = OpsPlane(OpsSources(), port=0)
    port = await plane.start()
    def get():
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as r:
            return r.status, r.read().decode()
    status, body = await asyncio.to_thread(get)
    assert status == 200
    for _kind, name in metrics.registered():
        assert metrics._sanitize(name) in body, name
    await plane.stop()

asyncio.run(main())
print("NOOP-EXPOSITION-OK")
"""


def test_exposition_parity_without_prometheus_subprocess():
    """The no-prometheus backing renders the identical family set —
    including over real HTTP through the ops plane."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    result = subprocess.run(
        [sys.executable, "-c", _NOOP_PARITY_SCRIPT],
        capture_output=True, text=True, cwd=str(ROOT), env=env, timeout=120,
    )
    assert result.returncode == 0, result.stderr
    assert "NOOP-EXPOSITION-OK" in result.stdout


# --- one serializer for REPL / HTTP / SIGUSR2 --------------------------------


def test_flightrec_dump_and_http_share_payload(tmp_path):
    """The SIGUSR2 dump file, ``payload()``, and the REPL rendering all
    come from one serializer — identical record dicts."""
    rec = get_flight_recorder()
    rec.clear()
    rec.record(FlightRecord(batch=8, lanes=16, occupancy=0.5,
                            stages_s={"execute": 0.001}, wall_s=0.0011))
    payload = rec.payload()
    assert payload["schema"] == "cpzk-flightrec/1"
    path = tmp_path / "dump.json"
    rec.dump(str(path))
    dumped = json.loads(path.read_text())
    assert dumped["records"] == payload["records"]
    assert dumped["schema"] == payload["schema"]
    # the REPL text renders the same dicts
    from cpzk_tpu.observability import format_flightrec

    out = format_flightrec(payload)
    assert "#1" in out and "n=8" in out
    rec.clear()


def test_tracez_payload_roundtrips_repl_rendering():
    from cpzk_tpu.observability import RequestContext, format_tracez

    tracer = get_tracer()
    tracer.clear()
    ctx = RequestContext()
    tracer.start(ctx, "OpsOp")
    tracer.add_span(ctx.trace_id, "queue_wait", 0.0, 0.002)
    tracer.finish(ctx.trace_id, "success")
    payload = tracer.payload()
    assert payload["schema"] == "cpzk-tracez/1"
    assert payload["traces"][0]["name"] == "OpsOp"
    assert payload["traces"][0]["spans"][0]["name"] == "queue_wait"
    out = format_tracez(payload)
    assert "OpsOp" in out and "queue_wait=2.00ms" in out
    tracer.clear()


# --- the HTTP server itself --------------------------------------------------


def test_unknown_path_404_and_method_not_allowed():
    async def main():
        plane = OpsPlane(OpsSources(), port=0)
        port = await plane.start()
        try:
            status, ctype, body = await aget(port, "/definitely-not-a-path")
            assert status == 404 and "json" in ctype
            doc = json.loads(body)
            assert sorted(doc["endpoints"]) == sorted(ENDPOINTS)

            def post():
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/metrics", data=b"x",
                    method="POST",
                )
                try:
                    urllib.request.urlopen(req, timeout=10)
                except urllib.error.HTTPError as e:
                    return e.code
                return 200

            assert await asyncio.to_thread(post) == 405
            # /slo without an engine attached is a 404, not a crash
            status, _, _ = await aget(port, "/slo")
            assert status == 404
        finally:
            await plane.stop()

    run(main())


def test_healthz_readiness_split():
    """/healthz keys its status code on liveness; ?service=readiness on
    readiness — mirroring the gRPC health split."""
    from cpzk_tpu.server.service import HealthService

    async def main():
        health = HealthService()
        plane = OpsPlane(OpsSources(health=health), port=0)
        port = await plane.start()
        try:
            status, _, body = await aget(port, "/healthz")
            doc = json.loads(body)
            assert status == 200 and doc["live"] and doc["ready"]
            # standby: live but not ready
            health.standby = True
            status, _, body = await aget(port, "/healthz")
            assert status == 200 and json.loads(body)["ready"] is False
            status, _, _ = await aget(port, "/healthz?service=readiness")
            assert status == 503
            # draining: not live either
            health.standby = False
            health.serving = False
            status, _, _ = await aget(port, "/healthz")
            assert status == 503
        finally:
            await plane.stop()

    run(main())


def test_start_in_thread_serves_and_stops():
    """The audit pipeline's attachment: the same server on a daemon
    thread next to a synchronous host."""
    plane = OpsPlane(OpsSources(role="audit"), port=0)
    port = plane.start_in_thread()
    try:
        status, _, body = http_get(port, "/healthz")
        assert status == 200
        assert json.loads(body)["live"] is True
        status, _, body = http_get(port, "/statusz")
        assert json.loads(body)["role"] == "audit"
    finally:
        plane.stop_thread()
    with pytest.raises(OSError):
        http_get(port, "/healthz", timeout=2.0)


# --- /statusz e2e against a live serving stack -------------------------------


def test_statusz_e2e_with_replication_and_audit(tmp_path):
    """The acceptance path: a live daemon-shaped stack (batcher +
    admission + audit trail + replication primary shipping to a real
    standby) serves /metrics /statusz /tracez /healthz /slo over plain
    HTTP, with every cross-plane block populated."""

    async def main():
        # standby side (real gRPC link, like test_replication.make_pair)
        sstate = ServerState()
        smgr = DurabilityManager(
            sstate, DurabilitySettings(enabled=True),
            str(tmp_path / "standby.json"),
        )
        await smgr.recover()
        ssettings = ReplicationSettings(
            enabled=True, role="standby", lease_ms=4000.0,
            renew_interval_ms=50.0, mode="sync", auto_promote=False,
        )
        replica = StandbyReplica(sstate, smgr, ssettings)
        sserver, sport = await serve(
            sstate, RateLimiter(100_000, 100_000), port=0, replica=replica
        )
        replica.start()

        # primary side: the full serving stack
        pstate = ServerState()
        pmgr = DurabilityManager(
            pstate, DurabilitySettings(enabled=True),
            str(tmp_path / "primary.json"),
        )
        await pmgr.recover()
        psettings = ReplicationSettings(
            enabled=True, role="primary", peer=f"127.0.0.1:{sport}",
            lease_ms=4000.0, renew_interval_ms=50.0, mode="sync",
        )
        shipper = SegmentShipper(pstate, pmgr, psettings)
        pmgr.attach_shipper(shipper)
        pstate.attach_replication_barrier(shipper.wait_replicated)
        batcher = DynamicBatcher(CpuBackend(), max_batch=64, window_ms=5.0)
        admission = AdmissionController(
            AdmissionSettings(), batcher=batcher
        )
        audit_log = ProofLogWriter(str(tmp_path / "proofs.log"))
        pserver, pport = await serve(
            pstate, RateLimiter(100_000, 100_000), port=0,
            batcher=batcher, admission=admission, audit_log=audit_log,
        )
        shipper.start()

        cfg = ServerConfig()
        engine = SloEngine(cfg.slo)
        sources = OpsSources(
            state=pstate, batcher=batcher, admission=admission,
            replication=shipper, audit_log=audit_log, durability=pmgr,
            health=pserver.health, service=pserver.auth_service,
            slo=engine, config_fingerprint=cfg.fingerprint(),
        )
        plane = OpsPlane(sources, port=0)
        ops_port = await plane.start()

        try:
            # drive real logins so every plane has numbers to report
            async with AuthClient(f"127.0.0.1:{pport}") as client:
                provers = {}
                for i in range(4):
                    p = Prover(
                        params, Witness(Ristretto255.random_scalar(rng))
                    )
                    provers[f"ops-u{i}"] = p
                    resp = await client.register(
                        f"ops-u{i}", EB(p.statement.y1), EB(p.statement.y2)
                    )
                    assert resp.success
                for uid, p in provers.items():
                    ch = await client.create_challenge(uid)
                    t = Transcript()
                    t.append_context(bytes(ch.challenge_id))
                    proof = p.prove_with_transcript(rng, t)
                    resp = await client.verify_proof(
                        uid, ch.challenge_id, proof.to_bytes()
                    )
                    assert resp.success

            # let the shipper push the journaled mutations to the standby
            deadline = asyncio.get_running_loop().time() + 5.0
            while shipper.acked_seq < pmgr.wal.seq:
                assert asyncio.get_running_loop().time() < deadline, (
                    shipper.status()
                )
                await asyncio.sleep(0.02)

            status, ctype, body = await aget(ops_port, "/statusz")
            assert status == 200 and "json" in ctype
            doc = json.loads(body)
            assert doc["schema"] == "cpzk-statusz/1"
            assert doc["uptime_s"] >= 0.0
            assert doc["config_fingerprint"] == cfg.fingerprint()
            # batcher block
            assert doc["batcher"]["queue_capacity"] == batcher.max_queue
            # shards: the registrations and sessions we just made
            assert doc["shards"]["count"] == pstate.num_shards
            assert doc["shards"]["users"] == 4
            assert doc["shards"]["sessions"] == 4
            assert len(doc["shards"]["per_shard"]) == pstate.num_shards
            # dispatch block: the batcher recorded flight records
            assert doc["dispatch"]["recorded_batches"] >= 1
            assert "execute" in doc["dispatch"]["stage_percentiles_ms"]
            # admission block
            assert doc["admission"]["level"] > 0
            # replication block: primary, synced, fresh last-ship
            repl = doc["replication"]
            assert repl["role"] == "primary"
            assert repl["lag_records"] == 0
            assert repl["last_ship_age_s"] is not None
            # audit block: one record per verify
            assert doc["audit"]["seq"] == 4
            assert doc["audit"]["bytes"] > 0
            # durability + health + streams blocks present
            assert doc["durability"]["wal_seq"] == pmgr.wal.seq
            assert doc["health"] == {"live": True, "ready": True}
            assert doc["streams"] == {"active": 0, "streams": []}

            # cross-plane histograms landed
            assert metrics.read_histogram("state.repl.ship_rtt")[0] >= 1
            assert metrics.read_histogram(
                "state.repl.apply_lag_seconds")[0] >= 1

            # /metrics: families from every plane, incl. scrape-time
            # per-shard gauges
            status, ctype, body = await aget(ops_port, "/metrics")
            text = body.decode()
            assert status == 200 and "text/plain" in ctype
            for family in ("rpc_requests", "state_repl_role",
                           "state_shard_size", "audit_log_appends",
                           "tpu_queue_depth", "state_repl_ship_rtt"):
                assert family in text, family
            assert metrics.read(
                "state.shard.size", "g",
                labels={"shard": str(pstate._shard_index("ops-u0")),
                        "kind": "users"},
            ) >= 1.0

            # /tracez: the logins we just drove, same serializer as REPL
            status, _, body = await aget(ops_port, "/tracez?n=50")
            traces = json.loads(body)
            assert traces["schema"] == "cpzk-tracez/1"
            assert any(
                t["name"] == "VerifyProof" for t in traces["traces"]
            )

            # /healthz + /slo
            status, _, body = await aget(ops_port, "/healthz")
            assert status == 200 and json.loads(body)["ready"] is True
            status, _, body = await aget(ops_port, "/slo")
            slo = json.loads(body)
            assert status == 200 and slo["schema"] == "cpzk-slo/1"
            assert slo["rpcs"]["VerifyProof"]["total_requests"] >= 4

            # unknown path: JSON 404 with the catalog
            status, _, body = await aget(ops_port, "/nope")
            assert status == 404
            assert sorted(json.loads(body)["endpoints"]) == sorted(ENDPOINTS)
        finally:
            await plane.stop()
            await batcher.stop()
            audit_log.close()
            await shipper.stop()
            await replica.stop()
            await pserver.stop(None)
            await sserver.stop(None)
            await pmgr.close()
            await smgr.close()

    run(main())


def test_statusz_reports_active_streams():
    """A live VerifyProofStream shows up as a per-stream /statusz row
    and in the auth.stream.active gauge, and unregisters on close."""

    async def main():
        state = ServerState()
        server, port = await serve(
            state, RateLimiter(10**9, 10**9), port=0,
        )
        service = server.auth_service
        try:
            p = Prover(params, Witness(Ristretto255.random_scalar(rng)))
            async with AuthClient(f"127.0.0.1:{port}") as client:
                resp = await client.register(
                    "s-u0", EB(p.statement.y1), EB(p.statement.y2)
                )
                assert resp.success

                async def entry():
                    ch = await client.create_challenge("s-u0")
                    t = Transcript()
                    t.append_context(bytes(ch.challenge_id))
                    return ("s-u0", bytes(ch.challenge_id),
                            p.prove_with_transcript(rng, t).to_bytes())

                entries = [await entry(), await entry()]

                async def gen():
                    yield entries[0]
                    # mid-stream: exactly one live stream, with rows
                    for _ in range(500):
                        if service.stream_stats()["active"] == 1:
                            break
                        await asyncio.sleep(0.01)
                    stats = service.stream_stats()
                    assert stats["active"] == 1
                    assert metrics.read("auth.stream.active", "g") == 1.0
                    yield entries[1]

                verdicts = [
                    v async for v in
                    client.verify_proof_stream(gen(), chunk=1)
                ]
                assert len(verdicts) == 2
                assert all(v.ok for v in verdicts)
            stats = service.stream_stats()
            assert stats["active"] == 0 and stats["streams"] == []
            assert metrics.read("auth.stream.active", "g") == 0.0
        finally:
            await server.stop(None)

    run(main())


# --- shard lock-wait sampling ------------------------------------------------


def test_shard_lock_wait_is_stride_sampled():
    async def main():
        shard = StateShard()
        before = metrics.read_histogram("state.shard.lock_wait")[0]
        for _ in range(2 * _LOCK_WAIT_STRIDE):
            async with shard.lock:
                pass
        after = metrics.read_histogram("state.shard.lock_wait")[0]
        assert after - before == 2  # exactly 1-in-stride observed

    run(main())


def test_shard_stats_and_gauges():
    async def main():
        state = ServerState(shards=4)
        p = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        from cpzk_tpu.server.state import UserData

        await state.register_user(UserData("g-u0", p.statement, 1))
        stats = state.shard_stats()
        assert len(stats) == 4
        assert sum(s["users"] for s in stats) == 1
        state.export_shard_gauges()
        idx = str(state._shard_index("g-u0"))
        assert metrics.read(
            "state.shard.size", "g", labels={"shard": idx, "kind": "users"}
        ) == 1.0

    run(main())


# --- SLO engine --------------------------------------------------------------


def _slo_drive(engine, clock, req, dur, ticks, dt, ok=0, fail=0,
               latency_s=None):
    for _ in range(ticks):
        clock[0] += dt
        if ok:
            req.labels(rpc="VerifyProof", outcome="success").inc(ok)
        if fail:
            req.labels(rpc="VerifyProof", outcome="failure").inc(fail)
        if latency_s is not None:
            dur.labels(rpc="VerifyProof").observe(latency_s)
        engine.tick()


def test_slo_burn_storm_pages_once_per_window_and_recovers(caplog):
    """The synthetic error storm: burn gauges cross during a 50%-failure
    storm, the page WARNING fires once per (short) window, an slo_burn
    event lands in the trace ring, and the budget recovers after."""
    clock = [10_000.0]
    engine = SloEngine(SloSettings(), clock=lambda: clock[0])
    req = metrics.counter("rpc.requests", labelnames=("rpc", "outcome"))
    dur = metrics.histogram("rpc.duration", labelnames=("rpc",))
    tracer = get_tracer()
    tracer.clear()

    engine.tick()  # baseline sample
    # healthy 10 minutes
    _slo_drive(engine, clock, req, dur, ticks=10, dt=60.0, ok=600)
    view = engine.snapshot()["rpcs"]["VerifyProof"]
    assert view["windows"]["5m"]["burn_rate"] < 1.0
    assert view["error_budget_remaining"] == 1.0
    assert view["paging"] == []

    # the storm: 50% failures for 5 minutes of 60s ticks
    with caplog.at_level(logging.WARNING, "cpzk_tpu.observability.slo"):
        _slo_drive(engine, clock, req, dur, ticks=5, dt=60.0,
                   ok=100, fail=100)
    view = engine.snapshot()["rpcs"]["VerifyProof"]
    assert view["windows"]["5m"]["burn_rate"] > engine.settings.fast_burn_threshold
    assert view["windows"]["1h"]["burn_rate"] > engine.settings.fast_burn_threshold
    assert "fast" in view["paging"]
    assert view["error_budget_remaining"] < 1.0
    # exported gauges crossed too
    assert metrics.read(
        "slo.burn_rate", "g", labels={"rpc": "VerifyProof", "window": "5m"}
    ) > engine.settings.fast_burn_threshold
    # WARNING once per (5m) window across the 5 storm ticks, not 5 times
    fast_warnings = [
        r for r in caplog.records if "SLO burn (fast)" in r.getMessage()
    ]
    assert len(fast_warnings) == 1
    # trace-ring slo_burn event on the shared timeline
    events = [t for t in tracer.completed() if t.name == "slo_burn"]
    assert events and events[0].spans[0].attrs["rpc"] == "VerifyProof"

    # recovery: hours of healthy traffic drain the windows
    _slo_drive(engine, clock, req, dur, ticks=100, dt=300.0, ok=1000)
    view = engine.snapshot()["rpcs"]["VerifyProof"]
    assert view["windows"]["5m"]["burn_rate"] == 0.0
    assert view["windows"]["6h"]["burn_rate"] < 1.0
    assert view["error_budget_remaining"] > 0.99
    assert view["paging"] == []
    tracer.clear()


def test_slo_latency_burn_component():
    """A latency regression (mean over target) burns even at 100%
    availability."""
    clock = [50_000.0]
    settings = SloSettings(latency_ms="VerifyProof=100")
    engine = SloEngine(settings, clock=lambda: clock[0])
    assert engine.latency_ms["VerifyProof"] == 100.0
    req = metrics.counter("rpc.requests", labelnames=("rpc", "outcome"))
    dur = metrics.histogram("rpc.duration", labelnames=("rpc",))
    engine.tick()
    # all successes, but 400ms mean against a 100ms target
    _slo_drive(engine, clock, req, dur, ticks=3, dt=60.0, ok=10,
               latency_s=0.4)
    view = engine.snapshot()["rpcs"]["VerifyProof"]
    w = view["windows"]["5m"]
    assert w["availability_burn"] == 0.0
    assert w["latency_burn"] == pytest.approx(4.0, rel=0.01)
    assert w["burn_rate"] == pytest.approx(4.0, rel=0.01)


def test_slo_known_burn_math():
    """1 failure in 1000 requests at a 99.9% target is burn exactly 1."""
    clock = [90_000.0]
    engine = SloEngine(
        SloSettings(availability_target=0.999), clock=lambda: clock[0]
    )
    req = metrics.counter("rpc.requests", labelnames=("rpc", "outcome"))
    engine.tick()
    clock[0] += 60.0
    req.labels(rpc="CreateChallenge", outcome="success").inc(999)
    req.labels(rpc="CreateChallenge", outcome="failure").inc(1)
    engine.tick()
    view = engine.snapshot()["rpcs"]["CreateChallenge"]
    assert view["windows"]["5m"]["availability_burn"] == pytest.approx(
        1.0, rel=0.01
    )
    # every known RPC class is tracked
    assert set(engine.snapshot()["rpcs"]) == set(RPC_CLASSES)


# --- daemon: ops plane refuses to bind when disabled -------------------------


def test_daemon_does_not_bind_opsplane_when_disabled(tmp_path):
    """[opsplane] enabled=false (the default) means NO HTTP listener —
    a real daemon boot, pinned by connection-refused on the configured
    ops port while gRPC is accepting."""
    grpc_port, ops_port = free_port(), free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("SERVER_OPSPLANE_ENABLED", None)
    env["SERVER_CONFIG_PATH"] = str(tmp_path / "no-such.toml")
    env["SERVER_OPSPLANE_PORT"] = str(ops_port)
    proc = subprocess.Popen(
        [sys.executable, "-m", "cpzk_tpu.server", "--no-repl",
         "--port", str(grpc_port)],
        cwd=str(ROOT), env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            assert proc.poll() is None, "daemon died during boot"
            try:
                socket.create_connection(
                    ("127.0.0.1", grpc_port), timeout=0.5
                ).close()
                break
            except OSError:
                time.sleep(0.1)
        else:
            raise AssertionError("gRPC listener never came up")
        with pytest.raises(OSError):
            socket.create_connection(("127.0.0.1", ops_port), timeout=0.5)
    finally:
        proc.terminate()
        proc.wait(timeout=30)


def test_daemon_metrics_fallback_without_prometheus(tmp_path):
    """The silent-no-exposition satellite: --metrics with
    prometheus_client missing used to leave the configured metrics port
    dead with no log line.  Now the daemon serves the ops-plane text
    exposition on that same port (and /metrics answers scrapes)."""
    shim = tmp_path / "prometheus_client.py"
    shim.write_text('raise ImportError("blocked for the fallback test")\n')
    grpc_port, metrics_port = free_port(), free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = f"{tmp_path}:{ROOT}"
    env["SERVER_CONFIG_PATH"] = str(tmp_path / "no-such.toml")
    proc = subprocess.Popen(
        [sys.executable, "-m", "cpzk_tpu.server", "--no-repl",
         "--port", str(grpc_port),
         "--metrics", "--metrics-port", str(metrics_port)],
        cwd=str(ROOT), env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
    )
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            assert proc.poll() is None, proc.stderr.read()
            try:
                status, ctype, body = http_get(
                    metrics_port, "/metrics", timeout=0.5
                )
                break
            except OSError:
                time.sleep(0.1)
        else:
            raise AssertionError("fallback /metrics never came up")
        assert status == 200 and "text/plain" in ctype
        assert b"# EOF" in body
    finally:
        proc.terminate()
        try:
            _, err = proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            _, err = proc.communicate(timeout=30)
    assert "prometheus_client is not installed" in err


# --- config surface ----------------------------------------------------------


def test_opsplane_slo_config_layering_and_env(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    cfg = ServerConfig.from_env()
    assert cfg.opsplane.enabled is False
    assert cfg.opsplane.port == 9092
    assert cfg.slo.availability_target == 0.999

    (tmp_path / "server.toml").write_text(
        "[opsplane]\nenabled = true\nport = 9192\n\n"
        '[slo]\navailability_target = 0.99\nlatency_ms = "VerifyProof=50"\n'
    )
    monkeypatch.setenv("SERVER_CONFIG_PATH", str(tmp_path / "server.toml"))
    cfg = ServerConfig.from_env()
    assert cfg.opsplane.enabled is True and cfg.opsplane.port == 9192
    assert cfg.slo.availability_target == 0.99
    assert cfg.slo.parsed_latency_ms() == {"VerifyProof": 50.0}
    cfg.validate()

    # env overrides the file
    monkeypatch.setenv("SERVER_OPSPLANE_PORT", "9292")
    monkeypatch.setenv("SERVER_SLO_FAST_BURN_THRESHOLD", "10")
    monkeypatch.setenv("SERVER_SLO_TICK_INTERVAL_MS", "250")
    cfg = ServerConfig.from_env()
    assert cfg.opsplane.port == 9292
    assert cfg.slo.fast_burn_threshold == 10.0
    assert cfg.slo.tick_interval_ms == 250.0
    cfg.validate()


def test_opsplane_slo_config_validation():
    for mutate, match in (
        (lambda c: setattr(c.opsplane, "port", 70000), "opsplane.port"),
        (lambda c: setattr(c.opsplane, "port", -1), "opsplane.port"),
        (lambda c: setattr(c.slo, "availability_target", 1.0),
         "availability_target"),
        (lambda c: setattr(c.slo, "availability_target", 0.0),
         "availability_target"),
        (lambda c: setattr(c.slo, "fast_burn_threshold", 0),
         "fast_burn_threshold"),
        (lambda c: setattr(c.slo, "slow_burn_threshold", -1),
         "slow_burn_threshold"),
        (lambda c: setattr(c.slo, "tick_interval_ms", 0),
         "tick_interval_ms"),
        (lambda c: setattr(c.slo, "latency_ms", "garbage"), "latency_ms"),
        (lambda c: setattr(c.slo, "latency_ms", "VerifyProof=-5"),
         "latency_ms"),
    ):
        cfg = ServerConfig()
        mutate(cfg)
        with pytest.raises(ValueError, match=match):
            cfg.validate()
    # enabled + empty host is rejected; port 0 (ephemeral) is fine
    cfg = ServerConfig()
    cfg.opsplane.enabled = True
    cfg.opsplane.host = ""
    with pytest.raises(ValueError, match="host"):
        cfg.validate()
    cfg = ServerConfig()
    cfg.opsplane.port = 0
    cfg.validate()


def test_opsplane_slo_config_keys_documented():
    """CI drift guard (pattern from test_durability.py): every
    [opsplane]/[slo] knob ships in the TOML example, the .env example,
    and the operations-doc knob inventory."""
    docs = (ROOT / "docs" / "operations.md").read_text()
    toml_text = (ROOT / "config" / "server.toml.example").read_text()
    env_text = (ROOT / ".env.example").read_text()
    for section, cls in (
        ("opsplane", OpsplaneSettings), ("slo", SloSettings),
    ):
        keys = [f.name for f in dataclasses.fields(cls)]
        assert keys
        m = re.search(rf"^\[{section}\]$", toml_text, re.M)
        assert m, f"[{section}] section missing from server.toml.example"
        body = toml_text[m.end():].split("\n[", 1)[0]
        for key in keys:
            assert re.search(rf"^{key}\s*=", body, re.M), (
                f"[{section}] key {key!r} missing from server.toml.example"
            )
            assert f"SERVER_{section.upper()}_{key.upper()}" in env_text, (
                f"SERVER_{section.upper()}_{key.upper()} missing from "
                ".env.example"
            )
            assert f"`{section}.{key}`" in docs, (
                f"`{section}.{key}` missing from the docs/operations.md "
                "knob inventory"
            )


def test_config_fingerprint_stable_and_sensitive():
    a, b = ServerConfig(), ServerConfig()
    assert a.fingerprint() == b.fingerprint()
    assert len(a.fingerprint()) == 12
    b.opsplane.port = 9193
    assert a.fingerprint() != b.fingerprint()
